"""AOT memory analysis of the v5p-64-sharded Llama-3-8B train step.

The north star (BASELINE.json) is an 8B pretrain on a v5p-64 slice at
>=40% MFU. No such slice is attached, but the memory story does not need
one: `jax.jit(...).lower(...).compile()` on a 64-device CPU mesh runs the
real GSPMD partitioner + buffer assignment for the per-device program, so
XLA's own accounting of per-chip argument/temp bytes is available ahead of
time (ref shape: the reference records per-run memory/assert artifacts for
its Alpa release tests, release/alpa_tests/train_opt_2_7b_minimum.py:315).

Writes `MEM_8B_r5.json`: for each candidate mesh, XLA-reported per-device
bytes (arguments = resident state shards, temp = activation/workspace
high-water mark) next to the analytic state-shard size, and whether the
layout fits a v5p chip's 95.7 GB HBM.

Like the dryrun, the parent NEVER touches the accelerator backend: it
re-execs itself onto a 64-device CPU mesh (the host sitecustomize
force-registers the wedge-prone axon backend unless PALLAS_AXON_POOL_IPS
is cleared before interpreter start).
"""

import json
import os
import subprocess
import sys

_CHILD_ENV = "_RAY_TPU_MEM8B_CHILD"
_N_DEVICES = 64
_V5P_HBM = 95.7e9  # bytes per chip (public spec: 95 GiB HBM2e)

# Candidate v5p-64 layouts for the 8B north star. Global batch 64,
# seq 4096 => 256k tokens/step; remat everything (the MFU recipe trades
# recompute for activation memory).
MESHES = [
    {"name": "fsdp64", "spec": dict(fsdp=64)},
    {"name": "fsdp16_tensor4", "spec": dict(fsdp=16, tensor=4)},
    {"name": "data4_fsdp16", "spec": dict(data=4, fsdp=16)},
]
BATCH, SEQ = 64, 4096


def _child() -> None:
    import jax
    import jax.numpy as jnp

    from ray_tpu.models import llama3_8b_config, make_optimizer
    from ray_tpu.models.training import (
        batch_sharding,
        make_init_fn,
        make_train_step,
        state_shardings,
    )
    from ray_tpu.parallel import MeshSpec

    assert len(jax.devices()) == _N_DEVICES, jax.devices()
    cfg = llama3_8b_config(max_seq_len=SEQ, param_dtype=jnp.bfloat16,
                           remat=True, remat_policy="nothing")
    tx = make_optimizer(3e-4, mu_dtype=jnp.bfloat16)
    state_shapes = jax.eval_shape(make_init_fn(cfg, tx), jax.random.key(0))
    batch_shapes = {
        "inputs": jax.ShapeDtypeStruct((BATCH, SEQ), jnp.int32),
        "targets": jax.ShapeDtypeStruct((BATCH, SEQ), jnp.int32),
    }
    # analytic bytes of the full (unsharded) train state
    state_bytes = sum(s.size * s.dtype.itemsize
                     for s in jax.tree.leaves(state_shapes))

    out = {
        "benchmark": "llama3_8b_v5p64_memory_analysis",
        "model": "llama3-8b",
        "params_b": round(cfg.num_params / 1e9, 3),
        "n_devices": _N_DEVICES,
        "global_batch": BATCH,
        "seq_len": SEQ,
        "remat": "full",
        "state_dtypes": "bf16 params, bf16 adam mu, fp32 nu",
        "state_total_gb": round(state_bytes / 1e9, 2),
        "hbm_per_chip_gb": round(_V5P_HBM / 1e9, 1),
        "meshes": [],
    }
    for cand in MESHES:
        mesh = MeshSpec(**cand["spec"]).build(jax.devices())
        step = make_train_step(cfg, tx, mesh)
        shardings = state_shardings(cfg, tx, mesh)
        sharded_state = jax.tree.map(
            lambda s, sh: jax.ShapeDtypeStruct(s.shape, s.dtype, sharding=sh),
            state_shapes, shardings)
        bsh = batch_sharding(mesh)
        sharded_batch = {k: jax.ShapeDtypeStruct(v.shape, v.dtype,
                                                 sharding=bsh)
                         for k, v in batch_shapes.items()}
        compiled = step.lower(sharded_state, sharded_batch).compile()
        ma = compiled.memory_analysis()
        # per-device shard of the resident state (arguments alias outputs
        # via donation, so "arguments" is the steady-state residency)
        entry = {
            "mesh": cand["name"],
            "axes": {k: v for k, v in cand["spec"].items()},
        }
        if ma is not None:
            arg = getattr(ma, "argument_size_in_bytes", 0)
            tmp = getattr(ma, "temp_size_in_bytes", 0)
            outb = getattr(ma, "output_size_in_bytes", 0)
            alias = getattr(ma, "alias_size_in_bytes", 0)
            peak = arg + tmp + outb - alias
            entry.update({
                "xla_argument_gb": round(arg / 1e9, 2),
                "xla_temp_gb": round(tmp / 1e9, 2),
                "xla_output_gb": round(outb / 1e9, 2),
                "xla_aliased_gb": round(alias / 1e9, 2),
                "xla_peak_per_device_gb": round(peak / 1e9, 2),
                "fits_v5p_95gb": bool(peak < _V5P_HBM),
                "hbm_utilization": round(peak / _V5P_HBM, 3),
            })
        # analytic cross-check: state shard + token batch shard
        shard_bytes = 0
        for s, sh in zip(jax.tree.leaves(state_shapes),
                         jax.tree.leaves(shardings)):
            n = 1
            for d in sh.spec:
                if d is not None:
                    ax = (d,) if isinstance(d, str) else d
                    for a in ax:
                        n *= mesh.shape[a]
            shard_bytes += s.size * s.dtype.itemsize // max(n, 1)
        entry["analytic_state_shard_gb"] = round(shard_bytes / 1e9, 2)
        out["meshes"].append(entry)
        print(f"# {cand['name']}: {entry}", file=sys.stderr)
    json.dump(out, open("MEM_8B_r5.json", "w"), indent=1)
    print(json.dumps(out))


def main() -> None:
    if os.environ.get(_CHILD_ENV) == "1":
        _child()
        return
    env = dict(os.environ)
    env[_CHILD_ENV] = "1"
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("PALLAS_AXON_POOL_IPS", None)
    flags = [f for f in env.get("XLA_FLAGS", "").split()
             if "xla_force_host_platform_device_count" not in f]
    flags.append(f"--xla_force_host_platform_device_count={_N_DEVICES}")
    env["XLA_FLAGS"] = " ".join(flags)
    here = os.path.dirname(os.path.abspath(__file__))
    env["PYTHONPATH"] = here + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.run([sys.executable, os.path.abspath(__file__)],
                          env=env, cwd=here, timeout=2400)
    if proc.returncode != 0:
        raise SystemExit(f"mem_8b child failed rc={proc.returncode}")


if __name__ == "__main__":
    main()
