#!/bin/bash
# Fire the full TPU evidence battery the moment the tunnel recovers.
# Order matters: most valuable artifact first, in case it wedges again.
set -x
cd /root/repo
rm -f /dev/shm/rtpu_*

# 1) serving artifact: continuous vs cohort + proxy (SERVE_BENCH_r5.json)
timeout 900 python bench_serve.py --model llama3-1b --duration 30 \
    --decode-chunk 16 --max-inflight 4 \
    --out SERVE_BENCH_r5.json 2>&1 | tail -5

# 2) slot-scaling experiment: decode is weight-streaming bound, so
#    doubling slots should raise tokens/s without hurting latency
timeout 600 python bench_serve.py --model llama3-1b --duration 12 \
    --slots 16 --decode-chunk 16 --max-inflight 4 --skip-cohort \
    --proxy-duration 1 --out /tmp/serve_slots16.json 2>&1 | \
    grep '"engine"' | tail -1

# 3) flagship MFU sanity (the driver runs the full ladder at round end)
timeout 900 python bench.py 2>&1 | tail -3
