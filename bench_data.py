"""Pipelined shuffle A/B bench -> BENCH_shuffle_r17.json.

Phases (bench_scale conventions: ``--phases``/``--out``, per-phase
``loop_lag`` blocks, JSON merge across processes; interleaved A/B pairs,
median-of-pairwise — this host has multi-x run drift, so only paired
ratios in one window mean anything). Inter-node links are PACED
(`RAY_TPU_HOST_EGRESS_LIMIT_BPS` seeds every process's transfer-server
token bucket) — unpaced loopback moves a 1 MiB part in ~1 ms and hides
exactly the transfer the exchange exists to overlap.

1. **shuffle** — N-block ``random_shuffle`` on a paced 2-node cluster:
   the pipelined exchange (streamed split admission with
   holder-locality, the merge fold tree with eager free, partition
   homes, columnar split/merge kernels, merge-side prefetch hints) vs
   the PRE-r17 drain-based executor preserved verbatim behind
   ``data_shuffle_pipelined=False`` (upstream ref drain, row-path
   kernels, every part held to its terminal merge) — row-identical
   output. Gate: pipelined wall <= 0.67x drain (median of pairs).

2. **footprint** — same workload, peak head-directory entries sampled
   by a background thread, A/B drain vs pipelined (borrow grace shrunk
   so the sampler sees true liveness, not the ~1s free-deferral tail).
   Gate: pipelined peak <= 0.7x drain peak — the eager-free
   O(n_out x (window+fanin)) bound vs the baseline's O(n_in x n_out).

3. **hints** — ``data_shuffle_prefetch_hints`` ON vs OFF (the per-task
   ``prefetch_args`` opt-out on fold/merge submissions), one fresh
   cluster per round so the cumulative phase histograms stay separable.
   Gates: merge-side ``arg_fetch`` p95 improves with hints on, and
   ``prefetch_issued > 0`` on the ON round.

Run: python bench_data.py [--pairs 3] [--phases shuffle,footprint,hints]
     [--out BENCH_shuffle_r17.json] [--smoke]
"""

import argparse
import json
import os
import statistics
import sys
import threading
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("TPU_CHIPS", "0")
os.environ.setdefault("RAY_TPU_PRESTART_WORKERS", "0")

# paced inter-node links (bench_pipeline convention)
LINK_MIB_S = 40
os.environ.setdefault("RAY_TPU_HOST_EGRESS_LIMIT_BPS",
                      str(LINK_MIB_S * 1024 * 1024))

N_BLOCKS = 24
BLOCK_KIB = 1024      # ~1 MiB blocks: parts cross the paced link
N_OUT = 8             # output partitions: parts are ~128 KiB plasma
#                       objects (by-ref merge args), not inline values
READ_S = 0.12         # per-block production cost (IO-paced read
#                       emulation): the overlap the pipelined exchange
#                       exists to exploit — splits/folds/transfers run
#                       UNDER the stream instead of after it
SMOKE = False


def _median(xs):
    return statistics.median(xs) if xs else 0.0


class _LoopLag:
    """Per-phase head loop-lag capture (bench_scale convention)."""

    def snap(self):
        from ray_tpu import state

        try:
            row = state.io_loop_stats()[0]
        except Exception:  # noqa: BLE001 — no cluster yet
            row = {}
        self._before = row
        return self

    def delta(self) -> dict:
        from ray_tpu import state

        try:
            row = state.io_loop_stats()[0]
        except Exception:  # noqa: BLE001
            return {}
        before = getattr(self, "_before", {})
        return {
            "loop_lag_ms_p50": row.get("loop_lag_ms_p50", 0.0),
            "loop_lag_ms_p99": row.get("loop_lag_ms_p99", 0.0),
            "loop_lag_ms_max": row.get("loop_lag_ms_max", 0.0),
            "slow_events": row.get("slow_events", 0)
            - before.get("slow_events", 0),
            "fold_queue_drops": row.get("fold_queue_drops", 0)
            - before.get("fold_queue_drops", 0),
        }


class _ArenaProbe:
    """Arena accounting during a phase (memory observatory, r20): a
    background sampler polls ``state.memory_summary()`` and keeps the
    per-node arena peaks (used bytes + the store's own highwater from
    the heartbeat) and the per-job peak resident-byte split. The time
    spent inside the summary calls is the accounting overhead; the gate
    bounds it at 2% of phase wall — observability that distorts the
    phase it observes would be worse than none."""

    def __init__(self, period_s: float = 1.0):
        # 1s period: the arena heartbeat itself only updates every
        # node_telemetry_period_s (2s), and a summary call costs ~14ms
        # on a busy directory — sampling at 0.25s measured 5% of wall,
        # violating the <=2% gate this block exists to enforce
        self.period_s = period_s
        self.node_peak = {}
        self.job_peak = {}
        self.samples = 0
        self.spent_s = 0.0
        self._stop = threading.Event()
        self._t0 = 0.0
        self._thread = None

    def _sample(self):
        from ray_tpu import state

        while not self._stop.is_set():
            t0 = time.perf_counter()
            try:
                s = state.memory_summary()
            except Exception:  # noqa: BLE001 — cluster tearing down
                break
            self.spent_s += time.perf_counter() - t0
            self.samples += 1
            for idx, row in (s.get("nodes") or {}).items():
                arena = row.get("arena") or {}
                p = self.node_peak.setdefault(
                    str(idx), {"used_bytes": 0, "highwater_bytes": 0,
                               "resident_bytes": 0})
                p["used_bytes"] = max(
                    p["used_bytes"], int(arena.get("used_bytes", 0)))
                p["highwater_bytes"] = max(
                    p["highwater_bytes"],
                    int(arena.get("highwater_bytes", 0)))
                p["resident_bytes"] = max(
                    p["resident_bytes"],
                    int(row.get("resident_bytes", 0)))
            for job, row in (s.get("jobs") or {}).items():
                self.job_peak[job or "(none)"] = max(
                    self.job_peak.get(job or "(none)", 0),
                    int(row.get("resident_bytes", 0)))
            self._stop.wait(self.period_s)

    def start(self):
        self._t0 = time.perf_counter()
        self._thread = threading.Thread(target=self._sample, daemon=True)
        self._thread.start()
        return self

    def block(self) -> dict:
        wall = time.perf_counter() - self._t0
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=5)
        frac = self.spent_s / wall if wall > 0 else 0.0
        return {
            "node_peaks": self.node_peak,
            "job_peak_resident_bytes": self.job_peak,
            "samples": self.samples,
            "sample_period_s": self.period_s,
            "phase_wall_s": round(wall, 3),
            "accounting_overhead_s": round(self.spent_s, 4),
            "overhead_frac_of_wall": round(frac, 5),
            "gate_overhead_le_2pct": frac <= 0.02,
        }


def _start_cluster():
    from ray_tpu.cluster_utils import Cluster

    cluster = Cluster(initialize_head=True,
                      head_node_args={"num_cpus": 2, "num_tpus": 0,
                                      "object_store_memory": 1 << 30})
    handle = cluster.add_remote_node(num_cpus=2,
                                     object_store_memory=512 << 20)
    return cluster, handle


def _dataset():
    import numpy as np

    from ray_tpu import data

    pad = np.zeros(BLOCK_KIB * 1024 // 8, np.uint8)

    def fatten(b):
        if READ_S:
            time.sleep(READ_S)
        n = len(b["id"])
        return {"id": b["id"], "pad": np.stack([pad] * max(n, 1))[:n]}

    return (data.range(N_BLOCKS * 8, parallelism=N_BLOCKS)
            .map_batches(fatten))


def _set_mode(drain: bool):
    """drain=True runs the PRE-r17 exchange verbatim
    (``data_shuffle_pipelined=False``: upstream ref drain, row-path
    kernels, no admission/locality/folds — the baseline this PR
    replaced); False runs the pipelined defaults."""
    from ray_tpu.core.config import get_config

    cfg = get_config()
    cfg.data_shuffle_pipelined = not drain
    if not drain:
        cfg.data_shuffle_inflight_window = 0  # auto
        cfg.data_shuffle_merge_fanin = 8


def _run_shuffle(seed: int):
    import ray_tpu

    ds = _dataset().random_shuffle(seed=seed,
                                   num_blocks=N_OUT).materialize()
    # materialize() returns at SUBMISSION of the terminal merges; the
    # wall must include execution — wait for every output block
    refs = ds.to_arrow_refs()
    ray_tpu.wait(refs, num_returns=len(refs), timeout=600,
                 fetch_local=False)
    return ds


# ------------------------------------------------------------ shuffle


def bench_shuffle(pairs: int) -> dict:
    cluster, handle = _start_cluster()
    lag = _LoopLag().snap()
    dataset_bytes = N_BLOCKS * BLOCK_KIB * 1024
    rows = []
    try:
        # warm: worker spawn + function export + first-touch paths
        _set_mode(False)
        _run_shuffle(0)
        arena = _ArenaProbe().start()
        for i in range(pairs):
            _set_mode(True)
            t0 = time.perf_counter()
            _run_shuffle(100 + i)
            drain_wall = time.perf_counter() - t0
            _set_mode(False)
            t0 = time.perf_counter()
            _run_shuffle(200 + i)
            pipe_wall = time.perf_counter() - t0
            rows.append({
                "drain_wall_s": round(drain_wall, 3),
                "pipe_wall_s": round(pipe_wall, 3),
                "ratio": round(pipe_wall / drain_wall, 3),
                "pipe_mb_s": round(dataset_bytes / pipe_wall / 1e6, 1),
                "drain_mb_s": round(dataset_bytes / drain_wall / 1e6,
                                    1),
            })
            print(f"  pair {i}: drain {drain_wall:.2f}s "
                  f"pipe {pipe_wall:.2f}s "
                  f"ratio {pipe_wall / drain_wall:.3f}",
                  file=sys.stderr, flush=True)
        arena_block = arena.block()
        lag_delta = lag.delta()
    finally:
        try:
            handle.terminate()
        except Exception:  # noqa: BLE001
            pass
        cluster.shutdown()
    ratio = _median([r["ratio"] for r in rows])
    return {
        "arena": arena_block,
        "blocks": N_BLOCKS, "block_mib": BLOCK_KIB / 1024,
        "n_out": N_OUT, "read_s_per_block": READ_S,
        "link_mib_s": LINK_MIB_S,
        "pairs": rows,
        "wall_ratio_median_of_pairs": ratio,
        "pipe_mb_s_median": _median([r["pipe_mb_s"] for r in rows]),
        "gate_ratio_le_0_67": ratio <= 0.67,
        "loop_lag": lag_delta,
    }


# ---------------------------------------------------------- footprint


def _peak_entries(run) -> int:
    from ray_tpu import state

    peak = [0]
    stop = threading.Event()

    def sample():
        while not stop.is_set():
            try:
                peak[0] = max(peak[0],
                              len(state.list_objects(limit=8000)))
            except Exception:  # noqa: BLE001
                break
            time.sleep(0.05)

    t = threading.Thread(target=sample, daemon=True)
    t.start()
    run()
    stop.set()
    t.join(timeout=5)
    return peak[0]


def bench_footprint(pairs: int) -> dict:
    from ray_tpu.core.context import get_context
    from ray_tpu.data import executor as dx

    cluster, handle = _start_cluster()
    lag = _LoopLag().snap()
    get_context().ref_counter._grace_s = 0.1
    rows = []
    try:
        _set_mode(False)
        _run_shuffle(0)  # warm
        for i in range(pairs):
            _set_mode(True)
            drain_peak = _peak_entries(lambda: _run_shuffle(300 + i))
            time.sleep(1)
            _set_mode(False)
            pipe_peak = _peak_entries(lambda: _run_shuffle(400 + i))
            time.sleep(1)
            rows.append({"drain_peak": drain_peak,
                         "pipe_peak": pipe_peak,
                         "ratio": round(pipe_peak / max(drain_peak, 1),
                                        3)})
            print(f"  pair {i}: drain peak {drain_peak} "
                  f"pipe peak {pipe_peak}", file=sys.stderr,
                  flush=True)
        stats = dict(dx.SHUFFLE_STATS)
        lag_delta = lag.delta()
    finally:
        try:
            handle.terminate()
        except Exception:  # noqa: BLE001
            pass
        cluster.shutdown()
    ratio = _median([r["ratio"] for r in rows])
    return {
        "blocks": N_BLOCKS, "n_out": N_OUT,
        "pairs": rows,
        "peak_ratio_median_of_pairs": ratio,
        "shuffle_stats": stats,
        "gate_peak_ratio_le_0_7": ratio <= 0.7,
        "loop_lag": lag_delta,
    }


# -------------------------------------------------------------- hints


def _merge_arg_fetch_p95() -> float:
    from ray_tpu import state
    from ray_tpu.core.context import get_context

    get_context().events.flush(sync=True)
    best = 0.0
    rows = state.phase_summary(["_merge_parts", "_concat_parts"])
    for func in ("_merge_parts", "_concat_parts"):
        p = rows.get(func, {}).get("arg_fetch", {})
        best = max(best, p.get("p95_ms", 0.0))
    return best


def bench_hints(pairs: int) -> dict:
    import ray_tpu.core.api as core_api
    from ray_tpu.core.config import get_config

    rounds = {"on": [], "off": []}
    issued_on = 0
    for i in range(pairs):
        for mode in ("off", "on"):
            cluster, handle = _start_cluster()
            try:
                cfg = get_config()
                cfg.data_shuffle_prefetch_hints = mode == "on"
                _set_mode(False)
                _run_shuffle(0)  # warm
                _run_shuffle(500 + i)
                p95 = _merge_arg_fetch_p95()
                rounds[mode].append(p95)
                if mode == "on":
                    issued_on += core_api._head.prefetch_issued
                print(f"  pair {i} hints {mode}: merge arg_fetch "
                      f"p95 {p95:.1f} ms", file=sys.stderr, flush=True)
            finally:
                try:
                    handle.terminate()
                except Exception:  # noqa: BLE001
                    pass
                cluster.shutdown()
    on, off = _median(rounds["on"]), _median(rounds["off"])
    # pairwise ratios (house methodology): each pair's on/off ran
    # back-to-back in one window, so only their ratio is comparable
    # across this host's multi-x run drift
    ratios = [a / b for a, b in zip(rounds["on"], rounds["off"]) if b]
    ratio = _median(ratios)
    return {
        "blocks": N_BLOCKS, "link_mib_s": LINK_MIB_S,
        "arg_fetch_p95_ms_hints_on_median": on,
        "arg_fetch_p95_ms_hints_off_median": off,
        "pairwise_p95_ratio_median": round(ratio, 3),
        "p95_reduction_pct": round(100 * (1 - ratio), 1),
        "prefetch_issued_on_rounds": issued_on,
        "gate_p95_improves": ratio < 1.0,
        "gate_prefetch_issued_gt_0": issued_on > 0,
        "rounds": rounds,
    }


def main():
    global N_BLOCKS, BLOCK_KIB, N_OUT, READ_S, SMOKE
    ap = argparse.ArgumentParser()
    ap.add_argument("--pairs", type=int, default=3)
    ap.add_argument("--phases", default="shuffle,footprint,hints",
                    help="comma list: shuffle,footprint,hints")
    ap.add_argument("--out", default="BENCH_shuffle_r17.json")
    ap.add_argument("--smoke", action="store_true",
                    help="tiny sizes, 1 pair, no gates enforced — the "
                         "tier-1 CI smoke")
    args = ap.parse_args()
    if args.smoke:
        SMOKE = True
        N_BLOCKS, BLOCK_KIB, N_OUT, READ_S = 8, 128, 4, 0.02
        args.pairs = 1
        os.environ["RAY_TPU_HOST_EGRESS_LIMIT_BPS"] = "0"
    phases = {p.strip() for p in args.phases.split(",") if p.strip()}

    result = {
        "benchmark": "shuffle_r17",
        "hardware": f"single host, {os.cpu_count()} cpu, real agent "
                    "processes, per-process egress buckets",
        "methodology": "interleaved A/B pairs vs the pre-r17 "
                       "drain-based executor preserved verbatim behind "
                       "data_shuffle_pipelined=False, "
                       "median-of-pairwise; paced 2-node link",
        "smoke": SMOKE,
    }
    if os.path.exists(args.out):
        try:
            with open(args.out) as f:
                prior = json.load(f)
            for k in ("shuffle", "footprint", "hints"):
                if k in prior:
                    result[k] = prior[k]
        except (OSError, ValueError):
            pass

    def flush():
        with open(args.out, "w") as f:
            json.dump(result, f, indent=1)

    if "shuffle" in phases:
        print(f"# shuffle: {N_BLOCKS} x {BLOCK_KIB} KiB blocks, "
              f"pipelined vs drain, {args.pairs} pairs",
              file=sys.stderr, flush=True)
        result["shuffle"] = bench_shuffle(args.pairs)
        print(json.dumps(result["shuffle"]), file=sys.stderr)
        flush()
    if "footprint" in phases:
        print("# footprint: peak store entries A/B", file=sys.stderr,
              flush=True)
        result["footprint"] = bench_footprint(args.pairs)
        print(json.dumps(result["footprint"]), file=sys.stderr)
        flush()
    if "hints" in phases:
        print("# hints: merge arg_fetch p95 on/off", file=sys.stderr,
              flush=True)
        result["hints"] = bench_hints(args.pairs)
        print(json.dumps(result["hints"]), file=sys.stderr)
        flush()
    print(json.dumps(result))


if __name__ == "__main__":
    main()
