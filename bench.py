"""Headline benchmark: flagship train-step throughput on the attached device.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Runs the GPT-2-small-scale decoder's full jitted train step (fwd+bwd+adamw,
bf16 compute) on whatever single device is attached (TPU via the axon tunnel
in CI; CPU elsewhere), measures tokens/sec/chip, and reports MFU-relative
progress: vs_baseline = achieved_MFU / 0.40, the north-star 40% MFU target
from BASELINE.json (the reference has no TPU number to compare against —
SURVEY.md §6).
"""

import json
import time

import jax
import jax.numpy as jnp

# bf16 peak FLOP/s per chip by TPU generation (public spec sheets).
_PEAK_FLOPS = {
    "v4": 275e12,
    "v5e": 197e12,
    "v5p": 459e12,
    "v6e": 918e12,
}


def _peak_flops() -> float:
    import os
    gen = os.environ.get("PALLAS_AXON_TPU_GEN", "")
    for key, val in _PEAK_FLOPS.items():
        if gen.startswith(key):
            return val
    if jax.default_backend() == "cpu":
        return 1e12  # nominal; CPU runs are smoke tests, not benchmarks
    return 197e12


def main():
    from ray_tpu.models import (
        gpt2_small_config,
        init_train_state,
        make_optimizer,
        make_train_step,
        tiny_config,
    )

    on_cpu = jax.default_backend() == "cpu"
    if on_cpu:
        cfg = tiny_config(max_seq_len=128)
        batch_size, seq, steps = 8, 128, 5
    else:
        cfg = gpt2_small_config()
        batch_size, seq, steps = 8, 1024, 10

    tx = make_optimizer(3e-4)
    state = init_train_state(jax.random.key(0), cfg, tx)
    step = make_train_step(cfg, tx)

    toks = jax.random.randint(jax.random.key(1), (batch_size, seq + 1), 0,
                              cfg.vocab_size, dtype=jnp.int32)
    batch = {"inputs": toks[:, :-1], "targets": toks[:, 1:]}

    # Warmup / compile.
    state, metrics = step(state, batch)
    jax.block_until_ready(metrics["loss"])

    t0 = time.perf_counter()
    for _ in range(steps):
        state, metrics = step(state, batch)
    jax.block_until_ready(metrics["loss"])
    dt = time.perf_counter() - t0

    tokens_per_sec = batch_size * seq * steps / dt
    flops_per_token = cfg.flops_per_token(seq)
    mfu = tokens_per_sec * flops_per_token / _peak_flops()

    print(json.dumps({
        "metric": "train_step_tokens_per_sec_per_chip",
        "value": round(tokens_per_sec, 1),
        "unit": "tokens/s",
        "vs_baseline": round(mfu / 0.40, 4),
    }))


if __name__ == "__main__":
    main()
