"""Headline benchmark: flagship train-step throughput on the attached device.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Runs the largest built-in decoder config whose full train state fits the
attached chip's HBM, measures tokens/sec with *verified* device execution,
and reports vs_baseline = achieved_MFU / 0.40 (the north-star 40% MFU target
from BASELINE.json; the reference has no TPU number — SURVEY.md §6).

Honesty guards (VERDICT round 1 flagged a physically impossible 27,500% MFU):
  1. Every timed step ends in a real device->host transfer (`float(loss)`),
     not just `block_until_ready` — on experimental backends the latter can
     be a no-op while a value fetch cannot.
  2. A calibration matmul with known FLOPs runs first; if it appears to beat
     the chip's spec-sheet peak, the clock/backend is broken and we abort.
  3. The final MFU must satisfy 0 < MFU <= 1.0 or the bench exits non-zero.
"""

import json
import os
import sys
import time

import jax
import jax.numpy as jnp

# The host sitecustomize force-registers the axon TPU backend, overriding
# the standard JAX_PLATFORMS env var; restore the expected semantics so
# `JAX_PLATFORMS=cpu python bench.py` really is a CPU smoke test.
if os.environ.get("JAX_PLATFORMS"):
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

# bf16 peak FLOP/s per chip by TPU generation (public spec sheets).
_PEAK_FLOPS = {
    "v4": 275e12,
    "v5e": 197e12,
    "v5p": 459e12,
    "v6e": 918e12,
}


def _peak_flops() -> float:
    import os
    gen = os.environ.get("PALLAS_AXON_TPU_GEN", "")
    for key, val in _PEAK_FLOPS.items():
        if gen.startswith(key):
            return val
    if jax.default_backend() == "cpu":
        return 1e12  # nominal; CPU runs are smoke tests, not benchmarks
    return 197e12


def _fetch(x) -> float:
    """Force a genuine device->host value transfer (not just a ready-flag)."""
    return float(jax.device_get(x))


def _calibrate(peak: float) -> float:
    """Time a known-FLOPs matmul; abort if the clock beats physics.

    Returns the measured matmul FLOP/s (a soft ceiling for any model step).
    """
    n = 4096 if jax.default_backend() != "cpu" else 512
    flops_per_call = 2.0 * n * n * n
    a = jnp.ones((n, n), jnp.bfloat16)
    b = jnp.ones((n, n), jnp.bfloat16)

    @jax.jit
    def mm(a, b):
        return jnp.sum(a @ b)

    _fetch(mm(a, b))  # compile + warm
    iters = 8
    t0 = time.perf_counter()
    acc = 0.0
    for _ in range(iters):
        acc += _fetch(mm(a, b))
    dt = time.perf_counter() - t0
    rate = flops_per_call * iters / dt
    if jax.default_backend() != "cpu" and rate > peak * 1.5:
        print(json.dumps({
            "metric": "train_step_tokens_per_sec_per_chip",
            "value": 0.0, "unit": "tokens/s", "vs_baseline": 0.0,
            "error": f"calibration matmul measured {rate:.3e} FLOP/s "
                     f"> 1.5x peak {peak:.3e}; timing is not trustworthy",
        }))
        sys.exit(1)
    return rate


def _candidate(name: str):
    """Benchmark candidates. The llama configs train with bf16 master
    params + bf16 adam mu + fp32 nu — measured 50.3% MFU for the 1B
    flagship on a single 16 GiB v5e chip (BENCH_r03). The 8b ladder
    (bs=1, full remat, descending seq) exists so the north-star geometry
    gets a real number wherever HBM allows (v5p: 95 GiB fits the 64 GiB
    lean-adam state; v5e 16 GiB cannot hold 8B bf16 params at all — the
    attempt is recorded honestly either way)."""
    from ray_tpu.models import (
        gpt2_small_config,
        llama3_8b_config,
        tiny_config,
    )
    from ray_tpu.models.config import llama3_1b_config

    bf16 = dict(param_dtype=jnp.bfloat16)
    lean_opt = dict(mu_dtype=jnp.bfloat16)
    remat = dict(remat=True, remat_policy="nothing")
    table = {
        "llama3-1b": (llama3_1b_config(max_seq_len=2048, **bf16),
                      4, 2048, 10, lean_opt),
        "llama3-8b": (llama3_8b_config(max_seq_len=2048, **bf16),
                      4, 2048, 3, lean_opt),
        "llama3-8b-bs1-s2048": (
            llama3_8b_config(max_seq_len=2048, **bf16, **remat),
            1, 2048, 3, lean_opt),
        "llama3-8b-bs1-s1024": (
            llama3_8b_config(max_seq_len=1024, **bf16, **remat),
            1, 1024, 3, lean_opt),
        "llama3-8b-bs1-s512": (
            llama3_8b_config(max_seq_len=512, **bf16, **remat),
            1, 512, 3, lean_opt),
        "gpt2-small": (gpt2_small_config(), 16, 1024, 20, {}),
        "tiny-cpu": (tiny_config(max_seq_len=128), 8, 128, 5, {}),
    }
    return table[name]


# Flagship (known to fit + the standing MFU record) runs FIRST so the
# artifact always contains a real number before any speculative 8b
# attempt can burn budget; then the 8b ladder largest-seq first.
CANDIDATE_ORDER = ("llama3-1b", "llama3-8b", "llama3-8b-bs1-s2048",
                   "llama3-8b-bs1-s1024", "llama3-8b-bs1-s512",
                   "gpt2-small")


def _run_single(cfg_name: str) -> None:
    """Measure ONE config on the attached device; exits 3 if the backend
    turns out to be CPU for a non-CPU candidate (caller falls back)."""
    from ray_tpu.models import (
        init_train_state,
        make_optimizer,
        make_train_step,
    )

    if jax.default_backend() == "cpu" and cfg_name != "tiny-cpu":
        sys.exit(3)
    peak = _peak_flops()
    matmul_rate = _calibrate(peak)
    cfg, batch_size, seq, steps, opt_kw = _candidate(cfg_name)
    print(f"# config={cfg_name} bs={batch_size} seq={seq} "
          f"({cfg.num_params / 1e9:.2f}B params)", file=sys.stderr)

    tx = make_optimizer(3e-4, **opt_kw)
    state = init_train_state(jax.random.key(0), cfg, tx)
    step = make_train_step(cfg, tx)
    toks = jax.random.randint(jax.random.key(1), (batch_size, seq + 1), 0,
                              cfg.vocab_size, dtype=jnp.int32)
    batch = {"inputs": toks[:, :-1], "targets": toks[:, 1:]}

    # Warmup / compile; verify the step produced a finite loss on-device.
    state, metrics = step(state, batch)
    warm_loss = _fetch(metrics["loss"])
    assert warm_loss == warm_loss, "warmup loss is NaN"

    t0 = time.perf_counter()
    for _ in range(steps):
        state, metrics = step(state, batch)
    final_loss = _fetch(metrics["loss"])  # chained state => waits for all
    dt = time.perf_counter() - t0
    assert final_loss == final_loss, "bench loss is NaN"

    tokens_per_sec = batch_size * seq * steps / dt
    flops_per_token = cfg.flops_per_token(seq)
    mfu = tokens_per_sec * flops_per_token / peak

    if not (0.0 < mfu <= 1.0) and jax.default_backend() != "cpu":
        print(json.dumps({
            "metric": "train_step_tokens_per_sec_per_chip",
            "value": round(tokens_per_sec, 1), "unit": "tokens/s",
            "vs_baseline": 0.0,
            "error": f"MFU {mfu:.4f} outside (0, 1]; measurement rejected "
                     f"(matmul calibration was {matmul_rate:.3e} FLOP/s)",
        }))
        sys.exit(1)

    print(json.dumps({
        "metric": "train_step_tokens_per_sec_per_chip",
        "value": round(tokens_per_sec, 1),
        "unit": "tokens/s",
        "vs_baseline": round(mfu / 0.40, 4),
        "config": cfg_name,
        "mfu": round(mfu, 4),
    }))


def main():
    """Run candidates EACH IN ITS OWN SUBPROCESS under a global deadline.

    Two observed backend behaviors force the subprocess structure: (a) a
    failed too-big allocation wedges this backend's allocator so later
    small allocations in the same process also fail, and (b) allocation
    probes lie (multi-100-GiB ``jnp.zeros`` "succeeds" lazily), so fit can
    only be tested by really running the config. The parent never touches
    the device — the tunnel backend serializes access to a single holder.

    Round-4 postmortem additions (BENCH_r04 was rc=124 with parsed=null):
      * a global deadline (RAY_TPU_BENCH_BUDGET_S, default 1500 s) with a
        per-child cap, so a wedged child can never consume the driver's
        whole budget;
      * the flagship config runs first to bank a real number before any
        speculative 8b rung;
      * children are SIGTERMed with a grace period before SIGKILL (a
        SIGKILLed mid-run TPU process wedges the tunnel for *subsequent*
        processes);
      * a child *timeout* (as opposed to a clean failure) marks the
        backend suspect and stops further TPU attempts;
      * the parent traps SIGTERM and ALWAYS prints exactly one JSON line
        — best successful config as the headline, every attempt recorded.
    """
    import os
    import signal
    import subprocess

    if len(sys.argv) > 2 and sys.argv[1] == "--config":
        _run_single(sys.argv[2])
        return
    here = os.path.abspath(__file__)
    t_start = time.monotonic()
    budget = float(os.environ.get("RAY_TPU_BENCH_BUDGET_S", "1500"))
    deadline = t_start + budget
    attempts = []   # [{config, status, ...}]
    results = []    # successful child JSON dicts
    live = []       # the at-most-one in-flight child Popen
    emitted = []    # idempotence flag for emit_and_exit

    def emit_and_exit(rc_hint=None, hard=False):
        if emitted:
            return
        emitted.append(True)
        for p in live:  # don't orphan an in-flight TPU child
            try:
                p.terminate()
            except OSError:
                pass
        best = max(results, key=lambda r: r.get("vs_baseline", 0.0),
                   default=None)
        if best is not None:
            out = dict(best)
            out["attempts"] = attempts
            rc = 0
        else:
            out = {"metric": "train_step_tokens_per_sec_per_chip",
                   "value": 0.0, "unit": "tokens/s", "vs_baseline": 0.0,
                   "error": "no candidate config produced a measurement",
                   "attempts": attempts}
            rc = rc_hint if rc_hint is not None else 1
        print(json.dumps(out))
        sys.stdout.flush()
        # from a signal handler, unwinding through arbitrary frames is not
        # safe (observed: SystemExit re-entering during atexit) — hard-exit
        os._exit(rc) if hard else sys.exit(rc)

    signal.signal(signal.SIGTERM,
                  lambda *_: emit_and_exit(1, hard=True))

    def run_child(cfg_name: str):
        """Returns (status, proc_or_None); status in
        {ok, failed, cpu_backend, timeout, no_budget}."""
        remaining = deadline - time.monotonic()
        if remaining < 45:
            attempts.append({"config": cfg_name, "status": "no_budget"})
            return "no_budget", None
        cap = min(remaining - 30, 720.0)
        proc = subprocess.Popen(
            [sys.executable, here, "--config", cfg_name],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)
        live.append(proc)
        try:
            out, err = proc.communicate(timeout=cap)
        except subprocess.TimeoutExpired:
            print(f"# {cfg_name} timed out after {cap:.0f}s; terminating",
                  file=sys.stderr)
            proc.terminate()  # graceful first: SIGKILL wedges the tunnel
            try:
                out, err = proc.communicate(timeout=20)
            except subprocess.TimeoutExpired:
                proc.kill()
                out, err = proc.communicate()
            live.remove(proc)
            sys.stderr.write((err or "")[-4000:])
            # the child may have finished measuring and wedged during
            # teardown (a documented tunnel failure mode) — salvage any
            # JSON it managed to print before declaring a timeout
            for line in reversed((out or "").strip().splitlines()):
                try:
                    parsed = json.loads(line)
                except ValueError:
                    continue
                if parsed.get("value") and "error" not in parsed:
                    attempts.append({"config": cfg_name,
                                     "status": "ok_salvaged_after_timeout",
                                     "tokens_per_sec": parsed.get("value"),
                                     "mfu": parsed.get("mfu")})
                    results.append(parsed)
                    break
            else:
                attempts.append({"config": cfg_name, "status": "timeout",
                                 "timeout_s": round(cap, 1)})
            return "timeout", None
        live.remove(proc)
        sys.stderr.write(err or "")
        if proc.returncode == 3:
            attempts.append({"config": cfg_name, "status": "cpu_backend"})
            return "cpu_backend", None
        if proc.returncode == 0 and out.strip():
            try:
                parsed = json.loads(out.strip().splitlines()[-1])
            except ValueError:
                attempts.append({"config": cfg_name, "status": "failed",
                                 "error": "unparseable child output"})
                return "failed", None
            attempts.append({"config": cfg_name, "status": "ok",
                             "tokens_per_sec": parsed.get("value"),
                             "mfu": parsed.get("mfu")})
            results.append(parsed)
            return "ok", parsed
        tail = (err or "").strip().splitlines()[-3:]
        attempts.append({"config": cfg_name, "status": "failed",
                         "rc": proc.returncode,
                         "error": " | ".join(tail)[-400:]})
        print(f"# {cfg_name} failed (rc={proc.returncode})",
              file=sys.stderr)
        return "failed", None

    flagship_ok = False
    for name in CANDIDATE_ORDER:
        if name.startswith("llama3-8b") and not flagship_ok:
            # flagship already failed/timed out; don't gamble what's left
            # of the budget on configs 6x bigger
            continue
        if name == "gpt2-small" and flagship_ok:
            break  # fallback config is pointless once the flagship landed
        status, _ = run_child(name)
        if status == "ok":
            if name == "llama3-1b":
                flagship_ok = True
                continue  # go on to attempt the 8b ladder
            break  # an 8b rung (or fallback) landed; done
        if status == "timeout":
            break  # backend suspect: stop touching the device
        if status == "no_budget":
            break
        if status == "cpu_backend":
            run_child("tiny-cpu")
            break
        if status == "failed" and name == "llama3-1b":
            # one retry with backoff — r4's UNAVAILABLE was transient-class
            time.sleep(10)
            retry_status, _ = run_child(name)
            if retry_status == "ok":
                flagship_ok = True
            elif retry_status in ("timeout", "no_budget"):
                break  # wedged/banked-out backend: stop touching it
            # else fall through: the startswith guard skips the 8b ladder
            # when the flagship failed, and the gpt2-small step-down still
            # gets the artifact a number
            continue
    emit_and_exit()


if __name__ == "__main__":
    main()
