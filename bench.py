"""Headline benchmark: flagship train-step throughput on the attached device.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Runs the largest built-in decoder config whose full train state fits the
attached chip's HBM, measures tokens/sec with *verified* device execution,
and reports vs_baseline = achieved_MFU / 0.40 (the north-star 40% MFU target
from BASELINE.json; the reference has no TPU number — SURVEY.md §6).

Honesty guards (VERDICT round 1 flagged a physically impossible 27,500% MFU):
  1. Every timed step ends in a real device->host transfer (`float(loss)`),
     not just `block_until_ready` — on experimental backends the latter can
     be a no-op while a value fetch cannot.
  2. A calibration matmul with known FLOPs runs first; if it appears to beat
     the chip's spec-sheet peak, the clock/backend is broken and we abort.
  3. The final MFU must satisfy 0 < MFU <= 1.0 or the bench exits non-zero.
"""

import json
import sys
import time

import jax
import jax.numpy as jnp

# bf16 peak FLOP/s per chip by TPU generation (public spec sheets).
_PEAK_FLOPS = {
    "v4": 275e12,
    "v5e": 197e12,
    "v5p": 459e12,
    "v6e": 918e12,
}


def _peak_flops() -> float:
    import os
    gen = os.environ.get("PALLAS_AXON_TPU_GEN", "")
    for key, val in _PEAK_FLOPS.items():
        if gen.startswith(key):
            return val
    if jax.default_backend() == "cpu":
        return 1e12  # nominal; CPU runs are smoke tests, not benchmarks
    return 197e12


def _hbm_bytes() -> int:
    try:
        stats = jax.devices()[0].memory_stats()
        return int(stats.get("bytes_limit", 0))
    except Exception:
        return 0


def _fetch(x) -> float:
    """Force a genuine device->host value transfer (not just a ready-flag)."""
    return float(jax.device_get(x))


def _calibrate(peak: float) -> float:
    """Time a known-FLOPs matmul; abort if the clock beats physics.

    Returns the measured matmul FLOP/s (a soft ceiling for any model step).
    """
    n = 4096 if jax.default_backend() != "cpu" else 512
    flops_per_call = 2.0 * n * n * n
    a = jnp.ones((n, n), jnp.bfloat16)
    b = jnp.ones((n, n), jnp.bfloat16)

    @jax.jit
    def mm(a, b):
        return jnp.sum(a @ b)

    _fetch(mm(a, b))  # compile + warm
    iters = 8
    t0 = time.perf_counter()
    acc = 0.0
    for _ in range(iters):
        acc += _fetch(mm(a, b))
    dt = time.perf_counter() - t0
    rate = flops_per_call * iters / dt
    if jax.default_backend() != "cpu" and rate > peak * 1.5:
        print(json.dumps({
            "metric": "train_step_tokens_per_sec_per_chip",
            "value": 0.0, "unit": "tokens/s", "vs_baseline": 0.0,
            "error": f"calibration matmul measured {rate:.3e} FLOP/s "
                     f"> 1.5x peak {peak:.3e}; timing is not trustworthy",
        }))
        sys.exit(1)
    return rate


def _pick_config(hbm: int):
    """Largest built-in config whose train state fits the chip's HBM.

    State bytes ~= num_params * 12 (fp32 master + 2 adam moments); leave
    >=2.5x headroom for activations, gradients, and XLA temp buffers.
    """
    from ray_tpu.models import (
        gpt2_small_config,
        llama3_8b_config,
        tiny_config,
    )
    from ray_tpu.models.config import llama3_1b_config

    if jax.default_backend() == "cpu":
        return tiny_config(max_seq_len=128), 8, 128, 5
    candidates = [
        (llama3_8b_config(max_seq_len=4096), 4, 4096, 5),
        (llama3_1b_config(), 8, 4096, 10),
        (gpt2_small_config(), 16, 1024, 20),
    ]
    for cfg, bs, seq, steps in candidates:
        need = cfg.num_params * 12 * 2.5
        if hbm and need < hbm:
            return cfg, bs, seq, steps
    return candidates[-1]


def main():
    from ray_tpu.models import (
        init_train_state,
        make_optimizer,
        make_train_step,
    )

    peak = _peak_flops()
    matmul_rate = _calibrate(peak)

    cfg, batch_size, seq, steps = _pick_config(_hbm_bytes())

    tx = make_optimizer(3e-4)
    state = init_train_state(jax.random.key(0), cfg, tx)
    step = make_train_step(cfg, tx)

    toks = jax.random.randint(jax.random.key(1), (batch_size, seq + 1), 0,
                              cfg.vocab_size, dtype=jnp.int32)
    batch = {"inputs": toks[:, :-1], "targets": toks[:, 1:]}

    # Warmup / compile; verify the step produced a finite loss on-device.
    state, metrics = step(state, batch)
    warm_loss = _fetch(metrics["loss"])
    assert warm_loss == warm_loss, "warmup loss is NaN"

    t0 = time.perf_counter()
    for _ in range(steps):
        state, metrics = step(state, batch)
    final_loss = _fetch(metrics["loss"])  # chained state => waits for all
    dt = time.perf_counter() - t0
    assert final_loss == final_loss, "bench loss is NaN"

    tokens_per_sec = batch_size * seq * steps / dt
    flops_per_token = cfg.flops_per_token(seq)
    mfu = tokens_per_sec * flops_per_token / peak

    if not (0.0 < mfu <= 1.0) and jax.default_backend() != "cpu":
        print(json.dumps({
            "metric": "train_step_tokens_per_sec_per_chip",
            "value": round(tokens_per_sec, 1), "unit": "tokens/s",
            "vs_baseline": 0.0,
            "error": f"MFU {mfu:.4f} outside (0, 1]; measurement rejected "
                     f"(matmul calibration was {matmul_rate:.3e} FLOP/s)",
        }))
        sys.exit(1)

    print(json.dumps({
        "metric": "train_step_tokens_per_sec_per_chip",
        "value": round(tokens_per_sec, 1),
        "unit": "tokens/s",
        "vs_baseline": round(mfu / 0.40, 4),
    }))


if __name__ == "__main__":
    main()
