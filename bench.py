"""Headline benchmark: flagship train-step throughput on the attached device.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Runs the largest built-in decoder config whose full train state fits the
attached chip's HBM, measures tokens/sec with *verified* device execution,
and reports vs_baseline = achieved_MFU / 0.40 (the north-star 40% MFU target
from BASELINE.json; the reference has no TPU number — SURVEY.md §6).

Honesty guards (VERDICT round 1 flagged a physically impossible 27,500% MFU):
  1. Every timed step ends in a real device->host transfer (`float(loss)`),
     not just `block_until_ready` — on experimental backends the latter can
     be a no-op while a value fetch cannot.
  2. A calibration matmul with known FLOPs runs first; if it appears to beat
     the chip's spec-sheet peak, the clock/backend is broken and we abort.
  3. The final MFU must satisfy 0 < MFU <= 1.0 or the bench exits non-zero.
"""

import json
import sys
import time

import jax
import jax.numpy as jnp

# bf16 peak FLOP/s per chip by TPU generation (public spec sheets).
_PEAK_FLOPS = {
    "v4": 275e12,
    "v5e": 197e12,
    "v5p": 459e12,
    "v6e": 918e12,
}


def _peak_flops() -> float:
    import os
    gen = os.environ.get("PALLAS_AXON_TPU_GEN", "")
    for key, val in _PEAK_FLOPS.items():
        if gen.startswith(key):
            return val
    if jax.default_backend() == "cpu":
        return 1e12  # nominal; CPU runs are smoke tests, not benchmarks
    return 197e12


def _fetch(x) -> float:
    """Force a genuine device->host value transfer (not just a ready-flag)."""
    return float(jax.device_get(x))


def _calibrate(peak: float) -> float:
    """Time a known-FLOPs matmul; abort if the clock beats physics.

    Returns the measured matmul FLOP/s (a soft ceiling for any model step).
    """
    n = 4096 if jax.default_backend() != "cpu" else 512
    flops_per_call = 2.0 * n * n * n
    a = jnp.ones((n, n), jnp.bfloat16)
    b = jnp.ones((n, n), jnp.bfloat16)

    @jax.jit
    def mm(a, b):
        return jnp.sum(a @ b)

    _fetch(mm(a, b))  # compile + warm
    iters = 8
    t0 = time.perf_counter()
    acc = 0.0
    for _ in range(iters):
        acc += _fetch(mm(a, b))
    dt = time.perf_counter() - t0
    rate = flops_per_call * iters / dt
    if jax.default_backend() != "cpu" and rate > peak * 1.5:
        print(json.dumps({
            "metric": "train_step_tokens_per_sec_per_chip",
            "value": 0.0, "unit": "tokens/s", "vs_baseline": 0.0,
            "error": f"calibration matmul measured {rate:.3e} FLOP/s "
                     f"> 1.5x peak {peak:.3e}; timing is not trustworthy",
        }))
        sys.exit(1)
    return rate


def _candidate(name: str):
    """Flagship candidates, largest first. The llama configs train with
    bf16 master params + bf16 adam mu + fp32 nu — measured 49.8% MFU for
    the 1B flagship on a single 16 GiB v5e chip."""
    from ray_tpu.models import (
        gpt2_small_config,
        llama3_8b_config,
        tiny_config,
    )
    from ray_tpu.models.config import llama3_1b_config

    bf16 = dict(param_dtype=jnp.bfloat16)
    lean_opt = dict(mu_dtype=jnp.bfloat16)
    table = {
        "llama3-8b": (llama3_8b_config(max_seq_len=2048, **bf16),
                      4, 2048, 5, lean_opt),
        "llama3-1b": (llama3_1b_config(max_seq_len=2048, **bf16),
                      4, 2048, 10, lean_opt),
        "gpt2-small": (gpt2_small_config(), 16, 1024, 20, {}),
        "tiny-cpu": (tiny_config(max_seq_len=128), 8, 128, 5, {}),
    }
    return table[name]


CANDIDATE_ORDER = ("llama3-8b", "llama3-1b", "gpt2-small")


def _run_single(cfg_name: str) -> None:
    """Measure ONE config on the attached device; exits 3 if the backend
    turns out to be CPU for a non-CPU candidate (caller falls back)."""
    from ray_tpu.models import (
        init_train_state,
        make_optimizer,
        make_train_step,
    )

    if jax.default_backend() == "cpu" and cfg_name != "tiny-cpu":
        sys.exit(3)
    peak = _peak_flops()
    matmul_rate = _calibrate(peak)
    cfg, batch_size, seq, steps, opt_kw = _candidate(cfg_name)
    print(f"# config={cfg_name} bs={batch_size} seq={seq} "
          f"({cfg.num_params / 1e9:.2f}B params)", file=sys.stderr)

    tx = make_optimizer(3e-4, **opt_kw)
    state = init_train_state(jax.random.key(0), cfg, tx)
    step = make_train_step(cfg, tx)
    toks = jax.random.randint(jax.random.key(1), (batch_size, seq + 1), 0,
                              cfg.vocab_size, dtype=jnp.int32)
    batch = {"inputs": toks[:, :-1], "targets": toks[:, 1:]}

    # Warmup / compile; verify the step produced a finite loss on-device.
    state, metrics = step(state, batch)
    warm_loss = _fetch(metrics["loss"])
    assert warm_loss == warm_loss, "warmup loss is NaN"

    t0 = time.perf_counter()
    for _ in range(steps):
        state, metrics = step(state, batch)
    final_loss = _fetch(metrics["loss"])  # chained state => waits for all
    dt = time.perf_counter() - t0
    assert final_loss == final_loss, "bench loss is NaN"

    tokens_per_sec = batch_size * seq * steps / dt
    flops_per_token = cfg.flops_per_token(seq)
    mfu = tokens_per_sec * flops_per_token / peak

    if not (0.0 < mfu <= 1.0) and jax.default_backend() != "cpu":
        print(json.dumps({
            "metric": "train_step_tokens_per_sec_per_chip",
            "value": round(tokens_per_sec, 1), "unit": "tokens/s",
            "vs_baseline": 0.0,
            "error": f"MFU {mfu:.4f} outside (0, 1]; measurement rejected "
                     f"(matmul calibration was {matmul_rate:.3e} FLOP/s)",
        }))
        sys.exit(1)

    print(json.dumps({
        "metric": "train_step_tokens_per_sec_per_chip",
        "value": round(tokens_per_sec, 1),
        "unit": "tokens/s",
        "vs_baseline": round(mfu / 0.40, 4),
        "config": cfg_name,
        "mfu": round(mfu, 4),
    }))


def main():
    """Try candidates largest-first, EACH IN ITS OWN SUBPROCESS.

    Two observed backend behaviors force this structure: (a) a failed
    too-big allocation wedges this backend's allocator so later small
    allocations in the same process also fail (in-process step-down would
    cascade to total failure), and (b) allocation probes lie (multi-100-GiB
    ``jnp.zeros`` "succeeds" lazily), so fit can only be tested by really
    running the config. The parent never touches the device — the tunnel
    backend serializes access to a single holder.
    """
    import os
    import subprocess

    if len(sys.argv) > 2 and sys.argv[1] == "--config":
        _run_single(sys.argv[2])
        return
    here = os.path.abspath(__file__)

    def run_child(cfg_name: str):
        try:
            return subprocess.run(
                [sys.executable, here, "--config", cfg_name],
                capture_output=True, text=True, timeout=3600)
        except subprocess.TimeoutExpired as e:
            # a wedged child (hung allocator) must step down, not crash
            # the bench without its JSON line
            print(f"# {cfg_name} timed out after {e.timeout}s",
                  file=sys.stderr)
            return None

    for name in CANDIDATE_ORDER:
        proc = run_child(name)
        if proc is None:
            continue
        sys.stderr.write(proc.stderr)
        if proc.returncode == 0 and proc.stdout.strip():
            sys.stdout.write(proc.stdout)
            return
        if proc.returncode == 3:
            # CPU backend: run the smoke-test config directly
            proc = run_child("tiny-cpu")
            if proc is not None:
                sys.stderr.write(proc.stderr)
                sys.stdout.write(proc.stdout)
                sys.exit(proc.returncode)
            break
        print(f"# {name} failed (rc={proc.returncode}); stepping down",
              file=sys.stderr)
    print(json.dumps({
        "metric": "train_step_tokens_per_sec_per_chip",
        "value": 0.0, "unit": "tokens/s", "vs_baseline": 0.0,
        "error": "every candidate config failed on this device"}))
    sys.exit(1)


if __name__ == "__main__":
    main()
