"""Scalability-envelope harness: many_nodes / many_tasks / many_actors /
many_pgs, producing one JSON artifact (SCALE_r4.json).

Ref analog: release/benchmarks/README.md:7-14 and the checked-in results
release/release_logs/2.6.1/benchmarks/{many_nodes,many_actors,many_pgs,
many_tasks}.json — the reference's envelope (2k nodes / 40k actors /
10k tasks / 1k PGs) is measured on a 64-node x 64-core cluster. This
harness runs the same shapes against ONE head on one host with virtual
(in-process) nodes, so it measures the control plane — registration,
scheduling, lease churn, PG 2PC — not fleet parallelism. Worker spawn
here is real (one process per worker) and interpreter-import bound on a
1-core host; the JSON records both ends so the two costs aren't
conflated.

Run: python bench_scale.py [--nodes 100] [--actors 1000]
     [--tasks 10000] [--pgs 1000] [--skip-actors] [--phases nodes,tasks]

Phase selection: ``--phases`` runs only the named phases (comma list of
nodes/tasks/pgs/actors) and ``--skip-actors`` drops just the actor wave
— it is SPAWN-bound (one real interpreter per actor, ~1/s on a small
host), so control-plane runs shouldn't pay interpreter import time.
Each phase's JSON also records the head IO loop-lag quantiles observed
during that phase (head.loop_lag_ms self-probe samples + slow-handler
deltas), so a throughput number can't silently ride a wedged loop.
"""

import argparse
import json
import os
import sys
import time

# the control plane under test must not pay worker-prestart forks or
# TPU autodetection
os.environ.setdefault("RAY_TPU_PRESTART_WORKERS", "0")
os.environ.setdefault("TPU_CHIPS", "0")
os.environ.setdefault("JAX_PLATFORMS", "cpu")
# the actor wave spawns ~1k real interpreter processes; on a small host
# that is spawn-bound at a few per second, so the default 60s creation
# deadline would mass-kill the tail of the wave mid-benchmark
os.environ.setdefault("RAY_TPU_ACTOR_CREATION_TIMEOUT_S", "1800")


def bench_many_nodes(cluster, n: int) -> dict:
    """Node registration + scheduler-table update rate."""
    t0 = time.perf_counter()
    for _ in range(n):
        # small stores: hundreds of virtual nodes x the 512 MiB default
        # would pin tens of GiB of tmpfs for data this phase never moves
        cluster.add_node(num_cpus=1, object_store_memory=64 << 20)
    dt = time.perf_counter() - t0
    import ray_tpu

    nodes = ray_tpu.nodes()
    assert len(nodes) >= n + 1, f"registered {len(nodes)} < {n + 1}"
    return {"nodes": n, "seconds": round(dt, 3),
            "nodes_per_s": round(n / dt, 1)}


def bench_many_tasks(n: int, nodes: int) -> dict:
    """Sustained no-op task throughput with tasks spread over every
    virtual node (lease churn across the whole node table)."""
    import ray_tpu

    @ray_tpu.remote(num_cpus=1)
    def noop():
        return 0

    # warm the worker pool so the measured phase is dispatch, not fork
    warm = [noop.remote() for _ in range(nodes)]
    ray_tpu.get(warm, timeout=600)
    # ... and let the warm-up actually finish: worker forks the warm
    # wave triggered can still be IMPORTING when get() returns (the
    # driver only needs a few of them to drain the warm tasks), and a
    # late interpreter import burns ~seconds of CPU inside the measured
    # window — fork noise, not control-plane throughput
    from ray_tpu import state

    deadline = time.perf_counter() + 60
    while time.perf_counter() < deadline:
        if not any(w["state"] == "starting"
                   for w in state.list_workers(limit=10000)):
            break
        time.sleep(0.25)
    time.sleep(1.0)
    t0 = time.perf_counter()
    refs = [noop.remote() for _ in range(n)]
    out = ray_tpu.get(refs, timeout=1200)
    dt = time.perf_counter() - t0
    assert len(out) == n
    return {"tasks": n, "seconds": round(dt, 3),
            "tasks_per_s": round(n / dt, 1)}


def bench_many_actors(n: int) -> dict:
    """Time from first create to every actor answering a method call.
    Worker processes are real; spawn cost (interpreter import) dominates
    on a small host and is reported separately via spawn_bound_estimate.
    """
    import ray_tpu

    @ray_tpu.remote(num_cpus=1)
    class A:
        def ping(self):
            return os.getpid()

    t0 = time.perf_counter()
    actors = [A.remote() for _ in range(n)]
    create_dt = time.perf_counter() - t0
    pings = [a.ping.remote() for a in actors]
    pids = ray_tpu.get(pings, timeout=3600)
    dt = time.perf_counter() - t0
    assert len(set(pids)) == n, "actors must be distinct processes"
    result = {"actors": n, "submit_seconds": round(create_dt, 3),
              "seconds_to_all_ready": round(dt, 3),
              "actors_per_s": round(n / dt, 1)}
    # cleanup is NOT part of the measurement and must not lose it: a
    # single kill RPC timing out against a head that is draining 1k
    # worker processes previously crashed the phase after the data was
    # already in hand
    for a in actors:
        try:
            ray_tpu.kill(a)
        except Exception:  # noqa: BLE001
            pass
    return result


def _quiesce_workers(max_wait_s: float = 120.0) -> dict:
    """Wait for the PRIOR phase's worker processes to exit before the
    next phase's t0 (r13: the r11 run's many_pgs started seconds after
    a 6s task wave, so up to 16 live interpreters were still burning
    the host's 2 cores through the measured window — its rate was not
    comparable across rounds). The idle keep-alive is shrunk so the
    head's reaper drains the pool promptly, then restored; the JSON
    records how long the drain took and how many workers were live at
    entry so a quiesce that times out is visible, not silent."""
    import time as _t

    from ray_tpu import state
    from ray_tpu.core.config import get_config

    from ray_tpu.core.context import get_context

    driver_id = get_context().worker_id

    def _live():
        # the worker table keeps "dead" rows for post-mortems, and the
        # DRIVER registers as a (never-reaped, never-leased) worker —
        # only live task interpreters burn CPU through the window
        return [w for w in state.list_workers(limit=10000)
                if w.get("state") != "dead"
                and w.get("worker_id") != driver_id]

    cfg = get_config()
    prev_keep = cfg.idle_worker_keep_alive_s
    cfg.idle_worker_keep_alive_s = 0.5
    t0 = _t.perf_counter()
    before = len(_live())
    try:
        deadline = t0 + max_wait_s
        while _t.perf_counter() < deadline:
            if not _live():
                break
            _t.sleep(0.25)
    finally:
        cfg.idle_worker_keep_alive_s = prev_keep
    remaining = len(_live())
    # settle: freshly-reaped interpreters can take a beat to actually
    # exit (signal delivery + interpreter teardown)
    _t.sleep(1.0)
    return {"workers_at_entry": before,
            "workers_remaining": remaining,
            "quiesce_seconds": round(_t.perf_counter() - t0, 2)}


def bench_many_pgs(n: int) -> dict:
    """Placement-group create->ready->remove churn (pure control plane:
    bundle reservation 2PC + shadow-resource accounting, no workers).
    Runs from a QUIESCED cluster: the prior task wave's workers must
    have exited before t0 (see _quiesce_workers)."""
    import ray_tpu

    # bundles sized so all n PGs fit the virtual cluster's CPU capacity
    # at once (fractional, fixed-point resource model)
    t0 = time.perf_counter()
    pgs = []
    for _ in range(n):
        pg = ray_tpu.placement_group([{"CPU": 0.05}, {"CPU": 0.05}],
                                     strategy="PACK")
        pgs.append(pg)
    for pg in pgs:
        assert pg.wait(timeout=300)
    created_dt = time.perf_counter() - t0
    t1 = time.perf_counter()
    for pg in pgs:
        ray_tpu.remove_placement_group(pg)
    removed_dt = time.perf_counter() - t1
    return {"pgs": n, "create_seconds": round(created_dt, 3),
            "remove_seconds": round(removed_dt, 3),
            "pg_create_per_s": round(n / created_dt, 1),
            "pg_remove_per_s": round(n / removed_dt, 1),
            "pg_roundtrip_per_s": round(n / (created_dt + removed_dt), 1)}


class _LoopLag:
    """Per-phase head loop-lag capture: snapshot the io_loop state row
    before a phase, report the lag quantiles + slow-handler delta after
    it. The lag gauges are the head's own self-probe samples
    (head.loop_lag_ms), refreshed every housekeeping tick."""

    def snap(self):
        from ray_tpu import state

        try:
            row = state.io_loop_stats()[0]
        except Exception:  # noqa: BLE001 — no cluster yet
            row = {}
        self._before = row
        return self

    def delta(self) -> dict:
        from ray_tpu import state

        try:
            row = state.io_loop_stats()[0]
        except Exception:  # noqa: BLE001
            return {}
        before = getattr(self, "_before", {})
        return {
            "loop_lag_ms_p50": row.get("loop_lag_ms_p50", 0.0),
            "loop_lag_ms_p99": row.get("loop_lag_ms_p99", 0.0),
            "loop_lag_ms_max": row.get("loop_lag_ms_max", 0.0),
            "slow_events": row.get("slow_events", 0)
            - before.get("slow_events", 0),
            "fold_queue_drops": row.get("fold_queue_drops", 0)
            - before.get("fold_queue_drops", 0),
        }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--nodes", type=int, default=100)
    ap.add_argument("--actors", type=int, default=1000)
    ap.add_argument("--tasks", type=int, default=10_000)
    ap.add_argument("--pgs", type=int, default=1000)
    ap.add_argument("--out", default="SCALE_r11.json")
    ap.add_argument("--skip-actors", action="store_true",
                    help="skip the spawn-bound actor wave")
    ap.add_argument("--phases", default="nodes,tasks,pgs,actors",
                    help="comma list: which phases to run")
    args = ap.parse_args()
    phases = {p.strip() for p in args.phases.split(",") if p.strip()}
    if args.skip_actors:
        phases.discard("actors")

    import ray_tpu
    from ray_tpu.cluster_utils import Cluster

    result = {
        "benchmark": "scalability_envelope",
        "hardware": f"single host, {os.cpu_count()} cpu, virtual nodes",
        "reference": "release/release_logs/2.6.1/benchmarks/*.json "
                     "(64 nodes x 64 cores)",
    }

    def flush():
        # partial results survive a later phase dying (e.g. the actor
        # wave timing out): the artifact is written after EVERY phase
        with open(args.out, "w") as f:
            json.dump(result, f, indent=1)

    cluster = Cluster(initialize_head=True,
                      head_node_args={"num_cpus": 4, "num_tpus": 0})
    lag = _LoopLag()
    try:
        if "nodes" in phases:
            print(f"# many_nodes({args.nodes})", file=sys.stderr,
                  flush=True)
            lag.snap()
            result["many_nodes"] = bench_many_nodes(cluster, args.nodes)
            result["many_nodes"]["loop_lag"] = lag.delta()
            print(json.dumps(result["many_nodes"]), file=sys.stderr)
            flush()
        elif {"tasks", "pgs"} & phases:
            # the task/pg phases expect the virtual node table
            for _ in range(args.nodes):
                cluster.add_node(num_cpus=1,
                                 object_store_memory=64 << 20)

        if "tasks" in phases:
            print(f"# many_tasks({args.tasks})", file=sys.stderr,
                  flush=True)
            lag.snap()
            result["many_tasks"] = bench_many_tasks(args.tasks,
                                                    args.nodes)
            result["many_tasks"]["loop_lag"] = lag.delta()
            print(json.dumps(result["many_tasks"]), file=sys.stderr)
            flush()

        if "pgs" in phases:
            print(f"# many_pgs({args.pgs})", file=sys.stderr, flush=True)
            quiesce = _quiesce_workers()  # task-wave workers must exit
            lag.snap()
            result["many_pgs"] = bench_many_pgs(args.pgs)
            result["many_pgs"]["quiesce"] = quiesce
            result["many_pgs"]["loop_lag"] = lag.delta()
            print(json.dumps(result["many_pgs"]), file=sys.stderr)
            flush()
    finally:
        cluster.shutdown()

    if "actors" not in phases:
        result["envelope"] = {
            "nodes_tested": args.nodes if "nodes" in phases else 0,
            "actors_tested": 0,
            "tasks_tested": args.tasks if "tasks" in phases else 0,
            "pgs_tested": args.pgs if "pgs" in phases else 0,
            "note": "control-plane rates on one host; actor wave "
                    "skipped (spawn-bound)",
        }
        flush()
        print(json.dumps(result))
        return

    # fresh cluster for the actor wave: 1 CPU per actor across the
    # node table, real worker process per actor
    n_nodes = max(1, args.actors // 12)
    cluster = Cluster(initialize_head=True,
                      head_node_args={"num_cpus": 4, "num_tpus": 0})
    try:
        for _ in range(n_nodes):
            cluster.add_node(num_cpus=12, object_store_memory=64 << 20)
        print(f"# many_actors({args.actors}) over {n_nodes} nodes",
              file=sys.stderr, flush=True)
        lag.snap()
        result["many_actors"] = bench_many_actors(args.actors)
        result["many_actors"]["loop_lag"] = lag.delta()
        print(json.dumps(result["many_actors"]), file=sys.stderr)
        flush()
    finally:
        cluster.shutdown()

    result["envelope"] = {
        "nodes_tested": args.nodes,
        "actors_tested": args.actors,
        "tasks_tested": args.tasks,
        "pgs_tested": args.pgs,
        "note": "control-plane rates on one host; reference envelope "
                "(2k nodes / 40k actors) is a 4096-core fleet number",
    }
    flush()
    print(json.dumps(result))


if __name__ == "__main__":
    main()
