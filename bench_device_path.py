"""Device-path A/B microbench -> BENCH_device_path.json.

Two coupled measurements, both interleaved seed/new pairs with
median-of-pairwise summaries (the MICROBENCH_r6 methodology — this host
has multi-x run-to-run drift, so only paired ratios inside one window
are meaningful):

1. **roundtrip** — 64 MiB ``jax.Array`` put+get through a shm arena.
   seed = ``serialization_device_zero_copy`` OFF (the pre-r13 pickle
   path: the payload is embedded in the pickle stream — one full
   traversal to build the stream, another to copy it into the arena,
   and the read side re-copies out of the stream); new = ON (frame 0 is
   dtype/shape metadata, the payload is an out-of-band buffer view
   written straight into the arena; the read side rebuilds from the
   arena-backed view with exactly one XLA import — the host->device
   transfer analog).

2. **prefetch** — e2e ``arg_fetch`` p95 (r10 ``task.phase_ms``) for
   cold by-ref args on a 2-node cluster (head + one real agent
   process), tasks pinned to the non-holder node so every arg must
   cross hosts. seed = ``arg_prefetch_enabled`` OFF (the pull starts
   only when the worker's ``_decode_args`` get() asks); new = ON (the
   head fires the pull at lease grant / task dispatch, overlapping it
   with the lease reply, driver dispatch and worker wakeup; the
   worker's get joins the in-flight pull). The holder's transfer
   server is egress-paced to emulate a shared uplink (the
   BENCH_broadcast precedent — unpaced localhost hides the transfer
   entirely).

Run: python bench_device_path.py [--pairs 3] [--size-mib 64]
     [--tasks 24] [--arg-mib 4] [--out BENCH_device_path.json]
"""

import argparse
import json
import os
import statistics
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("TPU_CHIPS", "0")
os.environ.setdefault("RAY_TPU_PRESTART_WORKERS", "0")


def _median(xs):
    return statistics.median(xs) if xs else 0.0


# ------------------------------------------------------------ roundtrip


def bench_roundtrip(pairs: int, size_mib: int) -> dict:
    import numpy as np

    import jax.numpy as jnp

    from ray_tpu.core import serialization
    from ray_tpu.core.config import get_config
    from ray_tpu.core.ids import ObjectID
    from ray_tpu.core.object_store import ShmObjectStore

    nbytes = size_mib << 20
    n = nbytes // 4
    cfg = get_config()
    store = ShmObjectStore(f"rtpu_bdp_{os.getpid():x}",
                           max(4 * nbytes, 256 << 20), create=True)
    rng = np.random.default_rng(0)

    def one_trial(zero_copy: bool) -> dict:
        cfg.serialization_device_zero_copy = zero_copy
        # fresh device array per trial: neither path gets a pre-warmed
        # host copy for free
        x = jnp.asarray(rng.normal(size=n).astype(np.float32))
        x.block_until_ready()
        oid = ObjectID.from_random()
        t0 = time.perf_counter()
        sv = serialization.serialize(x)
        store.put_serialized(oid, sv.frames)
        t1 = time.perf_counter()
        del sv
        frames = store.get_frames(oid, pin_borrows=True)
        y = serialization.deserialize(frames)
        del frames
        getattr(y, "block_until_ready", lambda: None)()
        t2 = time.perf_counter()
        assert float(np.asarray(y)[0]) == float(np.asarray(x)[0])
        del y
        import gc

        gc.collect()
        store.release(oid)
        store.delete(oid)
        put_s, get_s = t1 - t0, t2 - t1
        return {"put_s": round(put_s, 4), "get_s": round(get_s, 4),
                "put_gbps": round(nbytes / put_s / 1e9, 3),
                "get_gbps": round(nbytes / get_s / 1e9, 3),
                "roundtrip_s": round(put_s + get_s, 4)}

    prev = cfg.serialization_device_zero_copy
    try:
        one_trial(False), one_trial(True)  # warm both paths (JIT, pages)
        rows = []
        for _ in range(pairs):
            seed = one_trial(False)
            new = one_trial(True)
            rows.append({"seed": seed, "new": new,
                         "ratio": round(seed["roundtrip_s"]
                                        / new["roundtrip_s"], 3)})
    finally:
        cfg.serialization_device_zero_copy = prev
        store.close()
    return {
        "size_mib": size_mib,
        "pairs": rows,
        "roundtrip_speedup_median_of_pairs": _median(
            [r["ratio"] for r in rows]),
        "put_gbps_median": {
            "seed": _median([r["seed"]["put_gbps"] for r in rows]),
            "new": _median([r["new"]["put_gbps"] for r in rows])},
        "get_gbps_median": {
            "seed": _median([r["seed"]["get_gbps"] for r in rows]),
            "new": _median([r["new"]["get_gbps"] for r in rows])},
    }


# ------------------------------------------------------------- prefetch


def bench_prefetch(pairs: int, tasks: int, arg_mib: int) -> dict:
    import numpy as np

    import ray_tpu
    import ray_tpu.core.api as core_api
    from ray_tpu import state
    from ray_tpu.cluster_utils import Cluster
    from ray_tpu.core.api import NodeAffinitySchedulingStrategy
    from ray_tpu.core.config import get_config

    cluster = Cluster(initialize_head=True,
                      head_node_args={"num_cpus": 1, "num_tpus": 0,
                                      # rounds put ~90 MiB each and the
                                      # borrow-grace defers frees ~1s:
                                      # headroom keeps the spill
                                      # threshold out of the measurement
                                      "object_store_memory": 2 << 30})
    handle = cluster.add_remote_node(num_cpus=2,
                                     object_store_memory=2 << 30)
    head = core_api._head
    # AFTER init: init() re-creates the config singleton — a reference
    # grabbed earlier would mutate an orphan and the A/B toggle would
    # silently not take
    cfg = get_config()
    # shared-uplink emulation on the holder host (the head's transfer
    # server serves the driver's puts): unpaced localhost finishes a
    # 4 MiB pull in ~2 ms and the transfer vanishes into RPC noise.
    # Default prefetch caps: the pending queue paces over-cap requests
    # instead of dropping them, so no cap tuning is needed.
    head._transfer_server.egress_limit_bps = 100 * 1024 * 1024

    aff = NodeAffinitySchedulingStrategy(handle.node_idx)
    arg_elems = (arg_mib << 20) // 8
    rng = np.random.default_rng(7)

    RAMP = 6  # pipeline-fill transient, measured under its own func name

    def _make_task(name: str):
        def _consume(a):
            import time as _t

            # exec dominates (0.3s x tasks / 2 workers >> the paced
            # egress total): the backlog of queued tasks is the lead
            # time prefetch turns into overlap; an egress-BOUND round
            # has no window to hide transfers in and measures only the
            # uplink (and on a 2-vCPU host, an oversubscribed round
            # measures scheduler jitter, not the data plane)
            _t.sleep(0.3)
            return float(a[-1])

        _consume.__name__ = name
        _consume.__qualname__ = name
        return ray_tpu.remote(
            num_cpus=1, scheduling_strategy=aff)(_consume)

    def one_round(tag: str, prefetch_on: bool) -> dict:
        cfg.arg_prefetch_enabled = prefetch_on
        ramp_task = _make_task(f"dpr_{tag}")
        consume = _make_task(f"dp_{tag}")

        issued0 = head.prefetch_issued
        joined0 = head.prefetch_joined
        wasted0 = head.prefetch_wasted
        # every arg is a FRESH driver-side put: always cold on the
        # executing node, so each task's arg_fetch includes the pull
        args = [ray_tpu.put(rng.normal(size=arg_elems)) for _ in
                range(RAMP + tasks)]
        t0 = time.perf_counter()
        # ONE continuous paced stream — steady arrival is the workload
        # shape prefetch targets (pipeline activations, rollout
        # batches). The first RAMP tasks run under their own func name:
        # the stream head has no backlog yet, so it has no lead time
        # for ANY speculation to use — the measured histogram is the
        # steady state, where the p95 contract actually lives. (An
        # all-at-t0 burst instead makes every prefetch share the paced
        # uplink fairly and measures bucket queueing on both sides.)
        refs = []
        for i, a in enumerate(args):
            fn = ramp_task if i < RAMP else consume
            refs.append(fn.remote(a))
            time.sleep(0.1)
        out = ray_tpu.get(refs, timeout=600)
        wall = time.perf_counter() - t0
        assert len(out) == RAMP + tasks
        from ray_tpu.core.context import get_context

        get_context().events.flush(sync=True)  # fold barrier
        phases = state.summarize_tasks()["phases"].get(
            f"dp_{tag}", {})
        af = phases.get("arg_fetch", {})
        del args, refs
        # drain before the next round: owned-object frees ride a ~1s
        # shared-ref grace window, and a round measured on top of the
        # previous round's eviction churn reads as noise
        from ray_tpu.core.context import get_context as _gc

        deadline = time.perf_counter() + 10
        while _gc().store.bytes_in_use() > (64 << 20) and \
                time.perf_counter() < deadline:
            time.sleep(0.1)
        time.sleep(0.5)
        return {
            "prefetch": prefetch_on,
            "tasks": tasks,
            "ramp_tasks": RAMP,
            "wall_s": round(wall, 3),
            "arg_fetch_p50_ms": round(af.get("p50_ms", 0.0), 2),
            "arg_fetch_p95_ms": round(af.get("p95_ms", 0.0), 2),
            "arg_fetch_mean_ms": round(af.get("mean_ms", 0.0), 2),
            "prefetch_issued": head.prefetch_issued - issued0,
            "prefetch_joined": head.prefetch_joined - joined0,
            "prefetch_wasted": head.prefetch_wasted - wasted0,
        }

    prev = cfg.arg_prefetch_enabled
    rows = []
    try:
        one_round("warm", False)  # spawn+import the remote workers
        for i in range(pairs):
            seed = one_round(f"off{i}", False)
            new = one_round(f"on{i}", True)
            rows.append({
                "seed": seed, "new": new,
                "p95_reduction": round(
                    1.0 - (new["arg_fetch_p95_ms"]
                           / seed["arg_fetch_p95_ms"])
                    if seed["arg_fetch_p95_ms"] else 0.0, 3)})
    finally:
        cfg.arg_prefetch_enabled = prev
        try:
            handle.terminate()
        except Exception:  # noqa: BLE001
            pass
        cluster.shutdown()
    issued = sum(r["new"]["prefetch_issued"] for r in rows)
    wasted = sum(r["new"]["prefetch_wasted"] for r in rows)
    return {
        "tasks_per_round": tasks,
        "arg_mib": arg_mib,
        "holder_egress_mib_s": 100,
        "pairs": rows,
        "arg_fetch_p95_ms_median": {
            "seed": _median([r["seed"]["arg_fetch_p95_ms"]
                             for r in rows]),
            "new": _median([r["new"]["arg_fetch_p95_ms"]
                            for r in rows])},
        "p95_reduction_median_of_pairs": _median(
            [r["p95_reduction"] for r in rows]),
        "prefetch_issued_total": issued,
        "prefetch_wasted_total": wasted,
        "wasted_ratio": round(wasted / issued, 4) if issued else 0.0,
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--pairs", type=int, default=5)
    ap.add_argument("--size-mib", type=int, default=64)
    ap.add_argument("--tasks", type=int, default=16)
    ap.add_argument("--arg-mib", type=int, default=2)
    ap.add_argument("--out", default="BENCH_device_path.json")
    ap.add_argument("--skip-prefetch", action="store_true")
    ap.add_argument("--skip-roundtrip", action="store_true")
    args = ap.parse_args()

    result = {
        "benchmark": "device_path_r13",
        "hardware": f"single host, {os.cpu_count()} cpu, CPU jax",
        "methodology": "interleaved seed/new pairs, median-of-pairwise "
                       "(MICROBENCH_r6)",
    }
    # merge with an existing artifact: the two sections are best run as
    # SEPARATE processes (--skip-prefetch then --skip-roundtrip) — the
    # roundtrip section's 16 x 64 MiB copy storms leave the host hot
    # enough to contaminate the cluster section's tail latencies
    if os.path.exists(args.out):
        try:
            with open(args.out) as f:
                prior = json.load(f)
            for k in ("roundtrip", "prefetch"):
                if k in prior:
                    result[k] = prior[k]
        except (OSError, ValueError):
            pass
    if not args.skip_roundtrip:
        print(f"# roundtrip {args.size_mib} MiB x {args.pairs} pairs",
              file=sys.stderr, flush=True)
        result["roundtrip"] = bench_roundtrip(args.pairs, args.size_mib)
        print(json.dumps(result["roundtrip"]), file=sys.stderr)
        with open(args.out, "w") as f:
            json.dump(result, f, indent=1)
    if not args.skip_prefetch:
        print(f"# prefetch A/B {args.tasks} tasks x {args.pairs} pairs",
              file=sys.stderr, flush=True)
        result["prefetch"] = bench_prefetch(args.pairs, args.tasks,
                                            args.arg_mib)
        print(json.dumps(result["prefetch"]), file=sys.stderr)
    with open(args.out, "w") as f:
        json.dump(result, f, indent=1)
    print(json.dumps(result))


if __name__ == "__main__":
    main()
