"""RLlib learner-throughput benchmark (BASELINE.md north-star row 3:
"PPO + IMPALA, Atari-class, JAX learner on TPU + CPU rollout actors —
learner throughput (env-steps/s), match reference GPU learner").

Writes RLLIB_BENCH_r4.json with, per algorithm:
  - learner_env_steps_per_s: pure learner-update throughput — how many
    env steps of experience the jitted XLA update consumes per second
    (the row-3 metric; sampling excluded, batches prebuilt on host).
  - end_to_end_env_steps_per_s: algo.train() loop including rollout
    actors on this host's CPUs (bounded by host cores, reported for
    honesty, not the row-3 target).

Envs: Breakout-Mini (Atari-class, 400-dim observation) and CartPole.
Run: python bench_rllib.py [--duration 20]
"""

import argparse
import json
import os
import sys
import time

import numpy as np

# the host sitecustomize force-registers the axon TPU backend at
# interpreter start, overriding the standard JAX_PLATFORMS env var (and
# wedging forever if the tunnel is sick); restore the expected semantics
if os.environ.get("JAX_PLATFORMS"):
    import jax

    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])


def _fake_ppo_batch(obs_dim, num_actions, n, seed=0):
    from ray_tpu.rllib import SampleBatch
    from ray_tpu.rllib import sample_batch as SB

    rng = np.random.default_rng(seed)
    return SampleBatch({
        SB.OBS: rng.normal(size=(n, obs_dim)).astype(np.float32),
        SB.ACTIONS: rng.integers(0, num_actions, n),
        SB.REWARDS: rng.normal(size=n).astype(np.float32),
        SB.DONES: rng.random(n) < 0.05,
        SB.ACTION_LOGP: -np.abs(rng.normal(size=n)).astype(np.float32),
        SB.VF_PREDS: rng.normal(size=n).astype(np.float32),
        SB.ADVANTAGES: rng.normal(size=n).astype(np.float32),
        SB.VALUE_TARGETS: rng.normal(size=n).astype(np.float32),
    })


def _fake_impala_batch(obs_dim, num_actions, T, N, seed=0):
    from ray_tpu.rllib import SampleBatch
    from ray_tpu.rllib import sample_batch as SB

    rng = np.random.default_rng(seed)
    return SampleBatch({
        SB.OBS: rng.normal(size=(T, N, obs_dim)).astype(np.float32),
        SB.ACTIONS: rng.integers(0, num_actions, (T, N)),
        SB.REWARDS: rng.normal(size=(T, N)).astype(np.float32),
        SB.DONES: rng.random((T, N)) < 0.05,
        SB.ACTION_LOGP: -np.abs(rng.normal(size=(T, N))).astype(np.float32),
        "bootstrap_obs": rng.normal(size=(N, obs_dim)).astype(np.float32),
    })


def bench_learner(learner, batches, env_steps_per_update,
                  duration_s: float, update_kw=None) -> dict:
    """Spin learner.update for duration; -> env-steps/s consumed."""
    update_kw = update_kw or {}
    learner.update(batches[0], **update_kw)  # compile/warm
    n, i = 0, 0
    t0 = time.perf_counter()
    while time.perf_counter() - t0 < duration_s:
        learner.update(batches[i % len(batches)], **update_kw)
        n += 1
        i += 1
    dt = time.perf_counter() - t0
    return {"updates": n,
            "updates_per_s": round(n / dt, 2),
            "learner_env_steps_per_s": round(
                n * env_steps_per_update / dt, 1)}


def bench_end_to_end(config_builder, duration_s: float) -> dict:
    algo = config_builder()
    algo.train()  # warm/compile
    steps0 = algo._num_env_steps
    t0 = time.perf_counter()
    iters = 0
    while time.perf_counter() - t0 < duration_s:
        algo.train()
        iters += 1
    dt = time.perf_counter() - t0
    steps = algo._num_env_steps - steps0
    algo.stop()
    return {"train_iters": iters,
            "end_to_end_env_steps_per_s": round(steps / dt, 1)}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--duration", type=float, default=20.0)
    ap.add_argument("--out", default="RLLIB_BENCH_r4.json")
    ap.add_argument("--skip-end-to-end", action="store_true")
    args = ap.parse_args()

    import jax

    from ray_tpu.rllib import (APPOConfig, BreakoutMini, IMPALAConfig,
                               PPOConfig)
    from ray_tpu.rllib.appo import APPOLearner
    from ray_tpu.rllib.learner import ImpalaLearner, PPOLearner

    obs_dim = BreakoutMini.observation_dim  # 400: the Atari-class shape
    num_actions = BreakoutMini.num_actions
    result = {"benchmark": "rllib_learner_throughput",
              "backend": jax.default_backend(),
              "env": "Breakout-Mini-v0 (MinAtar-class, obs 400)",
              "model_hiddens": [256, 256]}

    def flush():
        # partial artifact survives a later phase dying / timing out
        with open(args.out, "w") as f:
            json.dump(result, f, indent=1)

    # ---- learner-only throughput (row-3 metric) ----
    ppo = PPOLearner(obs_dim, num_actions, hiddens=(256, 256))
    bs = 4096
    batches = [_fake_ppo_batch(obs_dim, num_actions, bs, seed=s)
               for s in range(4)]
    result["ppo"] = bench_learner(
        ppo, batches, bs * 4, args.duration,  # 4 epochs over the batch
        update_kw=dict(num_epochs=4, minibatch_size=1024))
    print(json.dumps({"ppo": result["ppo"]}), file=sys.stderr,
          flush=True)
    flush()

    T, N = 64, 64
    impala = ImpalaLearner(obs_dim, num_actions, hiddens=(256, 256))
    batches = [_fake_impala_batch(obs_dim, num_actions, T, N, seed=s)
               for s in range(4)]
    result["impala"] = bench_learner(impala, batches, T * N, args.duration)
    print(json.dumps({"impala": result["impala"]}), file=sys.stderr,
          flush=True)
    flush()

    appo = APPOLearner(obs_dim, num_actions, hiddens=(256, 256))
    result["appo"] = bench_learner(appo, batches, T * N, args.duration)
    print(json.dumps({"appo": result["appo"]}), file=sys.stderr,
          flush=True)
    flush()

    # DreamerV3 world-model + imagination update (replayed env steps
    # consumed per second; the heaviest per-step learner in the zoo)
    from ray_tpu.rllib.dreamer import DreamerLearner

    rng = np.random.default_rng(0)
    B, L = 16, 32
    dreamer = DreamerLearner(obs_dim, num_actions, deter=128, hidden=128)
    dbatches = [(rng.normal(size=(B, L, obs_dim)).astype(np.float32),
                 rng.integers(0, num_actions, (B, L)),
                 rng.normal(size=(B, L)).astype(np.float32),
                 np.ones((B, L), np.float32)) for _ in range(4)]

    class _DreamerShim:
        def update(self, batch):
            return dreamer.update(*batch)

    result["dreamerv3"] = bench_learner(
        _DreamerShim(), dbatches, B * L, args.duration)
    print(json.dumps({"dreamerv3": result["dreamerv3"]}),
          file=sys.stderr, flush=True)
    flush()

    # ---- end-to-end (host-CPU-bound rollouts; context, not the target)
    if not args.skip_end_to_end:
        os.environ.setdefault("TPU_CHIPS", "0")
        import ray_tpu

        ray_tpu.init(num_cpus=4, num_tpus=0, ignore_reinit_error=True)
        try:
            result["ppo_end_to_end"] = bench_end_to_end(
                lambda: PPOConfig().environment("Breakout-Mini-v0")
                .rollouts(num_rollout_workers=2, num_envs_per_worker=8,
                          rollout_fragment_length=64)
                .training(model_hiddens=(256, 256)).build(),
                args.duration)
            result["impala_end_to_end"] = bench_end_to_end(
                lambda: IMPALAConfig().environment("Breakout-Mini-v0")
                .rollouts(num_rollout_workers=2, num_envs_per_worker=8,
                          rollout_fragment_length=64)
                .training(model_hiddens=(256, 256)).build(),
                args.duration)
        finally:
            ray_tpu.shutdown()
        flush()

    result["reference_context"] = (
        "reference GPU learner throughput for PPO/IMPALA Atari is "
        "O(10k-50k) env-steps/s per GPU (release/rllib_tests); row-3 "
        "target is the learner_env_steps_per_s fields")
    flush()
    print(json.dumps(result))


if __name__ == "__main__":
    main()
