"""Serving benchmarks: r14 serve-at-scale + the r5 LLM/proxy sections.

r14 phases (default; writes ``SERVE_BENCH_r14.json``) — the ROADMAP's
flagship serving workload on a multi-node cluster of REAL agent
processes with a paced object-plane uplink:

  coldstart  Broadcast-powered replica cold-start: deployment weights
             (64 MiB) travel BY REFERENCE through the object plane;
             scale-up 1->8 with pre-warm at decision time (OBJECT_WARM
             -> r13 prefetch -> r9 cooperative broadcast tree) vs the
             sequential-fetch baseline (one replica at a time — the
             "linear in concurrent scale-ups" shape the broadcast
             removes). Gates: coop wall <= 0.5x sequential; root egress
             <= 2xS for the concurrent scale-up. Also records the
             cold-start vs fleet-size curve (2/4/8) and a flat
             concurrent trial (broadcast_fanout=0) for the egress
             comparison.

  autoscale  Telemetry-driven autoscaling under sustained OPEN-LOOP
             traffic (fixed arrival rate, unbounded concurrency):
             a queue-depth surge must trigger a scale-up within one
             policy period, p50/p99 are recorded before/during/after
             each scale event, the steady surge phase must show ZERO
             direction reversals (asserted from serve_autoscale cluster
             events), and p99 during the scale-up must stay within 2x
             the steady-state p99 (no ingress stall while replicas
             warm). A separate SLO-burn section drives slow-but-sparse
             requests that only the p99 signal can see.

  ingress    Zero-copy ingress A/B: large (2 MiB) request tensors
             through the handle path with the by-ref conversion ON
             (``serve_request_by_ref_min_bytes``) vs OFF (inline
             pickle), interleaved seed/new pairs, median-of-pairwise
             ratios (MICROBENCH_r6 methodology).

Legacy phases (r5 artifact shape): ``proxy`` (HTTP ingress RPS on a
noop deployment), ``llm`` (continuous-batching vs cohort on the model
engine; needs an accelerator or falls back to the tiny config).

Run: ``python bench_serve.py [--phases coldstart,autoscale,ingress]
[--out SERVE_BENCH_r14.json]``. Each phase embeds a ``loop_lag`` block
(head IO-loop health during the phase, bench_scale.py convention).
"""

import argparse
import json
import os
import sys
import threading
import time

# the host sitecustomize force-registers the axon TPU backend, overriding
# the standard JAX_PLATFORMS env var; restore the expected semantics
if os.environ.get("JAX_PLATFORMS"):
    import jax
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

# persistent compilation cache: warmup compiles the full (bucket x group)
# program menu through the tunneled backend (~minutes); cache them so
# repeat runs measure serving, not compilation
import jax  # noqa: E402

jax.config.update("jax_compilation_cache_dir",
                  os.path.join(os.path.dirname(os.path.abspath(__file__)),
                               ".jax_cache"))
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

from bench_scale import _LoopLag  # noqa: E402  (loop_lag block convention)

# ------------------------------------------------------- r14 constants

WEIGHTS_MIB = 64
# shared per-host uplink for the object plane during coldstart (the r9
# regime: pacing dominates, not 2-vCPU memcpy ceilings — at 40+ MiB/s
# the per-trial control overhead of the sequential baseline starts to
# rival its transfer time and the A/B stops isolating the data plane)
LINK_BPS = 20 * 1024 * 1024
FLEET = 8
AB_PAIRS = 3  # odd: the pairwise-ratio median is a real middle pair
INGRESS_PAYLOAD_MIB = 2
INGRESS_PAIRS = 3
INGRESS_CLIENTS = 8
INGRESS_HALF_S = 6.0


def _pct(sorted_vals, p):
    if not sorted_vals:
        return 0.0
    return sorted_vals[min(len(sorted_vals) - 1,
                           int(p / 100 * len(sorted_vals)))]


def _lat_ms(lats):
    s = sorted(lats)
    return {"n": len(s),
            "p50_ms": round(_pct(s, 50) * 1000, 1),
            "p99_ms": round(_pct(s, 99) * 1000, 1)}


# ========================================================== r14: shared


def _boot_cluster(n_agents: int):
    """Embedded head with NO schedulable CPUs + real agent processes
    (1 CPU each): every serve replica requesting a CPU lands on an
    agent, so cold-start moves weights across host boundaries. Agents
    inherit the paced object-plane uplink via the env-overridable
    config knob."""
    os.environ["RAY_TPU_HOST_EGRESS_LIMIT_BPS"] = str(LINK_BPS)
    from ray_tpu.cluster_utils import Cluster

    cluster = Cluster(initialize_head=True,
                      head_node_args={"num_cpus": 0, "num_tpus": 0})
    handles = []
    for _ in range(n_agents):
        handles.append(cluster.add_remote_node(
            num_cpus=1, object_store_memory=192 << 20))
    return cluster, handles


def _head():
    from ray_tpu.core.api import _head as h

    return h


def _coldstart_model(version="w1"):
    import numpy as np

    from ray_tpu import serve

    @serve.deployment(version=version, health_check_timeout_s=180,
                      ray_actor_options={"num_cpus": 1})
    class Model:
        def __init__(self, w):
            self.total = float(np.asarray(w).sum())

        def __call__(self, x=None):
            return self.total

    return Model


# ====================================================== r14: coldstart


def _warm_worker_pool(n_agents: int):
    """Leave one warm idle interpreter on every agent: a task wave of
    num_cpus=1 tasks spreads one per single-CPU agent, and the workers
    drop back to the idle pool on return. Replica actors then REUSE
    those interpreters (the head's idle-worker lease path) instead of
    forking — on this 2-vCPU host 8 concurrent forks cost more wall
    than the 64 MiB transfer the trial measures, and production fleets
    keep warm pools anyway (the reference WorkerPool's prestart)."""
    import ray_tpu

    @ray_tpu.remote(num_cpus=1)
    def _touch():
        time.sleep(0.3)
        return 1

    ray_tpu.get([_touch.remote() for _ in range(n_agents)], timeout=300)


def _coldstart_trial(Model, weights, mode: str, fleet: int) -> dict:
    """One cold-start trial: deploy 1 replica (weights land on its
    node), then scale to ``fleet``. mode: "coop" (concurrent scale-up,
    cooperative broadcast), "flat" (concurrent, broadcast_fanout=0 —
    every puller stripes off the sealed holders), "seq" (one replica
    at a time — the baseline whose wall-clock is linear in fleet)."""
    import ray_tpu
    from ray_tpu import serve
    from ray_tpu.core.config import get_config

    cfg = get_config()
    old_fanout = cfg.broadcast_fanout
    old_sources = cfg.pull_max_sources
    # seq is the naive serving baseline — every replica pulls the model
    # as ONE full stream (no striping, no relays: what a pod pulling
    # weights from a model store does), one replica at a time. flat (0)
    # keeps the concurrency but stripes off the sealed holder set (the
    # pre-r9 plan). coop is the r9/r14 path at the default fanout.
    if mode == "seq":
        cfg.broadcast_fanout = 0
        cfg.pull_max_sources = 1
    elif mode == "flat":
        cfg.broadcast_fanout = 0
    else:
        # narrow tree for the one-object weight broadcast: every hop is
        # a FULL-RATE single-source stream, so chunk pipelining holds at
        # the paced link (wider fanouts split each root's bucket into
        # half-rate striped streams — measured here, the relay chain
        # degrades toward store-and-forward and the leaf pays ~3x S/link)
        cfg.broadcast_fanout = 1
        cfg.pull_max_sources = 1
    head = _head()
    try:
        _warm_worker_pool(FLEET)
        wref = ray_tpu.put(weights)
        deadline = time.monotonic() + 30
        while wref.id not in head.objects and time.monotonic() < deadline:
            time.sleep(0.01)
        serve.run(Model.options(num_replicas=1).bind(wref),
                  name="cold", route_prefix=None, timeout_s=300)
        served0 = head._transfer_server.pull_requests
        egress0 = head._transfer_server.bytes_served
        t0 = time.monotonic()
        if mode == "seq":
            for k in range(2, fleet + 1):
                serve.run(Model.options(num_replicas=k).bind(wref),
                          name="cold", route_prefix=None, timeout_s=300)
        else:
            serve.run(Model.options(num_replicas=fleet).bind(wref),
                      name="cold", route_prefix=None, timeout_s=300)
        wall = time.monotonic() - t0
        st = serve.status()["applications"]["cold"]["deployments"]["Model"]
        auto = st["autoscaler"]
        expect = float(weights.sum())
        h = serve.get_app_handle("cold")
        vals = {h.remote().result(timeout_s=60) for _ in range(fleet * 2)}
        assert vals == {expect}, f"replica weights diverged: {vals}"
        out = {
            "mode": mode, "fleet": fleet,
            "wall_s": round(wall, 3),
            "root_egress_mib": round(
                (head._transfer_server.bytes_served - egress0) / 2**20, 1),
            "root_streams": head._transfer_server.pull_requests - served0,
            "cold_start": auto["cold_start"],
            "weights_by_ref": auto["weights_by_ref"],
        }
        serve.delete("cold")
        return out
    finally:
        cfg.broadcast_fanout = old_fanout
        cfg.pull_max_sources = old_sources


def bench_coldstart() -> dict:
    import numpy as np

    rng = np.random.default_rng(11)
    size = WEIGHTS_MIB * 2**20 // 8

    def fresh_weights():
        # fresh bytes per trial: every trial's object is cold on every
        # node (old trials' copies are GC'd when their refs die)
        return rng.random(size)

    Model = _coldstart_model()
    out = {"weights_mib": WEIGHTS_MIB,
           "link_mib_s": LINK_BPS // 2**20,
           "fleet": FLEET}

    # warmup: first trial pays every agent's worker-interpreter fork
    # plus jax/numpy imports; discard it
    _coldstart_trial(Model, fresh_weights(), "coop", FLEET)

    # headline A/B: interleaved (seq, coop) pairs, median of pairwise
    pairs = []
    for _ in range(AB_PAIRS):
        seq = _coldstart_trial(Model, fresh_weights(), "seq", FLEET)
        coop = _coldstart_trial(Model, fresh_weights(), "coop", FLEET)
        pairs.append({"seq": seq, "coop": coop,
                      "ratio": round(coop["wall_s"] / seq["wall_s"], 3)})
        print(json.dumps(pairs[-1]), file=sys.stderr, flush=True)
    ratios = sorted(p["ratio"] for p in pairs)
    out["ab_pairs"] = pairs
    out["coop_over_seq_wall_median"] = ratios[len(ratios) // 2]
    coop_egress = [p["coop"]["root_egress_mib"] for p in pairs]
    out["coop_root_egress_over_S_max"] = round(
        max(coop_egress) / WEIGHTS_MIB, 2)

    # flat concurrent (fanout=0): same concurrency, no broadcast tree —
    # isolates what the tree buys in root egress
    out["flat_concurrent"] = _coldstart_trial(
        Model, fresh_weights(), "flat", FLEET)

    # cold-start vs fleet-size curve (coop): near-constant, not linear
    out["curve"] = [
        _coldstart_trial(Model, fresh_weights(), "coop", n)
        for n in (2, 4, 8)]

    out["gates"] = {
        "coop_wall_le_half_seq":
            out["coop_over_seq_wall_median"] <= 0.5,
        # <= 2xS plus one transfer chunk of rounding slack
        "coop_root_egress_le_2S":
            out["coop_root_egress_over_S_max"] <= 2.0 + 8 / WEIGHTS_MIB,
    }
    return out


# ====================================================== r14: autoscale


def _open_loop(submit, rate_hz: float, duration_s: float, records: list,
               pool) -> None:
    """Fixed-arrival-rate driver: submissions never wait for earlier
    completions (open loop — queueing shows up as latency, closed-loop
    clients would throttle the surge instead)."""
    t_next = time.perf_counter()
    t_end = t_next + duration_s

    def one():
        t0 = time.perf_counter()
        try:
            submit()
            records.append((time.time(), time.perf_counter() - t0, True))
        except Exception:  # noqa: BLE001 — count, don't die
            records.append((time.time(), time.perf_counter() - t0, False))

    while t_next < t_end:
        now = time.perf_counter()
        if now < t_next:
            time.sleep(t_next - now)
        pool.submit(one)
        t_next += 1.0 / rate_hz


def _window(records, t0, t1):
    return _lat_ms([dt for ts, dt, ok in records if ok and t0 <= ts < t1])


def bench_autoscale() -> dict:
    from concurrent.futures import ThreadPoolExecutor

    import ray_tpu  # noqa: F401
    from ray_tpu import serve, state

    out = {}
    pool = ThreadPoolExecutor(max_workers=128)
    # scale-ups must reuse warm idle interpreters: a cold fork + numpy
    # import storm on this 2-vCPU host starves the RUNNING replicas'
    # serving path and pollutes the very p99-during-scale-up window the
    # gate measures (production fleets prestart workers anyway)
    _warm_worker_pool(FLEET)

    # ---- section 1: SLO burn. Sparse but SLOW requests: concurrency
    # stays under target (desired=1 by load), only the phase-histogram
    # p99 can see the degradation.
    @serve.deployment(
        version="s1", max_concurrent_queries=16,
        health_check_period_s=0.2,
        ray_actor_options={"num_cpus": 1},
        autoscaling_config=dict(
            min_replicas=1, max_replicas=3,
            target_num_ongoing_requests_per_replica=4.0,
            upscale_delay_s=0.5, downscale_delay_s=30.0,
            latency_slo_ms=300.0, slo_phase="e2e"))
    class SloModel:
        def __call__(self, ms):
            time.sleep(ms / 1000.0)
            return ms

    h = serve.run(SloModel.bind(), name="slo", route_prefix=None,
                  timeout_s=120)
    recs = []
    _open_loop(lambda: h.remote(40).result(timeout_s=60), 4.0, 8.0,
               recs, pool)          # fast steady: p99 ~ 45ms, desired 1
    slow_start = time.time()
    _open_loop(lambda: h.remote(600).result(timeout_s=60), 2.0, 16.0,
               recs, pool)          # slow: p99 blows the 300ms SLO
    time.sleep(2)
    evs = state.list_cluster_events(
        filters=[("type", "=", "serve_autoscale")])
    # only burns AFTER the slow traffic started count as reactions (the
    # per-func histograms are cumulative cluster-wide: an earlier
    # phase's slow samples can pre-arm the signal)
    slo_evs = [e for e in evs if e["extra"].get("app") == "slo"
               and "slo_burn" in e["extra"].get("reason", "")
               and e["ts"] >= slow_start - 0.25]
    out["slo_burn"] = {
        "fast_p99": _window(recs, 0, slow_start),
        "slow_p99": _window(recs, slow_start, time.time()),
        "upscale_events": len(slo_evs),
        "first_reason": slo_evs[0]["extra"]["reason"] if slo_evs else "",
        "reaction_s": round(slo_evs[0]["ts"] - slow_start, 2)
        if slo_evs else None,
    }
    serve.delete("slo")
    print(json.dumps({"slo_burn": out["slo_burn"]}), file=sys.stderr,
          flush=True)

    # ---- section 2: queue-depth surge under sustained open-loop load.
    UP_DELAY = 0.5
    @serve.deployment(
        version="a1", max_concurrent_queries=16,
        health_check_period_s=0.5,
        ray_actor_options={"num_cpus": 1},
        autoscaling_config=dict(
            min_replicas=1, max_replicas=4,
            target_num_ongoing_requests_per_replica=0.5,
            upscale_delay_s=UP_DELAY, downscale_delay_s=6.0,
            downscale_cooldown_s=8.0))
    class Sleeper:
        def __call__(self, ms):
            time.sleep(ms / 1000.0)
            return ms

    _warm_worker_pool(FLEET)  # slo replicas consumed/killed workers
    h = serve.run(Sleeper.bind(), name="surge", route_prefix=None,
                  timeout_s=120)
    recs = []
    t_low0 = time.time()
    _open_loop(lambda: h.remote(60).result(timeout_s=60), 6.0, 8.0,
               recs, pool)                     # steady low: fleet of 1
    t_surge = time.time()
    _open_loop(lambda: h.remote(60).result(timeout_s=60), 30.0, 22.0,
               recs, pool)                     # surge: fleet must grow
    t_after = time.time()
    _open_loop(lambda: h.remote(60).result(timeout_s=60), 6.0, 12.0,
               recs, pool)                     # back to low: shrink
    t_end = time.time()
    time.sleep(4)  # the averaged downscale window may land post-traffic

    evs = [e for e in state.list_cluster_events(
        filters=[("type", "=", "serve_autoscale")])
        if e["extra"].get("app") == "surge"]
    ups = [e for e in evs if e["extra"]["direction"] == "up"
           and e["ts"] >= t_surge - 0.5]
    downs = [e for e in evs if e["extra"]["direction"] == "down"]
    # steady surge phase: after the fleet stabilized, before the rate
    # drops — the no-flap window
    steady0, steady1 = t_surge + 8.0, t_after
    dirs = [e["extra"]["direction"] for e in evs
            if steady0 <= e["ts"] < steady1]
    reversals_steady = sum(1 for a, b in zip(dirs, dirs[1:]) if a != b) \
        + len(dirs)  # ANY decision inside the steady window counts
    during = _window(recs, t_surge, t_surge + 6.0)
    steady_high = _window(recs, steady0, steady1)
    st = serve.status()["applications"]["surge"]["deployments"]["Sleeper"]
    out["surge"] = {
        "rates_hz": {"low": 6, "surge": 30},
        "exec_ms": 60,
        "policy_period_s": UP_DELAY + 1.0,  # upscale window + signal poll
        "steady_low": _window(recs, t_low0 + 2, t_surge),
        "during_scale_up": during,
        "steady_surge": steady_high,
        "after_scale_down": _window(recs, t_after + 4, t_end),
        "reaction_s": round(ups[0]["ts"] - t_surge, 2) if ups else None,
        "up_events": [{"ts_rel": round(e["ts"] - t_surge, 2),
                       "from": e["extra"]["from"], "to": e["extra"]["to"],
                       "reason": e["extra"]["reason"]} for e in ups],
        "down_events": len(downs),
        "decisions_in_steady_window": len(dirs),
        "final": st["autoscaler"],
    }
    out["gates"] = {
        "reacted_within_policy_period":
            ups and out["surge"]["reaction_s"] is not None
            and out["surge"]["reaction_s"] <=
            out["surge"]["policy_period_s"] + 1.0,
        "zero_reversals_steady": reversals_steady == 0,
        "p99_during_le_2x_steady":
            during["p99_ms"] <= 2.0 * max(steady_high["p99_ms"], 1.0),
        "scaled_down_after": len(downs) >= 1,
    }
    serve.delete("surge")
    pool.shutdown(wait=False)
    return out


# ======================================================== r14: ingress


def bench_ingress() -> dict:
    """Seed/new A/B of the large-request ingress path through the
    handle: inline pickle (seed: by-ref conversion off) vs by-ref args
    through the object plane (new). Interleaved pairs, median of
    pairwise ratios. Replicas live on remote agent nodes, so the
    payload crosses a host boundary either way.

    Provenance: on THIS host (2 vCPUs, unpaced loopback) both paths are
    memcpy-bound and the inline path already rides the r8 zero-copy
    vectored wire over ONE socket hop, while by-ref pays an extra arena
    hop plus per-object control traffic (put/locate/pull/free) — so
    by-ref loses raw rps here, with the gap closing as payload size
    amortizes the fixed overhead (the ``size_sweep`` rows). The by-ref
    path's wins live where its mechanisms bite and are measured
    elsewhere in this artifact: shared-payload broadcast under a paced
    uplink (coldstart phase) and fetch/dispatch overlap (r13
    BENCH_device_path prefetch A/B, arg_fetch p95 −53%)."""
    import numpy as np

    import ray_tpu  # noqa: F401
    from ray_tpu import serve
    from ray_tpu.core.config import get_config

    @serve.deployment(version="i1", num_replicas=2,
                      max_concurrent_queries=16,
                      ray_actor_options={"num_cpus": 1})
    class SumModel:
        def __call__(self, x):
            return float(np.asarray(x).sum())

    h = serve.run(SumModel.bind(), name="ingress", route_prefix=None,
                  timeout_s=120)
    cfg = get_config()
    old = cfg.serve_request_by_ref_min_bytes

    def half(payload, expect, by_ref: bool) -> dict:
        cfg.serve_request_by_ref_min_bytes = 512 * 1024 if by_ref else 0
        lats, lock = [], threading.Lock()
        stop_at = time.perf_counter() + INGRESS_HALF_S

        def client():
            while time.perf_counter() < stop_at:
                t0 = time.perf_counter()
                assert h.remote(payload).result(timeout_s=120) == expect
                dt = time.perf_counter() - t0
                with lock:
                    lats.append(dt)

        threads = [threading.Thread(target=client, daemon=True)
                   for _ in range(INGRESS_CLIENTS)]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=INGRESS_HALF_S * 4 + 60)
        wall = time.perf_counter() - t0
        return {"rps": round(len(lats) / wall, 1), **_lat_ms(lats)}

    def one_pair(mib: int) -> dict:
        payload = np.random.default_rng(5).random(mib * 2**20 // 8)
        expect = float(payload.sum())
        seed = half(payload, expect, False)
        new = half(payload, expect, True)
        pair = {
            "payload_mib": mib,
            "seed_inline": seed, "new_by_ref": new,
            "rps_ratio": round(new["rps"] / max(seed["rps"], 1e-9), 3),
            "p99_ratio": round(new["p99_ms"] /
                               max(seed["p99_ms"], 1e-9), 3)}
        print(json.dumps(pair), file=sys.stderr, flush=True)
        return pair

    try:
        payload = np.random.default_rng(5).random(
            INGRESS_PAYLOAD_MIB * 2**20 // 8)
        expect = float(payload.sum())
        half(payload, expect, True)   # warm both paths before timing
        half(payload, expect, False)
        pairs = [one_pair(INGRESS_PAYLOAD_MIB)
                 for _ in range(INGRESS_PAIRS)]
        # fixed-overhead amortization: one interleaved pair per larger
        # payload size (per-object control cost stays flat, bytes grow)
        sweep = [one_pair(mib) for mib in (8, 16)]
    finally:
        cfg.serve_request_by_ref_min_bytes = old
    serve.delete("ingress")
    rps = sorted(p["rps_ratio"] for p in pairs)
    p99 = sorted(p["p99_ratio"] for p in pairs)
    return {
        "payload_mib": INGRESS_PAYLOAD_MIB,
        "clients": INGRESS_CLIENTS,
        "pairs": pairs,
        "by_ref_over_inline_rps_median": rps[len(rps) // 2],
        "by_ref_over_inline_p99_median": p99[len(p99) // 2],
        "size_sweep": sweep,
        "note": "unpaced 2-vCPU loopback: both paths memcpy-bound and "
                "inline already rides the r8 zero-copy wire one hop, so "
                "by-ref pays an extra arena hop + per-object control "
                "traffic and loses rps here, amortizing with payload "
                "size (see size_sweep); its wins are the paced-uplink "
                "broadcast cold-start (this artifact) and the r13 "
                "prefetch overlap (BENCH_device_path.json)",
    }


# ================================================ legacy (r5) sections


def _build(model_name: str):
    import jax

    from ray_tpu.models.config import get_config, tiny_config
    from ray_tpu.models.transformer import init_params
    import jax.numpy as jnp

    if jax.default_backend() == "cpu" and model_name != "tiny":
        print("# cpu backend: falling back to tiny config", file=sys.stderr)
        model_name = "tiny"
    if model_name == "tiny":
        cfg = tiny_config()
    else:
        cfg = get_config(model_name, param_dtype=jnp.bfloat16)
    params = init_params(jax.random.key(0), cfg)
    return model_name, cfg, params


def _workload(rng_seed: int, max_prompt: int, max_new: int):
    """Deterministic chat-shaped request stream: (prompt, max_new).

    80% short answers (U[max/16, max/4]) and 20% long generations
    (U[max/2, max]) — the high-variance mix continuous batching exists
    for: a cohort pays max_new for every member, so the short majority
    is held hostage by the long tail."""
    import random

    rng = random.Random(rng_seed)

    def next_request():
        plen = rng.randint(max(4, max_prompt // 8), max_prompt)
        if rng.random() < 0.8:
            want = rng.randint(max(2, max_new // 16), max(4, max_new // 4))
        else:
            want = rng.randint(max_new // 2, max_new)
        return [rng.randint(1, 200) for _ in range(plen)], want
    return next_request


def _closed_loop(submit, *, clients: int, duration_s: float, seed: int,
                 max_prompt: int, max_new: int):
    """`clients` threads each submit-wait-repeat for `duration_s`;
    returns (latencies, useful_tokens, n_done, wall)."""
    latencies, tokens, lock = [], [0], threading.Lock()
    stop = time.perf_counter() + duration_s

    def client(cid: int):
        nxt = _workload(seed + cid, max_prompt, max_new)
        while time.perf_counter() < stop:
            prompt, want = nxt()
            t0 = time.perf_counter()
            out = submit(prompt, want)
            dt = time.perf_counter() - t0
            with lock:
                latencies.append(dt)
                tokens[0] += len(out)

    threads = [threading.Thread(target=client, args=(i,), daemon=True)
               for i in range(clients)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=duration_s * 4 + 120)
    wall = time.perf_counter() - t0
    return latencies, tokens[0], len(latencies), wall


def _percentiles(lat):
    lat = sorted(lat)

    def pct(p):
        if not lat:
            return 0.0
        return lat[min(len(lat) - 1, int(p / 100 * len(lat)))]
    return {"p50_s": round(pct(50), 4), "p95_s": round(pct(95), 4),
            "p99_s": round(pct(99), 4)}


def bench_continuous(cfg, params, *, slots, max_prompt, max_new,
                     clients, duration_s, decode_chunk=16,
                     fetch_every=4, max_inflight=6):
    from ray_tpu.models.engine import InferenceEngine

    eng = InferenceEngine(params, cfg, slots=slots,
                          max_prompt_len=max_prompt,
                          max_new_tokens=max_new,
                          decode_chunk=decode_chunk,
                          fetch_every=fetch_every,
                          max_inflight=max_inflight)
    # compile every (group, bucket) prefill + the decode chunk up front
    eng.warmup().serve_forever()
    try:
        def submit(prompt, want):
            return eng.generate(prompt, want, timeout=600)

        lat, toks, n, wall = _closed_loop(
            submit, clients=clients, duration_s=duration_s, seed=17,
            max_prompt=max_prompt, max_new=max_new)
        return {"engine": "continuous", "requests": n,
                "rps": round(n / wall, 2),
                "useful_tokens_per_s": round(toks / wall, 1),
                "decode_steps": eng.stats["decode_steps"],
                "prefills": eng.stats["prefills"],
                "prefill_dispatches": eng.stats["prefill_dispatches"],
                "fetches": eng.stats["fetches"],
                "fetch_wall_s": round(eng.stats["fetch_wall_s"], 2),
                "dispatch_wall_s": round(eng.stats["dispatch_wall_s"], 2),
                "cap_stalls": eng.stats["cap_stalls"],
                **_percentiles(lat)}
    finally:
        eng.shutdown()


def bench_cohort(cfg, params, *, slots, max_prompt, max_new,
                 clients, duration_s):
    """Round-3 cohort path: coalesce up to `slots` requests, run ONE
    generate() to max_new for all, trim per request — the policy
    continuous batching replaces."""
    import numpy as np

    import jax
    from ray_tpu.models.generate import generate
    from ray_tpu.serve.batching import _Batcher

    batcher = _Batcher(slots, 0.005)

    def run_batch(requests):
        prompts = [p for (p, _w) in requests]
        toks = np.zeros((slots, max_prompt), np.int32)
        start = np.zeros(slots, np.int32)
        for i, p in enumerate(prompts):
            toks[i, max_prompt - len(p):] = p
            start[i] = max_prompt - len(p)
        out = generate(params, toks, cfg, max_new_tokens=max_new,
                       greedy=True, rng=jax.random.key(0),
                       start=start)
        out = np.asarray(out)[:len(prompts), max_prompt:]
        return [out[i, :w].tolist() for i, (_p, w) in enumerate(requests)]

    # warm/compile the one batched program
    run_batch([([1, 2, 3], 2)])

    def submit(prompt, want):
        return batcher.submit(run_batch, (prompt, want))

    lat, toks, n, wall = _closed_loop(
        submit, clients=clients, duration_s=duration_s, seed=17,
        max_prompt=max_prompt, max_new=max_new)
    return {"engine": "cohort", "requests": n, "rps": round(n / wall, 2),
            "useful_tokens_per_s": round(toks / wall, 1),
            **_percentiles(lat)}


def bench_proxy(clients: int, duration_s: float) -> dict:
    """Proxy-level RPS/latency on a trivial deployment (measures the
    asyncio ingress + router + replica hop, NOT model compute; ref:
    the reference's serve microbenchmarks hit a noop deployment the
    same way). Keep-alive HTTP/1.1 connections, closed loop."""
    import http.client

    import ray_tpu
    from ray_tpu import serve

    ray_tpu.init(num_cpus=4, num_tpus=0, ignore_reinit_error=True)

    @serve.deployment(max_concurrent_queries=64)
    def noop(payload):
        return payload

    serve.run(noop.bind(), name="proxybench", route_prefix="/noop")
    port = serve.start()

    lat, lock = [], threading.Lock()
    stop_at = time.perf_counter() + duration_s

    def client():
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
        body = json.dumps({"k": 1})
        try:
            while time.perf_counter() < stop_at:
                t0 = time.perf_counter()
                conn.request("POST", "/noop", body=body)
                resp = conn.getresponse()
                resp.read()
                dt = time.perf_counter() - t0
                if resp.status == 200:
                    with lock:
                        lat.append(dt)
        finally:
            conn.close()

    threads = [threading.Thread(target=client, daemon=True)
               for _ in range(clients)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=duration_s * 2 + 60)
    wall = time.perf_counter() - t0
    serve.shutdown()
    return {"deployment": "noop", "clients": clients,
            "requests": len(lat), "rps": round(len(lat) / wall, 1),
            **_percentiles(lat)}


def bench_llm(args) -> dict:
    import jax

    model_name, cfg, params = _build(args.model)
    if model_name == "tiny":
        args.duration = min(args.duration, 10.0)
    cont = bench_continuous(cfg, params, slots=args.slots,
                            max_prompt=args.max_prompt,
                            max_new=args.max_new, clients=args.clients,
                            duration_s=args.duration,
                            decode_chunk=args.decode_chunk,
                            fetch_every=args.fetch_every,
                            max_inflight=args.max_inflight)
    print(json.dumps(cont), file=sys.stderr)
    coh = bench_cohort(cfg, params, slots=args.slots,
                       max_prompt=args.max_prompt, max_new=args.max_new,
                       clients=args.clients, duration_s=args.duration)
    print(json.dumps(coh), file=sys.stderr)
    return {
        "model": model_name,
        "backend": jax.default_backend(),
        "slots": args.slots,
        "clients": args.clients,
        "continuous": cont,
        "cohort": coh,
        "continuous_over_cohort_tokens":
            round(cont["useful_tokens_per_s"] /
                  max(coh["useful_tokens_per_s"], 1e-9), 3),
        "continuous_over_cohort_p99":
            round(cont["p99_s"] / max(coh["p99_s"], 1e-9), 3),
    }


# ================================================================ main


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--phases", default="coldstart,autoscale,ingress",
                    help="comma list: coldstart,autoscale,ingress,"
                         "proxy,llm")
    ap.add_argument("--out", default="SERVE_BENCH_r14.json")
    ap.add_argument("--agents", type=int, default=FLEET,
                    help="real agent processes for the r14 phases")
    # legacy llm/proxy knobs
    ap.add_argument("--model", default="llama3-1b")
    ap.add_argument("--duration", type=float, default=45.0)
    ap.add_argument("--clients", type=int, default=24)
    ap.add_argument("--slots", type=int, default=8)
    ap.add_argument("--max-prompt", type=int, default=64)
    ap.add_argument("--max-new", type=int, default=64)
    ap.add_argument("--decode-chunk", type=int, default=16)
    ap.add_argument("--fetch-every", type=int, default=4)
    ap.add_argument("--max-inflight", type=int, default=6)
    ap.add_argument("--proxy-clients", type=int, default=16)
    ap.add_argument("--proxy-duration", type=float, default=15.0)
    args = ap.parse_args()
    phases = {p.strip() for p in args.phases.split(",") if p.strip()}

    result = {
        "benchmark": "serve_at_scale" if phases & {
            "coldstart", "autoscale", "ingress"}
        else "llm_serving_continuous_batching",
        "hardware": f"single host, {os.cpu_count()} cpu, "
                    f"{args.agents} real agent processes",
    }

    def flush():
        with open(args.out, "w") as f:
            json.dump(result, f, indent=1)

    lag = _LoopLag()
    r14 = phases & {"coldstart", "autoscale", "ingress"}
    cluster, handles = (None, [])
    try:
        if r14:
            print(f"# booting cluster ({args.agents} agents)",
                  file=sys.stderr, flush=True)
            cluster, handles = _boot_cluster(args.agents)
        if "coldstart" in phases:
            print("# coldstart", file=sys.stderr, flush=True)
            lag.snap()
            result["coldstart"] = bench_coldstart()
            result["coldstart"]["loop_lag"] = lag.delta()
            print(json.dumps(result["coldstart"]), file=sys.stderr)
            flush()
        if "autoscale" in phases or "ingress" in phases:
            # the r14 data-plane pacing exists for the coldstart
            # transfer regime; request/latency phases run unpaced
            _head()._transfer_server.egress_limit_bps = 0
        # autoscale runs BEFORE ingress: the SLO-burn signal reads the
        # cumulative per-func phase histograms, and the ingress A/B's
        # deliberately slow large-payload requests would pre-arm it
        if "autoscale" in phases:
            print("# autoscale", file=sys.stderr, flush=True)
            lag.snap()
            result["autoscale"] = bench_autoscale()
            result["autoscale"]["loop_lag"] = lag.delta()
            print(json.dumps(result["autoscale"]), file=sys.stderr)
            flush()
        if "ingress" in phases:
            print("# ingress A/B", file=sys.stderr, flush=True)
            lag.snap()
            result["ingress"] = bench_ingress()
            result["ingress"]["loop_lag"] = lag.delta()
            print(json.dumps(result["ingress"]), file=sys.stderr)
            flush()
        if "proxy" in phases:
            lag.snap()
            result["proxy"] = bench_proxy(args.proxy_clients,
                                          args.proxy_duration)
            result["proxy"]["loop_lag"] = lag.delta()
            print(json.dumps({"proxy": result["proxy"]}), file=sys.stderr)
            flush()
        if "llm" in phases:
            result.update(bench_llm(args))
            flush()
    finally:
        if r14 and cluster is not None:
            try:
                from ray_tpu import serve

                serve.shutdown()
            except Exception:  # noqa: BLE001
                pass
            for h in handles:
                h.terminate()
            cluster.shutdown()

    gates = {}
    for section in ("coldstart", "autoscale"):
        gates.update({f"{section}.{k}": v for k, v in
                      result.get(section, {}).get("gates", {}).items()})
    result["all_gates_pass"] = all(gates.values()) if gates else None
    flush()
    print(json.dumps(result))


if __name__ == "__main__":
    main()
