"""LLM serving benchmark: continuous batching vs cohort batching.

Measures the BASELINE.md north-star row 4 workload shape ("Serve
Llama-3, continuous batching, RPS/p99") on the attached device with a
closed-loop client pool issuing mixed-length generations, and writes
`SERVE_BENCH_r5.json`:

  - engine=continuous: `ray_tpu.models.engine.InferenceEngine` —
    per-step slot admission/eviction (a finished sequence's slot is
    refilled on the next decode step).
  - engine=cohort: the round-3 `@serve.batch`-style path — requests
    coalesce into a batch that runs `generate()` to the full
    max_new_tokens, so every member pays for the longest.

Both run the SAME model, client pool, and request distribution, so the
continuous/cohort ratio isolates the scheduling policy. Reported per
engine: requests/s, useful tokens/s, latency p50/p95/p99.

Run: `python bench_serve.py [--model llama3-1b] [--duration 45]`.
CPU fallback uses the tiny config (smoke numbers, not benchmarks).
"""

import argparse
import json
import os
import sys
import threading
import time

# the host sitecustomize force-registers the axon TPU backend, overriding
# the standard JAX_PLATFORMS env var; restore the expected semantics
if os.environ.get("JAX_PLATFORMS"):
    import jax
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

# persistent compilation cache: warmup compiles the full (bucket x group)
# program menu through the tunneled backend (~minutes); cache them so
# repeat runs measure serving, not compilation
import jax  # noqa: E402

jax.config.update("jax_compilation_cache_dir",
                  os.path.join(os.path.dirname(os.path.abspath(__file__)),
                               ".jax_cache"))
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)


def _build(model_name: str):
    import jax

    from ray_tpu.models.config import get_config, tiny_config
    from ray_tpu.models.transformer import init_params
    import jax.numpy as jnp

    if jax.default_backend() == "cpu" and model_name != "tiny":
        print("# cpu backend: falling back to tiny config", file=sys.stderr)
        model_name = "tiny"
    if model_name == "tiny":
        cfg = tiny_config()
    else:
        cfg = get_config(model_name, param_dtype=jnp.bfloat16)
    params = init_params(jax.random.key(0), cfg)
    return model_name, cfg, params


def _workload(rng_seed: int, max_prompt: int, max_new: int):
    """Deterministic chat-shaped request stream: (prompt, max_new).

    80% short answers (U[max/16, max/4]) and 20% long generations
    (U[max/2, max]) — the high-variance mix continuous batching exists
    for: a cohort pays max_new for every member, so the short majority
    is held hostage by the long tail."""
    import random

    rng = random.Random(rng_seed)

    def next_request():
        plen = rng.randint(max(4, max_prompt // 8), max_prompt)
        if rng.random() < 0.8:
            want = rng.randint(max(2, max_new // 16), max(4, max_new // 4))
        else:
            want = rng.randint(max_new // 2, max_new)
        return [rng.randint(1, 200) for _ in range(plen)], want
    return next_request


def _closed_loop(submit, *, clients: int, duration_s: float, seed: int,
                 max_prompt: int, max_new: int):
    """`clients` threads each submit-wait-repeat for `duration_s`;
    returns (latencies, useful_tokens, n_done, wall)."""
    latencies, tokens, lock = [], [0], threading.Lock()
    stop = time.perf_counter() + duration_s

    def client(cid: int):
        nxt = _workload(seed + cid, max_prompt, max_new)
        while time.perf_counter() < stop:
            prompt, want = nxt()
            t0 = time.perf_counter()
            out = submit(prompt, want)
            dt = time.perf_counter() - t0
            with lock:
                latencies.append(dt)
                tokens[0] += len(out)

    threads = [threading.Thread(target=client, args=(i,), daemon=True)
               for i in range(clients)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=duration_s * 4 + 120)
    wall = time.perf_counter() - t0
    return latencies, tokens[0], len(latencies), wall


def _percentiles(lat):
    lat = sorted(lat)

    def pct(p):
        if not lat:
            return 0.0
        return lat[min(len(lat) - 1, int(p / 100 * len(lat)))]
    return {"p50_s": round(pct(50), 4), "p95_s": round(pct(95), 4),
            "p99_s": round(pct(99), 4)}


def bench_continuous(cfg, params, *, slots, max_prompt, max_new,
                     clients, duration_s, decode_chunk=16,
                     fetch_every=4, max_inflight=6):
    from ray_tpu.models.engine import InferenceEngine

    eng = InferenceEngine(params, cfg, slots=slots,
                          max_prompt_len=max_prompt,
                          max_new_tokens=max_new,
                          decode_chunk=decode_chunk,
                          fetch_every=fetch_every,
                          max_inflight=max_inflight)
    # compile every (group, bucket) prefill + the decode chunk up front
    eng.warmup().serve_forever()
    try:
        def submit(prompt, want):
            return eng.generate(prompt, want, timeout=600)

        lat, toks, n, wall = _closed_loop(
            submit, clients=clients, duration_s=duration_s, seed=17,
            max_prompt=max_prompt, max_new=max_new)
        return {"engine": "continuous", "requests": n,
                "rps": round(n / wall, 2),
                "useful_tokens_per_s": round(toks / wall, 1),
                "decode_steps": eng.stats["decode_steps"],
                "prefills": eng.stats["prefills"],
                "prefill_dispatches": eng.stats["prefill_dispatches"],
                "fetches": eng.stats["fetches"],
                "fetch_wall_s": round(eng.stats["fetch_wall_s"], 2),
                "dispatch_wall_s": round(eng.stats["dispatch_wall_s"], 2),
                "cap_stalls": eng.stats["cap_stalls"],
                **_percentiles(lat)}
    finally:
        eng.shutdown()


def bench_cohort(cfg, params, *, slots, max_prompt, max_new,
                 clients, duration_s):
    """Round-3 cohort path: coalesce up to `slots` requests, run ONE
    generate() to max_new for all, trim per request — the policy
    continuous batching replaces."""
    import numpy as np

    import jax
    from ray_tpu.models.generate import generate
    from ray_tpu.serve.batching import _Batcher

    batcher = _Batcher(slots, 0.005)

    def run_batch(requests):
        prompts = [p for (p, _w) in requests]
        toks = np.zeros((slots, max_prompt), np.int32)
        start = np.zeros(slots, np.int32)
        for i, p in enumerate(prompts):
            toks[i, max_prompt - len(p):] = p
            start[i] = max_prompt - len(p)
        out = generate(params, toks, cfg, max_new_tokens=max_new,
                       greedy=True, rng=jax.random.key(0),
                       start=start)
        out = np.asarray(out)[:len(prompts), max_prompt:]
        return [out[i, :w].tolist() for i, (_p, w) in enumerate(requests)]

    # warm/compile the one batched program
    run_batch([([1, 2, 3], 2)])

    def submit(prompt, want):
        return batcher.submit(run_batch, (prompt, want))

    lat, toks, n, wall = _closed_loop(
        submit, clients=clients, duration_s=duration_s, seed=17,
        max_prompt=max_prompt, max_new=max_new)
    return {"engine": "cohort", "requests": n, "rps": round(n / wall, 2),
            "useful_tokens_per_s": round(toks / wall, 1),
            **_percentiles(lat)}


def bench_proxy(clients: int, duration_s: float) -> dict:
    """Proxy-level RPS/latency on a trivial deployment (measures the
    asyncio ingress + router + replica hop, NOT model compute; ref:
    the reference's serve microbenchmarks hit a noop deployment the
    same way). Keep-alive HTTP/1.1 connections, closed loop."""
    import http.client

    import ray_tpu
    from ray_tpu import serve

    ray_tpu.init(num_cpus=4, num_tpus=0, ignore_reinit_error=True)

    @serve.deployment(max_concurrent_queries=64)
    def noop(payload):
        return payload

    serve.run(noop.bind(), name="proxybench", route_prefix="/noop")
    port = serve.start()

    lat, lock = [], threading.Lock()
    stop_at = time.perf_counter() + duration_s

    def client():
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
        body = json.dumps({"k": 1})
        try:
            while time.perf_counter() < stop_at:
                t0 = time.perf_counter()
                conn.request("POST", "/noop", body=body)
                resp = conn.getresponse()
                resp.read()
                dt = time.perf_counter() - t0
                if resp.status == 200:
                    with lock:
                        lat.append(dt)
        finally:
            conn.close()

    threads = [threading.Thread(target=client, daemon=True)
               for _ in range(clients)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=duration_s * 2 + 60)
    wall = time.perf_counter() - t0
    serve.shutdown()
    return {"deployment": "noop", "clients": clients,
            "requests": len(lat), "rps": round(len(lat) / wall, 1),
            **_percentiles(lat)}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default="llama3-1b")
    ap.add_argument("--duration", type=float, default=45.0)
    ap.add_argument("--clients", type=int, default=24)
    ap.add_argument("--slots", type=int, default=8)
    ap.add_argument("--max-prompt", type=int, default=64)
    ap.add_argument("--max-new", type=int, default=64)
    ap.add_argument("--out", default="SERVE_BENCH_r5.json")
    ap.add_argument("--decode-chunk", type=int, default=16)
    ap.add_argument("--fetch-every", type=int, default=4)
    ap.add_argument("--max-inflight", type=int, default=6)
    ap.add_argument("--proxy-only", action="store_true",
                    help="measure the HTTP ingress only (no model)")
    ap.add_argument("--proxy-clients", type=int, default=16)
    ap.add_argument("--proxy-duration", type=float, default=15.0)
    ap.add_argument("--skip-cohort", action="store_true",
                    help="iterate on the continuous engine only")
    args = ap.parse_args()

    # proxy-level section first: it needs no accelerator, so the
    # artifact gets ingress numbers even when the model backend is down
    proxy = bench_proxy(args.proxy_clients, args.proxy_duration)
    print(json.dumps({"proxy": proxy}), file=sys.stderr)
    if args.proxy_only:
        result = {"benchmark": "llm_serving_continuous_batching",
                  "proxy": proxy}
        with open(args.out, "w") as f:
            json.dump(result, f, indent=1)
        print(json.dumps(result))
        return

    import jax

    model_name, cfg, params = _build(args.model)
    if model_name == "tiny":
        args.duration = min(args.duration, 10.0)

    cont = bench_continuous(cfg, params, slots=args.slots,
                            max_prompt=args.max_prompt,
                            max_new=args.max_new, clients=args.clients,
                            duration_s=args.duration,
                            decode_chunk=args.decode_chunk,
                            fetch_every=args.fetch_every,
                            max_inflight=args.max_inflight)
    print(json.dumps(cont), file=sys.stderr)
    if args.skip_cohort:
        print(json.dumps(cont))
        return
    coh = bench_cohort(cfg, params, slots=args.slots,
                       max_prompt=args.max_prompt, max_new=args.max_new,
                       clients=args.clients, duration_s=args.duration)
    print(json.dumps(coh), file=sys.stderr)

    result = {
        "benchmark": "llm_serving_continuous_batching",
        "model": model_name,
        "backend": jax.default_backend(),
        "slots": args.slots,
        "clients": args.clients,
        "max_prompt_len": args.max_prompt,
        "max_new_tokens": args.max_new,
        "duration_s": args.duration,
        # derived from _workload: keep in sync with that function
        "request_distribution":
            (f"prompt ~ U[{max(4, args.max_prompt // 8)}, "
             f"{args.max_prompt}]; new_tokens ~ 80% "
             f"U[{max(2, args.max_new // 16)}, {max(4, args.max_new // 4)}]"
             f" + 20% U[{args.max_new // 2}, {args.max_new}]"),
        "proxy": proxy,
        "continuous": cont,
        "cohort": coh,
        # both ratios are continuous/cohort: tokens >1 and p99 <1 mean
        # the continuous engine wins on both axes
        "continuous_over_cohort_tokens":
            round(cont["useful_tokens_per_s"] /
                  max(coh["useful_tokens_per_s"], 1e-9), 3),
        "continuous_over_cohort_p99":
            round(cont["p99_s"] / max(coh["p99_s"], 1e-9), 3),
    }
    with open(args.out, "w") as f:
        json.dump(result, f, indent=1)
    print(json.dumps(result))


if __name__ == "__main__":
    main()
