"""In-process engine-knob sweep: build + compile once, test many
(decode_chunk, max_inflight) configs against the bench_serve workload.
Tuning tool only — the checked-in artifact comes from bench_serve.py."""

import json
import os
import sys

if os.environ.get("JAX_PLATFORMS"):
    import jax
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

import bench_serve as bs


def main():
    import argparse

    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default="llama3-1b")
    ap.add_argument("--duration", type=float, default=12.0)
    ap.add_argument("--clients", type=int, default=24)
    ap.add_argument("--configs", default="16:4,16:3,12:4,24:3")
    args = ap.parse_args()

    model_name, cfg, params = bs._build(args.model)
    dur = min(args.duration, 6.0) if model_name == "tiny" else args.duration
    for spec in args.configs.split(","):
        chunk, inflight = (int(x) for x in spec.split(":"))
        r = bs.bench_continuous(
            cfg, params, slots=8, max_prompt=64, max_new=64,
            clients=args.clients, duration_s=dur,
            decode_chunk=chunk, fetch_every=4, max_inflight=inflight)
        r["decode_chunk"], r["max_inflight"] = chunk, inflight
        print(json.dumps(r), flush=True)
        print(json.dumps(r), file=sys.stderr, flush=True)


if __name__ == "__main__":
    main()
