"""Cooperative-broadcast benchmark: cold one-to-many object distribution.

Prints ONE JSON line (and writes BENCH_broadcast.json when run with
--write): for each N in {2, 4, 8} pullers, an INTERLEAVED A/B of

  seed plan:  every puller streams the full object from the ONE sealed
              holder (the pre-r9 planner: N x S bytes off the root's
              uplink, serialized),
  coop plan:  the r9 broadcast tree — the root serves at most
              ``broadcast_fanout`` streams and every other puller rides
              an in-progress peer's partial-object relay
              (object_transfer.py chunk re-serving).

Topology: 1 root host + N puller hosts (each a shm store + a
TransferServer + an ObjectPuller) on one IO loop over loopback TCP.
Every server's egress rides a SHARED token bucket
(``egress_limit_bps``), emulating a saturated host uplink — the regime
a weight broadcast actually bottlenecks on (a 200 MB/s DCN-ish link;
unpaced loopback numbers measure memcpy contention, not links). The
bench drives the transfer layer with the same (source, relay, failover)
assignments the head planner produces for N simultaneous cold pullers —
the planner itself (head._plan_pull_sources) is integration-tested in
tests/test_broadcast.py; keeping it out of the loop here removes
head/worker scheduling noise from the measurement.

Methodology (MICROBENCH_r6): trials alternate seed,coop back-to-back —
PAIRS pairs per N — and the headline ratio is the MEDIAN OF PAIRWISE
wall-clock ratios, so host-load drift hits both plans equally. Holder
egress is exact (the root server's bytes_served counter delta).
"""

import json
import statistics
import sys
import threading
import time

import numpy as np

from ray_tpu.core import protocol as P
from ray_tpu.core.ids import ObjectID
from ray_tpu.core.object_store import ShmObjectStore
from ray_tpu.core.object_transfer import ObjectPuller, TransferServer

PAYLOAD_MB = 64
FANOUT = 2           # the broadcast_fanout default
PULLER_COUNTS = (2, 4, 8)
PAIRS = 3
ARENA = (PAYLOAD_MB + 16) * 1024 * 1024
# Shared per-host uplink. Sized so the PACING dominates, not the 2-vCPU
# host's loopback/memcpy ceiling (~300-500 MB/s aggregate): the coop
# tree compresses the same 512 MiB into ~1/4 the wall-clock, and at 200
# MiB/s that aggregate demand ran into CPU, masking the link win the
# plan exists for. 40 MiB/s keeps both plans link-bound end to end.
LINK_BPS = 40 * 1024 * 1024


class Host:
    def __init__(self, io, name):
        self.store = ShmObjectStore(
            f"rtpu_bb_{name}_{ObjectID.from_random().hex()[:6]}", ARENA,
            create=True)

        def read(oid, _s=self.store):
            got = _s.get(oid)
            if got is None:
                return None
            d, m = got
            return d, bytes(m), (lambda: _s.release(oid))

        self.server = TransferServer(io, read, advertise_ip="127.0.0.1",
                                     partial_fn=self.store.partial)
        self.server.egress_limit_bps = LINK_BPS
        self.puller = ObjectPuller(io, self.store)

    def close(self):
        self.puller.close()
        self.server.close()
        self.store.close()


def plan_coop(root_addr, puller_addrs, fanout=FANOUT):
    """The source assignment head._plan_pull_sources makes for N
    SIMULTANEOUS cold pullers (none has completed, so no slot ever
    releases mid-plan): roots until saturated, then the least-loaded
    in-progress relay. Returns [(source_addr, is_relay)] per puller."""
    serving = {}
    inprog = []
    out = []
    for addr in puller_addrs:
        if serving.get(root_addr, 0) < fanout:
            src, relay = root_addr, False
        else:
            free = [a for a in inprog if serving.get(a, 0) < fanout]
            src, relay = (min(free, key=lambda a: serving.get(a, 0)), True) \
                if free else (root_addr, False)
        serving[src] = serving.get(src, 0) + 1
        out.append((src, relay))
        inprog.append(addr)
    return out


def run_trial(root, pullers, oid, size, coop):
    """One cold broadcast; returns (wallclock_s, root_egress_bytes)."""
    for h in pullers:
        h.store.delete(oid)
    root_addr = root.server.addr
    if coop:
        plan = plan_coop(root_addr, [h.server.addr for h in pullers])
    else:
        plan = [(root_addr, False)] * len(pullers)
    ok = [False] * len(pullers)

    def pull(i, src, relay):
        addrs = [src] if src == root_addr else [src, root_addr]
        ok[i] = pullers[i].puller.pull(
            oid, addrs, timeout=600, size_hint=size, max_sources=1,
            relay_addrs=[src] if relay else ())

    egress0 = root.server.bytes_served
    threads = [threading.Thread(target=pull, args=(i, src, relay))
               for i, (src, relay) in enumerate(plan)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    dt = time.perf_counter() - t0
    if not all(ok):
        print(json.dumps({"metric": "broadcast", "value": 0,
                          "error": f"pull failed (coop={coop})"}))
        sys.exit(1)
    return dt, root.server.bytes_served - egress0


def main():
    write = "--write" in sys.argv
    io = P.IOLoop("bench-bcast-io")
    io.start()
    payload = np.random.default_rng(0).integers(
        0, 256, PAYLOAD_MB * 1024 * 1024, dtype=np.uint8).tobytes()
    oid = ObjectID.from_random()
    root = Host(io, "root")
    buf = root.store.create(oid, len(payload))
    buf[:] = payload
    del buf
    root.store.seal(oid)
    size = len(payload)
    hosts = [Host(io, f"p{i}") for i in range(max(PULLER_COUNTS))]
    results = {}
    try:
        # warm every code path once (unpaced) + verify bytes end to end
        for h in hosts:
            h.server.egress_limit_bps = 0
        root.server.egress_limit_bps = 0
        run_trial(root, hosts[:2], oid, size, coop=True)
        got = hosts[1].store.get(oid)
        d, m = got
        assert bytes(d) == payload, "relayed bytes corrupt"
        del d, m, got
        hosts[1].store.release(oid)
        for h in hosts:
            h.server.egress_limit_bps = LINK_BPS
        root.server.egress_limit_bps = LINK_BPS

        for n in PULLER_COUNTS:
            sub = hosts[:n]
            pairs = []
            egress = {}
            for p in range(PAIRS):
                # alternate which plan runs first within each pair so
                # slow host windows hit both sides equally
                order = (False, True) if p % 2 == 0 else (True, False)
                trial = {}
                for coop in order:
                    dt, eg = run_trial(root, sub, oid, size, coop)
                    trial[coop] = dt
                    egress[coop] = eg  # stable across trials (exact plan)
                pairs.append(trial[True] / trial[False])
            results[str(n)] = {
                "seed_wallclock_s": round(trial[False], 3),
                "coop_wallclock_s": round(trial[True], 3),
                "ratio_vs_seed_median_of_pairwise": round(
                    statistics.median(pairs), 3),
                "pairwise_ratios": [round(r, 3) for r in pairs],
                "root_egress_seed_bytes": egress[False],
                "root_egress_coop_bytes": egress[True],
                "root_egress_coop_x_S": round(egress[True] / size, 2),
            }
        headline = results[str(max(PULLER_COUNTS))]
        out = {
            "metric": "broadcast_cold_1_to_8",
            "value": headline["ratio_vs_seed_median_of_pairwise"],
            "unit": "x_seed_wallclock (lower is better)",
            "payload_mb": PAYLOAD_MB,
            "fanout": FANOUT,
            "link_mb_s_per_host": LINK_BPS // (1024 * 1024),
            "pairs_per_n": PAIRS,
            "method": "interleaved seed,coop pairs; median of pairwise "
                      "wall-clock ratios (MICROBENCH_r6 methodology); "
                      "egress from the root server's byte counter",
            "per_pullers": results,
        }
        print(json.dumps(out))
        if write:
            with open("BENCH_broadcast.json", "w") as f:
                json.dump(out, f, indent=1)
    finally:
        root.close()
        for h in hosts:
            h.close()
        io.stop()


if __name__ == "__main__":
    main()
