"""MPMD pipeline A/B bench -> BENCH_pipeline_r15.json (+ r16 repair
phases -> BENCH_repair_r16.json, r18 DP/collective phases ->
BENCH_dp_r18.json).

Phases (bench_scale conventions: ``--phases``/``--out``, per-phase
``loop_lag`` blocks, JSON merge across processes so phases can run as
separate processes; interleaved A/B pairs, median-of-pairwise — this
host has multi-x run drift, so only paired ratios in one window mean
anything). Inter-node links are PACED (`RAY_TPU_HOST_EGRESS_LIMIT_BPS`
seeds every process's transfer-server token bucket) — unpaced loopback
finishes a 2 MiB activation hop in ~1 ms and hides exactly the transfer
the pipeline exists to overlap.

1. **schedule** — 4-stage x 8-microbatch 1F1B vs the sequential
   single-program baseline: the SAME raw stages (sleep-paced compute,
   2 MiB activations) run (a) one actor per node with store-to-store
   activation handoff + prefetch-overlapped pulls, vs (b) one actor
   executing all four stages per microbatch, no handoff at all.
   Sequential wall is M*S*(Tf+Tb); 1F1B's is ~(M+S-1)*(Tf+Tb) plus any
   transfer it fails to hide. Gate: wall ratio <= 0.5.

2. **hints** — same pipeline, ``arg_prefetch_enabled`` ON vs OFF. Actor
   tasks have no grant-time prefetch, so the dispatch-time
   PREFETCH_HINT path (r14 actor keys + r15 coalescing) is the ONLY
   speculation — toggling it isolates the handoff-overlap win on the
   consuming stages' ``arg_fetch`` p95 (the pull starts while the
   consumer still computes the previous microbatch instead of cold
   inside ``_decode_args``). Rounds are tagged via ``Pipeline.
   name_prefix`` so the cumulative phase histograms stay separable.
   Gates: median p95 reduction >= 30%, prefetch_wasted < 10% of issued.

3. **chaos** (r16, -> ``--repair-out`` BENCH_repair_r16.json) —
   4-stage x 8-microbatch 1F1B with wave-boundary stage checkpoints;
   kill -9 of a mid-pipeline stage's agent node mid-batch. Gates: the
   job completes with losses/grads NUMERICALLY EQUAL to the no-fault
   driver-side oracle, ``repair_redo_microbatches`` <= one wave, and
   repaired wall clock <= 2x the no-fault run.

4. **drain** (r16, same artifact) — graceful ``drain_node`` of a node
   hosting a live stage mid-batch. Gates: zero failed tasks (the stage
   migrates at a wave boundary BEFORE the shutdown),
   ``drain_migrated_leases`` >= 1, grads equal the oracle, and the
   drained node's object copies remain fetchable from survivors.

5. **collective** (r18, -> ``--dp-out`` BENCH_dp_r18.json) — ring vs
   rendezvous allreduce at 64 MiB x 4 ranks, one rank per paced agent
   node. Gates: ring effective bandwidth >= 2x the rendezvous
   baseline (median-of-pairs), and ZERO collective payload bytes
   through the driver (head relay-bytes + head-host transfer-server
   counters flat across the ring rounds).

6. **dp** (r18, same artifact) — the PP x DP composition:
   3-stage x 12-microbatch 1F1B at replicas_per_stage = 2 vs 1.
   Gates: wall ratio <= 0.65 (ideal (M/2+S-1)/(M+S-1) ~ 0.57 at this
   shape), grads within 1e-5 of the driver-side oracle, replica pairs
   bit-identical after the batch-end bucketed grad all-reduce.

Run: python bench_pipeline.py [--pairs 3]
     [--phases schedule,hints,chaos,drain,dp,collective]
     [--out BENCH_pipeline_r15.json] [--repair-out BENCH_repair_r16.json]
     [--dp-out BENCH_dp_r18.json]
"""

import argparse
import json
import os
import statistics
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("TPU_CHIPS", "0")
os.environ.setdefault("RAY_TPU_PRESTART_WORKERS", "0")

# paced inter-node links: every process (head host + each agent
# "host") seeds its TransferServer bucket from this env var at init
LINK_MIB_S = 40
os.environ.setdefault("RAY_TPU_HOST_EGRESS_LIMIT_BPS",
                      str(LINK_MIB_S * 1024 * 1024))

ACT_ELEMS = (1 << 20) // 4  # 1 MiB fp32 activations
TF, TB = 0.4, 0.4           # per-stage fwd/bwd compute (sleep-paced):
#                             deep enough that the fixed per-hop costs
#                             (paced 25 ms activation/grad pulls on the
#                             B-chain critical path, driver dispatch
#                             round-trips, 2-vCPU scheduler jitter)
#                             amortize — at 0.25 s/op they ate ~35% of
#                             the schedule's ideal win on this host
STAGES = 4
MICRO = 8


def _median(xs):
    return statistics.median(xs) if xs else 0.0


class _LoopLag:
    """Per-phase head loop-lag capture (bench_scale convention)."""

    def snap(self):
        from ray_tpu import state

        try:
            row = state.io_loop_stats()[0]
        except Exception:  # noqa: BLE001 — no cluster yet
            row = {}
        self._before = row
        return self

    def delta(self) -> dict:
        from ray_tpu import state

        try:
            row = state.io_loop_stats()[0]
        except Exception:  # noqa: BLE001
            return {}
        before = getattr(self, "_before", {})
        return {
            "loop_lag_ms_p50": row.get("loop_lag_ms_p50", 0.0),
            "loop_lag_ms_p99": row.get("loop_lag_ms_p99", 0.0),
            "loop_lag_ms_max": row.get("loop_lag_ms_max", 0.0),
            "slow_events": row.get("slow_events", 0)
            - before.get("slow_events", 0),
            "fold_queue_drops": row.get("fold_queue_drops", 0)
            - before.get("fold_queue_drops", 0),
        }


def _mk_stages(n_stages, tf, tb, grad_elems=ACT_ELEMS):
    """Raw-mode stages: sleep-paced compute, fresh 2 MiB activations
    (and, by default, grads) each hop, scalar loss off the last stage.
    ``grad_elems`` small makes backward cotangents inline — the hints
    phase uses it to isolate the FORWARD activation handoff."""
    import numpy as np

    def fwd_mid(params, x):
        time.sleep(tf)
        return np.full(ACT_ELEMS, 1.0, np.float32), None

    def fwd_last(params, x):
        time.sleep(tf)
        return float(np.asarray(x).ravel()[0]), None

    def bwd_mid(params, saved, g):
        time.sleep(tb)
        return None, np.full(grad_elems, 0.5, np.float32)

    def bwd_first(params, saved, g):
        time.sleep(tb)
        return None, None

    from ray_tpu.train.pipeline import PipelineStage

    stages = []
    for k in range(n_stages):
        stages.append(PipelineStage(
            fwd=fwd_last if k == n_stages - 1 else fwd_mid,
            bwd=bwd_first if k == 0 else bwd_mid))
    return stages


HINT_ACT_ELEMS = (1 << 20) // 4  # 1 MiB activations (hints phase)


def _mk_hetero_stages(tfs, tb):
    """Raw-mode stages with per-stage forward times (each consumer
    slower than its producer -> real backlog at every hop) and tiny
    inline backward cotangents."""
    import numpy as np

    from ray_tpu.train.pipeline import PipelineStage

    n = len(tfs)

    def mk_fwd(tf, last):
        def fwd(params, x):
            time.sleep(tf)
            if last:
                return float(np.asarray(x).ravel()[0]), None
            return np.full(HINT_ACT_ELEMS, 1.0, np.float32), None

        return fwd

    def bwd_mid(params, saved, g):
        time.sleep(tb)
        return None, np.full(8, 0.5, np.float32)

    def bwd_first(params, saved, g):
        time.sleep(tb)
        return None, None

    return [PipelineStage(fwd=mk_fwd(tfs[k], k == n - 1),
                          bwd=bwd_first if k == 0 else bwd_mid)
            for k in range(n)]


def _start_cluster(n_remote, store_bytes=512 << 20):
    from ray_tpu.cluster_utils import Cluster

    cluster = Cluster(initialize_head=True,
                      head_node_args={"num_cpus": 1, "num_tpus": 0,
                                      "object_store_memory": 1 << 30})
    handles = [cluster.add_remote_node(num_cpus=1,
                                       object_store_memory=store_bytes)
               for _ in range(n_remote)]
    return cluster, handles


# ------------------------------------------------------------ schedule


def bench_schedule(pairs: int) -> dict:
    import ray_tpu
    import ray_tpu.core.api as core_api
    from ray_tpu.core.api import NodeAffinitySchedulingStrategy
    from ray_tpu.train.pipeline import Pipeline, SingleProgramPipeline

    cluster, handles = _start_cluster(STAGES)
    head = core_api._head
    lag = _LoopLag().snap()
    stages = _mk_stages(STAGES, TF, TB)
    mbs = [float(i) for i in range(MICRO)]
    try:
        # pipeline: auto placement round-robins the 5 alive nodes —
        # stage0 lands on the head host, stages 1-3 on agents, so every
        # handoff crosses a paced link; the baseline actor gets the
        # remaining agent node so it never shares a CPU with a stage
        pipe = Pipeline(stages, schedule="1f1b")
        seq = SingleProgramPipeline(
            stages, scheduling_strategy=NodeAffinitySchedulingStrategy(
                handles[-1].node_idx))
        # warm both: actor/worker spawn + first-touch paths
        pipe.run_batch(mbs[:2], by_ref_min_bytes=0)
        seq.run_batch(mbs[:2], by_ref_min_bytes=0)
        rows = []
        served0 = head._transfer_server.bytes_served
        for i in range(pairs):
            t0 = time.perf_counter()
            seq.run_batch(mbs, by_ref_min_bytes=0)
            seq_wall = time.perf_counter() - t0
            t0 = time.perf_counter()
            pipe.run_batch(mbs, by_ref_min_bytes=0)
            pipe_wall = time.perf_counter() - t0
            rows.append({"seq_wall_s": round(seq_wall, 3),
                         "pipe_wall_s": round(pipe_wall, 3),
                         "ratio": round(pipe_wall / seq_wall, 3)})
            print(f"  pair {i}: seq {seq_wall:.2f}s "
                  f"pipe {pipe_wall:.2f}s "
                  f"ratio {pipe_wall / seq_wall:.3f}",
                  file=sys.stderr, flush=True)
        served = head._transfer_server.bytes_served - served0
        lag_delta = lag.delta()
        pipe.shutdown()
        seq.shutdown()
    finally:
        for h in handles:
            try:
                h.terminate()
            except Exception:  # noqa: BLE001
                pass
        cluster.shutdown()
    ratio = _median([r["ratio"] for r in rows])
    ideal = (MICRO + STAGES - 1) / (MICRO * STAGES)
    return {
        "stages": STAGES, "microbatches": MICRO,
        "fwd_s": TF, "bwd_s": TB,
        "activation_mib": ACT_ELEMS * 4 / 2**20,
        "link_mib_s": LINK_MIB_S,
        "pairs": rows,
        "wall_ratio_median_of_pairs": ratio,
        "ideal_ratio_no_transfer": round(ideal, 3),
        "head_host_egress_mib": round(served / 2**20, 1),
        "gate_ratio_le_0_5": ratio <= 0.5,
        "loop_lag": lag_delta,
    }


# --------------------------------------------------------------- hints


def bench_hints(pairs: int) -> dict:
    import ray_tpu
    import ray_tpu.core.api as core_api
    from ray_tpu import state
    from ray_tpu.core.config import get_config
    from ray_tpu.train.pipeline import Pipeline

    # The overlap window a dispatch-time hint exploits is the
    # consumer's BACKLOG: the pull runs while the consumer finishes
    # the ops already queued ahead. A perfectly rate-matched uniform
    # pipeline has near-zero queue at every hop (each activation
    # arrives just-in-time), so to measure the hint win where it
    # exists — and where real pipelines live — the stages are
    # HETEROGENEOUS: each stage slower than its producer, so every
    # hop's consumer carries a growing backlog that a prefetched pull
    # hides under (and a cold demand pull serializes in front of).
    M = 24
    tfs = [0.10 + 0.12 * k for k in range(STAGES)]
    tb = 0.03
    cluster, handles = _start_cluster(STAGES - 1)
    head = core_api._head
    lag = _LoopLag().snap()
    # AFTER init (the r13 footgun this round also FIXED in
    # reset_config — a pre-init reference now stays live; re-fetch
    # anyway to keep the bench honest about ordering)
    cfg = get_config()
    # tiny (inline-sized) backward cotangents: the large by-ref
    # traffic is then EXACTLY the forward activation handoff the hint
    # A/B measures — 2 MiB grads would contend for the same paced
    # links and smear both sides' arg_fetch with queueing noise
    stages = _mk_hetero_stages(tfs, tb)
    mbs = [float(i) for i in range(M)]
    consumer_stages = list(range(1, STAGES))

    pipe = Pipeline(stages, schedule="1f1b")

    def one_round(tag: str, on: bool) -> dict:
        from ray_tpu.core.context import get_context

        cfg.arg_prefetch_enabled = on
        pipe.name_prefix = f"h{tag}_"
        funcs = [f"h{tag}_stage{k}.fwd" for k in consumer_stages]
        iss0 = head.prefetch_issued
        wst0 = head.prefetch_wasted
        join0 = head.prefetch_joined
        t0 = time.perf_counter()
        pipe.run_batch(mbs, by_ref_min_bytes=0)
        wall = time.perf_counter() - t0
        get_context().events.flush(sync=True)
        # stage workers flush event buffers on their own cadence
        deadline = time.perf_counter() + 30
        phases = {}
        while time.perf_counter() < deadline:
            phases = state.phase_summary(funcs)
            if all(f in phases
                   and phases[f].get("exec", {}).get("count", 0) >= M
                   for f in funcs):
                break
            time.sleep(0.25)
        p95s = {k: phases[f].get("arg_fetch", {}).get("p95_ms", 0.0)
                for k, f in zip(consumer_stages, funcs)}
        time.sleep(1.5)  # borrow-grace drain before the next round
        return {
            "prefetch": on, "wall_s": round(wall, 3),
            "arg_fetch_p95_ms_by_stage": {
                str(k): round(v, 2) for k, v in p95s.items()},
            "arg_fetch_p95_ms_median": round(
                _median(list(p95s.values())), 2),
            "prefetch_issued": head.prefetch_issued - iss0,
            "prefetch_joined": head.prefetch_joined - join0,
            "prefetch_wasted": head.prefetch_wasted - wst0,
        }

    prev = cfg.arg_prefetch_enabled
    rows = []
    try:
        one_round("warm", False)  # spawn + import the stage workers
        for i in range(pairs):
            off = one_round(f"off{i}", False)
            on = one_round(f"on{i}", True)
            red = (1.0 - on["arg_fetch_p95_ms_median"]
                   / off["arg_fetch_p95_ms_median"]) \
                if off["arg_fetch_p95_ms_median"] else 0.0
            rows.append({"off": off, "on": on,
                         "p95_reduction": round(red, 3)})
            print(f"  pair {i}: off p95 "
                  f"{off['arg_fetch_p95_ms_median']}ms on p95 "
                  f"{on['arg_fetch_p95_ms_median']}ms "
                  f"(-{red * 100:.0f}%)", file=sys.stderr, flush=True)
        lag_delta = lag.delta()
        pipe.shutdown()
    finally:
        cfg.arg_prefetch_enabled = prev
        for h in handles:
            try:
                h.terminate()
            except Exception:  # noqa: BLE001
                pass
        cluster.shutdown()
    issued = sum(r["on"]["prefetch_issued"] for r in rows)
    wasted = sum(r["on"]["prefetch_wasted"] for r in rows)
    reduction = _median([r["p95_reduction"] for r in rows])
    return {
        "stages": STAGES, "microbatches": M,
        "fwd_s_by_stage": tfs, "bwd_s": tb,
        "activation_mib": HINT_ACT_ELEMS * 4 / 2**20,
        "link_mib_s": LINK_MIB_S,
        "pairs": rows,
        "arg_fetch_p95_ms_median": {
            "off": _median([r["off"]["arg_fetch_p95_ms_median"]
                            for r in rows]),
            "on": _median([r["on"]["arg_fetch_p95_ms_median"]
                           for r in rows])},
        "p95_reduction_median_of_pairs": reduction,
        "prefetch_issued_total": issued,
        "prefetch_joined_total": sum(
            r["on"]["prefetch_joined"] for r in rows),
        "prefetch_wasted_total": wasted,
        "wasted_ratio": round(wasted / issued, 4) if issued else 0.0,
        "gate_p95_reduction_ge_30pct": reduction >= 0.30,
        "gate_wasted_lt_10pct": (wasted / issued if issued else 0.0)
        < 0.10,
        "loop_lag": lag_delta,
    }


# ------------------------------------------------- chaos / drain (r16)


CKPT_D = 192  # param dim: 192x192 f32 weights (~147 KiB) keep stage
#               snapshots ABOVE the inline cap, so checkpoints ride the
#               object plane and the off-node replication path is real


def _mk_ckpt_jax_stages(n_stages, fwd_sleep_s, seed=0, dim=None,
                        micro=None):
    """jax-mode stages big enough that snapshots are plasma-resident
    (at the default ``dim=CKPT_D``); forward paced with a sleep
    (executes during the vjp trace). The r18 DP phase shrinks ``dim``
    (more workers, sleep-dominated walls) and widens ``micro``."""
    import jax.numpy as jnp
    import numpy as np

    from ray_tpu.train.pipeline import PipelineStage

    dim = CKPT_D if dim is None else dim
    micro = MICRO if micro is None else micro
    rng = np.random.default_rng(seed)

    def fn(p, x):
        if fwd_sleep_s:
            time.sleep(fwd_sleep_s)
        return jnp.tanh(x @ p["w"] + p["b"])

    stages = [
        PipelineStage(fn=fn, params={
            "w": jnp.asarray(
                rng.normal(size=(dim, dim)).astype(np.float32)
                * 0.05),
            "b": jnp.asarray(
                rng.normal(size=(dim,)).astype(np.float32))})
        for _ in range(n_stages)]

    def loss_fn(y, t):
        return jnp.mean((y - t) ** 2)

    mbs = [jnp.asarray(
        rng.normal(size=(4, dim)).astype(np.float32))
        for _ in range(micro)]
    tgts = [jnp.asarray(
        rng.normal(size=(4, dim)).astype(np.float32))
        for _ in range(micro)]
    return stages, loss_fn, mbs, tgts


def _tree_max_err(a, b):
    import jax
    import numpy as np

    return max(float(np.max(np.abs(np.asarray(x) - np.asarray(y))))
               for x, y in zip(jax.tree_util.tree_leaves(a),
                               jax.tree_util.tree_leaves(b)))


def bench_chaos() -> dict:
    """kill -9 of a mid-pipeline stage's agent node during a 4-stage x
    8-microbatch 1F1B batch with wave-boundary checkpoints (wave = 4).
    Gates: repaired numerics equal the no-fault oracle, redo <= one
    wave, repaired wall <= 2x the no-fault wall."""
    import threading

    from ray_tpu import state
    from ray_tpu.train.pipeline import Pipeline, \
        single_program_reference

    WAVE = 4
    cluster, handles = _start_cluster(STAGES)
    lag = _LoopLag().snap()
    try:
        stages, loss_fn, mbs, tgts = _mk_ckpt_jax_stages(
            STAGES, fwd_sleep_s=0.3)
        ref_loss, ref_grads = single_program_reference(
            stages, loss_fn, mbs, tgts)
        pipe = Pipeline(stages, loss_fn=loss_fn, schedule="1f1b",
                        max_inflight_microbatches=WAVE)
        pipe._refresh_stage_nodes()
        # no-fault reference run (also warms workers/imports)
        pipe.run_batch(mbs, tgts, by_ref_min_bytes=0)  # warm
        pipe.reset()
        t0 = time.perf_counter()
        nofault = pipe.run_batch(mbs, tgts, by_ref_min_bytes=0)
        wall_nofault = time.perf_counter() - t0
        nofault_grads = pipe.grads()
        err_nofault = max(
            _tree_max_err(nofault_grads[k], ref_grads[k])
            for k in range(STAGES))
        # fault run: SIGKILL the agent hosting a MID-pipeline stage
        victim_stage = 2
        victim = pipe.stage_nodes[victim_stage]
        handle = next(h for h in handles if h.node_idx == victim)
        pipe.reset()
        out = {}

        def run():
            t1 = time.perf_counter()
            try:
                out["res"] = pipe.run_batch(mbs, tgts,
                                            by_ref_min_bytes=0)
            except Exception as e:  # noqa: BLE001 — report, not crash
                out["err"] = repr(e)
            out["wall"] = time.perf_counter() - t1

        th = threading.Thread(target=run, daemon=True)
        th.start()
        kill_after = 0.4 * wall_nofault
        time.sleep(kill_after)  # mid-batch
        handle.terminate()  # kill -9 of the whole agent process
        th.join(timeout=600)
        repaired = not th.is_alive() and "res" in out
        wall_fault = out.get("wall", float("inf"))
        grads = pipe.grads() if repaired else None
        err_fault = max(
            _tree_max_err(grads[k], ref_grads[k])
            for k in range(STAGES)) if repaired else float("inf")
        loss_err = abs(out["res"]["loss"] - ref_loss) if repaired \
            else float("inf")
        st = pipe.stats()
        evs = state.list_cluster_events(
            filters=[("type", "=", "pipeline_stage_repaired")])
        lag_delta = lag.delta()
        pipe.shutdown()
    finally:
        for h in handles:
            try:
                h.terminate()
            except Exception:  # noqa: BLE001
                pass
        cluster.shutdown()
    return {
        "stages": STAGES, "microbatches": MICRO, "wave": WAVE,
        "param_dim": CKPT_D, "fwd_sleep_s": 0.3,
        "victim_stage": victim_stage, "victim_node": victim,
        "kill_after_s": round(kill_after, 3),
        "completed": repaired,
        "error": out.get("err", ""),
        "wall_nofault_s": round(wall_nofault, 3),
        "wall_fault_s": round(wall_fault, 3),
        "wall_ratio": round(wall_fault / wall_nofault, 3),
        "grad_max_err_nofault": err_nofault,
        "grad_max_err_repaired": err_fault,
        "loss_err_repaired": loss_err,
        "pipeline_repairs": st["pipeline_repairs"],
        "repair_redo_microbatches": st["repair_redo_microbatches"],
        "repair_events": len(evs),
        "gate_numerics_equal_oracle": bool(
            repaired and loss_err < 1e-6 and err_fault < 1e-5),
        "gate_redo_le_one_wave": bool(
            repaired and 0 < st["repair_redo_microbatches"] <= WAVE),
        "gate_wall_le_2x_nofault": bool(
            repaired and wall_fault <= 2.0 * wall_nofault),
        "loop_lag": lag_delta,
    }


def bench_drain() -> dict:
    """Graceful drain of a node hosting a live stage mid-batch: the
    stage migrates at a wave boundary BEFORE the shutdown. Gates: zero
    failed tasks, drain_migrated_leases >= 1, the drained node's
    object copies remain fetchable from survivors."""
    import threading

    import numpy as np

    import ray_tpu
    from ray_tpu import state
    from ray_tpu.core.api import NodeAffinitySchedulingStrategy
    from ray_tpu.train.pipeline import Pipeline, \
        single_program_reference

    WAVE = 2
    # one spare agent beyond the stages: the migration target needs a
    # free CPU while the old stage actor still holds the victim's
    cluster, handles = _start_cluster(STAGES)
    lag = _LoopLag().snap()
    try:
        stages, loss_fn, mbs, tgts = _mk_ckpt_jax_stages(
            STAGES, fwd_sleep_s=0.2)
        ref_loss, ref_grads = single_program_reference(
            stages, loss_fn, mbs, tgts)
        pipe = Pipeline(stages, loss_fn=loss_fn, schedule="1f1b",
                        max_inflight_microbatches=WAVE)
        pipe._refresh_stage_nodes()
        victim_stage = 1
        victim = pipe.stage_nodes[victim_stage]

        # a sole-copy object pinned on the victim: the drain must
        # leave it fetchable from survivors
        @ray_tpu.remote
        def make(n):
            return np.full(n, 3.0, np.float32)

        # num_cpus=0: the stage actor holds the victim's only CPU — a
        # 1-CPU marker task could never lease there
        marker = make.options(
            num_cpus=0,
            scheduling_strategy=NodeAffinitySchedulingStrategy(
                victim)).remote(200_000)
        ray_tpu.get(marker, timeout=60)
        pipe.run_batch(mbs[:2], tgts[:2], by_ref_min_bytes=0)  # warm
        pipe.reset()
        failed_before = len([r for r in state.list_tasks(limit=5000)
                             if r["state"] == "FAILED"])
        out = {}

        def run():
            try:
                out["res"] = pipe.run_batch(mbs, tgts,
                                            by_ref_min_bytes=0)
            except Exception as e:  # noqa: BLE001 — report, not crash
                out["err"] = repr(e)

        th = threading.Thread(target=run, daemon=True)
        th.start()
        time.sleep(1.5)  # mid-batch
        drained = ray_tpu.drain_node(victim)
        th.join(timeout=600)
        completed = not th.is_alive() and "res" in out
        grads = pipe.grads() if completed else None
        err = max(_tree_max_err(grads[k], ref_grads[k])
                  for k in range(STAGES)) if completed else float("inf")
        st = pipe.stats()
        # wait out the drain completion
        deadline = time.monotonic() + 90
        gone = False
        while time.monotonic() < deadline:
            rows = [r for r in state.list_nodes()
                    if r["node_idx"] == victim]
            if not rows:
                gone = True
                break
            time.sleep(0.5)
        io = state.io_loop_stats()[0]
        failed_after = len([r for r in state.list_tasks(limit=5000)
                            if r["state"] == "FAILED"])
        locs = ray_tpu.object_locations(marker)
        fetched = ray_tpu.get(marker, timeout=60)
        marker_ok = bool(float(fetched[0]) == 3.0
                         and victim not in locs["holders"])
        types = [e["type"] for e in state.list_cluster_events()]
        lag_delta = lag.delta()
        pipe.shutdown()
    finally:
        for h in handles:
            try:
                h.terminate()
            except Exception:  # noqa: BLE001
                pass
        cluster.shutdown()
    return {
        "stages": STAGES, "microbatches": MICRO, "wave": WAVE,
        "victim_stage": victim_stage, "victim_node": victim,
        "drain_started": bool(drained), "completed": completed,
        "error": out.get("err", ""),
        "node_gone": gone,
        "grad_max_err": err,
        "stage_migrations": st["stage_migrations"],
        "pipeline_repairs": st["pipeline_repairs"],
        "drain_migrated_leases": io["drain_migrated_leases"],
        "drains_completed": io["drains_completed"],
        "drains_forced": io["drains_forced"],
        "failed_tasks_during": failed_after - failed_before,
        "node_drained_event": "node_drained" in types,
        "marker_fetchable_from_survivors": marker_ok,
        "gate_zero_failed_tasks": failed_after - failed_before == 0,
        "gate_migrated_leases_ge_1": io["drain_migrated_leases"] >= 1,
        "gate_copies_survive": marker_ok,
        "gate_numerics_equal_oracle": bool(completed and err < 1e-5),
        "loop_lag": lag_delta,
    }


# --------------------------------------- DP x collective (r18)


class _CollMember:
    """Bench rank actor: builds its payload locally (the driver never
    ships tensor bytes) and times the allreduce in-process."""

    def __init__(self, rank: int):
        self.rank = rank

    def init_collective(self, world_size, rank, group_name):
        from ray_tpu import collective

        collective.init_collective_group(world_size, rank,
                                         group_name=group_name)
        return True

    def node(self):
        from ray_tpu.core.context import get_context

        return get_context().node_idx

    def timed_allreduce(self, group_name, n, transport):
        import numpy as np

        from ray_tpu import collective

        x = np.full(n, self.rank + 1.0, np.float32)
        t0 = time.perf_counter()
        out = collective.allreduce(x, group_name=group_name,
                                   transport=transport, timeout=300)
        wall = time.perf_counter() - t0
        return {"wall_s": wall, "first": float(out[0]),
                "last": float(out[-1])}


COLL_RANKS = 4
COLL_MIB = 64


def bench_collective(pairs: int) -> dict:
    """Ring vs rendezvous allreduce A/B: 64 MiB x 4 ranks, one rank
    actor per paced agent node. The gate baseline is the RENDEZVOUS
    FUNNEL (transport="rendezvous": every rank ships its full payload
    to the coordinator — the O(R·S)-through-one-node path ROADMAP item
    4 names); the r5 slice-exchange (transport="object") is measured
    alongside for honesty, since it already spreads bytes across
    stores and the ring's win over it is pipelining, not topology.
    Gates: ring effective bandwidth >= 2x the rendezvous baseline
    (median of interleaved pairs), results numerically identical, and
    ZERO collective payload bytes through the driver — counter-
    asserted on the head's relay-bytes and the head-host transfer
    server across the ring rounds (the driver's own wire egress is
    reported too; it carries only control frames)."""
    import ray_tpu
    import ray_tpu.core.api as core_api
    from ray_tpu import collective, state
    from ray_tpu.core import protocol as P
    from ray_tpu.core.api import NodeAffinitySchedulingStrategy

    n = COLL_MIB * (1 << 20) // 4  # fp32 elements
    payload_bytes = n * 4
    # 1 GiB agent arenas: the FUNNEL baseline parks R full-size result
    # objects on the coordinator's node per op, and grace-deferred
    # frees from the previous round may still be draining
    cluster, handles = _start_cluster(COLL_RANKS,
                                      store_bytes=1 << 30)
    head = core_api._head
    lag = _LoopLag().snap()
    g = "bench_coll"
    expected = (sum(r + 1.0 for r in range(COLL_RANKS)), )
    try:
        cls = ray_tpu.remote(_CollMember)
        members = [cls.options(
            num_cpus=1,
            scheduling_strategy=NodeAffinitySchedulingStrategy(
                h.node_idx, soft=False)).remote(r)
            for r, h in enumerate(handles)]
        collective.create_collective_group(
            members, COLL_RANKS, list(range(COLL_RANKS)), group_name=g)
        # warm: spawn + imports + first-touch of every code path, at
        # full size ONCE so pair 0 doesn't pay cold mmap/arena growth
        for t in ("rendezvous", "object", "ring"):
            ray_tpu.get([m.timed_allreduce.remote(g, 1 << 18, t)
                         for m in members], timeout=300)
        ray_tpu.get([m.timed_allreduce.remote(g, n, "ring")
                     for m in members], timeout=600)

        def one_round(transport):
            t0 = time.perf_counter()
            rows = ray_tpu.get(
                [m.timed_allreduce.remote(g, n, transport)
                 for m in members], timeout=600)
            driver_wall = time.perf_counter() - t0
            for row in rows:
                assert row["first"] == row["last"] == expected[0], row
            wall = max(r["wall_s"] for r in rows)
            # settle OUTSIDE the timed window: grace-deferred frees of
            # the round's objects drain before the next round's puts
            # contend for arena space
            time.sleep(2.0)
            return {"wall_s": round(wall, 3),
                    "driver_wall_s": round(driver_wall, 3),
                    "bw_mib_s": round(COLL_MIB / wall, 2)}

        rows = []
        ring_wire = relay_delta = served_delta = 0
        for i in range(pairs):
            rdv = one_round("rendezvous")
            exch = one_round("object")
            # driver-byte counters window the RING rounds only: the
            # funnel baseline legitimately parks its result objects on
            # whatever node hosts the coordinator (possibly the head's)
            # — that is its measured pathology, not the ring's
            w0 = P.WIRE.snapshot().get("bytes_sent", 0)
            relay0 = head.relay_bytes
            served0 = (head._transfer_server.bytes_served
                       if head._transfer_server else 0)
            ring = one_round("ring")
            ring_wire += P.WIRE.snapshot().get("bytes_sent", 0) - w0
            relay_delta += head.relay_bytes - relay0
            served_delta += (head._transfer_server.bytes_served
                             if head._transfer_server else 0) - served0
            rows.append({
                "rendezvous": rdv, "exchange": exch, "ring": ring,
                "bw_ratio": round(ring["bw_mib_s"] / rdv["bw_mib_s"],
                                  3),
                "bw_ratio_vs_exchange": round(
                    ring["bw_mib_s"] / exch["bw_mib_s"], 3)})
            print(f"  pair {i}: rdv {rdv['wall_s']}s "
                  f"({rdv['bw_mib_s']} MiB/s) exch {exch['wall_s']}s "
                  f"ring {ring['wall_s']}s "
                  f"({ring['bw_mib_s']} MiB/s) ratio "
                  f"{rows[-1]['bw_ratio']}", file=sys.stderr,
                  flush=True)
        coll_row = state.object_plane_stats().get("collective", {})
        lag_delta = lag.delta()
        for m in members:
            try:
                ray_tpu.kill(m)
            except Exception:  # noqa: BLE001
                pass
        collective.destroy_collective_group(g)
    finally:
        for h in handles:
            try:
                h.terminate()
            except Exception:  # noqa: BLE001
                pass
        cluster.shutdown()
    ratio = _median([r["bw_ratio"] for r in rows])
    ring_payload = pairs * COLL_RANKS * 2 * payload_bytes
    return {
        "ranks": COLL_RANKS, "payload_mib": COLL_MIB,
        "link_mib_s": LINK_MIB_S,
        "pairs": rows,
        "bw_mib_s_median": {
            "rendezvous": _median([r["rendezvous"]["bw_mib_s"]
                                   for r in rows]),
            "exchange": _median([r["exchange"]["bw_mib_s"]
                                 for r in rows]),
            "ring": _median([r["ring"]["bw_mib_s"] for r in rows])},
        "bw_ratio_median_of_pairs": ratio,
        "bw_ratio_vs_exchange_median": _median(
            [r["bw_ratio_vs_exchange"] for r in rows]),
        # driver-byte accounting across the RING rounds: payload moves
        # store-to-store between agent arenas, so the head-memory relay
        # path and the head host's transfer server must both stay flat;
        # the driver's socket egress is control-only (task submission,
        # state queries) and is reported against the ~payload volume
        "driver_relay_bytes_delta": relay_delta,
        "head_server_bytes_delta": served_delta,
        "driver_wire_mib_during_ring": round(ring_wire / 2**20, 3),
        "ring_payload_mib_total": round(ring_payload / 2**20, 1),
        "collective_counters": coll_row,
        "gate_bw_ratio_ge_2x": ratio >= 2.0,
        # "zero payload bytes": the head-memory relay stays EXACTLY
        # flat, and the head-host server / driver socket deltas stay
        # under ONE payload chunk (control frames — ref exchanges,
        # task submission — are KBs; a single smuggled payload chunk
        # would be >= collective_ring_chunk_bytes)
        "gate_zero_driver_payload_bytes": bool(
            relay_delta == 0 and served_delta < (1 << 20)
            and ring_wire < 8 * (1 << 20)),
        "loop_lag": lag_delta,
    }


DP_STAGES = 3
DP_MICRO = 12
DP_FWD_SLEEP = 0.35
DP_DIM = 64


def bench_dp(pairs: int) -> dict:
    """PP x DP composition A/B: the SAME 3-stage jax pipeline (sleep-
    paced forwards) at replicas_per_stage=1 vs 2, both runs over the
    full 12-microbatch batch. 2 replicas halve each stage's microbatch
    depth — 1F1B wall (M/R + S - 1)/(M + S - 1) ~ 0.57x ideal — while
    the batch-end bucketed grad all-reduce (overlapped with the tail
    backward waves) must keep grads EQUAL to the 1-replica oracle.
    Gates: wall ratio <= 0.65, grad max err < 1e-5 vs the driver-side
    oracle, replica pairs bit-identical after the sync."""
    import numpy as np

    import ray_tpu
    from ray_tpu import state
    from ray_tpu.train.pipeline import Pipeline, \
        single_program_reference

    # 6 agents x 2 cpus: the 1-replica gang (3 actors) and the DP gang
    # (6 actors) stay alive together for interleaved pairs; compute is
    # sleep-paced so co-hosted actors don't contend
    from ray_tpu.cluster_utils import Cluster

    cluster = Cluster(initialize_head=True,
                      head_node_args={"num_cpus": 1, "num_tpus": 0,
                                      "object_store_memory": 1 << 30})
    handles = [cluster.add_remote_node(num_cpus=2,
                                       object_store_memory=256 << 20)
               for _ in range(6)]
    lag = _LoopLag().snap()
    try:
        stages, loss_fn, mbs, tgts = _mk_ckpt_jax_stages(
            DP_STAGES, fwd_sleep_s=DP_FWD_SLEEP, seed=7, dim=DP_DIM,
            micro=DP_MICRO)
        ref_loss, ref_grads = single_program_reference(
            stages, loss_fn, mbs, tgts)
        pipe1 = Pipeline(stages, loss_fn=loss_fn, schedule="1f1b",
                         name_prefix="dp1_")
        pipe2 = Pipeline(stages, loss_fn=loss_fn, schedule="1f1b",
                         replicas_per_stage=2, name_prefix="dp2_")
        # warm: spawn + jax imports + first compiles on every worker
        pipe1.run_batch(mbs[:2], tgts[:2], by_ref_min_bytes=0)
        pipe2.run_batch(mbs[:4], tgts[:4], by_ref_min_bytes=0)
        rows = []
        for i in range(pairs):
            pipe1.reset()
            t0 = time.perf_counter()
            out1 = pipe1.run_batch(mbs, tgts, by_ref_min_bytes=0)
            wall1 = time.perf_counter() - t0
            pipe2.reset()
            t0 = time.perf_counter()
            out2 = pipe2.run_batch(mbs, tgts, by_ref_min_bytes=0)
            wall2 = time.perf_counter() - t0
            rows.append({"wall_1rep_s": round(wall1, 3),
                         "wall_2rep_s": round(wall2, 3),
                         "ratio": round(wall2 / wall1, 3)})
            print(f"  pair {i}: 1rep {wall1:.2f}s 2rep {wall2:.2f}s "
                  f"ratio {wall2 / wall1:.3f}", file=sys.stderr,
                  flush=True)
        # numerics from the LAST pair's DP run
        loss_err = abs(out2["loss"] - ref_loss)
        grads2 = pipe2.grads()
        grad_err = max(_tree_max_err(grads2[k], ref_grads[k])
                       for k in range(DP_STAGES))
        loss1_err = abs(out1["loss"] - ref_loss)
        # replica pairs hold identical grads after the sync
        sync_err = 0.0
        for k in range(DP_STAGES):
            g0, g1 = ray_tpu.get(
                [pipe2.actors[2 * k].grads.remote(True),
                 pipe2.actors[2 * k + 1].grads.remote(True)],
                timeout=120)
            sync_err = max(sync_err, _tree_max_err(g0, g1))
        st2 = pipe2.stats()
        coll_row = state.object_plane_stats().get("collective", {})
        lag_delta = lag.delta()
        # r19 comm-aware trace analysis over the session's timeline:
        # how much of the batch-end grad all-reduce the tail backward
        # waves actually hid (the overlap the lane-local AR sequencing
        # exists to create). Session-wide — warmup and 1-replica pairs
        # are in the union too — so read it as an indicator, not a
        # per-run measurement.
        analysis = {}
        try:
            from ray_tpu import tracing

            deadline = time.monotonic() + 15
            events = []
            while time.monotonic() < deadline:
                events = tracing.timeline()
                if any(e.get("cat") == "comm" and
                       e["name"].startswith("comm.ar.")
                       for e in events):
                    break
                time.sleep(0.5)  # worker buffers flush on a 1s period
            res = tracing.analyze(events=events)
            ar = [sp for sp in res["comm_spans"]
                  if sp["name"].startswith("comm.ar.")]
            ar_s = sum(sp["dur_s"] for sp in ar)
            analysis = {
                "total_comm_s": round(res["total"]["comm_s"], 4),
                "exposed_comm_s": round(
                    res["total"]["exposed_comm_s"], 4),
                "exposed_comm_frac": round(
                    res["total"]["exposed_comm_frac"], 4),
                "mean_lane_utilization": round(
                    res["total"]["utilization"], 4),
                "ar_spans": len(ar),
                "ar_comm_s": round(ar_s, 4),
                "ar_hidden_frac": round(
                    sum(sp["dur_s"] * sp["overlap_frac"]
                        for sp in ar) / max(1e-9, ar_s), 4),
                "critical_path_s": round(res["critical_path_s"], 3),
            }
        except Exception as e:  # noqa: BLE001 — analysis must never
            analysis = {"error": repr(e)[:200]}  # fail the bench
        pipe1.shutdown()
        pipe2.shutdown()
    finally:
        for h in handles:
            try:
                h.terminate()
            except Exception:  # noqa: BLE001
                pass
        cluster.shutdown()
    ratio = _median([r["ratio"] for r in rows])
    M, S = DP_MICRO, DP_STAGES
    ideal = (M // 2 + S - 1) / (M + S - 1)
    return {
        "stages": S, "replicas": 2, "microbatches": M,
        "fwd_sleep_s": DP_FWD_SLEEP, "param_dim": DP_DIM,
        "link_mib_s": LINK_MIB_S,
        "pairs": rows,
        "wall_ratio_median_of_pairs": ratio,
        "ideal_ratio_no_overhead": round(ideal, 3),
        "loss_err_1rep": loss1_err,
        "loss_err_2rep": loss_err,
        "grad_max_err_vs_oracle": grad_err,
        "replica_sync_max_err": sync_err,
        "grad_allreduces": st2["grad_allreduces"],
        "collective_counters": coll_row,
        "exposed_comm_analysis": analysis,
        "gate_wall_ratio_le_0_65": ratio <= 0.65,
        "gate_grads_equal_oracle": bool(grad_err < 1e-5
                                        and loss_err < 1e-6),
        "gate_replicas_synced": sync_err == 0.0,
        "loop_lag": lag_delta,
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--pairs", type=int, default=3)
    ap.add_argument("--phases", default="schedule,hints",
                    help="comma list: schedule,hints,chaos,drain,"
                         "dp,collective")
    ap.add_argument("--out", default="BENCH_pipeline_r15.json")
    ap.add_argument("--repair-out", default="BENCH_repair_r16.json",
                    help="artifact for the chaos/drain (r16) phases")
    ap.add_argument("--dp-out", default="BENCH_dp_r18.json",
                    help="artifact for the dp/collective (r18) phases")
    args = ap.parse_args()
    phases = {p.strip() for p in args.phases.split(",") if p.strip()}

    result = {
        "benchmark": "pipeline_r15",
        "hardware": f"single host, {os.cpu_count()} cpu, "
                    "real agent processes, per-process egress buckets",
        "methodology": "interleaved A/B pairs, median-of-pairwise "
                       "(MICROBENCH_r6); paced inter-node links",
    }
    # merge a prior artifact: phases may run as separate processes so
    # one phase's copy storms don't contaminate the other's tails
    if os.path.exists(args.out):
        try:
            with open(args.out) as f:
                prior = json.load(f)
            for k in ("schedule", "hints"):
                if k in prior:
                    result[k] = prior[k]
        except (OSError, ValueError):
            pass

    def flush():
        with open(args.out, "w") as f:
            json.dump(result, f, indent=1)

    # r16 repair phases merge into their own artifact (same process-
    # merge convention as the r15 phases)
    repair = {
        "benchmark": "pipeline_repair_r16",
        "hardware": f"single host, {os.cpu_count()} cpu, "
                    "real agent processes, per-process egress buckets",
        "methodology": "chaos = kill -9 of a mid-pipeline stage's "
                       "agent node mid-1F1B-batch vs the no-fault run "
                       "and the driver-side oracle; drain = graceful "
                       "drain_node of a live stage's node",
    }
    if os.path.exists(args.repair_out):
        try:
            with open(args.repair_out) as f:
                prior = json.load(f)
            for k in ("chaos", "drain"):
                if k in prior:
                    repair[k] = prior[k]
        except (OSError, ValueError):
            pass

    def flush_repair():
        with open(args.repair_out, "w") as f:
            json.dump(repair, f, indent=1)

    # r18 DP/collective phases merge into their own artifact
    dp = {
        "benchmark": "dp_collective_r18",
        "hardware": f"single host, {os.cpu_count()} cpu, "
                    "real agent processes, per-process egress buckets",
        "methodology": "interleaved A/B pairs, median-of-pairwise "
                       "(MICROBENCH_r6); paced inter-node links; "
                       "collective = ring vs rendezvous transports on "
                       "one group, driver-byte counters asserted "
                       "across the ring rounds; dp = replicas_per_"
                       "stage 2 vs 1 on the same batch, grads vs the "
                       "driver-side oracle",
    }
    if os.path.exists(args.dp_out):
        try:
            with open(args.dp_out) as f:
                prior = json.load(f)
            for k in ("dp", "collective"):
                if k in prior:
                    dp[k] = prior[k]
        except (OSError, ValueError):
            pass

    def flush_dp():
        with open(args.dp_out, "w") as f:
            json.dump(dp, f, indent=1)

    if "schedule" in phases:
        print(f"# schedule: {STAGES}-stage x {MICRO}-microbatch 1F1B "
              f"vs sequential, {args.pairs} pairs",
              file=sys.stderr, flush=True)
        result["schedule"] = bench_schedule(args.pairs)
        print(json.dumps(result["schedule"]), file=sys.stderr)
        flush()
    if "hints" in phases:
        print(f"# hints A/B, {args.pairs} pairs", file=sys.stderr,
              flush=True)
        result["hints"] = bench_hints(args.pairs)
        print(json.dumps(result["hints"]), file=sys.stderr)
        flush()
    if "chaos" in phases:
        print(f"# chaos: kill -9 mid-stage node, {STAGES}-stage x "
              f"{MICRO}-microbatch 1F1B", file=sys.stderr, flush=True)
        repair["chaos"] = bench_chaos()
        print(json.dumps(repair["chaos"]), file=sys.stderr)
        flush_repair()
    if "drain" in phases:
        print("# drain: graceful drain of a live stage's node",
              file=sys.stderr, flush=True)
        repair["drain"] = bench_drain()
        print(json.dumps(repair["drain"]), file=sys.stderr)
        flush_repair()
    if "collective" in phases:
        print(f"# collective: ring vs rendezvous, {COLL_MIB} MiB x "
              f"{COLL_RANKS} ranks, {args.pairs} pairs",
              file=sys.stderr, flush=True)
        dp["collective"] = bench_collective(args.pairs)
        print(json.dumps(dp["collective"]), file=sys.stderr)
        flush_dp()
    if "dp" in phases:
        print(f"# dp: {DP_STAGES} stages x 2 replicas vs 1, "
              f"{DP_MICRO} microbatches, {args.pairs} pairs",
              file=sys.stderr, flush=True)
        dp["dp"] = bench_dp(args.pairs)
        print(json.dumps(dp["dp"]), file=sys.stderr)
        flush_dp()
    if "chaos" in phases or "drain" in phases:
        print(json.dumps(repair))
    if "schedule" in phases or "hints" in phases:
        print(json.dumps(result))
    if "dp" in phases or "collective" in phases:
        print(json.dumps(dp))


if __name__ == "__main__":
    main()
