"""Cluster state API: list/summarize live entities from the head tables.

The reference's observability surface `ray.util.state`
(python/ray/util/state/api.py:782 list_tasks / list_actors / list_objects /
list_nodes / list_workers / list_placement_groups, :1009 summarize) queries
the GCS + per-node aggregators over HTTP. Here every table already lives in
the head (GCS-lite), so the API is one STATE_QUERY RPC; task rows come from
the task-event ring buffer workers flush to the head
(src/ray/core_worker/task_event_buffer.h analog in core/events.py).

Each ``list_*`` returns a list of plain dicts (the reference returns typed
rows convertible to dicts); ``filters`` are ``(key, "=", value)`` tuples
matched client-side.
"""

from __future__ import annotations

from collections import Counter
from typing import Any, Dict, List, Optional, Sequence, Tuple

from .core import protocol as P
from .core.context import get_context

_DEFAULT_LIMIT = 100


def _query(kind: str, limit: int) -> List[Dict[str, Any]]:
    ctx = get_context()
    (rows,) = ctx.head.call(P.STATE_QUERY, kind, limit, timeout=30)
    return rows


def _apply_filters(rows: List[Dict[str, Any]],
                   filters: Optional[Sequence[Tuple[str, str, Any]]]
                   ) -> List[Dict[str, Any]]:
    if not filters:
        return rows
    for key, op, value in filters:
        if op not in ("=", "=="):
            raise ValueError(f"unsupported filter op {op!r} (only '=')")
        rows = [r for r in rows if str(r.get(key)) == str(value)]
    return rows


def list_nodes(filters=None, limit: int = _DEFAULT_LIMIT):
    """Ref parity: ray.util.state.list_nodes (util/state/api.py:880).
    r16 adds the graceful-drain columns: ``draining`` (the node is
    being drained — excluded from new leases/placements/prefetches
    while its work migrates off) and ``drain_age_s`` (seconds since
    the drain began; past ``drain_deadline_s`` the head force-escalates
    and ``doctor_warnings()`` flags the node if it lingers)."""
    return _apply_filters(_query("nodes", limit), filters)


def list_workers(filters=None, limit: int = _DEFAULT_LIMIT):
    """Ref parity: ray.util.state.list_workers."""
    return _apply_filters(_query("workers", limit), filters)


def list_actors(filters=None, limit: int = _DEFAULT_LIMIT):
    """Ref parity: ray.util.state.list_actors (util/state/api.py:782)."""
    return _apply_filters(_query("actors", limit), filters)


def list_placement_groups(filters=None, limit: int = _DEFAULT_LIMIT):
    """Ref parity: ray.util.state.list_placement_groups."""
    return _apply_filters(_query("placement_groups", limit), filters)


def list_objects(filters=None, limit: int = _DEFAULT_LIMIT):
    """Ref parity: ray.util.state.list_objects (head object directory:
    plasma-resident + spilled objects; in-process inline values are not
    cluster-visible, matching the reference's plasma-only view)."""
    return _apply_filters(_query("objects", limit), filters)


def list_tasks(filters=None, limit: int = _DEFAULT_LIMIT):
    """Ref parity: ray.util.state.list_tasks — one folded timeline row
    per task id, newest activity first. Beyond the reference's columns,
    each row carries ``state_ts`` (state -> wall timestamp of every
    lifecycle transition: SUBMITTED, PENDING_ARGS_AVAIL,
    PENDING_NODE_ASSIGNMENT, SUBMITTED_TO_WORKER, FETCHING_ARGS,
    RUNNING, FINISHED/FAILED, RETURNED), ``phase_ms`` (derived
    sched_wait / dispatch / arg_fetch / exec / result_return / e2e
    durations, computed from monotonic stamps folded through per-node
    clock offsets and clamped >= 0), and ``straggler`` (set by the
    head's detector when the task ran past its func's robust exec
    bound)."""
    return _apply_filters(_query("tasks", limit), filters)


def list_slow_tasks(filters=None, limit: int = _DEFAULT_LIMIT):
    """Tasks the head's straggler detector flagged: each row carries the
    task/node/worker ids, ``running_ms_when_flagged``, and the phase
    breakdown known so far. A flagged task stays listed after it
    (eventually) finishes — filter on ``state`` for live ones."""
    return _apply_filters(_query("slow_tasks", limit), filters)


def list_cluster_events(filters=None, limit: int = 1000):
    """Ref parity: `ray list cluster-events` (util/state/api.py over the
    GCS event aggregator). Rows are severity-tagged structured records —
    ``{ts, severity, source, node_idx, entity_id, type, message, extra}``
    — oldest first; e.g. ``filters=[("severity", "=", "ERROR")]`` or
    ``[("type", "=", "node_dead")]``."""
    return _apply_filters(_query("cluster_events", limit), filters)


def object_plane_stats() -> Dict[str, Any]:
    """Object data-plane snapshot: directory shape (objects, bytes,
    replicated holder entries), locality-placement hit/miss counters, and
    head relay bytes (0 when all cross-host traffic rode the P2P plane)."""
    rows = _query("object_plane", 1)
    return rows[0] if rows else {}


def memory_summary(top_n: int = 0) -> Dict[str, Any]:
    """Cluster memory rollup (memory observatory; ref parity: `ray
    memory` / memory_utils.py's grouped object table + the dashboard
    memory view). Returns ``{nodes, jobs, owners, classes, dead_owner,
    top_objects, totals}``: per-node resident/spilled bytes merged with
    each node's last ``object_plane.arena_*`` heartbeat (store
    memory_stats()), per-job and per-owner resident-byte aggregates,
    the reference-class breakdown (sealed / spilled / checkpoint-held /
    prefetch-in-flight / borrow-pinned), resident bytes whose owner
    worker is dead (orphan refs), and the top-N largest objects with
    age and holder set. ``top_n`` > 0 caps the object list client-side
    (the head already caps at ``memory_summary_top_n``)."""
    rows = _query("memory_summary", 1 << 20)
    out = rows[0] if rows else {}
    if top_n > 0 and out.get("top_objects"):
        out["top_objects"] = out["top_objects"][:top_n]
    return out


def io_loop_stats() -> List[Dict[str, Any]]:
    """Head event-loop health (analog: the reference's
    instrumented_io_context / event_stats.h per-handler timing):
    events handled, busy seconds, slow-handler episodes, worst handler
    time, plus the r11 self-probe loop-lag quantiles
    (``loop_lag_ms_p50/p99/max`` — how long a fresh event waits for the
    IO thread; also published as ``head.loop_lag_ms`` gauges), the
    off-loop fold-queue health (``fold_queue_depth`` /
    ``fold_queue_drops``), the batched-lease counters
    (``lease_grant_batches`` / ``lease_grants_batched``), the head
    ring-buffer drop counters (``task_events_dropped`` /
    ``cluster_events_dropped``) so silent event-buffer overflow is
    detectable, and the head process's wire fast-path counters
    (``wire``); cluster-wide per-process wire totals are the ``wire.*``
    rows in ``metrics_summary()`` instead."""
    return _query("io_loop", 10)


def summarize_tasks(limit: int = 10_000) -> Dict[str, Any]:
    """Ref parity: ray.util.state.summarize_tasks (api.py:1009): count of
    tasks by (name, state) — extended with ``phases``: per-func-name
    p50/p95/p99/mean latency per lifecycle phase (sched_wait / dispatch /
    arg_fetch / exec / result_return / e2e), estimated from the head's
    ``task.phase_ms{func,phase}`` histograms (the `ray summary tasks`
    "where does task time go" answer), plus the detector's cumulative
    straggler / slow-node flag counts. Everything aggregates head-side
    over the full folded-timeline table (one small RPC — no fat rows
    ship just to be counted; ``limit`` is kept for API compatibility)."""
    del limit  # aggregation is head-side over all folded timelines
    summary = _query("task_summary", 1)
    s = summary[0] if summary else {}
    return {
        "total": s.get("total", 0),
        "by_func_name": s.get("by_func_name", {}),
        "phases": s.get("phases", {}),
        "stragglers_flagged": s.get("stragglers_flagged", 0),
        "slow_nodes_flagged": s.get("slow_nodes_flagged", 0),
    }


def phase_summary(funcs: Optional[Sequence[str]] = None
                  ) -> Dict[str, Dict[str, Any]]:
    """Func-scoped per-phase percentile summary — the focused slice of
    ``summarize_tasks()["phases"]`` (r14): ``{func: {phase: {count,
    mean_ms, p50_ms, p95_ms, p99_ms}}}`` for just the named funcs
    (all funcs when None). One small head RPC regardless of how many
    funcs the cluster has run; the serve controller polls this for its
    SLO-burn autoscaling signal (p99 of the replica methods' exec/e2e
    phases) without shipping the whole task summary every tick."""
    kind = "phase_summary"
    if funcs:
        kind += ":" + ",".join(funcs)
    rows = _query(kind, 1)
    return rows[0] if rows else {}


def metrics_history(names: Optional[Sequence[str]] = None,
                    window_s: Optional[float] = None) -> Dict[str, Any]:
    """Flight-recorder readback (r19): bounded time series the head
    sampled from its merged metric table every ``timeseries_sample_s``
    seconds — counters folded to per-second rates, gauges as-is,
    histograms as ``.p50/.p95/.p99`` point-estimate series. Returns
    ``{sample_s, window_s, samples_taken, series: {key: {kind, points:
    [[ts, v], ...], coarse: [[ts, v], ...]}}}`` where ``points`` is the
    fine ring (most recent ``timeseries_window_s`` at sample
    resolution) and ``coarse`` the 8:1 downsampled older tail. Series
    keys are ``name`` or ``name{tag=v,...}``. ``names`` entries may be
    exact keys, metric-name prefixes, or fnmatch globs
    (``["head.loop_lag_ms", "collective.*"]``); ``window_s`` trims the
    fine points to the trailing window. The reference gets this from an
    external Prometheus/Grafana pair scraping the dashboard agent; here
    the recent history is answerable by the head itself."""
    kind = "metrics_history"
    if names or window_s is not None:
        win = "" if window_s is None else repr(float(window_s))
        kind += f":{win}:" + ",".join(names or ())
    rows = _query(kind, 1)
    return rows[0] if rows else {}


def pipeline_stage_summary(prefix: Optional[str] = None
                           ) -> Dict[int, Dict[str, Any]]:
    """Per-pipeline-stage bubble/transfer/compute split (r15), derived
    from the same func-scoped phase histograms as ``phase_summary`` —
    stage actors submit their ops as ``{name_prefix}stage{k}.fwd`` /
    ``.bwd``, so no new head plumbing exists behind this. Returns
    ``{stage_idx: {"fwd": {...}, "bwd": {...}, "bubble_ms_p95",
    "transfer_ms_p95", "exec_ms_p95"}}`` where bubble = sched_wait (the
    stage sat idle waiting for work), transfer = arg_fetch (activation
    pull not hidden under compute) and exec = compute, each the p95 over
    that stage's ops — the per-stage attribution the MPMD paper's
    hand-rolled systems lack.

    ``prefix`` selects one ``Pipeline.name_prefix`` exactly (``""`` for
    unprefixed). Default ``None`` matches any prefix; when several
    pipelines ran under different prefixes, each (stage, op) slot keeps
    the variant with the most completed ops (pass ``prefix=`` to
    disambiguate an A/B explicitly).

    Data-parallel pipelines (r18) submit ops as
    ``{prefix}stage{k}r{rep}.fwd``; those rows land under the stage's
    ``"replicas"`` sub-dict — ``{rep: {"fwd": ..., "bwd": ...,
    "bubble_ms_p95", "transfer_ms_p95", "exec_ms_p95"}}`` — so a DP
    straggler attributes per (stage, replica), while the stage-level
    p95s aggregate over replicas (max: the gang waits for its slowest
    member)."""
    import re

    rows = phase_summary()
    stages: Dict[int, Dict[str, Any]] = {}
    pat = re.compile(r"^(.*?)stage(\d+)(?:r(\d+))?\.(fwd|bwd)$")

    def _n(phases):
        return phases.get("exec", {}).get("count", 0)

    for func, phases in rows.items():
        m = pat.match(func)
        if not m:
            continue
        pfx, k, rep, op = (m.group(1), int(m.group(2)), m.group(3),
                           m.group(4))
        if prefix is not None and pfx != prefix:
            continue
        slot = stages.setdefault(k, {})
        if rep is not None:
            slot = slot.setdefault("replicas", {}).setdefault(
                int(rep), {})
        if op not in slot or _n(phases) > _n(slot[op]):
            slot[op] = phases
    metrics = (("bubble_ms_p95", "sched_wait"),
               ("transfer_ms_p95", "arg_fetch"),
               ("exec_ms_p95", "exec"))

    def _agg(slot):
        for metric, phase in metrics:
            slot[metric] = max(
                (slot[op].get(phase, {}).get("p95_ms", 0.0)
                 for op in ("fwd", "bwd") if op in slot),
                default=0.0)

    for k, d in stages.items():
        reps = d.get("replicas", {})
        for rd in reps.values():
            _agg(rd)
        _agg(d)
        for metric, _ in metrics:
            d[metric] = max([d[metric]] + [rd[metric]
                                           for rd in reps.values()])
    return stages


def data_shuffle_summary() -> Dict[str, Any]:
    """Pipelined-exchange counters (r17): the cluster-merged
    ``data.shuffle_*`` metric rows (splits / fold+merge tasks /
    eagerly-freed part handles / arena-backpressure pauses, summed over
    every driver that ran an exchange) plus THIS process's live
    ``SHUFFLE_STATS`` (same counters, driver-local and synchronous —
    what the footprint tests and benches assert against, since metric
    pushes ride a periodic channel)."""
    from ray_tpu import metrics as _metrics
    from ray_tpu.data.executor import SHUFFLE_STATS

    merged: Dict[str, Any] = {}
    try:
        for row in _metrics.metrics_summary():
            if str(row.get("name", "")).startswith("data.shuffle"):
                merged[row["name"]] = row.get("value", 0.0)
    except Exception:  # noqa: BLE001 — no cluster: local view only
        pass
    return {"cluster": merged, "driver": dict(SHUFFLE_STATS)}


def summarize_actors(limit: int = 10_000) -> Dict[str, Any]:
    rows = list_actors(limit=limit)
    states = Counter(r["state"] for r in rows)
    return {"total": len(rows), "by_state": dict(states)}


def summarize_objects(limit: int = 10_000) -> Dict[str, Any]:
    rows = list_objects(limit=limit)
    return {
        "total": len(rows),
        "total_size_bytes": sum(r.get("size", 0) for r in rows),
        "spilled": sum(1 for r in rows if r.get("spilled")),
    }
