"""`python -m ray_tpu` — the cluster CLI.

Parity with the reference's `ray` CLI (python/ray/scripts/scripts.py):
``start --head`` / ``start --address`` / ``status`` / ``stop`` /
``list <entity>`` / ``summary tasks``. The head command runs a persistent
GCS-lite process other hosts join over TCP (node agents via
``start --address``, drivers via ``init(address=...)``); its coordinates
are written to ``--address-file`` (default ``/tmp/ray_tpu/head_address``)
so the sibling commands find it without flags.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import sys
import time

DEFAULT_ADDRESS_FILE = "/tmp/ray_tpu/head_address"


def _write_address_file(path: str, payload: dict):
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with open(path, "w") as f:
        json.dump(payload, f)


def _read_address(args) -> str:
    if getattr(args, "address", None):
        return args.address
    try:
        with open(args.address_file) as f:
            return json.load(f)["address"]
    except (OSError, KeyError, ValueError):
        sys.exit(f"no --address given and {args.address_file} not found; "
                 f"is a head running? (start one: python -m ray_tpu start "
                 f"--head)")


def cmd_start(args):
    if args.head:
        return _start_head(args)
    if not args.address:
        sys.exit("start needs --head or --address tcp:IP:PORT")
    # join an existing head as a node agent (reference: `ray start
    # --address`, raylet registration)
    from ray_tpu.core.node_agent import main as agent_main

    agent_args = ["--address", args.address,
                  "--num-cpus", str(args.num_cpus or os.cpu_count() or 1)]
    if args.num_tpus is not None:
        agent_args += ["--num-tpus", str(args.num_tpus)]
    return agent_main(agent_args)


def _start_head(args):
    import uuid

    from ray_tpu.core.head import Head
    from ray_tpu.dashboard import sweep_orphan_arenas

    # reclaim arenas a hard-killed predecessor (kill -9 head/agent)
    # left pinned in /dev/shm — nobody maps them, so they're garbage
    for path, size in sweep_orphan_arenas():
        print(f"swept orphaned arena {path} ({size >> 20} MB)",
              file=sys.stderr, flush=True)

    session_name = uuid.uuid4().hex[:10]
    session_dir = args.session_dir or \
        f"/tmp/ray_tpu/session_{session_name}"
    os.makedirs(session_dir, exist_ok=True)
    head = Head(session_dir, session_name)
    head.add_node(num_cpus=args.num_cpus, num_tpus=args.num_tpus)
    head.start()
    tcp = head.enable_tcp(port=args.port or 0)
    payload = {"address": head.addr, "tcp_address": tcp,
               "session_dir": session_dir, "pid": os.getpid()}
    _write_address_file(args.address_file, payload)
    print(f"head started\n  local driver address: {head.addr}\n"
          f"  cluster join address: {tcp}\n  session dir: {session_dir}\n"
          f"join from another host:\n  python -m ray_tpu start "
          f"--address {tcp}\nattach a driver:\n  ray_tpu.init("
          f"address={head.addr!r})", flush=True)

    stop = []
    signal.signal(signal.SIGTERM, lambda *a: stop.append(1))
    signal.signal(signal.SIGINT, lambda *a: stop.append(1))
    while not stop:
        time.sleep(0.2)
    head.shutdown()
    return 0


def cmd_stop(args):
    try:
        with open(args.address_file) as f:
            pid = json.load(f)["pid"]
    except (OSError, KeyError, ValueError):
        sys.exit(f"{args.address_file} not found; nothing to stop")
    try:
        os.kill(pid, signal.SIGTERM)
        print(f"sent SIGTERM to head (pid {pid})")
    except ProcessLookupError:
        print(f"head (pid {pid}) already gone")
    try:
        os.unlink(args.address_file)
    except OSError:
        pass
    return 0


def _attached(args):
    import ray_tpu

    ray_tpu.init(address=_read_address(args), log_to_driver=False)
    return ray_tpu


def cmd_status(args):
    rt = _attached(args)
    nodes = rt.nodes()
    total = rt.cluster_resources()
    avail = rt.available_resources()
    print(f"nodes: {len(nodes)}")
    for n in nodes:
        if n.get("draining"):
            state = "DRAINING"
        elif n.get("alive", True):
            state = "ALIVE"
        else:
            state = "DEAD"
        print(f"  node {n['node_idx']}: {state}  "
              f"{n.get('resources_total', {})}  "
              f"workers={n.get('num_workers', 0)}")
    print("resources (available / total):")
    for k in sorted(total):
        print(f"  {k}: {avail.get(k, 0):g} / {total[k]:g}")
    _print_timeseries_digest()
    return 0


def _spark(points, width: int = 24) -> str:
    """One-line unicode sparkline over [ts, value] points."""
    if not points:
        return ""
    vals = [v for _, v in points][-width:]
    lo, hi = min(vals), max(vals)
    bars = "▁▂▃▄▅▆▇█"
    if hi - lo < 1e-12:
        return bars[0] * len(vals)
    return "".join(
        bars[min(len(bars) - 1,
                 int((v - lo) / (hi - lo) * (len(bars) - 1)))]
        for v in vals)


def _print_timeseries_digest(window_s: float = 120.0):
    """Compact flight-recorder digest for `ray_tpu status` (r19): the
    recent window of a few load-bearing series as sparklines + last
    value. Quiet when the recorder is empty or the head predates it."""
    from ray_tpu import state as state_api

    try:
        hist = state_api.metrics_history(
            names=["head.loop_lag_ms", "collective.*", "object_plane.*",
                   "tasks.", "node."],
            window_s=window_s)
    except Exception:  # noqa: BLE001 — pre-r19 head
        return
    series = hist.get("series") or {}
    rows = [(k, s["points"]) for k, s in sorted(series.items())
            if s.get("points")]
    if not rows:
        return
    print(f"metrics (last {window_s:g}s, "
          f"{hist.get('sample_s', 0):g}s samples):")
    for key, pts in rows[:12]:
        print(f"  {key:<44} {_spark(pts)}  {pts[-1][1]:.3g}")
    if len(rows) > 12:
        print(f"  ... {len(rows) - 12} more series "
              f"(state.metrics_history() / /api/timeseries)")


def cmd_profile(args):
    """Flamegraph a live worker (ref analog: the dashboard's
    py-spy-on-PID endpoint, reporter/profile_manager.py)."""
    import sys

    from ray_tpu import profiling
    from ray_tpu import state as state_api
    from ray_tpu.core.context import get_context

    _attached(args)
    if args.worker_id == "driver":
        result = profiling.profile_self(duration_s=args.duration,
                                        hz=args.hz)
    else:
        rows = [w for w in state_api.list_workers(limit=10_000)
                if w.get("worker_id") == args.worker_id
                and w.get("state") != "dead"]
        if not rows:
            print(f"no live worker {args.worker_id!r}", file=sys.stderr)
            return 1
        remote_idxs = {n["node_idx"] for n in state_api.list_nodes()
                       if n.get("is_remote")}
        if rows[0].get("node_idx") in remote_idxs:
            # the pid belongs to ANOTHER host — signaling it here would
            # hit an unrelated local process
            print(f"worker {args.worker_id!r} runs on a remote node; "
                  f"run the profile from that host", file=sys.stderr)
            return 1
        session_dir = get_context().session_dir
        result = profiling.profile_pid(
            session_dir, args.worker_id, rows[0]["pid"],
            duration_s=args.duration, hz=args.hz)
    print(f"# {result['samples']} samples over {args.duration}s "
          f"(pid {result['pid']}); paste into flamegraph.pl/speedscope",
          file=sys.stderr)
    print(result["folded"])
    return 0


def cmd_drain(args):
    """Gracefully drain a node (r16): no new work lands on it, its
    sole-copy objects replicate off, running leases get up to
    ``drain_deadline_s`` to migrate/complete, then the node shuts
    down. ``--wait`` blocks until the node leaves the table."""
    import time as _time

    from ray_tpu import state as state_api

    from ray_tpu.core.config import get_config

    rt = _attached(args)
    idx = args.node_idx
    if not rt.drain_node(idx):
        print(f"node {idx}: unknown, already dead, or the head's "
              "bootstrap node (node 0 cannot be drained)",
              file=sys.stderr)
        return 1
    print(f"node {idx}: draining (deadline "
          f"{get_config().drain_deadline_s:g}s)")
    if not args.wait:
        return 0
    deadline = _time.monotonic() + args.timeout
    while _time.monotonic() < deadline:
        rows = [r for r in state_api.list_nodes()
                if r.get("node_idx") == idx]
        if not rows or not rows[0].get("alive"):
            print(f"node {idx}: drained")
            return 0
        _time.sleep(0.5)
    print(f"node {idx}: still draining after {args.timeout:g}s",
          file=sys.stderr)
    return 1


def cmd_doctor(args):
    """Boot a 2-node local cluster and smoke every dashboard endpoint;
    exit non-zero on any 500 (CI guard against endpoint rot)."""
    from ray_tpu.dashboard import doctor

    results = doctor(verbose=True)
    bad = [r for r in results if not r["ok"]]
    print(f"doctor: {len(results) - len(bad)}/{len(results)} endpoints "
          f"healthy")
    if bad:
        for r in bad:
            print(f"  FAILING: {r['endpoint']} -> {r['status']} "
                  f"{r['error']}", file=sys.stderr)
        return 1
    return 0


def cmd_timeline(args):
    """Export the cluster timeline as chrome-trace JSON (ref: `ray
    timeline`); ``--metrics`` additionally dumps the flight-recorder
    series next to it so counter movement correlates with the trace."""
    from ray_tpu import tracing

    _attached(args)
    events = tracing.timeline(args.out)
    print(f"wrote {len(events)} trace events to {args.out} "
          f"(open in chrome://tracing or ui.perfetto.dev)")
    if args.metrics:
        mout = args.metrics_out or (
            args.out.rsplit(".", 1)[0] + ".metrics.json")
        record = tracing.dump_flight_record(mout)
        print(f"wrote {len(record.get('series', {}))} metric series "
              f"to {mout}")
    return 0


def cmd_analyze(args):
    """Comm-aware trace analysis (r19): utilization, exposed-comm,
    pipeline bubbles and the critical path, rendered as text (or raw
    JSON with --json)."""
    from ray_tpu import tracing

    _attached(args)
    report = tracing.analyze()
    if args.json:
        print(json.dumps(report, indent=2, default=str))
        return 0
    t = report["total"]
    print(f"wall: {report['wall_s']:.3f}s   "
          f"utilization: {t['utilization']:.1%}")
    print(f"compute: {t['compute_s']:.3f}s   comm: {t['comm_s']:.3f}s   "
          f"exposed-comm: {t['exposed_comm_s']:.3f}s "
          f"({t['exposed_comm_frac']:.1%} of comm)")
    if report["lanes"]:
        print("lanes:")
        for lane, row in sorted(report["lanes"].items()):
            print(f"  {lane:<32} busy {row['busy_s']:7.3f}s "
                  f"({row['utilization']:6.1%})  "
                  f"comm {row['comm_s']:7.3f}s  "
                  f"exposed {row['exposed_comm_s']:7.3f}s")
    if report["stages"]:
        print("pipeline stages:")
        for key, st in sorted(report["stages"].items()):
            print(f"  {key:<12} fwd {st['fwd_s']:7.3f}s  "
                  f"bwd {st['bwd_s']:7.3f}s  ar {st['ar_s']:7.3f}s  "
                  f"bubble {st['bubble_s']:7.3f}s "
                  f"({st['bubble_frac']:.1%})")
    crit = report["critical_path"]
    if crit:
        print(f"critical path ({report['critical_path_s']:.3f}s, "
              f"{len(crit)} links):")
        for link in crit[-args.path_limit:]:
            print(f"  {link['start_s']:8.3f}s  {link['name']:<40} "
                  f"{link['dur_s']:7.3f}s  [{link['lane']}]")
    return 0


def cmd_list(args):
    from ray_tpu import state as state_api

    fn = {
        "nodes": state_api.list_nodes,
        "workers": state_api.list_workers,
        "actors": state_api.list_actors,
        "tasks": state_api.list_tasks,
        "objects": state_api.list_objects,
        "placement-groups": state_api.list_placement_groups,
        "cluster-events": state_api.list_cluster_events,
        "slow-tasks": state_api.list_slow_tasks,
    }[args.entity]
    _attached(args)
    rows = fn(limit=args.limit)
    if getattr(args, "sort_by", None):
        # descending for numeric keys (size, age_s) — the debugging
        # question is "what's biggest/oldest", ascending for the rest
        sample = next((r[args.sort_by] for r in rows
                       if r.get(args.sort_by) is not None), 0)
        numeric = isinstance(sample, (int, float))
        rows.sort(key=lambda r: r.get(args.sort_by) or
                  (0 if numeric else ""), reverse=numeric)
    print(json.dumps(rows, indent=2, default=str))
    return 0


_MEMORY_UNITS = {"b": 1, "kb": 1 << 10, "mb": 1 << 20, "gb": 1 << 30}


def _fmt_mem(n, units: str) -> str:
    if units != "auto":
        div = _MEMORY_UNITS[units]
        body = f"{n / div:,.2f}".rstrip("0").rstrip(".")
        return body + units.upper()
    for unit, div in (("GiB", 1 << 30), ("MiB", 1 << 20),
                      ("KiB", 1 << 10)):
        if abs(n) >= div:
            return f"{n / div:.2f}{unit}"
    return f"{n:.0f}B"


def cmd_memory(args):
    """Cluster memory observatory (ref: `ray memory` /
    memory_utils.py): resident bytes grouped by node / job / owner,
    the reference-class breakdown, and the top-N largest objects with
    age and holder set."""
    from ray_tpu import state as state_api

    _attached(args)
    s = state_api.memory_summary()
    if not s:
        print("no memory summary available (pre-r20 head?)",
              file=sys.stderr)
        return 1
    if args.json:
        print(json.dumps(s, indent=2, default=str))
        return 0
    u = args.units
    tot = s.get("totals", {})
    print(f"cluster resident: {_fmt_mem(tot.get('resident_bytes', 0), u)} "
          f"in {tot.get('resident_objects', 0)} object(s); "
          f"spilled: {_fmt_mem(tot.get('spilled_bytes', 0), u)}; "
          f"prefetch in flight: {tot.get('prefetch_inflight', 0)}")
    cls = s.get("classes", {})
    print("by reference class:")
    for label, key in (("sealed", "sealed_bytes"),
                       ("borrow-pinned", "borrow_pinned_bytes"),
                       ("checkpoint-held", "checkpoint_bytes"),
                       ("prefetch-in-flight", "prefetch_inflight_bytes"),
                       ("spilled", "spilled_bytes")):
        print(f"  {label:<20} {_fmt_mem(cls.get(key, 0), u)}")
    if args.group_by == "node":
        print("by node:")
        for idx, row in sorted(s.get("nodes", {}).items(),
                               key=lambda kv: str(kv[0])):
            arena = row.get("arena") or {}
            cap = arena.get("capacity", 0)
            used = arena.get("used_bytes", 0)
            fill = f"  arena {_fmt_mem(used, u)}/{_fmt_mem(cap, u)} " \
                   f"({used / cap:.0%}), highwater " \
                   f"{_fmt_mem(arena.get('highwater_bytes', 0), u)}" \
                if cap else "  (no arena heartbeat yet)"
            print(f"  node {idx}: {_fmt_mem(row['resident_bytes'], u)} "
                  f"in {row['resident_objects']} object(s)" + fill)
    elif args.group_by == "job":
        print("by job:")
        for job, row in sorted(s.get("jobs", {}).items(),
                               key=lambda kv: -kv[1]["resident_bytes"]):
            per_node = ", ".join(
                f"node {n}: {_fmt_mem(b, u)}" for n, b in
                sorted(row.get("per_node", {}).items(),
                       key=lambda kv: str(kv[0])))
            print(f"  job {job or '(none)'}: "
                  f"{_fmt_mem(row['resident_bytes'], u)} in "
                  f"{row['objects']} object(s)"
                  + (f"  [{per_node}]" if per_node else ""))
    else:  # owner
        print("by owner:")
        for owner, row in sorted(s.get("owners", {}).items(),
                                 key=lambda kv: -kv[1]["resident_bytes"]):
            live = "" if row.get("live", True) else "  DEAD OWNER"
            print(f"  {owner[:16] or '(none)':<16} "
                  f"{_fmt_mem(row['resident_bytes'], u)} in "
                  f"{row['objects']} object(s){live}")
    objs = s.get("top_objects", [])[:args.top]
    if args.sort_by == "age":
        objs = sorted(objs, key=lambda o: -o.get("age_s", 0.0))
    if objs:
        print(f"top {len(objs)} objects (by {args.sort_by}):")
        print(f"  {'object_id':<40} {'size':>10} {'age':>8} "
              f"{'node':>4}  {'job':<8} {'owner':<8} {'class':<10} "
              "holders")
        for o in objs:
            cls_label = o.get("tag") or \
                ("spilled" if o.get("spilled") else "sealed")
            print(f"  {o['object_id']:<40} "
                  f"{_fmt_mem(o['size'], u):>10} "
                  f"{o.get('age_s', 0.0):>7.1f}s "
                  f"{o.get('node_idx', -1):>4}  "
                  f"{(o.get('job') or '-')[:8]:<8} "
                  f"{(o.get('owner') or '-')[:8]:<8} "
                  f"{cls_label:<10} "
                  f"{','.join(str(h) for h in o.get('holders', []))}")
    dead = s.get("dead_owner") or {}
    if dead.get("bytes"):
        print(f"WARNING: {dead['objects']} object(s) "
              f"({_fmt_mem(dead['bytes'], u)}) held by dead owner(s) "
              f"{[o[:8] for o in dead.get('owners', [])]} — orphan refs")
    return 0


def cmd_summary(args):
    from ray_tpu import state as state_api

    _attached(args)
    fn = {"tasks": state_api.summarize_tasks,
          "actors": state_api.summarize_actors,
          "objects": state_api.summarize_objects}[args.entity]
    print(json.dumps(fn(), indent=2, default=str))
    return 0


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="ray_tpu",
                                description="ray_tpu cluster CLI")
    p.add_argument("--address-file", default=DEFAULT_ADDRESS_FILE,
                   help="where the head's coordinates are written/read")
    sub = p.add_subparsers(dest="cmd", required=True)

    sp = sub.add_parser("start", help="start a head or join as a node")
    sp.add_argument("--head", action="store_true")
    sp.add_argument("--address", help="tcp:IP:PORT of the head to join")
    sp.add_argument("--port", type=int, help="TCP port for the head")
    sp.add_argument("--num-cpus", type=int, default=None)
    sp.add_argument("--num-tpus", type=int, default=None)
    sp.add_argument("--session-dir", default="",
                    help="reuse a previous session dir to restore head "
                         "state from its WAL")
    sp.set_defaults(fn=cmd_start)

    sp = sub.add_parser("stop", help="stop the head started by `start`")
    sp.set_defaults(fn=cmd_stop)

    sp = sub.add_parser("status", help="cluster nodes + resources")
    sp.add_argument("--address")
    sp.set_defaults(fn=cmd_status)

    sp = sub.add_parser(
        "doctor",
        help="boot a 2-node cluster and smoke every dashboard endpoint")
    sp.set_defaults(fn=cmd_doctor)

    sp = sub.add_parser(
        "drain",
        help="gracefully drain a node (migrate work + copies, then "
             "shut it down)")
    sp.add_argument("node_idx", type=int)
    sp.add_argument("--wait", action="store_true",
                    help="block until the node leaves the cluster")
    sp.add_argument("--timeout", type=float, default=120.0,
                    help="--wait bound, seconds")
    sp.add_argument("--address")
    sp.set_defaults(fn=cmd_drain)

    sp = sub.add_parser("list", help="list cluster entities")
    sp.add_argument("entity", choices=["nodes", "workers", "actors",
                                       "tasks", "objects",
                                       "placement-groups",
                                       "cluster-events", "slow-tasks"])
    sp.add_argument("--address")
    sp.add_argument("--limit", type=int, default=100)
    sp.add_argument("--sort-by", default=None,
                    help="row key to sort by (descending for numeric "
                         "keys — e.g. `list objects --sort-by size`)")
    sp.set_defaults(fn=cmd_list)

    sp = sub.add_parser(
        "memory",
        help="cluster memory observatory: resident bytes by "
             "node/job/owner, class breakdown, largest objects "
             "(ref: `ray memory`)")
    sp.add_argument("--group-by", choices=["node", "job", "owner"],
                    default="node")
    sp.add_argument("--sort-by", choices=["size", "age"], default="size",
                    help="top-objects ordering")
    sp.add_argument("--units", choices=["b", "kb", "mb", "gb", "auto"],
                    default="auto")
    sp.add_argument("--top", type=int, default=20,
                    help="largest objects to show (head caps at "
                         "memory_summary_top_n)")
    sp.add_argument("--json", action="store_true",
                    help="print the raw memory_summary() dict")
    sp.add_argument("--address")
    sp.set_defaults(fn=cmd_memory)

    sp = sub.add_parser("summary", help="aggregate task/actor/object stats")
    sp.add_argument("entity", choices=["tasks", "actors", "objects"])
    sp.add_argument("--address")
    sp.set_defaults(fn=cmd_summary)

    sp = sub.add_parser("timeline",
                        help="export chrome-trace JSON (+ flight-"
                             "recorder metrics with --metrics)")
    sp.add_argument("--out", default="timeline.json")
    sp.add_argument("--metrics", action="store_true",
                    help="also dump state.metrics_history() to JSON")
    sp.add_argument("--metrics-out", default="",
                    help="metrics dump path (default: <out>.metrics.json)")
    sp.add_argument("--address")
    sp.set_defaults(fn=cmd_timeline)

    sp = sub.add_parser("analyze",
                        help="comm-aware trace analysis: utilization, "
                             "exposed-comm, bubbles, critical path")
    sp.add_argument("--json", action="store_true",
                    help="print the raw analyze() dict")
    sp.add_argument("--path-limit", type=int, default=12,
                    help="critical-path links to print")
    sp.add_argument("--address")
    sp.set_defaults(fn=cmd_analyze)

    sp = sub.add_parser(
        "profile",
        help="flamegraph a live worker (folded stacks to stdout)")
    sp.add_argument("worker_id", help="worker id from `list workers`, or "
                                      "'driver' for the head process")
    sp.add_argument("--duration", type=float, default=1.0)
    sp.add_argument("--hz", type=float, default=100.0)
    sp.add_argument("--address")
    sp.set_defaults(fn=cmd_profile)

    # ----- serve group (ref: the `serve` CLI, python/ray/serve/scripts.py)
    sp = sub.add_parser("serve", help="model-serving commands")
    serve_sub = sp.add_subparsers(dest="serve_cmd", required=True)
    d = serve_sub.add_parser("deploy", help="deploy apps from a YAML/JSON "
                                            "config")
    d.add_argument("config_file")
    d.add_argument("--address")
    d.set_defaults(fn=cmd_serve_deploy)
    d = serve_sub.add_parser("run", help="import and run module:app")
    d.add_argument("import_path")
    d.add_argument("--name", default="default")
    d.add_argument("--route-prefix", default=None)
    d.add_argument("--address")
    d.set_defaults(fn=cmd_serve_run)
    d = serve_sub.add_parser("status", help="application status")
    d.add_argument("--address")
    d.set_defaults(fn=cmd_serve_status)
    d = serve_sub.add_parser("shutdown", help="tear down all serve apps")
    d.add_argument("--address")
    d.set_defaults(fn=cmd_serve_shutdown)

    # ----- job group (ref: `ray job`, dashboard/modules/job/cli.py)
    sp = sub.add_parser("job", help="job submission commands")
    job_sub = sp.add_subparsers(dest="job_cmd", required=True)
    d = job_sub.add_parser("submit", help="run an entrypoint on the "
                                          "cluster")
    d.add_argument("entrypoint", nargs=argparse.REMAINDER)
    d.add_argument("--address")
    d.add_argument("--submission-id")
    d.set_defaults(fn=cmd_job_submit)
    d = job_sub.add_parser("status")
    d.add_argument("job_id")
    d.add_argument("--address")
    d.set_defaults(fn=cmd_job_status)
    d = job_sub.add_parser("logs")
    d.add_argument("job_id")
    d.add_argument("--address")
    d.set_defaults(fn=cmd_job_logs)
    d = job_sub.add_parser("list")
    d.add_argument("--address")
    d.set_defaults(fn=cmd_job_list)
    d = job_sub.add_parser("stop")
    d.add_argument("job_id")
    d.add_argument("--address")
    d.set_defaults(fn=cmd_job_stop)
    return p


def cmd_serve_deploy(args):
    from ray_tpu import serve

    _attached(args)
    names = serve.deploy_config(args.config_file)
    print(f"deployed applications: {names}")
    return 0


def cmd_serve_run(args):
    from ray_tpu import serve
    from ray_tpu.serve.schema import _import_target

    _attached(args)
    target = _import_target(args.import_path)
    serve.run(target, name=args.name,
              route_prefix=args.route_prefix or f"/{args.name}")
    print(f"app '{args.name}' running")
    return 0


def cmd_serve_status(args):
    from ray_tpu import serve

    _attached(args)
    status = serve.status()
    print(json.dumps(status, indent=2, default=str))
    # compact autoscaler digest (r14): one line per autoscaled
    # deployment so a scale event is debuggable without jq — desired
    # vs running, live queue depth, the last decision + reason, recent
    # direction flips, and cold-start percentiles
    lines = []
    for app, info in status.get("applications", {}).items():
        for dn, dep in info.get("deployments", {}).items():
            auto = dep.get("autoscaler") or {}
            if not auto.get("enabled"):
                continue
            last = auto.get("last_decision") or {}
            cold = auto.get("cold_start") or {}
            lines.append(
                f"  {app}/{dn}: desired={auto.get('desired')} "
                f"running={auto.get('running')} "
                f"queue={auto.get('queue_depth')} "
                f"reversals_60s={auto.get('reversals_60s')} "
                f"cold_start_p50={cold.get('p50_s', 0)}s "
                f"p95={cold.get('p95_s', 0)}s"
                + (f"\n    last: {last.get('direction')} "
                   f"{last.get('from')}->{last.get('to')} "
                   f"({last.get('reason')})" if last else ""))
    if lines:
        print("autoscaler:")
        for ln in lines:
            print(ln)
    return 0


def cmd_serve_shutdown(args):
    from ray_tpu import serve

    _attached(args)
    serve.shutdown()
    print("serve shut down")
    return 0


def _job_client(args):
    from ray_tpu.jobs import JobSubmissionClient

    _attached(args)
    return JobSubmissionClient()


def cmd_job_submit(args):
    entry = " ".join(args.entrypoint).lstrip("- ")
    if not entry:
        sys.exit("job submit needs an entrypoint, e.g. "
                 "`job submit -- python my_script.py`")
    client = _job_client(args)
    jid = client.submit_job(entrypoint=entry,
                            submission_id=args.submission_id)
    print(jid)
    return 0


def cmd_job_status(args):
    print(_job_client(args).get_job_status(args.job_id))
    return 0


def cmd_job_logs(args):
    print(_job_client(args).get_job_logs(args.job_id), end="")
    return 0


def cmd_job_list(args):
    print(json.dumps(_job_client(args).list_jobs(), indent=2,
                     default=str))
    return 0


def cmd_job_stop(args):
    print(_job_client(args).stop_job(args.job_id))
    return 0


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    return args.fn(args) or 0


if __name__ == "__main__":
    sys.exit(main())
