"""Mixture-of-Experts FFN with expert parallelism over the ``expert`` axis.

Absent from the reference (SURVEY.md §2.3 marks EP as greenfield-mandatory).
TPU-first design: GShard/Switch-style *dense* dispatch — routing becomes two
einsums against a one-hot capacity tensor, so the whole layer is static-shaped
matmuls the MXU likes, and sharding the expert-major tensors over the
``expert`` mesh axis makes XLA insert the canonical all-to-all pair around
the expert FFN (no ragged ops, no host loops).

Routing: top-k (default 2) with combine weights renormalized to sum to 1
(Mixtral-style). With all experts initialized identically the layer is then
numerically EQUAL to the dense FFN it replaces — the parity tests exploit
this. Tokens overflowing an expert's capacity C = ceil(T*k/E * factor) are
dropped (contribute zero), the standard Switch behavior.
"""

from __future__ import annotations

import math
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from ray_tpu.parallel.sharding import with_logical_constraint as _wlc

Params = Dict[str, Any]


def moe_param_logical_axes() -> Dict[str, tuple]:
    """Logical axes for one layer-stack of MoE parameters (leading layers
    axis; experts axis sharded over the ``expert`` mesh axis)."""
    return {
        "router": ("layers", "embed", "experts"),
        "w_gate": ("layers", "experts", "embed", "mlp"),
        "w_up": ("layers", "experts", "embed", "mlp"),
        "w_down": ("layers", "experts", "mlp", "embed"),
    }


def init_moe_params(rng: jax.Array, cfg) -> Params:
    """Stacked per-layer MoE params: router [L,d,E] + expert FFNs [L,E,...]."""
    L, d, ff, E = cfg.n_layers, cfg.d_model, cfg.d_ff, cfg.moe_experts
    pd = cfg.param_dtype
    k = iter(jax.random.split(rng, 8))

    def normal(key, shape, scale):
        return (jax.random.normal(key, shape, jnp.float32) * scale).astype(pd)

    in_scale = d ** -0.5
    out_scale = (2 * L) ** -0.5 * d ** -0.5 * (ff / d) ** 0.5
    return {
        "router": normal(next(k), (L, d, E), in_scale),
        "w_gate": normal(next(k), (L, E, d, ff), in_scale),
        "w_up": normal(next(k), (L, E, d, ff), in_scale),
        "w_down": normal(next(k), (L, E, ff, d), out_scale),
    }


def moe_ffn(h: jax.Array, lp: Params, cfg, mesh: Optional[Mesh] = None
            ) -> Tuple[jax.Array, jax.Array]:
    """One MoE FFN layer. h: [B, T, d] -> (out [B, T, d], aux_loss scalar).

    lp: per-layer params {router [d,E], w_gate/w_up [E,d,f], w_down [E,f,d]}.
    aux_loss is the Switch load-balance term E * sum_e f_e * p_e (1.0 when
    perfectly balanced); weight it into the train loss via
    cfg.moe_aux_weight.
    """
    B, T, d = h.shape
    E, k = cfg.moe_experts, cfg.moe_top_k
    C = max(1, math.ceil(T * k / E * cfg.moe_capacity_factor))
    dtype = h.dtype

    logits = jnp.einsum("btd,de->bte", h.astype(jnp.float32),
                        lp["router"].astype(jnp.float32))
    probs = jax.nn.softmax(logits, axis=-1)  # [B,T,E] float32

    top_p, top_i = jax.lax.top_k(probs, k)  # [B,T,k]
    top_p = top_p / jnp.maximum(top_p.sum(-1, keepdims=True), 1e-9)

    # Flatten the k routing slots into a priority-ordered stream per batch
    # row; earlier tokens (and within a token, higher-probability slots)
    # claim capacity first.
    oh = jax.nn.one_hot(top_i, E, dtype=jnp.float32)     # [B,T,k,E]
    oh = oh.reshape(B, T * k, E)                          # [B,S,E]
    pos = jnp.cumsum(oh, axis=1) - 1.0                    # slot within expert
    in_cap = (pos < C) * oh                               # [B,S,E]
    slot = jax.nn.one_hot(pos.astype(jnp.int32), C,
                          dtype=jnp.float32) * in_cap[..., None]  # [B,S,E,C]

    # dispatch: [B,S,E,C] x [B,S,d] -> [E,B,C,d] (all-to-all over `expert`)
    hk = jnp.broadcast_to(h[:, :, None, :], (B, T, k, d)).reshape(B, T * k, d)
    xin = jnp.einsum("bsec,bsd->ebcd", slot.astype(dtype), hk)
    xin = _wlc(xin, ("experts", "batch", None, "embed"), mesh=mesh)

    # expert FFN (SwiGLU), expert-major so E shards over the expert axis
    gate = jnp.einsum("ebcd,edf->ebcf", xin, lp["w_gate"].astype(dtype))
    up = jnp.einsum("ebcd,edf->ebcf", xin, lp["w_up"].astype(dtype))
    act = jax.nn.silu(gate) * up
    act = _wlc(act, ("experts", "batch", None, "mlp"), mesh=mesh)
    out = jnp.einsum("ebcf,efd->ebcd", act, lp["w_down"].astype(dtype))

    # combine: weight each claimed slot by its (renormalized) router prob
    combine = slot * top_p.reshape(B, T * k, 1, 1).astype(jnp.float32)
    y = jnp.einsum("ebcd,bsec->bsd", out.astype(jnp.float32), combine)
    y = y.reshape(B, T, k, d).sum(axis=2).astype(dtype)
    y = _wlc(y, ("batch", "seq", "embed"), mesh=mesh)

    # Switch aux loss: fraction of tokens dispatched to e (top-1 slot) times
    # mean router prob for e, scaled by E — 1.0 at perfect balance.
    top1 = jax.nn.one_hot(top_i[..., 0], E, dtype=jnp.float32)
    frac = top1.reshape(-1, E).mean(axis=0)
    mean_p = probs.reshape(-1, E).mean(axis=0)
    aux = E * jnp.sum(frac * mean_p)
    return y, aux
