"""Llama-family decoder in functional JAX: pytree params, scan over layers.

TPU-first design choices:
  - Layer weights are *stacked* on a leading `layers` axis and the block is a
    `lax.scan` body — one trace/compile of the block regardless of depth, and
    a natural substrate for pipeline parallelism later.
  - Every parameter and activation carries *logical* axis names; actual
    sharding comes from `ray_tpu.parallel.sharding` rules, so the same model
    runs DP, FSDP, TP, and ring-CP unchanged.
  - Compute in bfloat16 on the MXU, master params float32, loss/softmax
    accumulation float32.
  - `jax.checkpoint` on the scanned block trades FLOPs for HBM (remat).
"""

from __future__ import annotations

import functools
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from ray_tpu.models.config import TransformerConfig
from ray_tpu.parallel.ring import reference_attention, ring_attention
from ray_tpu.parallel.sharding import with_logical_constraint as _wlc

Params = Dict[str, Any]


# ---- parameter structure ---------------------------------------------------

def param_logical_axes(cfg: TransformerConfig) -> Params:
    """Same-structure pytree of logical axis tuples (for shardings)."""
    lay = {
        "attn_norm": ("layers", "embed"),
        "wq": ("layers", "embed", "heads", "qkv_dim"),
        "wk": ("layers", "embed", "kv_heads", "qkv_dim"),
        "wv": ("layers", "embed", "kv_heads", "qkv_dim"),
        "wo": ("layers", "heads", "qkv_dim", "embed"),
        "mlp_norm": ("layers", "embed"),
    }
    if cfg.moe_experts:
        from ray_tpu.models.moe import moe_param_logical_axes

        lay.update(moe_param_logical_axes())
    else:
        lay.update({
            "w_gate": ("layers", "embed", "mlp"),
            "w_up": ("layers", "embed", "mlp"),
            "w_down": ("layers", "mlp", "embed"),
        })
    axes = {
        "embed": ("vocab", "embed"),
        "layers": lay,
        "final_norm": ("embed",),
    }
    if not cfg.tie_embeddings:
        axes["lm_head"] = ("embed", "vocab")
    return axes


def init_params(rng: jax.Array, cfg: TransformerConfig) -> Params:
    d, v, L = cfg.d_model, cfg.vocab_size, cfg.n_layers
    hd, H, KV, ff = cfg.head_dim, cfg.n_heads, cfg.kv_heads, cfg.d_ff
    pd = cfg.param_dtype
    k = iter(jax.random.split(rng, 16))

    def normal(key, shape, scale):
        return (jax.random.normal(key, shape, jnp.float32) * scale).astype(pd)

    emb_scale = d ** -0.5
    in_scale = d ** -0.5
    out_scale = (2 * L) ** -0.5 * d ** -0.5  # depth-scaled residual outputs
    lay = {
        "attn_norm": jnp.ones((L, d), pd),
        "wq": normal(next(k), (L, d, H, hd), in_scale),
        "wk": normal(next(k), (L, d, KV, hd), in_scale),
        "wv": normal(next(k), (L, d, KV, hd), in_scale),
        "wo": normal(next(k), (L, H, hd, d), out_scale),
        "mlp_norm": jnp.ones((L, d), pd),
    }
    if cfg.moe_experts:
        from ray_tpu.models.moe import init_moe_params

        lay.update(init_moe_params(next(k), cfg))
    else:
        lay.update({
            "w_gate": normal(next(k), (L, d, ff), in_scale),
            "w_up": normal(next(k), (L, d, ff), in_scale),
            "w_down": normal(next(k), (L, ff, d),
                             out_scale * (ff / d) ** 0.5),
        })
    params: Params = {
        "embed": normal(next(k), (v, d), emb_scale),
        "layers": lay,
        "final_norm": jnp.ones((d,), pd),
    }
    if not cfg.tie_embeddings:
        params["lm_head"] = normal(next(k), (d, v), in_scale)
    return params


# ---- building blocks -------------------------------------------------------

def rms_norm(x, gamma, eps):
    x32 = x.astype(jnp.float32)
    scale = jax.lax.rsqrt(jnp.mean(x32 * x32, axis=-1, keepdims=True) + eps)
    return (x32 * scale).astype(x.dtype) * gamma.astype(x.dtype)


def _rope(x, positions, theta):
    """Rotary embedding. x: [B, T, H, D]; positions: [T] (shared across
    the batch) or [B, T] (per-row — continuous-batching decode, where
    each cache slot sits at its own write position)."""
    d_half = x.shape[-1] // 2
    freqs = theta ** (-jnp.arange(0, d_half, dtype=jnp.float32) / d_half)
    angles = positions.astype(jnp.float32)[..., None] * freqs  # [...,T,Dh]
    if angles.ndim == 2:
        angles = angles[None]  # shared positions: broadcast over batch
    cos = jnp.cos(angles)[:, :, None, :]
    sin = jnp.sin(angles)[:, :, None, :]
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    out = jnp.concatenate([x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1)
    return out.astype(x.dtype)


def _select_attention(cfg: TransformerConfig, mesh: Optional[Mesh]):
    impl = cfg.attention_impl
    if impl == "auto":
        if mesh is not None and mesh.shape.get("sequence", 1) > 1:
            impl = "ring"
        elif jax.default_backend() not in ("cpu",):
            impl = "pallas"
        else:
            impl = "xla"
    return impl


def _attention(q, k, v, cfg: TransformerConfig, mesh: Optional[Mesh],
               positions):
    impl = _select_attention(cfg, mesh)
    if impl == "ring":
        return ring_attention(q, k, v, mesh, causal=cfg.causal)
    if impl == "pallas":
        from ray_tpu.ops import flash_attention  # lazy: pallas import cost
        return flash_attention(q, k, v, causal=cfg.causal)
    return reference_attention(q, k, v, causal=cfg.causal)


def qkv_proj(h, lp, cfg: TransformerConfig, positions):
    """Q/K/V projections + RoPE — the single definition shared by the
    training forward and the KV-cache inference path (models/generate),
    so a numeric change (e.g. QK-norm) lands in both."""
    q = jnp.einsum("btd,dhk->bthk", h, lp["wq"].astype(cfg.dtype))
    k = jnp.einsum("btd,dhk->bthk", h, lp["wk"].astype(cfg.dtype))
    v = jnp.einsum("btd,dhk->bthk", h, lp["wv"].astype(cfg.dtype))
    return (_rope(q, positions, cfg.rope_theta),
            _rope(k, positions, cfg.rope_theta), v)


def ffn_block(h, lp, cfg: TransformerConfig, mesh: Optional[Mesh] = None):
    """SwiGLU (or MoE) FFN -> (down, aux); shared by train + inference."""
    if cfg.moe_experts:
        from ray_tpu.models.moe import moe_ffn

        return moe_ffn(h, lp, cfg, mesh)
    gate = jnp.einsum("btd,df->btf", h, lp["w_gate"].astype(cfg.dtype))
    up = jnp.einsum("btd,df->btf", h, lp["w_up"].astype(cfg.dtype))
    ff = jax.nn.silu(gate) * up
    ff = _wlc(ff, ("batch", "seq", "mlp"), mesh=mesh)
    down = jnp.einsum("btf,fd->btd", ff, lp["w_down"].astype(cfg.dtype))
    return down, jnp.zeros((), jnp.float32)


def lm_head(params: Params, x, cfg: TransformerConfig,
            mesh: Optional[Mesh] = None):
    """Final norm + (tied or separate) vocabulary projection."""
    x = rms_norm(x, params["final_norm"], cfg.rms_eps)
    head = (params["embed"].T if cfg.tie_embeddings else params["lm_head"])
    logits = jnp.einsum("btd,dv->btv", x.astype(jnp.float32),
                        head.astype(jnp.float32))
    return _wlc(logits, ("batch", "seq", "vocab"), mesh=mesh)


# ---- forward ---------------------------------------------------------------

def forward(params: Params, tokens: jax.Array, cfg: TransformerConfig,
            mesh: Optional[Mesh] = None, return_aux: bool = False):
    """tokens [B, T] int32 -> logits [B, T, vocab] float32.

    With ``return_aux=True`` returns (logits, aux) where aux is the summed
    MoE load-balance loss (0.0 for dense or pipelined execution)."""
    B, T = tokens.shape
    x = params["embed"].astype(cfg.dtype)[tokens]  # [B, T, d]
    x = _wlc(x, ("batch", "seq", "embed"), mesh=mesh)
    positions = jnp.arange(T)

    def block(x, lp):
        h = rms_norm(x, lp["attn_norm"], cfg.rms_eps)
        q, k, v = qkv_proj(h, lp, cfg, positions)
        reps = cfg.n_heads // cfg.kv_heads
        if reps > 1:  # GQA: expand kv heads to match q heads
            k = jnp.repeat(k, reps, axis=2)
            v = jnp.repeat(v, reps, axis=2)
        q = _wlc(q, ("batch", "seq", "heads", None), mesh=mesh)
        o = _attention(q, k, v, cfg, mesh, positions)
        o = jnp.einsum("bthk,hkd->btd", o, lp["wo"].astype(cfg.dtype))
        x = x + _wlc(o, ("batch", "seq", "embed"), mesh=mesh)

        h = rms_norm(x, lp["mlp_norm"], cfg.rms_eps)
        down, aux = ffn_block(h, lp, cfg, mesh)
        x = x + _wlc(down, ("batch", "seq", "embed"), mesh=mesh)
        # aux (MoE load-balance loss) rides the scan's per-layer outputs;
        # the pipelined path drops it (pipeline stages emit activations
        # only) — acceptable: aux is a regularizer, not the model output.
        return x, aux

    body = block
    if cfg.remat:
        policy = (
            jax.checkpoint_policies.dots_with_no_batch_dims_saveable
            if getattr(cfg, "remat_policy", "nothing") == "dots"
            else jax.checkpoint_policies.nothing_saveable)
        body = jax.checkpoint(body, policy=policy)
    aux = jnp.zeros((), jnp.float32)
    if mesh is not None and mesh.shape.get("pipeline", 1) > 1:
        # GPipe-style microbatched stages over the pipeline mesh axis; the
        # same block body, numerically identical to the plain scan
        # (parallel/pipeline.py).
        from ray_tpu.parallel.pipeline import pipeline_scan

        x = pipeline_scan(body, x, params["layers"], mesh,
                          cfg.pipeline_microbatches)
    else:
        x, layer_aux = jax.lax.scan(
            lambda c, lp: body(c, lp), x, params["layers"])
        aux = layer_aux.sum()

    logits = lm_head(params, x, cfg, mesh)
    return (logits, aux) if return_aux else logits


def loss_fn(params: Params, batch: Dict[str, jax.Array],
            cfg: TransformerConfig, mesh: Optional[Mesh] = None):
    """Next-token cross entropy. batch: {"tokens": [B,T]} (targets shifted)
    or {"inputs": [B,T], "targets": [B,T], optional "mask": [B,T]}."""
    if "inputs" in batch:
        inputs, targets = batch["inputs"], batch["targets"]
        mask = batch.get("mask")
    else:
        toks = batch["tokens"]
        inputs, targets = toks[:, :-1], toks[:, 1:]
        mask = None
    logits, aux = forward(params, inputs, cfg, mesh, return_aux=True)
    logz = jax.nn.logsumexp(logits, axis=-1)
    gold = jnp.take_along_axis(logits, targets[..., None], axis=-1)[..., 0]
    nll = logz - gold
    if mask is not None:
        denom = jnp.maximum(mask.sum(), 1.0)
        loss = (nll * mask).sum() / denom
    else:
        loss = nll.mean()
    metrics = {"loss": loss, "perplexity": jnp.exp(loss)}
    if cfg.moe_experts:
        metrics["moe_aux"] = aux
        loss = loss + cfg.moe_aux_weight * aux
        metrics["total_loss"] = loss
    return loss, metrics
