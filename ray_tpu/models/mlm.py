"""Masked-language-model batch preparation for encoder configs.

Ref analog: the reference's BERT-base JaxTrainer/TorchTrainer pretraining
config (BASELINE.md) — there the masking lives in the HF data collator;
here it is one vectorized numpy transform that pairs with
``transformer.loss_fn``'s inputs/targets/mask form (loss on masked
positions only, no target shift). BERT 80/10/10 recipe: of the selected
positions, 80% become [MASK], 10% a random token, 10% stay unchanged.
"""

from __future__ import annotations

from typing import Dict, Optional

import numpy as np


def mask_tokens(tokens: np.ndarray, *, mask_id: int, vocab_size: int,
                mask_prob: float = 0.15,
                rng: Optional[np.random.Generator] = None,
                special_ids=()) -> Dict[str, np.ndarray]:
    """tokens [B, T] -> {"inputs", "targets", "mask"} for loss_fn.

    ``mask`` is 1.0 exactly at the selected (predict-me) positions;
    ``inputs`` applies the 80/10/10 corruption; ``targets`` is the
    original token everywhere (loss_fn ignores unmasked positions via
    the mask).
    """
    if rng is None:  # unseeded: repeated calls must mask DIFFERENT
        rng = np.random.default_rng()  # positions or MLM loses coverage
    tokens = np.asarray(tokens)
    selectable = np.ones(tokens.shape, bool)
    for sid in special_ids:
        selectable &= tokens != sid
    sel = (rng.random(tokens.shape) < mask_prob) & selectable
    # guarantee at least one prediction per row (a zero-mask row would
    # contribute nothing and skew the mean loss denominator)
    for i in range(tokens.shape[0]):
        if not sel[i].any() and selectable[i].any():
            sel[i, rng.choice(np.flatnonzero(selectable[i]))] = True

    inputs = tokens.copy()
    u = rng.random(tokens.shape)
    to_mask = sel & (u < 0.8)
    to_rand = sel & (u >= 0.8) & (u < 0.9)
    inputs[to_mask] = mask_id
    inputs[to_rand] = rng.integers(0, vocab_size,
                                   size=int(to_rand.sum()))
    return {"inputs": inputs.astype(np.int32),
            "targets": tokens.astype(np.int32),
            "mask": sel.astype(np.float32)}
