"""Built-in model family: Llama-style decoders, TPU-first (SURVEY.md §7.6)."""

from ray_tpu.models.config import (
    PRESETS,
    TransformerConfig,
    bert_base_config,
    get_config,
    gpt2_small_config,
    llama3_8b_config,
    llama3_70b_config,
    tiny_config,
)
from ray_tpu.models.mlm import mask_tokens
# NOTE: the generate() function itself is not re-exported — it would
# shadow the ray_tpu.models.generate submodule; use
# ``from ray_tpu.models.generate import generate``.
from ray_tpu.models.generate import decode_step, init_cache, prefill
from ray_tpu.models.transformer import (
    forward,
    init_params,
    loss_fn,
    param_logical_axes,
)
from ray_tpu.models.training import (
    batch_sharding,
    init_train_state,
    make_eval_step,
    make_optimizer,
    make_train_step,
    state_shardings,
)

__all__ = [
    "TransformerConfig", "get_config", "PRESETS", "tiny_config",
    "gpt2_small_config", "llama3_8b_config", "llama3_70b_config",
    "bert_base_config", "mask_tokens",
    "forward", "init_params", "loss_fn", "param_logical_axes",
    "prefill", "decode_step", "init_cache",
    "make_optimizer", "make_train_step", "make_eval_step",
    "init_train_state", "state_shardings", "batch_sharding",
]
