"""Continuous-batching inference engine: per-step slot admission/eviction.

Ref analog: the reference serves LLMs through replica actors whose
batching is *request-cohort* shaped (`python/ray/serve/batching.py:337`
coalesces waiting calls; `python/ray/serve/_private/replica.py:237` runs
them) — a cohort must finish before its slots free, so one long
generation stalls the batch. This engine is the vLLM/Orca-style redesign
the reference delegates to external vLLM workers for, built TPU-first:

  - The KV cache is a fixed pool of B *slots* over one contiguous
    [L, B, S, KV, hd] array — static shapes, one compiled decode program
    for the life of the engine. A slot is a row; admission writes a new
    prompt's K/V into a freed row, eviction is just host bookkeeping.
  - Each decode step advances EVERY active slot by one token in a single
    batched program (per-row cache positions, per-row RoPE), then the
    host admits queued prompts into any slots that finished — finished
    sequences never block running ones.
  - Prefill is a separate B=1 program per power-of-two prompt bucket
    (bounded compile count) whose K/V lands directly in the slot row;
    prefills interleave with decode steps, so time-to-first-token stays
    bounded under load.
  - Sampling happens on-device; the host sees B int32s per step — the
    decode loop's host<->device traffic is O(slots), not O(vocab).
  - Tensor parallelism comes from sharding, not new code: params carry
    their logical axes (kv_heads/heads/mlp/vocab -> "tensor") and the
    cache shards on its KV-head axis; XLA propagates the TP layout
    through the same jitted step and inserts the collectives.
"""

from __future__ import annotations

import itertools
import queue
import threading
from dataclasses import dataclass, field
from functools import partial
from typing import Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.models.config import TransformerConfig
from ray_tpu.models.generate import (_final_logits, _gqa_attention,
                                     _prefill_hidden)
from ray_tpu.models.transformer import (Params, ffn_block,
                                        param_logical_axes, qkv_proj,
                                        rms_norm)

SlotCache = Dict[str, jax.Array]
# {"k"/"v": [L, B, S, KV, hd], "pos": [B], "start": [B]} — pos[b] is slot
# b's next write position; start[b] its first real (non-pad) position.


def init_slot_cache(cfg: TransformerConfig, slots: int,
                    max_len: int) -> SlotCache:
    shape = (cfg.n_layers, slots, max_len, cfg.kv_heads, cfg.head_dim)
    return {"k": jnp.zeros(shape, cfg.dtype),
            "v": jnp.zeros(shape, cfg.dtype),
            "pos": jnp.zeros((slots,), jnp.int32),
            "start": jnp.zeros((slots,), jnp.int32)}


def cache_logical_axes() -> Dict[str, tuple]:
    """Logical axes of the slot cache (slots axis stays unsharded —
    serving shards the model, not the batch)."""
    kv = ("layers", None, None, "kv_heads", None)
    return {"k": kv, "v": kv, "pos": (None,), "start": (None,)}


def _sample(logits, rng, greedy: bool, temperature):
    if greedy:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    return jax.random.categorical(
        rng, logits / jnp.maximum(temperature, 1e-6)).astype(jnp.int32)


@partial(jax.jit, static_argnames=("cfg", "greedy"), donate_argnums=(1,))
def prefill_slot(params: Params, cache: SlotCache, tokens: jax.Array,
                 slot: jax.Array, start: jax.Array, rng: jax.Array,
                 cfg: TransformerConfig, greedy: bool = True,
                 temperature: float = 1.0):
    """Run the prompt ``tokens`` [1, P] (left-padded to its bucket, first
    real token at ``start``) and write its K/V into slot row ``slot``;
    -> (cache, first sampled token []). One compiled program per bucket P.
    """
    P = tokens.shape[1]
    x, c1 = _prefill_hidden(params, tokens, cfg, P, start[None])
    last = _final_logits(params, x[:, -1:], cfg)[:, 0]  # [1, V]
    tok = _sample(last, rng, greedy, temperature)[0]
    # c1["k"]: [L, 1, P, KV, hd] -> row `slot`, seq offset 0
    zero = jnp.zeros((), jnp.int32)
    k = jax.lax.dynamic_update_slice(
        cache["k"], c1["k"].astype(cache["k"].dtype),
        (zero, slot, zero, zero, zero))
    v = jax.lax.dynamic_update_slice(
        cache["v"], c1["v"].astype(cache["v"].dtype),
        (zero, slot, zero, zero, zero))
    return {"k": k, "v": v,
            "pos": cache["pos"].at[slot].set(P),
            "start": cache["start"].at[slot].set(start)}, tok


def _write_rows(layer_cache, kv, pos):
    """Per-row cache write: layer_cache [B, S, KV, hd] <- kv [B, 1, KV, hd]
    at per-row seq positions ``pos`` [B].

    A one-hot select, NOT a vmapped dynamic_update_slice: per-row dynamic
    indices lower to a scatter that falls off the TPU fast path (measured
    ~5x decode slowdown); the select is pure elementwise bandwidth over
    a cache the decode step already reads in full."""
    S = layer_cache.shape[1]
    hit = (jnp.arange(S)[None, :] == pos[:, None])[:, :, None, None]
    return jnp.where(hit, kv.astype(layer_cache.dtype), layer_cache)


def _decode_one(params: Params, cache: SlotCache, tokens: jax.Array,
                cfg: TransformerConfig):
    """One decode step for every slot: tokens [B] (each slot's pending
    token) -> (cache with pos advanced, logits [B, V]).

    pos/RoPE/attention masks are all per-row, so slots admitted at
    different times decode together in one program.
    """
    pos, start = cache["pos"], cache["start"]
    x = params["embed"].astype(cfg.dtype)[tokens[:, None]]  # [B, 1, d]
    positions = pos[:, None]  # [B, 1] per-row RoPE

    def block(x, scanned):
        lp, k_layer, v_layer = scanned
        h = rms_norm(x, lp["attn_norm"], cfg.rms_eps)
        q, k, v = qkv_proj(h, lp, cfg, positions)
        k_layer = _write_rows(k_layer, k, pos)
        v_layer = _write_rows(v_layer, v, pos)
        S = k_layer.shape[1]
        kpos = jnp.arange(S)[None, None, None, None, :]
        mask = (kpos <= pos[:, None, None, None, None]) & \
            (kpos >= start[:, None, None, None, None])
        o = _gqa_attention(q, k_layer, v_layer, mask)
        o = jnp.einsum("bthk,hkd->btd", o, lp["wo"].astype(cfg.dtype))
        x = x + o
        h = rms_norm(x, lp["mlp_norm"], cfg.rms_eps)
        down, _ = ffn_block(h, lp, cfg, None)
        x = x + down
        return x, (k_layer, v_layer)

    x, (k_all, v_all) = jax.lax.scan(
        block, x, (params["layers"], cache["k"], cache["v"]))
    logits = _final_logits(params, x, cfg)[:, 0]  # [B, V]
    return {"k": k_all, "v": v_all, "pos": pos + 1, "start": start}, logits


@partial(jax.jit, static_argnames=("cfg", "greedy", "steps"),
         donate_argnums=(1,))
def decode_slots(params: Params, cache: SlotCache, tokens: jax.Array,
                 active: jax.Array, rng: jax.Array,
                 cfg: TransformerConfig, greedy: bool = True,
                 temperature: float = 1.0, eos_id: int = -1,
                 steps: int = 1):
    """``steps`` decode substeps for every slot in ONE compiled program:
    tokens [B] (pending sampled-but-not-decoded tokens), active [B]
    bool; -> (cache, [B, steps+1]) where column 0 echoes the INPUT
    tokens and columns 1..steps are the new samples.

    Multi-step scheduling: the host pays one dispatch + one transfer per
    chunk instead of per token — admission granularity becomes ``steps``
    decode steps, host overhead drops by the same factor. The echoed
    input column lets the pipelined host loop learn prefill-sampled
    first tokens from the same fetch (the token chain itself never
    leaves the device). Rows whose input is ``eos_id`` or that hit it
    mid-chunk freeze on-device (keep emitting eos, like generate());
    inactive slots compute junk into a position the next real write or
    prefill overwrites, their positions don't advance, and the host
    ignores their samples.
    """
    pos0 = cache["pos"]

    def substep(carry, step_rng):
        cache, tok, done = carry
        cache, logits = _decode_one(params, cache, tok, cfg)
        nxt = _sample(logits, step_rng, greedy, temperature)
        nxt = jnp.where(done, jnp.asarray(eos_id, nxt.dtype), nxt)
        done = done | (nxt == eos_id)
        return (cache, nxt, done), nxt

    done0 = tokens == eos_id
    (cache, _, _), toks = jax.lax.scan(
        substep, (cache, tokens, done0), jax.random.split(rng, steps))
    # only active rows advance; inactive rows' junk substep writes are
    # overwritten by the next prefill/real decode at their frozen pos
    new_pos = jnp.where(active, cache["pos"],
                        pos0).astype(jnp.int32)
    cache = {"k": cache["k"], "v": cache["v"], "pos": new_pos,
             "start": cache["start"]}
    return cache, jnp.concatenate([tokens[:, None], toks.T], axis=1)


# ---- host-side scheduler ----------------------------------------------------

_FINISH_EOS = "eos"
_FINISH_LENGTH = "length"


@dataclass
class _Request:
    rid: int
    prompt: List[int]
    max_new_tokens: int
    tokens: List[int] = field(default_factory=list)
    done: threading.Event = field(default_factory=threading.Event)
    stream_q: Optional[queue.Queue] = None
    finish_reason: Optional[str] = None
    error: Optional[BaseException] = None

    def emit(self, tok: int):
        self.tokens.append(tok)
        if self.stream_q is not None:
            self.stream_q.put(tok)

    def finish(self, reason: str):
        self.finish_reason = reason
        if self.stream_q is not None:
            self.stream_q.put(None)  # sentinel: stream closed
        self.done.set()


class InferenceEngine:
    """Slot scheduler over ``prefill_slot``/``decode_slots``.

    ``step()`` is one engine iteration: admit queued prompts into free
    slots (prefill), then advance every active slot one token (decode).
    ``serve_forever`` runs steps on a background thread; ``submit`` /
    ``submit_stream`` are thread-safe entry points.
    """

    def __init__(self, params: Params, cfg: TransformerConfig, *,
                 slots: int = 8, max_prompt_len: int = 64,
                 max_new_tokens: int = 32, greedy: bool = True,
                 temperature: float = 1.0, eos_id: int = -1,
                 pad_id: int = 0, mesh=None, seed: int = 0,
                 min_bucket: int = 16, decode_chunk: int = 4,
                 fetch_every: int = 1):
        self.cfg = cfg
        self.slots = int(slots)
        self.max_prompt_len = int(max_prompt_len)
        self.max_new_tokens = int(max_new_tokens)
        self.greedy = bool(greedy)
        self.temperature = float(temperature)
        self.eos_id = int(eos_id)
        self.pad_id = int(pad_id)
        self.mesh = mesh
        # multi-step scheduling: decode_chunk substeps per dispatch (one
        # host round-trip per chunk); admission happens between chunks
        self.decode_chunk = max(1, int(decode_chunk))
        # fetch batching: accumulate this many dispatched chunks, then
        # concatenate their token outputs ON DEVICE and fetch once — on
        # backends where a device->host fetch serializes with execution
        # (tunneled TPU), the fetch round trip is the dominant per-chunk
        # cost and amortizing it this way is the main throughput lever.
        # The price is bookkeeping latency: finishes are detected (and
        # slots refilled) every fetch_every chunks.
        self.fetch_every = max(1, int(fetch_every))
        self._max_len = self.max_prompt_len + self.max_new_tokens
        self._buckets = []
        b = max(8, int(min_bucket))
        while b < self.max_prompt_len:
            self._buckets.append(b)
            b *= 2
        self._buckets.append(self.max_prompt_len)

        if mesh is not None:
            from ray_tpu.parallel.sharding import shard_array, tree_shardings

            shardings = tree_shardings(mesh, param_logical_axes(cfg))
            params = jax.tree.map(
                lambda x, s: jax.device_put(x, s), params, shardings)
            cache = init_slot_cache(cfg, self.slots, self._max_len)
            self.cache = {k: shard_array(mesh, v, cache_logical_axes()[k])
                          for k, v in cache.items()}
        else:
            self.cache = init_slot_cache(cfg, self.slots, self._max_len)
        self.params = params

        self._rng = jax.random.key(seed)
        self._step_i = itertools.count()
        self._rid = itertools.count()
        self._queue: "queue.Queue[_Request]" = queue.Queue()
        self._slot_req: List[Optional[_Request]] = [None] * self.slots
        # planned-occupancy scheduling: _slot_left[s] is how many tokens
        # the resident request is still OWED BY DISPATCH (not by fetch).
        # Residency is length-bounded and known at submit time, so
        # admission decisions never wait for a device->host fetch — the
        # fetch is pure result delivery. eos can only shorten a plan; it
        # is reclaimed when a fetch reveals it.
        self._slot_left: List[int] = [0] * self.slots
        # the token chain lives ON DEVICE: chunk N+1's inputs are chunk
        # N's last samples (or a prefill's first sample, merged in with
        # .at[slot].set) — the host never syncs to keep the chain going
        self._next_tok_dev = jnp.zeros(self.slots, jnp.int32)
        # dispatched-but-unfetched chunks: [(toks_dev [B, K+1],
        # [(slot, request, emit_from_col)])] — fetched together (one
        # device-side concat, one transfer) once fetch_every have
        # accumulated, or when the engine runs out of dispatchable work
        self._inflight: List[tuple] = []
        self._work = threading.Event()  # set when there may be work
        self._lock = threading.Lock()   # guards step() vs concurrent step()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # set when the step loop died on an unrecoverable error (device /
        # XLA failure); submit() raises from then on instead of queueing
        # work that nothing will ever drain. _death_lock orders submit's
        # check+enqueue against _die's drain (NOT _lock — that is held for
        # the whole of a step(), and submissions must not block on it)
        self._fatal: Optional[BaseException] = None
        self._death_lock = threading.Lock()
        # running counters for benchmarking / observability
        self.stats = {"prefills": 0, "decode_steps": 0, "tokens_out": 0,
                      "requests_done": 0}

    # -------------------------------------------------------- submission

    def submit(self, prompt: Sequence[int],
               max_new_tokens: Optional[int] = None) -> _Request:
        """Enqueue a prompt; returns the request (``result()`` to wait)."""
        req = self._make_request(prompt, max_new_tokens, stream=False)
        with self._death_lock:
            self._check_alive()
            self._queue.put(req)
        self._work.set()
        return req

    def submit_stream(self, prompt: Sequence[int],
                      max_new_tokens: Optional[int] = None):
        """Enqueue a prompt; returns an iterator of token ids that ends
        when the sequence finishes (eos or length)."""
        req = self._make_request(prompt, max_new_tokens, stream=True)
        with self._death_lock:
            self._check_alive()
            self._queue.put(req)
        self._work.set()

        def gen():
            while True:
                tok = req.stream_q.get()
                if tok is None:
                    if req.error is not None:
                        raise req.error
                    return
                yield tok
        return gen()

    def _make_request(self, prompt, max_new_tokens, stream: bool):
        prompt = list(prompt)
        if not prompt:
            raise ValueError("empty prompt")
        if len(prompt) > self.max_prompt_len:
            raise ValueError(
                f"prompt length {len(prompt)} exceeds this engine's "
                f"max_prompt_len={self.max_prompt_len}")
        mnt = self.max_new_tokens if max_new_tokens is None \
            else min(int(max_new_tokens), self.max_new_tokens)
        if mnt <= 0:
            raise ValueError("max_new_tokens must be >= 1")
        return _Request(rid=next(self._rid), prompt=prompt,
                        max_new_tokens=mnt,
                        stream_q=queue.Queue() if stream else None)

    # ------------------------------------------------------------- engine

    def _next_rng(self):
        return jax.random.fold_in(self._rng, next(self._step_i))

    def _bucket(self, n: int) -> int:
        for b in self._buckets:
            if n <= b:
                return b
        return self.max_prompt_len

    def _admit(self, req: _Request, slot: int):
        """Dispatch a prefill into ``slot`` (ASYNC — the sampled first
        token joins the device-side chain; its value reaches the host in
        the next chunk's echoed input column)."""
        P = self._bucket(len(req.prompt))
        toks = np.full((1, P), self.pad_id, np.int32)
        toks[0, P - len(req.prompt):] = req.prompt
        start = P - len(req.prompt)
        self.cache, tok = prefill_slot(
            self.params, self.cache, jnp.asarray(toks),
            jnp.asarray(slot, jnp.int32), jnp.asarray(start, jnp.int32),
            self._next_rng(), self.cfg, self.greedy, self.temperature)
        self._slot_req[slot] = req
        self._next_tok_dev = self._next_tok_dev.at[slot].set(tok)
        self.stats["prefills"] += 1

    def _emit_to(self, req: _Request, slot: int, tok: int):
        """Record one generated token; on an eos finish, reclaim the
        slot's remaining planned occupancy (the plan is length-based and
        eos can only shorten it)."""
        req.emit(tok)
        self.stats["tokens_out"] += 1
        reason = None
        if tok == self.eos_id:
            reason = _FINISH_EOS
        elif len(req.tokens) >= req.max_new_tokens:
            reason = _FINISH_LENGTH
        if reason is not None:
            if self._slot_req[slot] is req:
                self._slot_req[slot] = None
                self._slot_left[slot] = 0
            self.stats["requests_done"] += 1
            req.finish(reason)

    def step(self) -> bool:
        """One engine iteration; returns True if any work was done."""
        with self._lock:
            return self._step_locked()

    def _pow2_floor(self, x: int) -> int:
        return 1 << (max(1, min(x, self.decode_chunk)).bit_length() - 1)

    def _step_locked(self) -> bool:
        # 1) admission: a slot whose planned occupancy ran out is free —
        #    no fetch needed to know it (delivery of its resident's
        #    tokens rides the already-recorded snapshots). Prefills are
        #    async dispatches chained on the device queue.
        admitted = set()
        for slot in range(self.slots):
            if self._slot_left[slot] > 0:
                continue
            if self._slot_req[slot] is not None:
                # planned release: dispatching for it is complete
                self._slot_req[slot] = None
            try:
                req = self._queue.get_nowait()
            except queue.Empty:
                continue
            try:
                self._admit(req, slot)
                # the plan includes the prefill-sampled first token; it
                # reaches the host in the next chunk's echo column
                self._slot_left[slot] = req.max_new_tokens
                admitted.add(slot)
            except BaseException as e:  # surface to the waiter, keep going
                req.error = e
                req.finish("error")
                continue
        # 2) dispatch one decode chunk (async) for every slot with planned
        #    work. Width adapts: under admission pressure the chunk is cut
        #    at the earliest planned release (power-of-two widths bound
        #    the compile count); otherwise the full decode_chunk runs.
        active_slots = [s for s in range(self.slots)
                        if self._slot_left[s] > 0]
        dispatched = False
        if active_slots:
            if self._queue.qsize() > 0:
                need = min(self._slot_left[s] - (1 if s in admitted else 0)
                           for s in active_slots)
                width = self._pow2_floor(max(1, need))
            else:
                width = self.decode_chunk
            snapshot = []
            for slot in active_slots:
                req = self._slot_req[slot]
                new = slot in admitted
                take = min(self._slot_left[slot], width + (1 if new else 0))
                snapshot.append((slot, req, 0 if new else 1, take))
                self._slot_left[slot] = max(
                    0, self._slot_left[slot] - (width + 1 if new else width))
            active = np.zeros(self.slots, bool)
            active[active_slots] = True
            self.cache, toks = decode_slots(
                self.params, self.cache, self._next_tok_dev,
                jnp.asarray(active), self._next_rng(), self.cfg,
                self.greedy, self.temperature, self.eos_id,
                steps=width)
            self._next_tok_dev = toks[:, -1]
            self.stats["decode_steps"] += width
            self._inflight.append((toks, snapshot))
            dispatched = True
        # 3) flush: one device-side concat + ONE transfer for every
        #    accumulated chunk, once fetch_every are pending (or the
        #    engine has nothing left to dispatch). The fetch round trip
        #    is amortized over fetch_every chunks of device compute.
        processed = False
        if self._inflight and (len(self._inflight) >= self.fetch_every
                               or not dispatched):
            pending, self._inflight = self._inflight, []
            # pad every chunk to one uniform width before the device-side
            # concat: adaptive widths would otherwise make the concat's
            # shape signature (and its compiled program) vary per width
            # combination
            W = self.decode_chunk + 1
            parts = [t if t.shape[1] == W
                     else jnp.pad(t, ((0, 0), (0, W - t.shape[1])))
                     for t, _ in pending]
            big = np.asarray(parts[0] if len(parts) == 1
                             else jnp.concatenate(parts, axis=1))
            for i, (toks_dev, snap) in enumerate(pending):
                width = toks_dev.shape[1]
                seg = big[:, i * W:i * W + width]
                for slot, req, from_col, take in snap:
                    if req.done.is_set():
                        continue  # finished in an earlier chunk
                    for t in range(from_col, from_col + take):
                        self._emit_to(req, slot, int(seg[slot, t]))
                        if req.done.is_set():
                            break  # rest of the row is frozen eos/junk
            processed = True
        return bool(admitted or dispatched or processed)

    # ---------------------------------------------------- background loop

    def serve_forever(self):
        """Run the engine on a daemon thread until ``shutdown()``."""
        if self._thread is not None:
            return self
        self._stop.clear()

        def loop():
            while not self._stop.is_set():
                try:
                    busy = self.step()
                except BaseException as e:
                    # an error escaping step() (device/XLA failure at
                    # dispatch or fetch) kills the engine: error out every
                    # in-flight and queued request so no waiter hangs, and
                    # refuse new submissions
                    self._die(e)
                    return
                if not busy:
                    # idle: sleep until a submission arrives
                    self._work.clear()
                    if not self._queue.qsize():
                        self._work.wait(timeout=0.05)
        self._thread = threading.Thread(target=loop, name="llm-engine",
                                        daemon=True)
        self._thread.start()
        return self

    def _check_alive(self):
        if self._fatal is not None:
            raise RuntimeError(
                "InferenceEngine is dead (step loop failed)") \
                from self._fatal

    def _die(self, exc: BaseException):
        """Mark the engine dead and fail every known request."""
        failed = [r for r in self._slot_req if r is not None]
        self._slot_req = [None] * self.slots
        self._slot_left = [0] * self.slots
        with self._death_lock:
            # after this block no submit() can enqueue: _fatal is visible
            # to every subsequent check, and the queue is drained
            self._fatal = exc
            while True:
                try:
                    failed.append(self._queue.get_nowait())
                except queue.Empty:
                    break
        for _, snap in self._inflight:
            failed.extend(req for _, req, _, _ in snap)
        self._inflight = []
        for req in failed:
            if not req.done.is_set():
                req.error = exc
                req.finish("error")

    def shutdown(self):
        self._stop.set()
        self._work.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None

    # ------------------------------------------------------- conveniences

    def generate(self, prompt: Sequence[int],
                 max_new_tokens: Optional[int] = None,
                 timeout: float = 300.0) -> List[int]:
        """Blocking single-prompt helper (drives steps inline if no
        background thread is running)."""
        req = self.submit(prompt, max_new_tokens)
        if self._thread is None:
            while not req.done.is_set():
                if not self.step():
                    break
        if not req.done.wait(timeout):
            raise TimeoutError("generation timed out")
        if req.error is not None:
            raise req.error
        return list(req.tokens)
