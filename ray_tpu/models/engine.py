"""Continuous-batching inference engine: per-step slot admission/eviction.

Ref analog: the reference serves LLMs through replica actors whose
batching is *request-cohort* shaped (`python/ray/serve/batching.py:337`
coalesces waiting calls; `python/ray/serve/_private/replica.py:237` runs
them) — a cohort must finish before its slots free, so one long
generation stalls the batch. This engine is the vLLM/Orca-style redesign
the reference delegates to external vLLM workers for, built TPU-first:

  - The KV cache is a fixed pool of B *slots* over one contiguous
    [L, B, S, KV, hd] array — static shapes, one compiled decode program
    for the life of the engine. A slot is a row; admission writes a new
    prompt's K/V into a freed row, eviction is just host bookkeeping.
  - Each decode step advances EVERY active slot by one token in a single
    batched program (per-row cache positions, per-row RoPE), then the
    host admits queued prompts into any slots that finished — finished
    sequences never block running ones.
  - Prefill is a separate program per (group size, prompt bucket) pair
    (both power-of-two, bounded compile count) whose K/V lands directly
    in the slot rows; queued prompts admit in groups of up to 4 as ONE
    batched program, and prefills interleave with decode chunks so
    time-to-first-token stays bounded under load.
  - Dispatch and fetch are pipelined across two threads: the scheduler
    thread admits + dispatches (cheap async calls), the fetcher thread
    does the device->host token transfers, which overlap with queued
    execution — on a tunneled backend the ~100x gap between dispatch
    cost and fetch round-trip makes this split the difference between
    losing and beating cohort batching (bench_serve.py).
  - Sampling happens on-device; the host sees B int32s per step — the
    decode loop's host<->device traffic is O(slots), not O(vocab).
  - Tensor parallelism comes from sharding, not new code: params carry
    their logical axes (kv_heads/heads/mlp/vocab -> "tensor") and the
    cache shards on its KV-head axis; XLA propagates the TP layout
    through the same jitted step and inserts the collectives.
"""

from __future__ import annotations

import itertools
import queue
import threading
import time
from dataclasses import dataclass, field
from functools import partial
from typing import Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.models.config import TransformerConfig
from ray_tpu.models.generate import (_final_logits, _gqa_attention,
                                     _prefill_hidden)
from ray_tpu.models.transformer import (Params, ffn_block,
                                        param_logical_axes, qkv_proj,
                                        rms_norm)

SlotCache = Dict[str, jax.Array]
# {"k"/"v": [L, B, S, KV, hd], "pos": [B], "start": [B]} — pos[b] is slot
# b's next write position; start[b] its first real (non-pad) position.


def init_slot_cache(cfg: TransformerConfig, slots: int,
                    max_len: int) -> SlotCache:
    shape = (cfg.n_layers, slots, max_len, cfg.kv_heads, cfg.head_dim)
    return {"k": jnp.zeros(shape, cfg.dtype),
            "v": jnp.zeros(shape, cfg.dtype),
            "pos": jnp.zeros((slots,), jnp.int32),
            "start": jnp.zeros((slots,), jnp.int32)}


def cache_logical_axes() -> Dict[str, tuple]:
    """Logical axes of the slot cache (slots axis stays unsharded —
    serving shards the model, not the batch)."""
    kv = ("layers", None, None, "kv_heads", None)
    return {"k": kv, "v": kv, "pos": (None,), "start": (None,)}


def _sample(logits, rng, greedy: bool, temperature):
    if greedy:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    return jax.random.categorical(
        rng, logits / jnp.maximum(temperature, 1e-6)).astype(jnp.int32)


@partial(jax.jit, static_argnames=("cfg", "greedy"), donate_argnums=(1,))
def prefill_slot(params: Params, cache: SlotCache, tokens: jax.Array,
                 slot: jax.Array, start: jax.Array, rng: jax.Array,
                 cfg: TransformerConfig, greedy: bool = True,
                 temperature: float = 1.0):
    """Run the prompt ``tokens`` [1, P] (left-padded to its bucket, first
    real token at ``start``) and write its K/V into slot row ``slot``;
    -> (cache, first sampled token []). One compiled program per bucket P.
    """
    cache, toks = prefill_slots(params, cache, tokens, slot[None],
                                start[None], rng, cfg, greedy, temperature)
    return cache, toks[0]


@partial(jax.jit, static_argnames=("cfg", "greedy"), donate_argnums=(1,))
def prefill_slots(params: Params, cache: SlotCache, tokens: jax.Array,
                  slots: jax.Array, starts: jax.Array, rng: jax.Array,
                  cfg: TransformerConfig, greedy: bool = True,
                  temperature: float = 1.0):
    """Batched prefill: ``tokens`` [K, P] (left-padded to one shared
    bucket, first real token of row i at ``starts[i]``) lands in cache
    rows ``slots`` [K]; -> (cache, first sampled tokens [K]).

    One compiled program per (K, P) pair; K is kept to a few power-of-two
    group sizes by the scheduler. Batching prefills is a dispatch-count
    lever: on a tunneled backend each program dispatch costs ~ms and the
    B=1 prefill wastes most of the MXU, so admitting 4 queued prompts as
    one [4, P] program is ~3x cheaper than 4 serial [1, P] programs.
    """
    K, P = tokens.shape
    x, cK = _prefill_hidden(params, tokens, cfg, P, starts)
    last = _final_logits(params, x[:, -1:], cfg)[:, 0]  # [K, V]
    toks = _sample(last, rng, greedy, temperature)      # [K]
    # cK["k"]: [L, K, P, KV, hd] -> row i into slot row slots[i]
    k, v = cache["k"], cache["v"]
    zero = jnp.zeros((), jnp.int32)
    for i in range(K):  # K is static: unrolled row writes
        k = jax.lax.dynamic_update_slice(
            k, cK["k"][:, i:i + 1].astype(k.dtype),
            (zero, slots[i], zero, zero, zero))
        v = jax.lax.dynamic_update_slice(
            v, cK["v"][:, i:i + 1].astype(v.dtype),
            (zero, slots[i], zero, zero, zero))
    return {"k": k, "v": v,
            "pos": cache["pos"].at[slots].set(P),
            "start": cache["start"].at[slots].set(starts)}, toks


def _write_rows(layer_cache, kv, pos):
    """Per-row cache write: layer_cache [B, S, KV, hd] <- kv [B, 1, KV, hd]
    at per-row seq positions ``pos`` [B].

    A one-hot select, NOT a vmapped dynamic_update_slice: per-row dynamic
    indices lower to a scatter that falls off the TPU fast path (measured
    ~5x decode slowdown); the select is pure elementwise bandwidth over
    a cache the decode step already reads in full."""
    S = layer_cache.shape[1]
    hit = (jnp.arange(S)[None, :] == pos[:, None])[:, :, None, None]
    return jnp.where(hit, kv.astype(layer_cache.dtype), layer_cache)


def _decode_one(params: Params, cache: SlotCache, tokens: jax.Array,
                cfg: TransformerConfig):
    """One decode step for every slot: tokens [B] (each slot's pending
    token) -> (cache with pos advanced, logits [B, V]).

    pos/RoPE/attention masks are all per-row, so slots admitted at
    different times decode together in one program.
    """
    pos, start = cache["pos"], cache["start"]
    x = params["embed"].astype(cfg.dtype)[tokens[:, None]]  # [B, 1, d]
    positions = pos[:, None]  # [B, 1] per-row RoPE

    def block(x, scanned):
        lp, k_layer, v_layer = scanned
        h = rms_norm(x, lp["attn_norm"], cfg.rms_eps)
        q, k, v = qkv_proj(h, lp, cfg, positions)
        k_layer = _write_rows(k_layer, k, pos)
        v_layer = _write_rows(v_layer, v, pos)
        S = k_layer.shape[1]
        kpos = jnp.arange(S)[None, None, None, None, :]
        mask = (kpos <= pos[:, None, None, None, None]) & \
            (kpos >= start[:, None, None, None, None])
        o = _gqa_attention(q, k_layer, v_layer, mask)
        o = jnp.einsum("bthk,hkd->btd", o, lp["wo"].astype(cfg.dtype))
        x = x + o
        h = rms_norm(x, lp["mlp_norm"], cfg.rms_eps)
        down, _ = ffn_block(h, lp, cfg, None)
        x = x + down
        return x, (k_layer, v_layer)

    x, (k_all, v_all) = jax.lax.scan(
        block, x, (params["layers"], cache["k"], cache["v"]))
    logits = _final_logits(params, x, cfg)[:, 0]  # [B, V]
    return {"k": k_all, "v": v_all, "pos": pos + 1, "start": start}, logits


@partial(jax.jit, static_argnames=("cfg", "greedy", "steps"),
         donate_argnums=(1,))
def decode_slots(params: Params, cache: SlotCache, tokens: jax.Array,
                 active: jax.Array, rng: jax.Array,
                 cfg: TransformerConfig, greedy: bool = True,
                 temperature: float = 1.0, eos_id: int = -1,
                 steps: int = 1):
    """``steps`` decode substeps for every slot in ONE compiled program:
    tokens [B] (pending sampled-but-not-decoded tokens), active [B]
    bool; -> (cache, [B, steps+1]) where column 0 echoes the INPUT
    tokens and columns 1..steps are the new samples.

    Multi-step scheduling: the host pays one dispatch + one transfer per
    chunk instead of per token — admission granularity becomes ``steps``
    decode steps, host overhead drops by the same factor. The echoed
    input column lets the pipelined host loop learn prefill-sampled
    first tokens from the same fetch (the token chain itself never
    leaves the device). Rows whose input is ``eos_id`` or that hit it
    mid-chunk freeze on-device (keep emitting eos, like generate());
    inactive slots compute junk into a position the next real write or
    prefill overwrites, their positions don't advance, and the host
    ignores their samples.
    """
    pos0 = cache["pos"]

    def substep(carry, step_rng):
        cache, tok, done = carry
        cache, logits = _decode_one(params, cache, tok, cfg)
        nxt = _sample(logits, step_rng, greedy, temperature)
        nxt = jnp.where(done, jnp.asarray(eos_id, nxt.dtype), nxt)
        done = done | (nxt == eos_id)
        return (cache, nxt, done), nxt

    done0 = tokens == eos_id
    (cache, _, _), toks = jax.lax.scan(
        substep, (cache, tokens, done0), jax.random.split(rng, steps))
    # only active rows advance; inactive rows' junk substep writes are
    # overwritten by the next prefill/real decode at their frozen pos
    new_pos = jnp.where(active, cache["pos"],
                        pos0).astype(jnp.int32)
    cache = {"k": cache["k"], "v": cache["v"], "pos": new_pos,
             "start": cache["start"]}
    return cache, jnp.concatenate([tokens[:, None], toks.T], axis=1)


# ---- host-side scheduler ----------------------------------------------------

_FINISH_EOS = "eos"
_FINISH_LENGTH = "length"


def _chunk_ready(x) -> bool:
    """True when the device has finished computing ``x`` (non-blocking);
    conservatively False on backends without is_ready."""
    try:
        return bool(x.is_ready())
    except AttributeError:
        return False


@dataclass
class _Request:
    rid: int
    prompt: List[int]
    max_new_tokens: int
    tokens: List[int] = field(default_factory=list)
    done: threading.Event = field(default_factory=threading.Event)
    stream_q: Optional[queue.Queue] = None
    finish_reason: Optional[str] = None
    error: Optional[BaseException] = None

    def emit(self, tok: int):
        self.tokens.append(tok)
        if self.stream_q is not None:
            self.stream_q.put(tok)

    def finish(self, reason: str):
        self.finish_reason = reason
        if self.stream_q is not None:
            self.stream_q.put(None)  # sentinel: stream closed
        self.done.set()


class InferenceEngine:
    """Slot scheduler over ``prefill_slot``/``decode_slots``.

    ``step()`` is one engine iteration: admit queued prompts into free
    slots (prefill), then advance every active slot one token (decode).
    ``serve_forever`` runs steps on a background thread; ``submit`` /
    ``submit_stream`` are thread-safe entry points.
    """

    def __init__(self, params: Params, cfg: TransformerConfig, *,
                 slots: int = 8, max_prompt_len: int = 64,
                 max_new_tokens: int = 32, greedy: bool = True,
                 temperature: float = 1.0, eos_id: int = -1,
                 pad_id: int = 0, mesh=None, seed: int = 0,
                 min_bucket: int = 16, decode_chunk: int = 4,
                 fetch_every: int = 1, max_inflight: int = 6):
        self.cfg = cfg
        self.slots = int(slots)
        self.max_prompt_len = int(max_prompt_len)
        self.max_new_tokens = int(max_new_tokens)
        self.greedy = bool(greedy)
        self.temperature = float(temperature)
        self.eos_id = int(eos_id)
        self.pad_id = int(pad_id)
        self.mesh = mesh
        # multi-step scheduling: decode_chunk substeps per dispatch (one
        # host round-trip per chunk); admission happens between chunks
        self.decode_chunk = max(1, int(decode_chunk))
        # fetch batching (inline step() mode): accumulate this many
        # dispatched chunks, then concatenate their token outputs ON
        # DEVICE and fetch once. Under serve_forever the dedicated
        # fetcher thread self-paces instead (drain everything pending
        # per cycle) and this knob is unused.
        self.fetch_every = max(1, int(fetch_every))
        # pipelined mode: how many dispatched-but-unfetched decode chunks
        # may exist before the dispatch loop waits for the fetcher.
        # Measured on the tunneled TPU: a device->host fetch costs
        # ~240 ms wall but OVERLAPS with queued execution, so the win is
        # dispatching ahead while a previous fetch is in flight; the cap
        # bounds result-delivery latency (~cap * chunk_time + one fetch).
        self.max_inflight = max(1, int(max_inflight))
        self._max_len = self.max_prompt_len + self.max_new_tokens
        self._buckets = []
        b = max(8, int(min_bucket))
        while b < self.max_prompt_len:
            self._buckets.append(b)
            b *= 2
        self._buckets.append(self.max_prompt_len)

        if mesh is not None:
            from ray_tpu.parallel.sharding import shard_array, tree_shardings

            shardings = tree_shardings(mesh, param_logical_axes(cfg))
            params = jax.tree.map(
                lambda x, s: jax.device_put(x, s), params, shardings)
            cache = init_slot_cache(cfg, self.slots, self._max_len)
            self.cache = {k: shard_array(mesh, v, cache_logical_axes()[k])
                          for k, v in cache.items()}
        else:
            self.cache = init_slot_cache(cfg, self.slots, self._max_len)
        self.params = params

        self._rng = jax.random.key(seed)
        self._step_i = itertools.count()
        self._rid = itertools.count()
        self._queue: "queue.Queue[_Request]" = queue.Queue()
        self._slot_req: List[Optional[_Request]] = [None] * self.slots
        # planned-occupancy scheduling: _slot_left[s] is how many tokens
        # the resident request is still OWED BY DISPATCH (not by fetch).
        # Residency is length-bounded and known at submit time, so
        # admission decisions never wait for a device->host fetch — the
        # fetch is pure result delivery. eos can only shorten a plan; it
        # is reclaimed when a fetch reveals it.
        self._slot_left: List[int] = [0] * self.slots
        # slots admitted but not yet decoded once: their next chunk's
        # echo column carries the prefill-sampled token (emit from col 0)
        self._slot_new: List[bool] = [False] * self.slots
        # the token chain lives ON DEVICE: chunk N+1's inputs are chunk
        # N's last samples (or a prefill's first sample, merged in with
        # .at[slot].set) — the host never syncs to keep the chain going
        self._next_tok_dev = jnp.zeros(self.slots, jnp.int32)
        # dispatched-but-unfetched chunks: [(toks_dev [B, K+1],
        # [(slot, request, emit_from_col)])] — fetched together (one
        # device-side concat, one transfer) once fetch_every have
        # accumulated, or when the engine runs out of dispatchable work
        self._inflight: List[tuple] = []
        self._work = threading.Event()  # set when there may be work
        self._lock = threading.Lock()   # guards step() vs concurrent step()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # pipelined fetcher (serve_forever only): consumes _inflight so
        # the dispatch loop never blocks on a device->host transfer
        self._fetcher: Optional[threading.Thread] = None
        self._fetch_evt = threading.Event()   # work for the fetcher
        # set when the step loop died on an unrecoverable error (device /
        # XLA failure); submit() raises from then on instead of queueing
        # work that nothing will ever drain. _death_lock orders submit's
        # check+enqueue against _die's drain (NOT _lock — that is held for
        # the whole of a step(), and submissions must not block on it)
        self._fatal: Optional[BaseException] = None
        self._death_lock = threading.Lock()
        # running counters for benchmarking / observability
        self.stats = {"prefills": 0, "prefill_dispatches": 0,
                      "decode_steps": 0, "fetches": 0, "tokens_out": 0,
                      "requests_done": 0, "fetch_wall_s": 0.0,
                      "cap_stalls": 0, "dispatch_wall_s": 0.0}
        self._at_cap = False

    # -------------------------------------------------------- submission

    def submit(self, prompt: Sequence[int],
               max_new_tokens: Optional[int] = None) -> _Request:
        """Enqueue a prompt; returns the request (``result()`` to wait)."""
        req = self._make_request(prompt, max_new_tokens, stream=False)
        with self._death_lock:
            self._check_alive()
            self._queue.put(req)
        self._work.set()
        return req

    def submit_stream(self, prompt: Sequence[int],
                      max_new_tokens: Optional[int] = None):
        """Enqueue a prompt; returns an iterator of token ids that ends
        when the sequence finishes (eos or length)."""
        req = self._make_request(prompt, max_new_tokens, stream=True)
        with self._death_lock:
            self._check_alive()
            self._queue.put(req)
        self._work.set()

        def gen():
            while True:
                tok = req.stream_q.get()
                if tok is None:
                    if req.error is not None:
                        raise req.error
                    return
                yield tok
        return gen()

    def _make_request(self, prompt, max_new_tokens, stream: bool):
        prompt = list(prompt)
        if not prompt:
            raise ValueError("empty prompt")
        if len(prompt) > self.max_prompt_len:
            raise ValueError(
                f"prompt length {len(prompt)} exceeds this engine's "
                f"max_prompt_len={self.max_prompt_len}")
        mnt = self.max_new_tokens if max_new_tokens is None \
            else min(int(max_new_tokens), self.max_new_tokens)
        if mnt <= 0:
            raise ValueError("max_new_tokens must be >= 1")
        return _Request(rid=next(self._rid), prompt=prompt,
                        max_new_tokens=mnt,
                        stream_q=queue.Queue() if stream else None)

    # ------------------------------------------------------------- engine

    def _next_rng(self):
        return jax.random.fold_in(self._rng, next(self._step_i))

    def _bucket(self, n: int) -> int:
        for b in self._buckets:
            if n <= b:
                return b
        return self.max_prompt_len

    def _admit_group(self, group: List[tuple]):
        """Dispatch ONE batched prefill for ``group`` = [(slot, req)]
        (ASYNC — the sampled first tokens join the device-side chain;
        their values reach the host in the next chunk's echo column).
        All rows pad to the largest member's bucket so the group shares
        one compiled (K, P) program."""
        K = len(group)
        P = max(self._bucket(len(req.prompt)) for _, req in group)
        toks = np.full((K, P), self.pad_id, np.int32)
        slots = np.zeros(K, np.int32)
        starts = np.zeros(K, np.int32)
        for i, (slot, req) in enumerate(group):
            toks[i, P - len(req.prompt):] = req.prompt
            slots[i] = slot
            starts[i] = P - len(req.prompt)
        self.cache, first = prefill_slots(
            self.params, self.cache, jnp.asarray(toks),
            jnp.asarray(slots), jnp.asarray(starts),
            self._next_rng(), self.cfg, self.greedy, self.temperature)
        self._next_tok_dev = self._next_tok_dev.at[jnp.asarray(slots)] \
            .set(first)
        for slot, req in group:
            self._slot_req[slot] = req
        self.stats["prefills"] += K
        self.stats["prefill_dispatches"] += 1

    _GROUP_SIZES = (4, 2, 1)  # compiled-prefill batch sizes, largest first

    def warmup(self):
        """Compile every program the serving loop can hit (per-bucket x
        per-group-size prefills, the decode chunk) so no compile lands
        mid-traffic. Resets slot state afterwards; call before serving."""
        sizes = [s for s in self._GROUP_SIZES if s <= self.slots]
        for bucket in self._buckets:
            for K in sizes:
                toks = np.full((K, bucket), self.pad_id, np.int32)
                toks[:, -1] = 1
                self.cache, first = prefill_slots(
                    self.params, self.cache, jnp.asarray(toks),
                    jnp.arange(K, dtype=jnp.int32),
                    jnp.full((K,), bucket - 1, jnp.int32),
                    self._next_rng(), self.cfg, self.greedy,
                    self.temperature)
                # warm the chain-merge too (_admit_group runs it per
                # group size; a mid-traffic compile stalls the loop)
                self._next_tok_dev = self._next_tok_dev.at[
                    jnp.arange(K, dtype=jnp.int32)].set(first)
        cache, toks = decode_slots(
            self.params, self.cache, self._next_tok_dev,
            jnp.ones(self.slots, bool), self._next_rng(), self.cfg,
            self.greedy, self.temperature, self.eos_id,
            steps=self.decode_chunk)
        self._next_tok_dev = toks[:, -1]  # warm the last-column slice
        jax.block_until_ready(self._next_tok_dev)
        # reset bookkeeping: positions to zero, junk K/V is unreachable
        self.cache = {"k": cache["k"], "v": cache["v"],
                      "pos": jnp.zeros_like(cache["pos"]),
                      "start": jnp.zeros_like(cache["start"])}
        self._next_tok_dev = jnp.zeros(self.slots, jnp.int32)
        return self

    def _emit_to(self, req: _Request, slot: int, tok: int):
        """Record one generated token; on an eos finish, reclaim the
        slot's remaining planned occupancy (the plan is length-based and
        eos can only shorten it)."""
        req.emit(tok)
        self.stats["tokens_out"] += 1
        reason = None
        if tok == self.eos_id:
            reason = _FINISH_EOS
        elif len(req.tokens) >= req.max_new_tokens:
            reason = _FINISH_LENGTH
        if reason is not None:
            if self._slot_req[slot] is req:
                self._slot_req[slot] = None
                self._slot_left[slot] = 0
            self.stats["requests_done"] += 1
            req.finish(reason)

    def step(self) -> bool:
        """One engine iteration; returns True if any work was done."""
        with self._lock:
            return self._step_locked()

    def _step_locked(self) -> bool:
        # 1) admission: a slot whose planned occupancy ran out is free —
        #    no fetch needed to know it (delivery of its resident's
        #    tokens rides the already-recorded snapshots). Prefills are
        #    batched async dispatches chained on the device queue.
        admitted = self._admit_locked()
        # 2) dispatch one full-width decode chunk (async) when there is
        #    planned work and (pipelined mode) fetch headroom.
        dispatched = self._dispatch_locked()
        # 3) delivery. Inline mode fetches here (one device-side concat +
        #    ONE transfer per fetch_every chunks); pipelined mode hands
        #    the accumulated chunks to the fetcher thread instead, so the
        #    dispatch loop never blocks on a device->host round trip.
        processed = False
        if self._fetcher is None:
            if self._inflight and (len(self._inflight) >= self.fetch_every
                                   or not dispatched):
                pending, self._inflight = self._inflight, []
                self._deliver_locked(self._fetch_chunks(pending), pending)
                processed = True
        elif self._inflight:
            self._fetch_evt.set()
        return bool(admitted or dispatched or processed)

    def _admit_locked(self) -> int:
        """Admit queued prompts into planned-free slots; dispatches one
        batched prefill per power-of-two group. Returns #admitted."""
        take: List[tuple] = []
        for slot in range(self.slots):
            if self._slot_left[slot] > 0:
                continue
            if self._slot_req[slot] is not None:
                # planned release: dispatching for it is complete
                self._slot_req[slot] = None
            try:
                req = self._queue.get_nowait()
            except queue.Empty:
                break
            take.append((slot, req))
        i = 0
        while i < len(take):
            K = next(k for k in self._GROUP_SIZES if k <= len(take) - i)
            group = take[i:i + K]
            i += K
            try:
                self._admit_group(group)
            except BaseException as e:
                # a failed prefill dispatch poisons the whole engine
                # (device/XLA error); fail this group's waiters AND every
                # later dequeued-but-ungrouped request here — none of
                # them are queued or slotted anymore, so _die cannot see
                # them and they would otherwise hang forever
                for _slot, req in group + take[i:]:
                    req.error = e
                    req.finish("error")
                raise
            for slot, req in group:
                # the plan includes the prefill-sampled first token; it
                # reaches the host in the next chunk's echo column
                self._slot_left[slot] = req.max_new_tokens
                self._slot_new[slot] = True
        return len(take)

    def _dispatch_locked(self) -> bool:
        active_slots = [s for s in range(self.slots)
                        if self._slot_left[s] > 0]
        if not active_slots:
            return False
        if self._fetcher is not None and \
                len(self._inflight) >= self.max_inflight:
            # count stall EPISODES, not the parked loop's 50ms wakeups —
            # one fetch-bound stall would otherwise inflate the counter
            # by however many times the loop re-polled it
            if not self._at_cap:
                self.stats["cap_stalls"] += 1
                self._at_cap = True
            return False  # dispatch-ahead cap: wait for the fetcher
        self._at_cap = False
        t0 = time.perf_counter()
        width = self.decode_chunk
        snapshot = []
        for slot in active_slots:
            new = self._slot_new[slot]
            self._slot_new[slot] = False
            take = min(self._slot_left[slot], width + (1 if new else 0))
            snapshot.append((slot, self._slot_req[slot],
                             0 if new else 1, take))
            self._slot_left[slot] = max(
                0, self._slot_left[slot] - (width + 1 if new else width))
        active = np.zeros(self.slots, bool)
        active[active_slots] = True
        self.cache, toks = decode_slots(
            self.params, self.cache, self._next_tok_dev,
            jnp.asarray(active), self._next_rng(), self.cfg,
            self.greedy, self.temperature, self.eos_id, steps=width)
        self._next_tok_dev = toks[:, -1]
        self.stats["decode_steps"] += width
        self.stats["dispatch_wall_s"] += time.perf_counter() - t0
        self._inflight.append((toks, snapshot))
        return True

    def _fetch_chunks(self, pending) -> np.ndarray:
        """ONE batched host transfer for ``pending`` chunks (each
        [B, decode_chunk+1]), concatenated on the host. Device-side
        concat would compile a fresh program per distinct chunk count —
        mid-traffic compiles measured as multi-second stalls through the
        tunneled backend. Called outside the lock by the fetcher; inline
        mode calls it under the lock."""
        t0 = time.perf_counter()
        parts = jax.device_get([t for t, _ in pending])
        big = parts[0] if len(parts) == 1 else np.concatenate(
            parts, axis=1)
        self.stats["fetches"] += 1
        self.stats["fetch_wall_s"] += time.perf_counter() - t0
        return big

    def _deliver_locked(self, big: np.ndarray, pending) -> None:
        W = self.decode_chunk + 1
        for i, (_toks_dev, snap) in enumerate(pending):
            seg = big[:, i * W:(i + 1) * W]
            for slot, req, from_col, take in snap:
                if req.done.is_set():
                    continue  # finished in an earlier chunk
                for t in range(from_col, from_col + take):
                    self._emit_to(req, slot, int(seg[slot, t]))
                    if req.done.is_set():
                        break  # rest of the row is frozen eos/junk

    # ---------------------------------------------------- background loop

    def serve_forever(self):
        """Run the engine on a daemon thread until ``shutdown()``, plus a
        fetcher thread that pipelines device->host transfers behind the
        dispatch loop (the transfer overlaps queued device execution, so
        its ~latency costs delivery time, never throughput)."""
        if self._thread is not None:
            return self
        self._stop.clear()

        def loop():
            while not self._stop.is_set():
                if self._fatal is not None:
                    return
                try:
                    busy = self.step()
                except BaseException as e:
                    # an error escaping step() (device/XLA failure at
                    # dispatch or fetch) kills the engine: error out every
                    # in-flight and queued request so no waiter hangs, and
                    # refuse new submissions
                    self._die(e)
                    return
                if not busy:
                    # idle or at the dispatch-ahead cap: PARK until state
                    # can change (submit(), fetcher taking chunks, or
                    # delivery all set _work). A busy-spin here would eat
                    # the host core the fetcher and request threads need
                    # — measured as ~half the device sitting idle on a
                    # 1-core host.
                    self._work.clear()
                    self._work.wait(timeout=0.05)

        def fetch_loop():
            while True:
                if self._fatal is not None:
                    return
                if self._stop.is_set() and not self._inflight:
                    return
                self._fetch_evt.wait(timeout=0.05)
                with self._lock:
                    if not self._inflight:
                        self._fetch_evt.clear()
                        pending = []
                    else:
                        # take the OLDEST chunk (delivery must advance)
                        # plus any younger chunks the device has already
                        # finished — their transfer piggybacks for free.
                        # Taking the whole backlog instead would block
                        # this cycle on the newest, just-dispatched chunk
                        # and stretch delivery latency to the backlog
                        # depth.
                        pending = [self._inflight.pop(0)]
                        while self._inflight and \
                                _chunk_ready(self._inflight[0][0]):
                            pending.append(self._inflight.pop(0))
                if not pending:
                    continue
                # taking the chunks made room under the dispatch cap —
                # wake the dispatch loop BEFORE the slow transfer so it
                # overlaps with queued execution
                self._work.set()
                try:
                    big = self._fetch_chunks(pending)  # blocking transfer
                    with self._lock:
                        self._deliver_locked(big, pending)
                except BaseException as e:
                    self._die(e)
                    return
                # room under the cap + possibly eos-freed slots
                self._work.set()

        self._thread = threading.Thread(target=loop, name="llm-engine",
                                        daemon=True)
        self._fetcher = threading.Thread(target=fetch_loop,
                                         name="llm-engine-fetch",
                                         daemon=True)
        self._thread.start()
        self._fetcher.start()
        return self

    def _check_alive(self):
        if self._fatal is not None:
            raise RuntimeError(
                "InferenceEngine is dead (step loop failed)") \
                from self._fatal

    def _die(self, exc: BaseException):
        """Mark the engine dead and fail every known request."""
        failed = [r for r in self._slot_req if r is not None]
        self._slot_req = [None] * self.slots
        self._slot_left = [0] * self.slots
        self._slot_new = [False] * self.slots
        with self._death_lock:
            # after this block no submit() can enqueue: _fatal is visible
            # to every subsequent check, and the queue is drained
            self._fatal = exc
            while True:
                try:
                    failed.append(self._queue.get_nowait())
                except queue.Empty:
                    break
        for _, snap in self._inflight:
            failed.extend(req for _, req, _, _ in snap)
        self._inflight = []
        for req in failed:
            if not req.done.is_set():
                req.error = exc
                req.finish("error")

    def shutdown(self):
        self._stop.set()
        self._work.set()
        self._fetch_evt.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None
        if self._fetcher is not None:
            self._fetcher.join(timeout=10)
            self._fetcher = None

    # ------------------------------------------------------- conveniences

    def generate(self, prompt: Sequence[int],
                 max_new_tokens: Optional[int] = None,
                 timeout: float = 300.0) -> List[int]:
        """Blocking single-prompt helper (drives steps inline if no
        background thread is running)."""
        req = self.submit(prompt, max_new_tokens)
        if self._thread is None:
            while not req.done.is_set():
                if not self.step():
                    break
        if not req.done.wait(timeout):
            raise TimeoutError("generation timed out")
        if req.error is not None:
            raise req.error
        return list(req.tokens)
