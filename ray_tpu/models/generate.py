"""Autoregressive generation with a KV cache: prefill + decode.

The inference half of the model stack (ref analog: the vLLM-backed
``ray.serve`` LLM deployments and ``rayllm`` batched-generation path the
reference ships for "Serve Llama-3 inference" — BASELINE.json configs).
TPU-first design:

  - Static shapes everywhere: the cache is allocated at ``max_len`` up
    front and written with ``lax.dynamic_update_slice``; the decode loop
    is a ``lax.scan`` over step index, so the whole generation of N
    tokens is ONE compiled XLA program (no per-token Python dispatch).
  - The layer dimension rides the same stacked-params ``lax.scan`` as
    training (`transformer.forward`), so depth costs one trace and the
    cache is a single [L, B, S, KV, hd] array per k/v — contiguous HBM,
    no per-layer Python lists.
  - Keys/values are cached *post-RoPE* and *pre-GQA-expansion* (KV heads,
    not Q heads): memory scales with kv_heads, and the repeat to Q heads
    happens inside the attention contraction.
  - Decode attention is a dense masked contraction over the cache — at
    T=1 per step it is HBM-bandwidth-bound (reads the cache once), which
    is the TPU roofline for decode; batching raises MXU utilization.
"""

from __future__ import annotations

from functools import partial
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from ray_tpu.models.config import TransformerConfig
from ray_tpu.models.transformer import (Params, ffn_block, lm_head,
                                        qkv_proj, rms_norm)

KVCache = Dict[str, jax.Array]  # {"k": [L,B,S,KV,hd], "v": ..., "pos": []}

# Large-finite instead of -inf for masked scores: a fully-masked query row
# (a pad position in a left-padded batch) then softmaxes to uniform junk
# instead of NaN — junk at pad positions is never attended (their keys are
# masked) nor read (only real positions' logits are consumed), while NaN
# would propagate through 0*NaN in the value contraction.
_MASKED = jnp.float32(jnp.finfo(jnp.float32).min / 2)


def init_cache(cfg: TransformerConfig, batch: int, max_len: int) -> KVCache:
    shape = (cfg.n_layers, batch, max_len, cfg.kv_heads, cfg.head_dim)
    return {"k": jnp.zeros(shape, cfg.dtype),
            "v": jnp.zeros(shape, cfg.dtype),
            "pos": jnp.zeros((), jnp.int32)}


def _ffn(h, lp, cfg):
    # shared definition with the training path (transformer.ffn_block);
    # inference drops the MoE aux loss
    down, _ = ffn_block(h, lp, cfg, None)
    return down


def _gqa_attention(q, k, v, mask):
    """q [B,T,H,hd] vs keys/values [B,S,KV,hd] under a broadcastable
    mask [B,T,1,1,S]. GQA expansion happens by reshaping q into
    [KV, reps] groups — no materialized repeat of k/v."""
    B, T, H, hd = q.shape
    KV = k.shape[2]
    reps = H // KV
    qg = q.reshape(B, T, KV, reps, hd)
    scores = jnp.einsum("btkrh,bskh->btkrs", qg.astype(jnp.float32),
                        k.astype(jnp.float32)) * (hd ** -0.5)
    scores = jnp.where(mask, scores, _MASKED)
    probs = jax.nn.softmax(scores, axis=-1)
    o = jnp.einsum("btkrs,bskh->btkrh", probs, v.astype(jnp.float32))
    return o.reshape(B, T, H, hd).astype(q.dtype)


def _cached_attention(q, k_cache, v_cache, valid_len, start):
    """Decode attention against the full cache, masking key positions
    outside [start[b], valid_len). ``start`` [B] supports left-padded
    batches (pad tokens are never attended; RoPE is relative, so the
    absolute offset is harmless)."""
    S = k_cache.shape[1]
    kpos = jnp.arange(S)[None, None, None, None, :]
    mask = (kpos < valid_len) & \
        (kpos >= start[:, None, None, None, None])
    return _gqa_attention(q, k_cache, v_cache, mask)


def _final_logits(params, x, cfg):
    # shared final norm + head with the training path
    return lm_head(params, x, cfg, None)


def _prefill_hidden(params: Params, tokens: jax.Array,
                    cfg: TransformerConfig, max_len: int,
                    start: jax.Array):
    """Prompt pass returning final HIDDEN states [B,P,d] + the filled
    cache — generate() projects only the last position to vocab space
    (a [B,P,V] float32 logits tensor is ~2 GB for llama3-8b at P=512
    and is pure waste on the serving hot path)."""
    B, P = tokens.shape
    if max_len < P:
        raise ValueError(f"max_len={max_len} < prompt length {P}")
    if not cfg.causal:
        # autoregressive decoding over a bidirectional encoder would
        # silently contradict the forward() the params were trained with
        raise ValueError("generation requires a causal (decoder) config; "
                         "this config has causal=False")
    x = params["embed"].astype(cfg.dtype)[tokens]
    positions = jnp.arange(P)

    causal = jnp.arange(P)[:, None] >= jnp.arange(P)[None, :]
    valid = jnp.arange(P)[None, :] >= start[:, None]  # [B, S]
    prompt_mask = causal[None, :, None, None, :] & \
        valid[:, None, None, None, :]

    def block(x, lp):
        h = rms_norm(x, lp["attn_norm"], cfg.rms_eps)
        q, k, v = qkv_proj(h, lp, cfg, positions)
        o = _gqa_attention(q, k, v, prompt_mask)
        o = jnp.einsum("bthk,hkd->btd", o, lp["wo"].astype(cfg.dtype))
        x = x + o
        h = rms_norm(x, lp["mlp_norm"], cfg.rms_eps)
        x = x + _ffn(h, lp, cfg)
        # pad this layer's k/v out to max_len for the cache
        pad = [(0, 0), (0, max_len - P), (0, 0), (0, 0)]
        return x, (jnp.pad(k, pad), jnp.pad(v, pad))

    x, (k_all, v_all) = jax.lax.scan(block, x, params["layers"])
    cache = {"k": k_all, "v": v_all,
             "pos": jnp.asarray(P, jnp.int32)}
    return x, cache


@partial(jax.jit, static_argnames=("cfg", "max_len"))
def prefill(params: Params, tokens: jax.Array, cfg: TransformerConfig,
            max_len: int, start: Optional[jax.Array] = None
            ) -> Tuple[jax.Array, KVCache]:
    """Process the whole prompt [B, P] in one pass; -> (logits [B,P,V],
    cache filled at positions [0, P)). ``start`` [B] marks the first
    REAL token per row for left-padded batches (earlier positions are
    masked out of attention)."""
    if start is None:
        start = jnp.zeros((tokens.shape[0],), jnp.int32)
    x, cache = _prefill_hidden(params, tokens, cfg, max_len, start)
    return _final_logits(params, x, cfg), cache


@partial(jax.jit, static_argnames=("cfg",))
def decode_step(params: Params, cache: KVCache, tokens: jax.Array,
                cfg: TransformerConfig,
                start: Optional[jax.Array] = None
                ) -> Tuple[jax.Array, KVCache]:
    """One token per sequence: tokens [B] at position cache['pos'];
    -> (logits [B, V], cache advanced by one)."""
    pos = cache["pos"]
    if start is None:
        start = jnp.zeros((tokens.shape[0],), jnp.int32)
    x = params["embed"].astype(cfg.dtype)[tokens[:, None]]  # [B,1,d]
    positions = pos[None]  # [1]

    def block(x, scanned):
        lp, k_layer, v_layer = scanned
        h = rms_norm(x, lp["attn_norm"], cfg.rms_eps)
        q, k, v = qkv_proj(h, lp, cfg, positions)
        B = x.shape[0]
        k_layer = jax.lax.dynamic_update_slice(
            k_layer, k.astype(k_layer.dtype), (0, pos, 0, 0))
        v_layer = jax.lax.dynamic_update_slice(
            v_layer, v.astype(v_layer.dtype), (0, pos, 0, 0))
        o = _cached_attention(q, k_layer, v_layer, pos + 1, start)
        o = jnp.einsum("bthk,hkd->btd", o, lp["wo"].astype(cfg.dtype))
        x = x + o
        h = rms_norm(x, lp["mlp_norm"], cfg.rms_eps)
        x = x + _ffn(h, lp, cfg)
        return x, (k_layer, v_layer)

    x, (k_all, v_all) = jax.lax.scan(
        block, x, (params["layers"], cache["k"], cache["v"]))
    new_cache = {"k": k_all, "v": v_all, "pos": pos + 1}
    return _final_logits(params, x, cfg)[:, 0], new_cache


@partial(jax.jit,
         static_argnames=("cfg", "max_new_tokens", "max_len", "greedy"))
def generate(params: Params, prompt: jax.Array, cfg: TransformerConfig,
             *, max_new_tokens: int, max_len: Optional[int] = None,
             temperature: float = 1.0, greedy: bool = True,
             eos_id: int = -1, rng: Optional[jax.Array] = None,
             start: Optional[jax.Array] = None) -> jax.Array:
    """prompt [B, P] -> [B, P + max_new_tokens]. One compiled program:
    prefill, then a lax.scan of decode steps (greedy or temperature
    sampling). Sequences that hit ``eos_id`` keep emitting eos.
    ``start`` [B]: first real-token position per row (left-padded
    batches of unequal prompt lengths)."""
    B, P = prompt.shape
    S = max_len or (P + max_new_tokens)
    if S < P + max_new_tokens:
        # an undersized cache would silently clamp dynamic_update_slice
        # writes onto the last slot and corrupt attention — refuse
        raise ValueError(
            f"max_len={S} < prompt_len({P}) + max_new_tokens"
            f"({max_new_tokens}); the KV cache must hold every position")
    if rng is None:
        rng = jax.random.key(0)
    if start is None:
        start = jnp.zeros((B,), jnp.int32)
    if max_new_tokens == 0:  # static arg: a free Python-level branch
        if not cfg.causal:  # same contract as the nonzero path
            raise ValueError("generation requires a causal (decoder) "
                             "config; this config has causal=False")
        return prompt
    x, cache = _prefill_hidden(params, prompt, cfg, S, start)
    # only the LAST position's logits seed decoding: project [B,1,d]
    # instead of materializing the full [B,P,V] prompt logits
    last = _final_logits(params, x[:, -1:], cfg)[:, 0]

    def pick(logits, step_rng):
        if greedy:
            return jnp.argmax(logits, axis=-1).astype(prompt.dtype)
        return jax.random.categorical(
            step_rng, logits / jnp.maximum(temperature, 1e-6)
        ).astype(prompt.dtype)

    # The first token comes straight from the prefill logits; the scan
    # then runs max_new_tokens-1 decode steps, each decoding the PREVIOUS
    # token and sampling the next — so the final sampled token never pays
    # for a decode_step whose logits nobody reads.
    rngs = jax.random.split(rng, max_new_tokens)
    tok0 = pick(last, rngs[0])
    done0 = tok0 == eos_id

    def step(carry, step_rng):
        cache, prev_tok, done = carry
        logits, cache = decode_step(params, cache, prev_tok, cfg, start)
        tok = pick(logits, step_rng)
        tok = jnp.where(done, jnp.asarray(eos_id, tok.dtype), tok)
        done = done | (tok == eos_id)
        return (cache, tok, done), tok

    (_, _, _), toks = jax.lax.scan(step, (cache, tok0, done0), rngs[1:])
    toks = jnp.concatenate([tok0[None], toks], axis=0)  # [N, B]
    return jnp.concatenate([prompt, toks.T], axis=1)
