"""Train-step factory: optax + jit with sharded, donated state.

The reference's training loop lives in user code wrapped by DDP (ref:
python/ray/train/torch/train_loop_utils.py:75); here the framework owns a
canonical SPMD step: grads/optimizer fused into one XLA program, state
donated (no HBM copy), shardings inferred from the model's logical axes so
ZeRO-3 (fsdp), TP, and CP fall out of the rule table.
"""

from __future__ import annotations

import functools
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ray_tpu.models.config import TransformerConfig
from ray_tpu.models.transformer import (
    init_params,
    loss_fn,
    param_logical_axes,
)
from ray_tpu.parallel.sharding import logical_sharding

TrainState = Dict[str, Any]  # {"step", "params", "opt_state"}


def make_optimizer(learning_rate: float = 3e-4, *, weight_decay: float = 0.1,
                   b1: float = 0.9, b2: float = 0.95, grad_clip: float = 1.0,
                   warmup_steps: int = 0, total_steps: Optional[int] = None,
                   mu_dtype=None):
    """AdamW + global-norm clip (+ optional warmup-cosine schedule).

    ``mu_dtype=jnp.bfloat16`` halves the first-moment buffer — with fp32
    master params + fp32 nu that's params x 10 bytes instead of x 12,
    which is what lets the 1B flagship train on a single 16 GiB chip.
    """
    if warmup_steps or total_steps:
        schedule = optax.warmup_cosine_decay_schedule(
            0.0, learning_rate, max(warmup_steps, 1),
            max(total_steps or warmup_steps * 10, warmup_steps + 1))
    else:
        schedule = learning_rate
    return optax.chain(
        optax.clip_by_global_norm(grad_clip),
        optax.adamw(schedule, b1=b1, b2=b2, weight_decay=weight_decay,
                    mu_dtype=mu_dtype),
    )


def make_init_fn(cfg: TransformerConfig, tx):
    def init(rng) -> TrainState:
        params = init_params(rng, cfg)
        return {"step": jnp.zeros((), jnp.int32), "params": params,
                "opt_state": tx.init(params)}
    return init


def _is_axes(x):
    return isinstance(x, tuple) and all(a is None or isinstance(a, str)
                                        for a in x)


def state_shardings(cfg: TransformerConfig, tx, mesh: Mesh, rules=None):
    """Sharding pytree for the whole TrainState.

    Optimizer moments mirror param shapes, so shardings are propagated by
    shape-matching against the params tree (ZeRO: moments shard exactly like
    their params). Anything unmatched (step counts, scalars) is replicated.
    """
    init = make_init_fn(cfg, tx)
    shapes = jax.eval_shape(init, jax.random.key(0))
    p_axes = param_logical_axes(cfg)
    by_shape = {}
    for leaf, ax in zip(jax.tree.leaves(shapes["params"]),
                        jax.tree.leaves(p_axes, is_leaf=_is_axes)):
        by_shape[leaf.shape] = logical_sharding(mesh, ax, rules)
    repl = NamedSharding(mesh, P())
    return jax.tree.map(lambda s: by_shape.get(s.shape, repl), shapes)


def batch_sharding(mesh: Mesh, rules=None):
    """Per-key sharding for a token batch dict ([B, T] arrays).

    Note: under sequence parallelism use the {"inputs", "targets"} batch
    format with T divisible by the sequence axis — a raw {"tokens": [B, T+1]}
    batch generally isn't evenly shardable on the seq dim.
    """
    # Returned as a single sharding: jit treats it as a pytree prefix that
    # applies to every [B, T] leaf of the batch dict.
    return logical_sharding(mesh, ("batch", "seq"), rules)


def init_train_state(rng, cfg: TransformerConfig, tx,
                     mesh: Optional[Mesh] = None, rules=None) -> TrainState:
    """Initialize params/opt state directly into their shards (no host copy)."""
    init = make_init_fn(cfg, tx)
    if mesh is None:
        return jax.jit(init)(rng)
    shardings = state_shardings(cfg, tx, mesh, rules)
    return jax.jit(init, out_shardings=shardings)(rng)


def make_train_step(cfg: TransformerConfig, tx, mesh: Optional[Mesh] = None,
                    rules=None):
    """Returns jitted `(state, batch) -> (state, metrics)`; state donated."""

    def step(state: TrainState, batch):
        grad_fn = jax.value_and_grad(
            functools.partial(loss_fn, cfg=cfg, mesh=mesh), has_aux=True)
        (_, metrics), grads = grad_fn(state["params"], batch)
        updates, new_opt = tx.update(grads, state["opt_state"],
                                     state["params"])
        new_params = optax.apply_updates(state["params"], updates)
        metrics = dict(metrics,
                       grad_norm=optax.global_norm(grads),
                       step=state["step"] + 1)
        return {"step": state["step"] + 1, "params": new_params,
                "opt_state": new_opt}, metrics

    if mesh is None:
        return jax.jit(step, donate_argnums=0)
    shardings = state_shardings(cfg, tx, mesh, rules)
    return jax.jit(
        step,
        in_shardings=(shardings, batch_sharding(mesh, rules)),
        out_shardings=(shardings, None),
        donate_argnums=0)


def make_eval_step(cfg: TransformerConfig, mesh: Optional[Mesh] = None):
    def step(params, batch):
        _, metrics = loss_fn(params, batch, cfg, mesh)
        return metrics
    return jax.jit(step)
