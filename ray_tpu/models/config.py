"""Model configurations for the built-in transformer family.

The reference ships no LLM definitions (its model zoo is RLlib's small
policy nets, rllib/models/ — SURVEY.md §2.4); the flagship LLM family here
serves the north-star workloads in BASELINE.json (GPT-2-small data-parallel,
Llama-3-8B FSDP pretrain, Llama-3-8B serving).
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    """A Llama-3-style decoder-only transformer (RMSNorm, RoPE, GQA, SwiGLU)."""

    vocab_size: int = 32000
    d_model: int = 512
    n_layers: int = 4
    n_heads: int = 8
    n_kv_heads: Optional[int] = None  # None -> MHA
    d_ff: int = 1408
    max_seq_len: int = 2048
    rope_theta: float = 500_000.0
    rms_eps: float = 1e-5
    dtype: jnp.dtype = jnp.bfloat16        # activation/compute dtype
    param_dtype: jnp.dtype = jnp.float32   # master weights
    tie_embeddings: bool = False
    # False -> bidirectional (encoder / BERT-class) attention; the same
    # blocks, RoPE, and loss_fn (inputs/targets/mask form = MLM) apply.
    causal: bool = True
    remat: bool = True                     # checkpoint each layer (HBM <-> FLOPs)
    # "nothing": rematerialize everything (min HBM); "dots": save matmul
    # outputs, recompute elementwise only (less recompute FLOPs -> higher
    # MFU when the saved activations still fit HBM)
    remat_policy: str = "nothing"
    # "auto": ring attention iff mesh's sequence axis > 1, else pallas flash
    # on TPU, else plain XLA attention.
    attention_impl: str = "auto"
    # Microbatches for pipeline parallelism (mesh pipeline axis > 1);
    # None -> 2 * n_stages. Bubble fraction is (S-1)/(M+S-1).
    pipeline_microbatches: Optional[int] = None
    # Mixture-of-Experts FFN (models/moe.py): 0 = dense. Experts shard over
    # the `expert` mesh axis; top-k routing with renormalized combine
    # weights; capacity C = ceil(T*k/E * capacity_factor).
    moe_experts: int = 0
    moe_top_k: int = 2
    moe_capacity_factor: float = 1.25
    moe_aux_weight: float = 0.01

    @property
    def kv_heads(self) -> int:
        return self.n_kv_heads or self.n_heads

    @property
    def head_dim(self) -> int:
        assert self.d_model % self.n_heads == 0
        return self.d_model // self.n_heads

    @property
    def num_params(self) -> int:
        d, v, L = self.d_model, self.vocab_size, self.n_layers
        hd, H, KV, ff = self.head_dim, self.n_heads, self.kv_heads, self.d_ff
        per_layer = (d * H * hd + 2 * d * KV * hd + H * hd * d  # attn
                     + 3 * d * ff                               # swiglu
                     + 2 * d)                                   # norms
        head = 0 if self.tie_embeddings else d * v
        return v * d + L * per_layer + d + head

    def flops_per_token(self, seq_len: Optional[int] = None) -> float:
        """Approximate training FLOPs/token: 6*N + attention quadratic term."""
        s = seq_len or self.max_seq_len
        attn = 12 * self.n_layers * self.d_model * s  # fwd+bwd qk^T and av
        return 6.0 * self.num_params + attn


# ---- presets ---------------------------------------------------------------

def tiny_config(**kw) -> TransformerConfig:
    """Unit-test sized; runs in milliseconds on CPU."""
    base = dict(vocab_size=256, d_model=64, n_layers=2, n_heads=4,
                n_kv_heads=2, d_ff=128, max_seq_len=128,
                dtype=jnp.float32, remat=False)
    base.update(kw)
    return TransformerConfig(**base)


def gpt2_small_config(**kw) -> TransformerConfig:
    """124M-class decoder (GPT-2-small scale, modern Llama-style blocks)."""
    base = dict(vocab_size=50304, d_model=768, n_layers=12, n_heads=12,
                n_kv_heads=12, d_ff=3072, max_seq_len=1024,
                tie_embeddings=True)
    base.update(kw)
    return TransformerConfig(**base)


def llama3_1b_config(**kw) -> TransformerConfig:
    """~1.2B-param Llama-3.2-1B-class geometry; single-chip bench flagship."""
    base = dict(vocab_size=128_256, d_model=2048, n_layers=16, n_heads=32,
                n_kv_heads=8, d_ff=8192, max_seq_len=4096,
                rope_theta=500_000.0, tie_embeddings=True)
    base.update(kw)
    return TransformerConfig(**base)


def llama3_8b_config(**kw) -> TransformerConfig:
    """Llama-3-8B geometry (the north-star pretrain target)."""
    base = dict(vocab_size=128_256, d_model=4096, n_layers=32, n_heads=32,
                n_kv_heads=8, d_ff=14336, max_seq_len=8192,
                rope_theta=500_000.0)
    base.update(kw)
    return TransformerConfig(**base)


def llama3_70b_config(**kw) -> TransformerConfig:
    base = dict(vocab_size=128_256, d_model=8192, n_layers=80, n_heads=64,
                n_kv_heads=8, d_ff=28672, max_seq_len=8192)
    base.update(kw)
    return TransformerConfig(**base)


def bert_base_config(**kw) -> TransformerConfig:
    """BERT-base-scale bidirectional encoder (110M class): same blocks
    as the decoders but ``causal=False``; train with ``loss_fn`` in its
    inputs/targets/mask form (= masked-language-model objective, see
    models.mlm). Ref analog: the reference's BERT-base data-parallel
    TorchTrainer benchmark config (BASELINE.md)."""
    # d_ff=2048 keeps the 3-matrix SwiGLU FFN at BERT's 2-matrix-GELU
    # parameter budget (3*768*2048 ≈ 2*768*3072), so the preset stays
    # a 110M-class model
    base = dict(vocab_size=30_522, d_model=768, n_layers=12, n_heads=12,
                d_ff=2048, max_seq_len=512, causal=False,
                tie_embeddings=True)
    base.update(kw)
    return TransformerConfig(**base)


PRESETS = {
    "tiny": tiny_config,
    "bert-base": bert_base_config,
    "gpt2-small": gpt2_small_config,
    "llama3-1b": llama3_1b_config,
    "llama3-8b": llama3_8b_config,
    "llama3-70b": llama3_70b_config,
}


def get_config(name: str, **kw) -> TransformerConfig:
    if name not in PRESETS:
        raise KeyError(f"unknown model preset {name!r}; have {list(PRESETS)}")
    return PRESETS[name](**kw)
