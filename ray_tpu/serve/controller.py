"""Serve controller: singleton actor reconciling target vs actual state.

Ref analogs: python/ray/serve/controller.py:82 (ServeController),
_private/deployment_state.py:1140 (DeploymentState reconciler),
_private/application_state.py, _private/autoscaling_policy.py:106.

Re-design: one actor, one background reconcile thread, non-blocking
polling of replica ping/metrics futures via ``wait(timeout=0)`` — no
asyncio control loop, no long-poll broker. Routers poll the controller's
monotonically increasing ``routing_version`` and refresh membership on
change (cheap: a version int + a handle list per deployment).
"""

from __future__ import annotations

import hashlib
import threading
import time
import traceback
from typing import Any, Dict, List, Optional

import ray_tpu
from ray_tpu.core.serialization import dumps

from .config import AutoscalingConfig, DeploymentConfig

CONTROLLER_NAME = "SERVE_CONTROLLER"

STARTING = "STARTING"
RUNNING = "RUNNING"
STOPPING = "STOPPING"

# deployment-level statuses (ref: serve/_private/common.py DeploymentStatus)
DEPLOY_UPDATING = "UPDATING"
DEPLOY_HEALTHY = "HEALTHY"
DEPLOY_UNHEALTHY = "UNHEALTHY"

_TICK_S = 0.05
_MAX_CONSECUTIVE_START_FAILURES = 3


class _Replica:
    def __init__(self, replica_id: str, handle, version: str):
        self.replica_id = replica_id
        self.handle = handle
        self.version = version
        self.state = STARTING
        self.started_at = time.monotonic()
        self.ping_ref = None
        self.metrics_ref = None
        self.ongoing = 0
        self.last_seen = time.monotonic()


class _DeploymentState:
    def __init__(self, app: str, name: str, payload: bytes,
                 config: DeploymentConfig, version: str):
        self.app = app
        self.name = name
        self.payload = payload
        self.config = config
        self.version = version
        self.replicas: List[_Replica] = []
        self.status = DEPLOY_UPDATING
        self.message = ""
        self.start_failures = 0
        self.next_replica_idx = 0
        # autoscaling state
        self.autoscale_desired = config.num_replicas
        self._above_since: Optional[float] = None
        self._below_since: Optional[float] = None

    # ----- helpers

    def target_replicas(self) -> int:
        if self.config.autoscaling_config is not None:
            return self.autoscale_desired
        return self.config.num_replicas

    def running(self, version: Optional[str] = None) -> List[_Replica]:
        return [r for r in self.replicas
                if r.state == RUNNING and
                (version is None or r.version == version)]


class ServeController:
    """The singleton controller actor (create with max_concurrency >= 4)."""

    def __init__(self):
        self._lock = threading.RLock()
        # app -> {"route_prefix", "ingress", "deployments": {name: state}}
        self._apps: Dict[str, dict] = {}
        self._routing_version = 0
        self._shutdown = False
        # every replica drain ever spawned (scale-downs, rolling updates,
        # deletes) — shutdown_serve must join ALL of them, not just the
        # ones it starts, or an in-flight drain dies with the controller
        # and leaks the replica's worker. Pruned of finished threads as
        # new drains start.
        self._drains: List[threading.Thread] = []
        self._thread = threading.Thread(target=self._control_loop,
                                        daemon=True, name="serve-reconcile")
        self._thread.start()

    # ================================================= declarative API

    def deploy_app(self, app_name: str, route_prefix: Optional[str],
                   ingress: str, deployments: List[dict]):
        """Set the target state for one application (idempotent).

        ``deployments``: [{name, payload, config}] — payload is the pickled
        replica spec (callable + init args with HandleMarkers).
        """
        with self._lock:
            app = self._apps.setdefault(
                app_name, {"route_prefix": None, "ingress": ingress,
                           "deployments": {}})
            app["route_prefix"] = route_prefix
            app["ingress"] = ingress
            new_names = set()
            for d in deployments:
                name, payload, config = d["name"], d["payload"], d["config"]
                version = config.version or \
                    hashlib.sha1(payload).hexdigest()[:12]
                new_names.add(name)
                cur = app["deployments"].get(name)
                if cur is None:
                    app["deployments"][name] = _DeploymentState(
                        app_name, name, payload, config, version)
                else:
                    cur.payload = payload
                    cur.config = config
                    cur.version = version
                    cur.status = DEPLOY_UPDATING
                    cur.start_failures = 0
                    if config.autoscaling_config is not None:
                        lo = config.autoscaling_config.min_replicas
                        hi = config.autoscaling_config.max_replicas
                        cur.autoscale_desired = min(
                            max(cur.autoscale_desired, lo), hi)
                    else:
                        cur.autoscale_desired = config.num_replicas
            # deployments removed from the app spec are torn down
            for name in list(app["deployments"]):
                if name not in new_names:
                    self._teardown_deployment(app["deployments"].pop(name))
            self._routing_version += 1
        return True

    def delete_app(self, app_name: str):
        with self._lock:
            app = self._apps.pop(app_name, None)
            if app is None:
                return False
            for dep in app["deployments"].values():
                self._teardown_deployment(dep)
            self._routing_version += 1
        return True

    def shutdown_serve(self):
        with self._lock:
            for name in list(self._apps):
                app = self._apps.pop(name)
                for dep in app["deployments"].values():
                    self._teardown_deployment(dep)
            self._shutdown = True
            self._routing_version += 1
            drains = list(self._drains)
        # The caller kills this controller actor right after this returns,
        # which would orphan any replica whose drain is still in flight —
        # the replica's worker (and lease) then leaks forever. Wait for
        # every replica to actually die before reporting shutdown done.
        for t in drains:
            t.join(timeout=30)
        return True

    def _teardown_deployment(self, dep: _DeploymentState):
        for r in dep.replicas:
            self._stop_replica(dep, r, graceful=True)
        dep.replicas = []

    # ================================================= router-facing API

    def routing_version(self) -> int:
        return self._routing_version

    def get_routing_snapshot(self, app_name: str, deployment: str):
        """(version, [(replica_id, handle)], max_concurrent_queries)."""
        with self._lock:
            app = self._apps.get(app_name)
            if app is None:
                return self._routing_version, [], 1
            dep = app["deployments"].get(deployment)
            if dep is None:
                return self._routing_version, [], 1
            return (self._routing_version,
                    [(r.replica_id, r.handle) for r in dep.running()],
                    dep.config.max_concurrent_queries)

    def get_routes(self) -> Dict[str, str]:
        """route_prefix -> app name (for the HTTP proxy)."""
        with self._lock:
            return {app["route_prefix"]: name
                    for name, app in self._apps.items()
                    if app["route_prefix"]}

    def get_ingress(self, app_name: str) -> Optional[str]:
        with self._lock:
            app = self._apps.get(app_name)
            return app["ingress"] if app else None

    def status(self) -> dict:
        with self._lock:
            out = {}
            for name, app in self._apps.items():
                deps = {}
                statuses = []
                for dn, dep in app["deployments"].items():
                    counts: Dict[str, int] = {}
                    for r in dep.replicas:
                        counts[r.state] = counts.get(r.state, 0) + 1
                    deps[dn] = {"status": dep.status,
                                "message": dep.message,
                                "replica_states": counts,
                                "target_replicas": dep.target_replicas(),
                                "version": dep.version}
                    statuses.append(dep.status)
                if any(s == DEPLOY_UNHEALTHY for s in statuses):
                    app_status = "UNHEALTHY"
                elif all(s == DEPLOY_HEALTHY for s in statuses) and statuses:
                    app_status = "RUNNING"
                else:
                    app_status = "DEPLOYING"
                out[name] = {"status": app_status,
                             "route_prefix": app["route_prefix"],
                             "deployments": deps}
            return out

    # ================================================= reconcile loop

    def _control_loop(self):
        while not self._shutdown:
            try:
                with self._lock:
                    deps = [dep for app in self._apps.values()
                            for dep in app["deployments"].values()]
                for dep in deps:
                    self._reconcile_deployment(dep)
            except Exception:
                traceback.print_exc()
            time.sleep(_TICK_S)

    def _reconcile_deployment(self, dep: _DeploymentState):
        with self._lock:
            # The dep was snapshotted outside the lock; shutdown_serve or
            # delete_app may have torn it down in the window. Reconciling a
            # stale dep would resurrect replicas nobody tracks or drains.
            if self._shutdown:
                return
            app = self._apps.get(dep.app)
            if app is None or app["deployments"].get(dep.name) is not dep:
                return
            self._check_starting(dep)
            self._check_health_and_autoscale(dep)
            self._scale(dep)
            self._update_status(dep)

    # ----- phase 1: STARTING -> RUNNING on successful ping

    def _check_starting(self, dep: _DeploymentState):
        for r in list(dep.replicas):
            if r.state != STARTING:
                continue
            if r.ping_ref is None:
                r.ping_ref = r.handle.ping.remote()
            done, _ = ray_tpu.wait([r.ping_ref], num_returns=1, timeout=0,
                                   fetch_local=False)
            if not done:
                if time.monotonic() - r.started_at > \
                        dep.config.health_check_timeout_s:
                    self._replica_failed(
                        dep, r, "replica start timed out")
                continue
            try:
                ray_tpu.get(r.ping_ref, timeout=1)
            except Exception as e:  # noqa: BLE001 — ctor/ping failure
                self._replica_failed(dep, r, repr(e))
                continue
            r.ping_ref = None
            r.state = RUNNING
            dep.start_failures = 0
            self._routing_version += 1

    def _replica_failed(self, dep: _DeploymentState, r: _Replica, msg: str):
        dep.replicas.remove(r)
        try:
            ray_tpu.kill(r.handle)
        except Exception:
            pass
        dep.start_failures += 1
        dep.message = msg
        if dep.start_failures >= _MAX_CONSECUTIVE_START_FAILURES:
            dep.status = DEPLOY_UNHEALTHY

    # ----- phase 2: health checks + autoscaling metrics on RUNNING

    def _check_health_and_autoscale(self, dep: _DeploymentState):
        now = time.monotonic()
        total_ongoing = 0
        n_reporting = 0
        for r in list(dep.replicas):
            if r.state != RUNNING:
                continue
            if r.metrics_ref is None:
                if now - r.last_seen >= dep.config.health_check_period_s:
                    r.metrics_ref = r.handle.metrics.remote()
            else:
                done, _ = ray_tpu.wait([r.metrics_ref], num_returns=1,
                                       timeout=0, fetch_local=False)
                if done:
                    try:
                        m = ray_tpu.get(r.metrics_ref, timeout=1)
                        r.ongoing = m.num_ongoing_requests
                        r.last_seen = now
                    except Exception as e:  # noqa: BLE001 — replica died
                        dep.replicas.remove(r)
                        dep.message = f"replica died: {e!r}"
                        self._routing_version += 1
                        try:
                            ray_tpu.kill(r.handle)
                        except Exception:
                            pass
                        continue
                    r.metrics_ref = None
                elif now - r.last_seen > dep.config.health_check_timeout_s:
                    dep.replicas.remove(r)
                    dep.message = "replica health check timed out"
                    self._routing_version += 1
                    try:
                        ray_tpu.kill(r.handle)
                    except Exception:
                        pass
                    continue
            total_ongoing += r.ongoing
            n_reporting += 1
        cfg = dep.config.autoscaling_config
        if cfg is not None and n_reporting:
            self._autoscale(dep, cfg, total_ongoing, now)

    def _autoscale(self, dep: _DeploymentState, cfg: AutoscalingConfig,
                   total_ongoing: int, now: float):
        import math

        raw = math.ceil(
            cfg.smoothing_factor * total_ongoing /
            cfg.target_num_ongoing_requests_per_replica)
        desired = min(max(raw, cfg.min_replicas), cfg.max_replicas)
        cur = dep.autoscale_desired
        if desired > cur:
            dep._below_since = None
            if dep._above_since is None:
                dep._above_since = now
            if now - dep._above_since >= cfg.upscale_delay_s:
                dep.autoscale_desired = desired
                dep._above_since = None
        elif desired < cur:
            dep._above_since = None
            if dep._below_since is None:
                dep._below_since = now
            if now - dep._below_since >= cfg.downscale_delay_s:
                dep.autoscale_desired = desired
                dep._below_since = None
        else:
            dep._above_since = None
            dep._below_since = None

    # ----- phase 3: converge replica set to target count + version

    def _scale(self, dep: _DeploymentState):
        if dep.status == DEPLOY_UNHEALTHY:
            return
        target = dep.target_replicas()
        current = [r for r in dep.replicas if r.state in (STARTING, RUNNING)]
        new_version = [r for r in current if r.version == dep.version]
        old_version = [r for r in current if r.version != dep.version]

        # rolling update: bring up the new version to target, then retire old
        if len(new_version) < target:
            for _ in range(target - len(new_version)):
                self._start_replica(dep)
        elif old_version and len(dep.running(dep.version)) >= target:
            for r in old_version:
                dep.replicas.remove(r)
                self._stop_replica(dep, r, graceful=True)
            self._routing_version += 1
        elif not old_version and len(new_version) > target:
            # scale down newest-first among non-running, else last started
            doomed = sorted(new_version,
                            key=lambda r: (r.state == RUNNING, r.started_at)
                            )[target - len(new_version):]
            running_removed = False
            for r in doomed:
                running_removed |= r.state == RUNNING
                dep.replicas.remove(r)
                self._stop_replica(dep, r, graceful=True)
            if running_removed:
                self._routing_version += 1

    def _start_replica(self, dep: _DeploymentState):
        from .replica import ServeReplica

        opts = dict(dep.config.ray_actor_options)
        replica_id = f"{dep.app}#{dep.name}#{dep.next_replica_idx}"
        dep.next_replica_idx += 1
        actor_cls = ray_tpu.remote(ServeReplica).options(
            num_cpus=opts.get("num_cpus", 0),
            num_tpus=opts.get("num_tpus"),
            resources=opts.get("resources"),
            # queries + ping/metrics/drain must run concurrently
            max_concurrency=dep.config.max_concurrent_queries + 3,
        )
        handle = actor_cls.remote(dep.payload, replica_id)
        dep.replicas.append(_Replica(replica_id, handle, dep.version))

    def _stop_replica(self, dep: _DeploymentState, r: _Replica,
                      graceful: bool) -> threading.Thread:
        r.state = STOPPING

        def _drain(handle=r.handle,
                   timeout=dep.config.graceful_shutdown_timeout_s):
            try:
                if graceful:
                    ray_tpu.get(handle.prepare_shutdown.remote(timeout),
                                timeout=timeout + 5)
            except Exception:
                pass
            try:
                ray_tpu.kill(handle)
            except Exception:
                pass

        t = threading.Thread(target=_drain, daemon=True)
        with self._lock:
            self._drains = [d for d in self._drains if d.is_alive()]
            self._drains.append(t)
        t.start()
        return t

    # ----- phase 4: status rollup

    def _update_status(self, dep: _DeploymentState):
        if dep.status == DEPLOY_UNHEALTHY:
            return
        target = dep.target_replicas()
        if len(dep.running(dep.version)) == target and \
                all(r.state == RUNNING for r in dep.replicas):
            dep.status = DEPLOY_HEALTHY
            dep.message = ""
        else:
            dep.status = DEPLOY_UPDATING


def get_or_create_controller():
    """Find the singleton controller, creating it on first use."""
    try:
        return ray_tpu.get_actor(CONTROLLER_NAME)
    except ValueError:
        pass
    handle = ray_tpu.remote(ServeController).options(
        name=CONTROLLER_NAME, num_cpus=0, max_concurrency=8).remote()
    # wait until the name resolves and the actor answers
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        try:
            ray_tpu.get(handle.routing_version.remote(), timeout=5)
            return handle
        except Exception:
            time.sleep(0.05)
    raise RuntimeError("serve controller failed to start")
