"""Serve controller: singleton actor reconciling target vs actual state.

Ref analogs: python/ray/serve/controller.py:82 (ServeController),
_private/deployment_state.py:1140 (DeploymentState reconciler),
_private/application_state.py, _private/autoscaling_policy.py:106.

Re-design: one actor, one background reconcile thread, non-blocking
polling of replica ping/metrics futures via ``wait(timeout=0)`` — no
asyncio control loop, no long-poll broker. Routers poll the controller's
monotonically increasing ``routing_version`` and refresh membership on
change (cheap: a version int + a handle list per deployment).

r14 (serve at production scale): the autoscaler fuses queue depth
(router-reported in-flight counts piggybacked on snapshot refreshes +
replica-reported ongoing), the head's per-func phase-histogram p99
(latency SLO burn), and ``node.*`` gauges (downscale veto on hot nodes),
with per-direction hysteresis windows AND cooldowns so it never flaps;
every decision is emitted as a rate-limited ``serve_autoscale`` cluster
event. Deployment weights travel by reference: the controller pre-warms
them onto nodes at scale-up decision time (``OBJECT_WARM`` -> the r13
prefetch machinery), so N concurrent replica cold-starts form the r9
cooperative broadcast tree instead of N root streams. All control-plane
polling (node table, phase summary) is rate-limited to ~1/s inside the
reconcile thread — nothing here rides the per-request hot path.
"""

from __future__ import annotations

import hashlib
import math
import threading
import time
import traceback
from collections import deque
from typing import Any, Dict, List, Optional

import ray_tpu
from ray_tpu.core.serialization import dumps

from .config import AutoscalingConfig, DeploymentConfig

CONTROLLER_NAME = "SERVE_CONTROLLER"

STARTING = "STARTING"
RUNNING = "RUNNING"
STOPPING = "STOPPING"

# deployment-level statuses (ref: serve/_private/common.py DeploymentStatus)
DEPLOY_UPDATING = "UPDATING"
DEPLOY_HEALTHY = "HEALTHY"
DEPLOY_UNHEALTHY = "UNHEALTHY"

_TICK_S = 0.05
_MAX_CONSECUTIVE_START_FAILURES = 3
# router-reported queue depths older than this are a dead/idle router's
# leftovers, not live demand
_ROUTER_DEPTH_TTL_S = 3.0
# node-table / phase-summary poll period (the autoscaler's slow signals)
_SIGNAL_POLL_S = 1.0
# min gap between serve_autoscale cluster events per deployment
_DECISION_EVENT_MIN_GAP_S = 0.5
# min gap between weight pre-warm sweeps per deployment
_PREWARM_MIN_GAP_S = 5.0
# the replica entrypoints whose phase histograms feed the SLO signal
_SLO_FUNCS = ("handle_request", "start_stream")
# SLO-burn look-back: p99 is computed over the requests of the last
# window only (delta of the head's cumulative bucket vectors between
# snapshots), not the lifetime distribution — an all-time percentile
# stops moving once history dwarfs the recent past, so a long-lived
# cluster would neither trip on fresh degradation nor recover after a
# bad episode (lifetime p99 stuck over budget pins the fleet at max)
_SLO_WINDOW_S = 30.0


def _windowed_p99(snaps: "deque", now: float) -> Optional[float]:
    """p99 over the requests between the oldest and newest cumulative
    bucket snapshots in ``snaps`` ([(ts, [buckets..., +inf, sum, n],
    boundaries)], window-pruned by the poller). None when the window
    holds no new samples — no signal, not 'healthy'."""
    if len(snaps) < 2:
        return None
    if now - snaps[-1][0] > _SLO_WINDOW_S:
        return None  # newest snapshot predates the window: stale signal
    (_, v0, _), (_, v1, bounds) = snaps[0], snaps[-1]
    if len(v0) != len(v1):
        return None  # boundary config changed between snapshots
    delta = [v1[i] - v0[i] for i in range(len(v1))]
    if delta[-1] <= 0:
        return None
    from ray_tpu.core.head import _hist_quantile
    return _hist_quantile(bounds, delta, 0.99)


def _record_decision(dep: "_DeploymentState", direction: str, frm: int,
                     to: int, reason: str, sig: dict, now: float) -> dict:
    """Stamp a fired scale decision onto the deployment state (module
    level so the policy stays callable with self=None in unit tests)."""
    dep.last_scale_ts = now
    dep.scale_events.append((now, direction))
    decision = {"ts": time.time(), "direction": direction,
                "from": frm, "to": to, "reason": reason,
                "queue_depth": sig.get("queue_depth", 0),
                "p99_ms": sig.get("p99_ms")}
    dep.last_decision = decision
    return decision


class _Replica:
    def __init__(self, replica_id: str, handle, version: str):
        self.replica_id = replica_id
        self.handle = handle
        self.version = version
        self.state = STARTING
        self.started_at = time.monotonic()
        self.ping_ref = None
        self.metrics_ref = None
        self.ongoing = 0
        self.last_seen = time.monotonic()
        self.node_idx = -1


class _DeploymentState:
    def __init__(self, app: str, name: str, payload: bytes,
                 config: DeploymentConfig, version: str,
                 weights_refs: Optional[list] = None):
        self.app = app
        self.name = name
        self.payload = payload
        self.config = config
        self.version = version
        self.replicas: List[_Replica] = []
        self.status = DEPLOY_UPDATING
        self.message = ""
        self.start_failures = 0
        self.next_replica_idx = 0
        # by-ref init args (r14): live ObjectRefs held HERE so the
        # weights outlive the driver that called serve.run() — the
        # payload only carries the (pickled) refs; replicas fetch
        # through the object plane and the controller pre-warms these
        # at scale-up decision time
        self.weights_refs: list = list(weights_refs or [])
        # autoscaling state
        self.autoscale_desired = config.num_replicas
        self._above_since: Optional[float] = None
        self._below_since: Optional[float] = None
        self.last_scale_ts = -1e18
        self.last_decision: Optional[dict] = None
        # (monotonic, direction) of recent scale events — flap detection
        self.scale_events: deque = deque(maxlen=64)
        self._last_event_ts = -1e18
        self._last_prewarm_ts = -1e18
        # router_id -> (monotonic, {replica_id: inflight}) piggybacked
        # on get_routing_snapshot; TTL'd, summed into the queue signal
        self.router_depths: Dict[str, tuple] = {}
        # (monotonic, cold_start_s, fleet_size_at_start) per replica
        # that reached RUNNING — feeds status()/doctor cold-start p50/p95
        self.cold_starts: deque = deque(maxlen=256)
        # (monotonic, fused load) per policy evaluation: the downscale
        # side reads a windowed AVERAGE of these (reference: the
        # look-back averaging in autoscaling_policy) so one transient
        # in-flight spike cannot keep restarting the below-window
        self.load_hist: deque = deque(maxlen=2048)

    # ----- helpers

    def target_replicas(self) -> int:
        if self.config.autoscaling_config is not None:
            return self.autoscale_desired
        return self.config.num_replicas

    def running(self, version: Optional[str] = None) -> List[_Replica]:
        return [r for r in self.replicas
                if r.state == RUNNING and
                (version is None or r.version == version)]

    def queue_depth(self, now: float) -> int:
        """Fused router-reported demand: queued + executing requests
        across every router process, TTL'd so dead routers decay."""
        total = 0
        for key in list(self.router_depths):
            ts, counts = self.router_depths[key]
            if now - ts > _ROUTER_DEPTH_TTL_S:
                del self.router_depths[key]
                continue
            total += sum(counts.values())
        return total

    def cold_start_quantiles(self) -> Dict[str, float]:
        vals = sorted(cs for _, cs, _ in self.cold_starts)
        if not vals:
            return {"count": 0, "p50_s": 0.0, "p95_s": 0.0}

        def pct(p):
            return vals[min(len(vals) - 1, int(p / 100 * len(vals)))]
        return {"count": len(vals), "p50_s": round(pct(50), 3),
                "p95_s": round(pct(95), 3)}

    def reversals(self, now: float, window_s: float = 60.0) -> int:
        """Direction changes among scale events inside the window."""
        dirs = [d for ts, d in self.scale_events if now - ts <= window_s]
        return sum(1 for a, b in zip(dirs, dirs[1:]) if a != b)

    def windowed_load(self, now: float, window_s: float) -> float:
        """Mean fused load over evaluations in the last ``window_s``
        seconds (window 0 degrades to the newest sample)."""
        vals = [ld for ts, ld in self.load_hist if ts >= now - window_s]
        if not vals:
            return float(self.load_hist[-1][1]) if self.load_hist else 0.0
        return sum(vals) / len(vals)


class ServeController:
    """The singleton controller actor (create with max_concurrency >= 4)."""

    def __init__(self):
        self._lock = threading.RLock()
        # app -> {"route_prefix", "ingress", "deployments": {name: state}}
        self._apps: Dict[str, dict] = {}
        self._routing_version = 0
        self._shutdown = False
        # every replica drain ever spawned (scale-downs, rolling updates,
        # deletes) — shutdown_serve must join ALL of them, not just the
        # ones it starts, or an in-flight drain dies with the controller
        # and leaks the replica's worker. Pruned of finished threads as
        # new drains start.
        self._drains: List[threading.Thread] = []
        # slow-signal cache (1/s polls off the reconcile thread): the
        # detector-flagged node set, per-node cpu gauges, and the
        # per-func phase summary for the SLO-burn signal
        self._slow_nodes: frozenset = frozenset()
        self._node_cpu: Dict[int, float] = {}
        self._phases: Dict[str, dict] = {}
        # (func, phase) -> deque[(ts, cumulative buckets, boundaries)]
        # for the windowed SLO p99 (see _windowed_p99)
        self._phase_snaps: Dict[tuple, deque] = {}
        self._last_signal_poll = -1e18
        self._decisions_total = 0
        self._thread = threading.Thread(target=self._control_loop,
                                        daemon=True, name="serve-reconcile")
        self._thread.start()

    # ================================================= declarative API

    def deploy_app(self, app_name: str, route_prefix: Optional[str],
                   ingress: str, deployments: List[dict]):
        """Set the target state for one application (idempotent).

        ``deployments``: [{name, payload, config[, weights_refs]}] —
        payload is the pickled replica spec (callable + init args with
        HandleMarkers; large array init args arrive as ObjectRefs with
        the live refs duplicated in ``weights_refs`` so the controller
        keeps them alive and can pre-warm them).
        """
        with self._lock:
            app = self._apps.setdefault(
                app_name, {"route_prefix": None, "ingress": ingress,
                           "deployments": {}})
            app["route_prefix"] = route_prefix
            app["ingress"] = ingress
            new_names = set()
            for d in deployments:
                name, payload, config = d["name"], d["payload"], d["config"]
                weights = d.get("weights_refs")
                version = config.version or \
                    hashlib.sha1(payload).hexdigest()[:12]
                new_names.add(name)
                cur = app["deployments"].get(name)
                if cur is None:
                    app["deployments"][name] = _DeploymentState(
                        app_name, name, payload, config, version, weights)
                else:
                    cur.payload = payload
                    cur.config = config
                    cur.version = version
                    cur.status = DEPLOY_UPDATING
                    cur.start_failures = 0
                    cur.weights_refs = list(weights or [])
                    if config.autoscaling_config is not None:
                        lo = config.autoscaling_config.min_replicas
                        hi = config.autoscaling_config.max_replicas
                        cur.autoscale_desired = min(
                            max(cur.autoscale_desired, lo), hi)
                    else:
                        cur.autoscale_desired = config.num_replicas
            # deployments removed from the app spec are torn down
            for name in list(app["deployments"]):
                if name not in new_names:
                    self._teardown_deployment(app["deployments"].pop(name))
            self._routing_version += 1
        return True

    def delete_app(self, app_name: str):
        with self._lock:
            app = self._apps.pop(app_name, None)
            if app is None:
                return False
            for dep in app["deployments"].values():
                self._teardown_deployment(dep)
            self._routing_version += 1
        return True

    def shutdown_serve(self):
        with self._lock:
            for name in list(self._apps):
                app = self._apps.pop(name)
                for dep in app["deployments"].values():
                    self._teardown_deployment(dep)
            self._shutdown = True
            self._routing_version += 1
            drains = list(self._drains)
        # The caller kills this controller actor right after this returns,
        # which would orphan any replica whose drain is still in flight —
        # the replica's worker (and lease) then leaks forever. Wait for
        # every replica to actually die before reporting shutdown done.
        for t in drains:
            t.join(timeout=30)
        return True

    def _teardown_deployment(self, dep: _DeploymentState):
        for r in dep.replicas:
            self._stop_replica(dep, r, graceful=True)
        dep.replicas = []

    # ================================================= router-facing API

    def routing_version(self) -> int:
        return self._routing_version

    def get_routing_snapshot(self, app_name: str, deployment: str,
                             router_id: Optional[str] = None,
                             inflight: Optional[dict] = None):
        """(version, [(replica_id, handle, node_idx)],
        max_concurrent_queries, [slow_node_idx]).

        ``router_id``/``inflight`` piggyback the calling router's
        per-replica in-flight counts (its live queue view) into the
        autoscaler's queue-depth signal — the refresh the router makes
        anyway doubles as its metrics report, so the controller stays
        off the per-request path."""
        with self._lock:
            app = self._apps.get(app_name)
            dep = app["deployments"].get(deployment) if app else None
            if dep is None:
                return self._routing_version, [], 1, []
            if router_id is not None:
                dep.router_depths[router_id] = (
                    time.monotonic(), dict(inflight or {}))
            return (self._routing_version,
                    [(r.replica_id, r.handle, r.node_idx)
                     for r in dep.running()],
                    dep.config.max_concurrent_queries,
                    sorted(self._slow_nodes))

    def get_routes(self) -> Dict[str, str]:
        """route_prefix -> app name (for the HTTP proxy)."""
        with self._lock:
            return {app["route_prefix"]: name
                    for name, app in self._apps.items()
                    if app["route_prefix"]}

    def get_ingress(self, app_name: str) -> Optional[str]:
        with self._lock:
            app = self._apps.get(app_name)
            return app["ingress"] if app else None

    def status(self) -> dict:
        now = time.monotonic()
        with self._lock:
            out = {}
            for name, app in self._apps.items():
                deps = {}
                statuses = []
                for dn, dep in app["deployments"].items():
                    counts: Dict[str, int] = {}
                    for r in dep.replicas:
                        counts[r.state] = counts.get(r.state, 0) + 1
                    row = {"status": dep.status,
                           "message": dep.message,
                           "replica_states": counts,
                           "target_replicas": dep.target_replicas(),
                           "version": dep.version}
                    # autoscaler introspection (r14): desired vs
                    # running, the last decision + its reason, queue
                    # depth, recent direction flips, cold-start
                    # percentiles — everything `serve status` / the
                    # dashboard / doctor need to debug a scale event
                    row["autoscaler"] = {
                        "enabled":
                            dep.config.autoscaling_config is not None,
                        "desired": dep.target_replicas(),
                        "running": counts.get(RUNNING, 0),
                        "queue_depth": dep.queue_depth(now),
                        "last_decision": dict(dep.last_decision)
                        if dep.last_decision else None,
                        "reversals_60s": dep.reversals(now),
                        "cold_start": dep.cold_start_quantiles(),
                        "weights_by_ref": len(dep.weights_refs),
                    }
                    deps[dn] = row
                    statuses.append(dep.status)
                if any(s == DEPLOY_UNHEALTHY for s in statuses):
                    app_status = "UNHEALTHY"
                elif all(s == DEPLOY_HEALTHY for s in statuses) and statuses:
                    app_status = "RUNNING"
                else:
                    app_status = "DEPLOYING"
                out[name] = {"status": app_status,
                             "route_prefix": app["route_prefix"],
                             "deployments": deps}
            return out

    # ================================================= reconcile loop

    def _control_loop(self):
        while not self._shutdown:
            try:
                self._poll_signals()
                with self._lock:
                    deps = [dep for app in self._apps.values()
                            for dep in app["deployments"].values()]
                for dep in deps:
                    self._reconcile_deployment(dep)
            except Exception:
                traceback.print_exc()
            time.sleep(_TICK_S)

    def _poll_signals(self):
        """Refresh the slow autoscaling signals (~1/s, reconcile thread
        only): detector-flagged nodes + node.cpu gauges from the nodes
        state rows, and the per-func phase summary (p99) for the SLO
        signal. Failures keep the stale cache — scaling on old signals
        beats crashing the reconciler."""
        now = time.monotonic()
        if now - self._last_signal_poll < _SIGNAL_POLL_S:
            return
        self._last_signal_poll = now
        from ray_tpu import state
        from ray_tpu.core.context import get_context_if_exists

        # never park the reconcile thread on a head outage: a state.*
        # call through a detached ReconnectingConnection blocks for the
        # whole reconnect window (up to head_reconnect_timeout_s), and
        # no replica restart or scale decision would run meanwhile.
        # Keep the stale signal cache instead (same guard as
        # warm_object / emit_cluster_event).
        ctx = get_context_if_exists()
        if ctx is None or not ctx.head.is_attached():
            return

        try:
            slow, cpu = set(), {}
            for n in state.list_nodes():
                if not n.get("alive", True):
                    continue
                if n.get("slow"):
                    slow.add(n["node_idx"])
                c = (n.get("telemetry") or {}).get("node.cpu_percent")
                if c is not None:
                    cpu[n["node_idx"]] = float(c)
            with self._lock:
                self._slow_nodes = frozenset(slow)
                self._node_cpu = cpu
        except Exception:  # noqa: BLE001 — head unreachable: keep stale
            pass
        with self._lock:
            slo_active = any(
                dep.config.autoscaling_config is not None
                and dep.config.autoscaling_config.latency_slo_ms > 0
                for app in self._apps.values()
                for dep in app["deployments"].values())
        if not slo_active:
            return  # nobody reads the phase summary: skip the head RPC
        try:
            self._phases = state.phase_summary(_SLO_FUNCS)
        except Exception:  # noqa: BLE001
            return
        # fold this poll's cumulative bucket vectors into the per-
        # (func, phase) snapshot windows the SLO signal deltas over
        for func, phases in self._phases.items():
            for phase, row in phases.items():
                buckets = row.get("buckets")
                if buckets is None:
                    continue  # pre-r14.1 head: lifetime-only summary
                snaps = self._phase_snaps.setdefault(
                    (func, phase), deque())
                if snaps and (len(snaps[-1][1]) != len(buckets)
                              or buckets[-1] < snaps[-1][1][-1]
                              # polling gap wider than the window (SLO
                              # was disabled for a while): the old
                              # baseline would delta a long-dead
                              # episode into a fresh burn
                              or now - snaps[-1][0] > _SLO_WINDOW_S):
                    snaps.clear()
                snaps.append((now, buckets, row.get("boundaries")))
                # keep one snapshot at/behind the window start as the
                # delta baseline so the window spans _SLO_WINDOW_S
                while len(snaps) > 2 and snaps[1][0] <= now - _SLO_WINDOW_S:
                    snaps.popleft()

    def _reconcile_deployment(self, dep: _DeploymentState):
        with self._lock:
            # The dep was snapshotted outside the lock; shutdown_serve or
            # delete_app may have torn it down in the window. Reconciling a
            # stale dep would resurrect replicas nobody tracks or drains.
            if self._shutdown:
                return
            app = self._apps.get(dep.app)
            if app is None or app["deployments"].get(dep.name) is not dep:
                return
            self._check_starting(dep)
            self._check_health_and_autoscale(dep)
            self._scale(dep)
            self._update_status(dep)

    # ----- phase 1: STARTING -> RUNNING on successful ping

    def _check_starting(self, dep: _DeploymentState):
        for r in list(dep.replicas):
            if r.state != STARTING:
                continue
            if r.ping_ref is None:
                r.ping_ref = r.handle.ping.remote()
            done, _ = ray_tpu.wait([r.ping_ref], num_returns=1, timeout=0,
                                   fetch_local=False)
            if not done:
                if time.monotonic() - r.started_at > \
                        dep.config.health_check_timeout_s:
                    self._replica_failed(
                        dep, r, "replica start timed out")
                continue
            try:
                pong = ray_tpu.get(r.ping_ref, timeout=1)
            except Exception as e:  # noqa: BLE001 — ctor/ping failure
                self._replica_failed(dep, r, repr(e))
                continue
            if isinstance(pong, dict):
                r.node_idx = pong.get("node_idx", -1)
            r.ping_ref = None
            r.state = RUNNING
            dep.start_failures = 0
            now = time.monotonic()
            # cold-start sample: placement + ctor + weights fetch
            dep.cold_starts.append(
                (now, now - r.started_at, len(dep.replicas)))
            self._routing_version += 1

    def _replica_failed(self, dep: _DeploymentState, r: _Replica, msg: str):
        dep.replicas.remove(r)
        try:
            ray_tpu.kill(r.handle)
        except Exception:
            pass
        dep.start_failures += 1
        dep.message = msg
        if dep.start_failures >= _MAX_CONSECUTIVE_START_FAILURES:
            dep.status = DEPLOY_UNHEALTHY

    # ----- phase 2: health checks + autoscaling metrics on RUNNING

    def _check_health_and_autoscale(self, dep: _DeploymentState):
        now = time.monotonic()
        total_ongoing = 0
        n_reporting = 0
        for r in list(dep.replicas):
            if r.state != RUNNING:
                continue
            if r.metrics_ref is None:
                if now - r.last_seen >= dep.config.health_check_period_s:
                    r.metrics_ref = r.handle.metrics.remote()
            else:
                done, _ = ray_tpu.wait([r.metrics_ref], num_returns=1,
                                       timeout=0, fetch_local=False)
                if done:
                    try:
                        m = ray_tpu.get(r.metrics_ref, timeout=1)
                        r.ongoing = m.num_ongoing_requests
                        if getattr(m, "node_idx", -1) >= 0:
                            r.node_idx = m.node_idx
                        r.last_seen = now
                    except Exception as e:  # noqa: BLE001 — replica died
                        dep.replicas.remove(r)
                        dep.message = f"replica died: {e!r}"
                        self._routing_version += 1
                        try:
                            ray_tpu.kill(r.handle)
                        except Exception:
                            pass
                        continue
                    r.metrics_ref = None
                elif now - r.last_seen > dep.config.health_check_timeout_s:
                    dep.replicas.remove(r)
                    dep.message = "replica health check timed out"
                    self._routing_version += 1
                    try:
                        ray_tpu.kill(r.handle)
                    except Exception:
                        pass
                    continue
            total_ongoing += r.ongoing
            n_reporting += 1
        cfg = dep.config.autoscaling_config
        if cfg is not None and n_reporting:
            decision = self._autoscale(
                dep, cfg, total_ongoing, now,
                signals=self._gather_signals(dep, cfg, now))
            if decision is not None:
                self._on_scale_decision(dep, decision, now)

    def _gather_signals(self, dep: _DeploymentState,
                        cfg: AutoscalingConfig, now: float) -> dict:
        """Assemble the fused-signal dict for one policy evaluation
        (caller holds the lock; everything here reads cached polls)."""
        sig = {"queue_depth": dep.queue_depth(now)}
        if cfg.latency_slo_ms > 0:
            p99 = None
            poll_now = self._last_signal_poll
            for func in _SLO_FUNCS:
                snaps = self._phase_snaps.get((func, cfg.slo_phase))
                w = _windowed_p99(snaps, poll_now) if snaps else None
                if w is None:
                    # no windowed delta yet (fresh controller, pre-r14.1
                    # head, or no traffic in the window): fall back to
                    # the lifetime percentile only while the summary has
                    # a single snapshot — beyond that, an empty window
                    # means no recent requests, which is not a burn
                    row = self._phases.get(func, {}).get(cfg.slo_phase)
                    if row and len(snaps or ()) < 2:
                        w = row["p99_ms"]
                if w is not None:
                    p99 = max(p99 or 0.0, w)
            sig["p99_ms"] = p99
        if cfg.downscale_cpu_block_pct > 0:
            cpus = [self._node_cpu.get(r.node_idx)
                    for r in dep.replicas if r.node_idx >= 0]
            cpus = [c for c in cpus if c is not None]
            sig["nodes_hot"] = bool(cpus) and \
                min(cpus) >= cfg.downscale_cpu_block_pct
        return sig

    def _autoscale(self, dep: _DeploymentState, cfg: AutoscalingConfig,
                   total_ongoing: int, now: float,
                   signals: Optional[dict] = None) -> Optional[dict]:
        """One policy evaluation. Pure deployment-state math (no self
        access — unit-testable with self=None): fuses the signals into
        a desired replica count, applies hysteresis windows + per-
        direction cooldowns + min/max clamps, and mutates
        ``dep.autoscale_desired`` when a scale decision fires.
        Returns the decision record (or None).

        Signal asymmetry (reference: look-back averaging in
        autoscaling_policy): the UP side reads the instantaneous fused
        load (react to a surge within one policy period), the DOWN side
        reads the mean load over the last ``downscale_delay_s`` — a
        single transient in-flight spike must not keep restarting the
        below-window and pin a drained fleet at its peak forever."""
        sig = signals or {}
        load = max(total_ongoing, sig.get("queue_depth", 0))
        dep.load_hist.append((now, load))
        target = cfg.target_num_ongoing_requests_per_replica
        desired = math.ceil(cfg.smoothing_factor * load / target)
        reason = (f"load={load} (ongoing={total_ongoing}, "
                  f"queue={sig.get('queue_depth', 0)})")
        p99 = sig.get("p99_ms")
        burning = cfg.latency_slo_ms > 0 and p99 is not None and \
            p99 > cfg.latency_slo_ms
        if burning and dep.autoscale_desired + 1 > desired:
            # SLO burn: latency over budget scales up one step per
            # satisfied upscale window even when concurrency alone
            # would not (slower requests, not more of them)
            desired = dep.autoscale_desired + 1
            reason = (f"slo_burn p99={p99:.0f}ms > "
                      f"{cfg.latency_slo_ms:g}ms ({cfg.slo_phase})")
        desired = min(max(desired, cfg.min_replicas), cfg.max_replicas)
        cur = dep.autoscale_desired
        avg = dep.windowed_load(now, cfg.downscale_delay_s)
        down_to = min(max(math.ceil(cfg.smoothing_factor * avg / target),
                          cfg.min_replicas), cfg.max_replicas)
        if desired > cur:
            dep._below_since = None
            if dep._above_since is None:
                dep._above_since = now
            if now - dep._above_since >= cfg.upscale_delay_s and \
                    now - dep.last_scale_ts >= cfg.upscale_cooldown_s:
                dep.autoscale_desired = desired
                dep._above_since = None
                return _record_decision(dep, "up", cur, desired,
                                        reason, sig, now)
        elif down_to < cur:
            dep._above_since = None
            if sig.get("nodes_hot") or burning:
                # every hosting node pegged (shrinking just moves the
                # queue) or the latency SLO is burning (fewer replicas
                # cannot help it): hold, and restart the downscale
                # window so the veto also delays the eventual shrink
                dep._below_since = None
                return None
            if dep._below_since is None:
                dep._below_since = now
            if now - dep._below_since >= cfg.downscale_delay_s and \
                    now - dep.last_scale_ts >= cfg.downscale_cooldown_s:
                dep.autoscale_desired = down_to
                dep._below_since = None
                reason = (f"avg_load={avg:.1f}/{cfg.downscale_delay_s:g}s"
                          f" (ongoing={total_ongoing}, "
                          f"queue={sig.get('queue_depth', 0)})")
                return _record_decision(dep, "down", cur, down_to,
                                        reason, sig, now)
        else:
            dep._above_since = None
            dep._below_since = None
        return None

    def _on_scale_decision(self, dep: _DeploymentState, decision: dict,
                           now: float):
        """Side effects of a scale decision (caller holds the lock):
        pre-warm the broadcast for scale-ups BEFORE any replica is
        placed, and emit the rate-limited cluster event."""
        self._decisions_total += 1
        if decision["direction"] == "up":
            self._prewarm(dep, now, force=True)
        if now - dep._last_event_ts >= _DECISION_EVENT_MIN_GAP_S:
            dep._last_event_ts = now
            from ray_tpu.core.events import emit_cluster_event

            emit_cluster_event(
                "INFO", "serve", "serve_autoscale",
                f"{dep.app}/{dep.name}: scale {decision['direction']} "
                f"{decision['from']} -> {decision['to']} "
                f"({decision['reason']})",
                entity_id=f"{dep.app}/{dep.name}",
                extra={"app": dep.app, "deployment": dep.name,
                       **{k: v for k, v in decision.items()
                          if k != "ts"}})

    def _prewarm(self, dep: _DeploymentState, now: float,
                 force: bool = False):
        """Ship the deployment's by-ref weights toward every node
        BEFORE new replicas are placed (OBJECT_WARM -> r13 prefetch ->
        r9 broadcast tree): cold-start then finds the bytes local or
        joins the in-flight pull, so N concurrent scale-ups cost ~2xS
        root egress instead of NxS. Fire-and-forget; rate-limited per
        deployment unless forced by a fresh scale-up decision."""
        if not dep.weights_refs:
            return
        if not force and now - dep._last_prewarm_ts < _PREWARM_MIN_GAP_S:
            return
        dep._last_prewarm_ts = now
        for ref in dep.weights_refs:
            try:
                ray_tpu.warm_object(ref)
            except Exception:  # noqa: BLE001 — speculation only
                pass

    # ----- phase 3: converge replica set to target count + version

    def _scale(self, dep: _DeploymentState):
        if dep.status == DEPLOY_UNHEALTHY:
            return
        target = dep.target_replicas()
        current = [r for r in dep.replicas if r.state in (STARTING, RUNNING)]
        new_version = [r for r in current if r.version == dep.version]
        old_version = [r for r in current if r.version != dep.version]

        # rolling update: bring up the new version to target, then retire old
        if len(new_version) < target:
            if target - len(new_version) >= 2:
                # CONCURRENT scale-up (manual redeploy path; autoscaler
                # decisions already pre-warmed at decision time): ship
                # the weights toward the fleet before the actors are
                # even placed. A single new replica skips this — one
                # demand pull off the holder set is already optimal,
                # and warming the whole cluster for it would waste
                # every other node's arena.
                self._prewarm(dep, time.monotonic())
            for _ in range(target - len(new_version)):
                self._start_replica(dep)
        elif old_version and len(dep.running(dep.version)) >= target:
            for r in old_version:
                dep.replicas.remove(r)
                self._stop_replica(dep, r, graceful=True)
            self._routing_version += 1
        elif not old_version and len(new_version) > target:
            # scale down — doom in priority order: non-running first
            # (cheapest to kill), then replicas on detector-flagged
            # slow nodes (shed the degraded host), then newest-started.
            # Ascending sort puts the doomed at the FRONT: non-RUNNING
            # (False) < RUNNING, in-slow (False) < clean, newest
            # (-started_at) smallest.
            slow = self._slow_nodes
            doomed = sorted(
                new_version,
                key=lambda r: (r.state == RUNNING,
                               r.node_idx not in slow, -r.started_at)
            )[:len(new_version) - target]
            running_removed = False
            for r in doomed:
                running_removed |= r.state == RUNNING
                dep.replicas.remove(r)
                self._stop_replica(dep, r, graceful=True)
            if running_removed:
                self._routing_version += 1

    def _start_replica(self, dep: _DeploymentState):
        from .replica import ServeReplica

        opts = dict(dep.config.ray_actor_options)
        replica_id = f"{dep.app}#{dep.name}#{dep.next_replica_idx}"
        dep.next_replica_idx += 1
        actor_cls = ray_tpu.remote(ServeReplica).options(
            num_cpus=opts.get("num_cpus", 0),
            num_tpus=opts.get("num_tpus"),
            resources=opts.get("resources"),
            # queries + ping/metrics/drain must run concurrently
            max_concurrency=dep.config.max_concurrent_queries + 3,
        )
        handle = actor_cls.remote(dep.payload, replica_id)
        dep.replicas.append(_Replica(replica_id, handle, dep.version))

    def _stop_replica(self, dep: _DeploymentState, r: _Replica,
                      graceful: bool) -> threading.Thread:
        r.state = STOPPING

        def _drain(handle=r.handle,
                   timeout=dep.config.graceful_shutdown_timeout_s):
            try:
                if graceful:
                    ray_tpu.get(handle.prepare_shutdown.remote(timeout),
                                timeout=timeout + 5)
            except Exception:
                pass
            try:
                ray_tpu.kill(handle)
            except Exception:
                pass

        t = threading.Thread(target=_drain, daemon=True)
        with self._lock:
            self._drains = [d for d in self._drains if d.is_alive()]
            self._drains.append(t)
        t.start()
        return t

    # ----- phase 4: status rollup

    def _update_status(self, dep: _DeploymentState):
        if dep.status == DEPLOY_UNHEALTHY:
            return
        target = dep.target_replicas()
        if len(dep.running(dep.version)) == target and \
                all(r.state == RUNNING for r in dep.replicas):
            dep.status = DEPLOY_HEALTHY
            dep.message = ""
        else:
            dep.status = DEPLOY_UPDATING


def get_or_create_controller():
    """Find the singleton controller, creating it on first use."""
    try:
        return ray_tpu.get_actor(CONTROLLER_NAME)
    except ValueError:
        pass
    handle = ray_tpu.remote(ServeController).options(
        name=CONTROLLER_NAME, num_cpus=0, max_concurrency=8).remote()
    # wait until the name resolves and the actor answers
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        try:
            ray_tpu.get(handle.routing_version.remote(), timeout=5)
            return handle
        except Exception:
            time.sleep(0.05)
    raise RuntimeError("serve controller failed to start")
