"""Client-side request router: power-of-two-choices replica selection.

Ref analog: python/ray/serve/_private/router.py:281
(PowerOfTwoChoicesReplicaScheduler) + :985 (Router). Re-design: no asyncio —
a per-process router per deployment tracks its own in-flight count per
replica, picks the less-loaded of two random replicas, and blocks (with
backpressure) when every replica is at ``max_concurrent_queries``. Replica
membership is refreshed from the controller when its ``routing_version``
moves (polled with a small TTL; the reference uses a long-poll broker).

r14 additions:

- **Slow-node awareness**: the routing snapshot carries the set of nodes
  the head's ``slow_node`` detector currently flags; replicas on flagged
  nodes are DEPRIORITIZED — power-of-two-choices runs over the clean
  pool and falls back to flagged replicas only when every clean one is
  at its concurrency bound (degraded capacity still beats a timeout).
- **Queue-depth reporting**: each snapshot refresh piggybacks this
  router's per-replica in-flight counts PLUS the callers currently
  blocked in ``_acquire_replica`` (reserved ``__waiting__`` key) to the
  controller, which fuses them across router processes into the
  autoscaler's queue-depth signal — the replica itself only sees
  requests its executor already started, and slot counts alone saturate
  at capacity, so in-flight + waiters IS the queue. No extra RPC: the
  report rides the refresh the router makes anyway, keeping the
  controller off the per-request hot path.
"""

from __future__ import annotations

import random
import threading
import time
from typing import Dict, FrozenSet, List, Optional, Tuple

import ray_tpu

_REFRESH_TTL_S = 0.25


class Router:
    def __init__(self, app_name: str, deployment: str):
        from ray_tpu.core.ids import _random_bytes

        self.app = app_name
        self.deployment = deployment
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        # (replica_id, handle, node_idx) triples
        self._replicas: List[Tuple[str, object, int]] = []
        self._slow_nodes: FrozenSet[int] = frozenset()
        self._inflight: Dict[str, int] = {}
        # callers blocked in _acquire_replica: demand beyond capacity.
        # Slot-holding counts saturate at n_replicas * max_q, so without
        # this the autoscaler can never see a backlog past capacity
        # (and would happily SHRINK a saturated fleet).
        self._waiting = 0
        self._max_q = 1
        self._version = -1
        self._last_refresh = 0.0
        self._outstanding: Dict[object, str] = {}  # ObjectRef -> replica_id
        self._model_affinity: Dict[str, str] = {}  # model_id -> replica_id
        self._drainer: Optional[threading.Thread] = None
        self._controller = None
        # stable identity for the controller's per-router depth table
        self._router_id = _random_bytes(8).hex()

    # ------------------------------------------------------------ membership

    def _controller_handle(self):
        if self._controller is None:
            from .controller import CONTROLLER_NAME

            self._controller = ray_tpu.get_actor(CONTROLLER_NAME)
        return self._controller

    def _refresh(self, force: bool = False):
        now = time.monotonic()
        if not force and now - self._last_refresh < _REFRESH_TTL_S:
            return
        self._last_refresh = now
        ctrl = self._controller_handle()
        with self._lock:
            depths = dict(self._inflight)
            if self._waiting:
                # reserved key (replica ids are hex): fused into the
                # controller's queue-depth sum like any replica count
                depths["__waiting__"] = self._waiting
        version, replicas, max_q, slow = ray_tpu.get(
            ctrl.get_routing_snapshot.remote(self.app, self.deployment,
                                             self._router_id, depths),
            timeout=30)
        with self._lock:
            self._slow_nodes = frozenset(slow)
            if version != self._version:
                self._version = version
                self._replicas = replicas
                self._max_q = max(1, max_q)
                known = {rid for rid, _, _ in replicas}
                self._inflight = {rid: self._inflight.get(rid, 0)
                                  for rid in known}
                self._cond.notify_all()

    # ------------------------------------------------------------- dispatch

    def assign(self, method_name: str, args: tuple, kwargs: dict,
               timeout_s: float = 60.0, meta: Optional[dict] = None):
        """Pick a replica (power of two choices) and push the request.

        Returns the resulting ObjectRef. Blocks while all replicas are at
        max_concurrent_queries (client-side backpressure). Positional
        request args ship as REAL task args (``*args`` tail) so by-ref
        payloads ride the zero-copy wire path end-to-end."""
        rid, handle = self._acquire_replica(timeout_s, meta)
        ref = None
        try:
            ref = handle.handle_request.remote(
                method_name, kwargs, meta, *args)
            with self._lock:
                self._outstanding[ref] = rid
                self._ensure_drainer_locked()
            return ref
        finally:
            if ref is None:  # submission itself failed
                self.release(rid)

    def assign_stream(self, method_name: str, args: tuple, kwargs: dict,
                      timeout_s: float = 60.0,
                      meta: Optional[dict] = None):
        """Pick a replica for a STREAMING request. Returns (replica_id,
        actor_handle, stream_id_ref); the caller drives stream_next and
        MUST call release(replica_id) when the stream ends — the slot
        stays held for the stream's whole lifetime."""
        rid, handle = self._acquire_replica(timeout_s, meta)
        try:
            sid_ref = handle.start_stream.remote(
                method_name, kwargs, meta, *args)
        except BaseException:
            self.release(rid)
            raise
        return rid, handle, sid_ref

    def release(self, rid: str):
        with self._lock:
            if rid in self._inflight:
                self._inflight[rid] = max(0, self._inflight[rid] - 1)
            self._cond.notify_all()

    def _acquire_replica(self, timeout_s: float, meta: Optional[dict]):
        self._refresh()
        model_id = (meta or {}).get("multiplexed_model_id", "")
        deadline = time.monotonic() + timeout_s
        waiting = False
        try:
            while True:
                with self._lock:
                    choice = self._choose_locked(model_id)
                    if choice is not None:
                        rid, handle = choice
                        self._inflight[rid] = \
                            self._inflight.get(rid, 0) + 1
                        if model_id:
                            # pin affinity only when the model has no
                            # live holder: a request spilling off a
                            # momentarily saturated holder must not
                            # migrate the model (load/evict ping-pong
                            # under bursts)
                            cur = self._model_affinity.get(model_id)
                            if cur is None or cur not in {
                                    r for r, _, _ in self._replicas}:
                                self._model_affinity[model_id] = rid
                        return rid, handle
                    if not waiting:
                        # blocked past capacity: count this caller into
                        # the queue-depth report (see _waiting)
                        waiting = True
                        self._waiting += 1
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        raise TimeoutError(
                            f"no replica of {self.app}/{self.deployment} "
                            f"available within {timeout_s}s")
                    self._cond.wait(min(remaining, _REFRESH_TTL_S))
                self._refresh(force=not self._replicas)
        finally:
            if waiting:
                with self._lock:
                    self._waiting -= 1

    def _choose_locked(self, model_id: str = ""
                       ) -> Optional[Tuple[str, object]]:
        if model_id:
            # multiplexing affinity: prefer the replica that already holds
            # the model, unless it is saturated (ref: multiplexed routing
            # in the reference's replica scheduler). The holder rides
            # THROUGH the slow-node filter below — the model is already
            # resident there, and re-loading it on a clean replica costs
            # more than the flagged host's latency (and would scatter the
            # model into the load/evict ping-pong the pin exists to stop)
            want = self._model_affinity.get(model_id)
            if want is not None:
                for rid, h, _n in self._replicas:
                    if rid == want and \
                            self._inflight.get(rid, 0) < self._max_q:
                        return rid, h
        avail = [(rid, h) for rid, h, n in self._replicas
                 if self._inflight.get(rid, 0) < self._max_q
                 and n not in self._slow_nodes]
        if not avail:
            # every clean replica is saturated (or none exist): fall
            # back to replicas on detector-flagged nodes — a slow host
            # still beats refusing the request (the reference likewise
            # soft-deprioritizes rather than hard-drains)
            avail = [(rid, h) for rid, h, _n in self._replicas
                     if self._inflight.get(rid, 0) < self._max_q]
        if not avail:
            return None
        if len(avail) == 1:
            return avail[0]
        a, b = random.sample(avail, 2)
        return a if self._inflight.get(a[0], 0) <= \
            self._inflight.get(b[0], 0) else b

    # ------------------------------------------------------------ drain loop

    def _ensure_drainer_locked(self):
        if self._drainer is None or not self._drainer.is_alive():
            self._drainer = threading.Thread(
                target=self._drain_loop, daemon=True,
                name=f"serve-router-{self.deployment}")
            self._drainer.start()

    def _drain_loop(self):
        """Release in-flight slots as replica replies land."""
        while True:
            with self._lock:
                refs = list(self._outstanding)
            if not refs:
                with self._lock:
                    if not self._outstanding:
                        self._drainer = None
                        return
                continue
            done, _ = ray_tpu.wait(refs, num_returns=1, timeout=0.2,
                                   fetch_local=False)
            if not done:
                continue
            with self._lock:
                for ref in done:
                    rid = self._outstanding.pop(ref, None)
                    if rid is not None and rid in self._inflight:
                        self._inflight[rid] = max(
                            0, self._inflight[rid] - 1)
                self._cond.notify_all()


_routers: Dict[Tuple[str, str], Router] = {}
_routers_lock = threading.Lock()


def get_router(app_name: str, deployment: str) -> Router:
    key = (app_name, deployment)
    with _routers_lock:
        r = _routers.get(key)
        if r is None:
            r = _routers[key] = Router(app_name, deployment)
        return r


def reset_routers():
    """Drop cached routers (test isolation across serve sessions)."""
    with _routers_lock:
        _routers.clear()
