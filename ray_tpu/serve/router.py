"""Client-side request router: power-of-two-choices replica selection.

Ref analog: python/ray/serve/_private/router.py:281
(PowerOfTwoChoicesReplicaScheduler) + :985 (Router). Re-design: no asyncio —
a per-process router per deployment tracks its own in-flight count per
replica, picks the less-loaded of two random replicas, and blocks (with
backpressure) when every replica is at ``max_concurrent_queries``. Replica
membership is refreshed from the controller when its ``routing_version``
moves (polled with a small TTL; the reference uses a long-poll broker).
"""

from __future__ import annotations

import random
import threading
import time
from typing import Dict, List, Optional, Tuple

import ray_tpu

_REFRESH_TTL_S = 0.25


class Router:
    def __init__(self, app_name: str, deployment: str):
        self.app = app_name
        self.deployment = deployment
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._replicas: List[Tuple[str, object]] = []  # (replica_id, handle)
        self._inflight: Dict[str, int] = {}
        self._max_q = 1
        self._version = -1
        self._last_refresh = 0.0
        self._outstanding: Dict[object, str] = {}  # ObjectRef -> replica_id
        self._model_affinity: Dict[str, str] = {}  # model_id -> replica_id
        self._drainer: Optional[threading.Thread] = None
        self._controller = None

    # ------------------------------------------------------------ membership

    def _controller_handle(self):
        if self._controller is None:
            from .controller import CONTROLLER_NAME

            self._controller = ray_tpu.get_actor(CONTROLLER_NAME)
        return self._controller

    def _refresh(self, force: bool = False):
        now = time.monotonic()
        if not force and now - self._last_refresh < _REFRESH_TTL_S:
            return
        self._last_refresh = now
        ctrl = self._controller_handle()
        version, replicas, max_q = ray_tpu.get(
            ctrl.get_routing_snapshot.remote(self.app, self.deployment),
            timeout=30)
        with self._lock:
            if version != self._version:
                self._version = version
                self._replicas = replicas
                self._max_q = max(1, max_q)
                known = {rid for rid, _ in replicas}
                self._inflight = {rid: self._inflight.get(rid, 0)
                                  for rid in known}
                self._cond.notify_all()

    # ------------------------------------------------------------- dispatch

    def assign(self, method_name: str, args: tuple, kwargs: dict,
               timeout_s: float = 60.0, meta: Optional[dict] = None):
        """Pick a replica (power of two choices) and push the request.

        Returns the resulting ObjectRef. Blocks while all replicas are at
        max_concurrent_queries (client-side backpressure)."""
        rid, handle = self._acquire_replica(timeout_s, meta)
        ref = None
        try:
            ref = handle.handle_request.remote(
                method_name, args, kwargs, meta)
            with self._lock:
                self._outstanding[ref] = rid
                self._ensure_drainer_locked()
            return ref
        finally:
            if ref is None:  # submission itself failed
                self.release(rid)

    def assign_stream(self, method_name: str, args: tuple, kwargs: dict,
                      timeout_s: float = 60.0,
                      meta: Optional[dict] = None):
        """Pick a replica for a STREAMING request. Returns (replica_id,
        actor_handle, stream_id_ref); the caller drives stream_next and
        MUST call release(replica_id) when the stream ends — the slot
        stays held for the stream's whole lifetime."""
        rid, handle = self._acquire_replica(timeout_s, meta)
        try:
            sid_ref = handle.start_stream.remote(
                method_name, args, kwargs, meta)
        except BaseException:
            self.release(rid)
            raise
        return rid, handle, sid_ref

    def release(self, rid: str):
        with self._lock:
            if rid in self._inflight:
                self._inflight[rid] = max(0, self._inflight[rid] - 1)
            self._cond.notify_all()

    def _acquire_replica(self, timeout_s: float, meta: Optional[dict]):
        self._refresh()
        model_id = (meta or {}).get("multiplexed_model_id", "")
        deadline = time.monotonic() + timeout_s
        while True:
            with self._lock:
                choice = self._choose_locked(model_id)
                if choice is not None:
                    rid, handle = choice
                    self._inflight[rid] = self._inflight.get(rid, 0) + 1
                    if model_id:
                        # pin affinity only when the model has no live
                        # holder: a request spilling off a momentarily
                        # saturated holder must not migrate the model
                        # (load/evict ping-pong under bursts)
                        cur = self._model_affinity.get(model_id)
                        if cur is None or cur not in {
                                r for r, _ in self._replicas}:
                            self._model_affinity[model_id] = rid
                    return rid, handle
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise TimeoutError(
                        f"no replica of {self.app}/{self.deployment} "
                        f"available within {timeout_s}s")
                self._cond.wait(min(remaining, _REFRESH_TTL_S))
            self._refresh(force=not self._replicas)

    def _choose_locked(self, model_id: str = ""
                       ) -> Optional[Tuple[str, object]]:
        avail = [(rid, h) for rid, h in self._replicas
                 if self._inflight.get(rid, 0) < self._max_q]
        if not avail:
            return None
        if model_id:
            # multiplexing affinity: prefer the replica that already holds
            # the model, unless it is saturated (ref: multiplexed routing
            # in the reference's replica scheduler)
            want = self._model_affinity.get(model_id)
            for rid, h in avail:
                if rid == want:
                    return rid, h
        if len(avail) == 1:
            return avail[0]
        a, b = random.sample(avail, 2)
        return a if self._inflight.get(a[0], 0) <= \
            self._inflight.get(b[0], 0) else b

    # ------------------------------------------------------------ drain loop

    def _ensure_drainer_locked(self):
        if self._drainer is None or not self._drainer.is_alive():
            self._drainer = threading.Thread(
                target=self._drain_loop, daemon=True,
                name=f"serve-router-{self.deployment}")
            self._drainer.start()

    def _drain_loop(self):
        """Release in-flight slots as replica replies land."""
        while True:
            with self._lock:
                refs = list(self._outstanding)
            if not refs:
                with self._lock:
                    if not self._outstanding:
                        self._drainer = None
                        return
                continue
            done, _ = ray_tpu.wait(refs, num_returns=1, timeout=0.2,
                                   fetch_local=False)
            if not done:
                continue
            with self._lock:
                for ref in done:
                    rid = self._outstanding.pop(ref, None)
                    if rid is not None and rid in self._inflight:
                        self._inflight[rid] = max(
                            0, self._inflight[rid] - 1)
                self._cond.notify_all()


_routers: Dict[Tuple[str, str], Router] = {}
_routers_lock = threading.Lock()


def get_router(app_name: str, deployment: str) -> Router:
    key = (app_name, deployment)
    with _routers_lock:
        r = _routers.get(key)
        if r is None:
            r = _routers[key] = Router(app_name, deployment)
        return r


def reset_routers():
    """Drop cached routers (test isolation across serve sessions)."""
    with _routers_lock:
        _routers.clear()
