"""``@serve.batch`` — coalesce concurrent single calls into one batch call.

Ref analog: python/ray/serve/batching.py:337 (@serve.batch, asyncio-queue
based). Re-design for threaded replicas: callers land on the replica's
thread pool and block on per-item futures while a dedicated daemon
*drainer* thread (started lazily, exits when idle) slices the queue into
batches of at most ``max_batch_size``, waiting up to
``batch_wait_timeout_s`` for each to fill, and runs the wrapped function
once per batch. This is how an XLA-compiled model replica turns N
concurrent requests into one padded forward pass.
"""

from __future__ import annotations

import concurrent.futures
import functools
import threading
import time
from typing import Any, Callable, Dict, List, Optional

# per-process registry of batchers for plain-function @serve.batch targets
_global_batchers: Dict[Any, "_Batcher"] = {}


class _Batcher:
    """Coalesces concurrent submit() calls into capped batches.

    A lazily started daemon *drainer* thread (not one of the callers — a
    caller-as-leader design either returns early and strands queued items
    or drains forever and never returns under sustained load) slices the
    queue into batches of at most ``max_bs``, waiting up to ``wait_s`` for
    each to fill. Replicas compiled for a padded XLA batch shape must never
    receive oversized batches, so the cap is a hard invariant.
    """

    def __init__(self, max_batch_size: int, batch_wait_timeout_s: float):
        self.max_bs = max_batch_size
        self.wait_s = batch_wait_timeout_s
        self.lock = threading.Lock()
        self.cv = threading.Condition(self.lock)
        self.queue: List = []  # (item, Future, call_batch)
        self.drainer: Optional[threading.Thread] = None  # guarded by lock

    def submit(self, call_batch: Callable[[list], list], item: Any) -> Any:
        fut: concurrent.futures.Future = concurrent.futures.Future()
        with self.lock:
            reentrant = threading.current_thread() is self.drainer
            if not reentrant:
                self.queue.append((item, fut, call_batch))
                if self.drainer is None:
                    t = threading.Thread(
                        target=self._drain, daemon=True, name="serve-batcher")
                    self.drainer = t
                    try:
                        t.start()
                    except BaseException:
                        # Thread exhaustion: reset ownership and fail queued
                        # futures so nothing blocks on a drainer that never
                        # ran.
                        self.drainer = None
                        pending, self.queue = self.queue, []
                        for _, f, _ in pending:
                            if not f.done():
                                f.set_exception(RuntimeError(
                                    "could not start @serve.batch drainer "
                                    "thread"))
                        raise
                else:
                    self.cv.notify()
        if reentrant:
            # Re-entrant call from inside call_batch: enqueueing would
            # deadlock (the drainer would wait on itself), so run the item
            # as its own batch inline, outside the lock.
            results = call_batch([item])
            if results is None or len(results) != 1:
                raise ValueError(
                    "@serve.batch function must return one result per input "
                    f"(1 in, {len(results) if results is not None else 0} "
                    "out)")
            return results[0]
        return fut.result()

    def _drain(self) -> None:
        try:
            while True:
                with self.lock:
                    if not self.queue:
                        # Exit under the lock: the next submit() sees
                        # drainer None and starts a fresh thread.
                        self.drainer = None
                        return
                    deadline = time.monotonic() + self.wait_s
                    while len(self.queue) < self.max_bs:
                        remaining = deadline - time.monotonic()
                        if remaining <= 0 or not self.cv.wait(remaining):
                            break
                    batch = self.queue[:self.max_bs]
                    self.queue = self.queue[self.max_bs:]
                self._run_one(batch)
        except BaseException:
            # Never leave waiters blocked on futures nobody will resolve:
            # fail everything queued, clear ownership so the next submit
            # restarts a drainer, then let the error surface.
            with self.lock:
                self.drainer = None
                pending, self.queue = self.queue, []
            for _, f, _ in pending:
                if not f.done():
                    f.set_exception(
                        RuntimeError("@serve.batch drainer thread died"))
            raise

    def _run_one(self, batch: List) -> None:
        items = [i for i, _, _ in batch]
        call_batch = batch[0][2]
        try:
            results = call_batch(items)
            if results is None or len(results) != len(items):
                raise ValueError(
                    f"@serve.batch function must return one result per "
                    f"input ({len(items)} in, "
                    f"{len(results) if results is not None else 0} out)")
        except BaseException as e:  # noqa: BLE001 — propagate to all callers
            for _, f, _ in batch:
                if not f.done():
                    f.set_exception(e)
            if not isinstance(e, Exception):
                raise  # SystemExit/KeyboardInterrupt: don't swallow
            return
        for (_, f, _), r in zip(batch, results):
            f.set_result(r)


def batch(_func: Optional[Callable] = None, *, max_batch_size: int = 8,
          batch_wait_timeout_s: float = 0.01):
    """Decorate a function/method taking a list so single calls batch up.

    The wrapped callable must accept a list of items and return a list of
    results of the same length. Call sites pass ONE item and get ONE result.
    """

    def decorate(fn):
        attr = f"__serve_batcher_{fn.__name__}"

        @functools.wraps(fn)
        def wrapper(*args):
            # Batchers hold locks, so they are created lazily per process
            # and never captured in the closure — the deployment payload is
            # pickled by value and locks don't pickle.
            if len(args) == 2:  # bound method: (self, item)
                self_, item = args
                batcher = getattr(self_, attr, None)
                if batcher is None:
                    # created once under a racy-but-idempotent setattr
                    # (worst case one extra object)
                    batcher = _Batcher(max_batch_size, batch_wait_timeout_s)
                    if not hasattr(self_, attr):
                        setattr(self_, attr, batcher)
                    batcher = getattr(self_, attr)
                return batcher.submit(lambda items: fn(self_, items), item)
            if len(args) == 1:
                batcher = _global_batchers.get(wrapper)
                if batcher is None:
                    batcher = _global_batchers.setdefault(
                        wrapper, _Batcher(max_batch_size,
                                          batch_wait_timeout_s))
                return batcher.submit(lambda items: fn(items), args[0])
            raise TypeError(
                "@serve.batch functions take exactly one item argument")

        wrapper._is_serve_batch = True  # noqa: SLF001
        return wrapper

    if _func is not None:
        return decorate(_func)
    return decorate
