"""``@serve.batch`` — coalesce concurrent single calls into one batch call.

Ref analog: python/ray/serve/batching.py:337 (@serve.batch, asyncio-queue
based). Re-design for threaded replicas: callers land on the replica's
thread pool; the first caller in a window becomes the *leader*, waits up to
``batch_wait_timeout_s`` (cut short the moment the batch fills), then runs
the wrapped function once on the whole batch while the other callers block
on their per-item futures. This is how an XLA-compiled model replica turns
N concurrent requests into one padded forward pass.
"""

from __future__ import annotations

import concurrent.futures
import functools
import threading
from typing import Any, Callable, Dict, List, Optional

# per-process registry of batchers for plain-function @serve.batch targets
_global_batchers: Dict[Any, "_Batcher"] = {}


class _Batcher:
    def __init__(self, max_batch_size: int, batch_wait_timeout_s: float):
        self.max_bs = max_batch_size
        self.wait_s = batch_wait_timeout_s
        self.lock = threading.Lock()
        self.full = threading.Event()
        self.queue: List = []  # (item, Future)

    def submit(self, call_batch: Callable[[list], list], item: Any) -> Any:
        fut: concurrent.futures.Future = concurrent.futures.Future()
        with self.lock:
            leader = not self.queue
            self.queue.append((item, fut))
            if leader:
                self.full.clear()
            if len(self.queue) >= self.max_bs:
                self.full.set()
        if leader:
            self.full.wait(self.wait_s)
            with self.lock:
                batch, self.queue = self.queue, []
            items = [i for i, _ in batch]
            try:
                results = call_batch(items)
                if results is None or len(results) != len(items):
                    raise ValueError(
                        f"@serve.batch function must return one result per "
                        f"input ({len(items)} in, "
                        f"{len(results) if results is not None else 0} out)")
            except Exception as e:  # noqa: BLE001 — propagate to all callers
                for _, f in batch:
                    f.set_exception(e)
                raise
            for (_, f), r in zip(batch, results):
                f.set_result(r)
        return fut.result()


def batch(_func: Optional[Callable] = None, *, max_batch_size: int = 8,
          batch_wait_timeout_s: float = 0.01):
    """Decorate a function/method taking a list so single calls batch up.

    The wrapped callable must accept a list of items and return a list of
    results of the same length. Call sites pass ONE item and get ONE result.
    """

    def decorate(fn):
        attr = f"__serve_batcher_{fn.__name__}"

        @functools.wraps(fn)
        def wrapper(*args):
            # Batchers hold locks, so they are created lazily per process
            # and never captured in the closure — the deployment payload is
            # pickled by value and locks don't pickle.
            if len(args) == 2:  # bound method: (self, item)
                self_, item = args
                batcher = getattr(self_, attr, None)
                if batcher is None:
                    # created once under a racy-but-idempotent setattr
                    # (worst case one extra object)
                    batcher = _Batcher(max_batch_size, batch_wait_timeout_s)
                    if not hasattr(self_, attr):
                        setattr(self_, attr, batcher)
                    batcher = getattr(self_, attr)
                return batcher.submit(lambda items: fn(self_, items), item)
            if len(args) == 1:
                batcher = _global_batchers.get(wrapper)
                if batcher is None:
                    batcher = _global_batchers.setdefault(
                        wrapper, _Batcher(max_batch_size,
                                          batch_wait_timeout_s))
                return batcher.submit(lambda items: fn(items), args[0])
            raise TypeError(
                "@serve.batch functions take exactly one item argument")

        wrapper._is_serve_batch = True  # noqa: SLF001
        return wrapper

    if _func is not None:
        return decorate(_func)
    return decorate
