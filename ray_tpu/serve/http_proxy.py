"""HTTP ingress proxy: routes HTTP requests to application ingress handles.

Ref analog: python/ray/serve/_private/http_proxy.py:661 (HTTPProxyActor,
uvicorn/ASGI). Re-design: a threaded stdlib HTTP server inside a plain
actor — no ASGI layer; JSON bodies map to handle args, results map back to
JSON. Routes come from the controller's route table (route_prefix -> app),
longest prefix wins, refreshed with a small TTL.
"""

from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

import ray_tpu

PROXY_NAME = "SERVE_HTTP_PROXY"
_ROUTES_TTL_S = 1.0


class HTTPProxy:
    """Actor hosting the HTTP server (create with max_concurrency > 1)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self._routes = {}
        self._routes_at = 0.0
        self._controller = None
        proxy = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):  # silence per-request stderr spam
                pass

            def _reply(self, code: int, payload: bytes,
                       ctype: str = "application/json"):
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(payload)))
                self.end_headers()
                self.wfile.write(payload)

            def _dispatch(self, body: Optional[bytes]):
                path = self.path.split("?", 1)[0]
                if path == "/-/healthz":
                    self._reply(200, b'"ok"')
                    return
                if path == "/-/routes":
                    self._reply(200, json.dumps(
                        proxy._route_table()).encode())
                    return
                app = proxy._match(path)
                if app is None:
                    self._reply(404, json.dumps(
                        {"error": f"no app mounted at {path}"}).encode())
                    return
                try:
                    arg = None
                    if body:
                        try:
                            arg = json.loads(body)
                        except json.JSONDecodeError:
                            arg = body.decode("utf-8", "replace")
                    handle = proxy._app_handle(app)
                    if self.headers.get("X-Serve-Stream") == "1":
                        # chunked ndjson streaming (ref: StreamingResponse
                        # over a generator deployment, replica.py:339)
                        gen = handle.options(stream=True).remote(arg)
                        try:
                            self.send_response(200)
                            self.send_header("Content-Type",
                                             "application/x-ndjson")
                            self.send_header("Transfer-Encoding",
                                             "chunked")
                            self.end_headers()
                            for item in gen:
                                chunk = (json.dumps(item) + "\n").encode()
                                self.wfile.write(
                                    f"{len(chunk):x}\r\n".encode()
                                    + chunk + b"\r\n")
                            self.wfile.write(b"0\r\n\r\n")
                        finally:
                            # client disconnects mid-stream must not leak
                            # the replica slot
                            gen.close()
                        return
                    result = handle.remote(arg).result(timeout_s=60)
                    if isinstance(result, bytes):
                        self._reply(200, result,
                                    "application/octet-stream")
                    else:
                        self._reply(200, json.dumps(result).encode())
                except Exception as e:  # noqa: BLE001 — surface to client
                    self._reply(500, json.dumps(
                        {"error": repr(e)}).encode())

            def do_GET(self):
                self._dispatch(None)

            def do_POST(self):
                n = int(self.headers.get("Content-Length") or 0)
                self._dispatch(self.rfile.read(n) if n else None)

        self._server = ThreadingHTTPServer((host, port), Handler)
        self._server.daemon_threads = True
        self._port = self._server.server_address[1]
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        daemon=True, name="serve-http")
        self._thread.start()

    # ------------------------------------------------------------- helpers

    def _controller_handle(self):
        if self._controller is None:
            from .controller import CONTROLLER_NAME

            self._controller = ray_tpu.get_actor(CONTROLLER_NAME)
        return self._controller

    def _route_table(self) -> dict:
        now = time.monotonic()
        if now - self._routes_at > _ROUTES_TTL_S:
            try:
                self._routes = ray_tpu.get(
                    self._controller_handle().get_routes.remote(), timeout=10)
                self._routes_at = now
            except Exception:
                pass
        return self._routes

    def _match(self, path: str) -> Optional[str]:
        best, best_len = None, -1
        for prefix, app in self._route_table().items():
            norm = prefix.rstrip("/") or "/"
            if (path == norm or path.startswith(norm.rstrip("/") + "/")
                    or norm == "/") and len(norm) > best_len:
                best, best_len = app, len(norm)
        return best

    def _app_handle(self, app: str):
        from .handle import DeploymentHandle

        ingress = ray_tpu.get(
            self._controller_handle().get_ingress.remote(app), timeout=10)
        return DeploymentHandle(ingress, app)

    # -------------------------------------------------------------- public

    def port(self) -> int:
        return self._port

    def ready(self) -> bool:
        return True

    def stop(self):
        self._server.shutdown()
        return True
