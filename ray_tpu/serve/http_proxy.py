"""HTTP ingress proxy: asyncio event loop routing requests to app handles.

Ref analog: python/ray/serve/_private/http_proxy.py:661 (HTTPProxyActor
over uvicorn/ASGI). Re-design: a stdlib ``asyncio.start_server`` HTTP/1.1
server inside a plain actor — no ASGI layer; JSON bodies map to handle
args, results map back to JSON. One event loop handles every connection
(keep-alive included); awaiting a response rides ObjectRef.__await__'s
callback future, so an in-flight request costs a coroutine, not a
thread. Explicit backpressure: at most ``max_inflight`` requests execute
concurrently, at most ``max_queued`` wait behind them, and everything
beyond that is refused with 503 + Retry-After (the reference's
proxy-level backpressure knob family: max_ongoing_requests/queue len).

Routes come from the controller's route table (route_prefix -> app),
longest prefix wins, refreshed with a small TTL.
"""

from __future__ import annotations

import asyncio
import json
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Optional, Tuple

import ray_tpu

PROXY_NAME = "SERVE_HTTP_PROXY"
_ROUTES_TTL_S = 1.0
_MAX_HEADER = 64 * 1024
_MAX_BODY = 64 * 1024 * 1024
_REQUEST_TIMEOUT_S = 60.0


class _BadRequest(Exception):
    pass


class _CloseConnection(Exception):
    """Raised after response bytes are already on the wire in a shape
    that cannot be followed by another response (e.g. an aborted chunked
    stream) — the connection must close, not 500."""


class HTTPProxy:
    """Actor hosting the asyncio HTTP server."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 max_inflight: int = 64, max_queued: int = 128):
        self._routes = {}
        self._routes_at = 0.0
        self._controller = None
        self._max_inflight = max_inflight
        self._max_queued = max_queued
        self._inflight = 0
        self._queued = 0
        # blocking runtime calls (handle submission, route refresh) run
        # here so the event loop never blocks; stream pumps get their OWN
        # pool because each occupies a thread for its stream's lifetime
        # and must not starve short-lived submissions
        self._pool = ThreadPoolExecutor(max_workers=16,
                                        thread_name_prefix="serve-io")
        self._stream_pool = ThreadPoolExecutor(
            max_workers=max(max_inflight, 1),
            thread_name_prefix="serve-stream")
        self._refresh_fut = None  # in-flight route refresh (coalesced)
        self._handles = {}        # app -> DeploymentHandle (TTL = routes)
        self._sem: Optional[asyncio.Semaphore] = None
        self._loop = asyncio.new_event_loop()
        self._started = threading.Event()
        self._server: Optional[asyncio.AbstractServer] = None
        self._port = 0

        def run():
            asyncio.set_event_loop(self._loop)
            self._loop.run_until_complete(self._start(host, port))
            self._started.set()
            self._loop.run_forever()

        self._thread = threading.Thread(target=run, daemon=True,
                                        name="serve-http")
        self._thread.start()
        if not self._started.wait(30):
            raise RuntimeError("http proxy failed to start")

    async def _start(self, host: str, port: int):
        self._sem = asyncio.Semaphore(self._max_inflight)
        self._server = await asyncio.start_server(
            self._handle_conn, host, port, limit=_MAX_HEADER)
        self._port = self._server.sockets[0].getsockname()[1]

    # -------------------------------------------------------- http plumbing

    async def _handle_conn(self, reader: asyncio.StreamReader,
                           writer: asyncio.StreamWriter):
        try:
            while True:  # HTTP/1.1 keep-alive loop
                req = await self._read_request(reader)
                if req is None:
                    break
                method, path, headers, body = req
                keep = headers.get("connection", "").lower() != "close"
                try:
                    await self._dispatch(method, path, headers, body,
                                         writer)
                except (ConnectionResetError, BrokenPipeError,
                        _CloseConnection):
                    break
                except Exception as e:  # noqa: BLE001 — surface to client
                    await self._reply(writer, 500, json.dumps(
                        {"error": repr(e)}).encode())
                if not keep:
                    break
        except _BadRequest as e:
            try:
                await self._reply(writer, 400, json.dumps(
                    {"error": str(e)}).encode())
            except Exception:  # noqa: BLE001
                pass
        except (asyncio.IncompleteReadError, asyncio.LimitOverrunError,
                ConnectionResetError):
            pass
        finally:
            try:
                writer.close()
                await writer.wait_closed()
            except Exception:  # noqa: BLE001
                pass

    async def _read_request(self, reader) -> Optional[Tuple]:
        try:
            head = await reader.readuntil(b"\r\n\r\n")
        except (asyncio.IncompleteReadError, ConnectionResetError):
            return None
        lines = head.decode("latin1").split("\r\n")
        parts = lines[0].split(" ")
        if len(parts) < 3:
            return None
        method, target = parts[0], parts[1]
        headers = {}
        for line in lines[1:]:
            if ":" in line:
                k, v = line.split(":", 1)
                headers[k.strip().lower()] = v.strip()
        try:
            n = int(headers.get("content-length") or 0)
        except ValueError:
            raise _BadRequest("invalid Content-Length") from None
        if n < 0 or n > _MAX_BODY:
            raise _BadRequest("Content-Length out of range")
        body = await reader.readexactly(n) if n else b""
        return method, target, headers, body

    async def _reply(self, writer, code: int, payload: bytes,
                     ctype: str = "application/json",
                     extra: str = ""):
        status = {200: "OK", 400: "Bad Request", 404: "Not Found",
                  500: "Internal Server Error",
                  503: "Service Unavailable"}.get(code, "OK")
        writer.write(
            f"HTTP/1.1 {code} {status}\r\n"
            f"Content-Type: {ctype}\r\n"
            f"Content-Length: {len(payload)}\r\n{extra}"
            f"\r\n".encode("latin1") + payload)
        await writer.drain()

    # ------------------------------------------------------------ dispatch

    async def _dispatch(self, method, target, headers, body, writer):
        path = target.split("?", 1)[0]
        if path == "/-/healthz":
            await self._reply(writer, 200, b'"ok"')
            return
        if path == "/-/routes":
            table = await self._route_table_async()
            await self._reply(writer, 200, json.dumps(table).encode())
            return
        app = self._match(await self._route_table_async(), path)
        if app is None:
            await self._reply(writer, 404, json.dumps(
                {"error": f"no app mounted at {path}"}).encode())
            return
        # ---- backpressure gate (FIFO: asyncio.Semaphore wakes waiters
        # in acquisition order). EVERY acquirer counts as queued while it
        # waits — gating on an inflight counter instead would let
        # requests arriving in the release->wakeup window wait uncounted,
        # bypassing the max_queued cap.
        if self._sem.locked() and self._queued >= self._max_queued:
            await self._reply(writer, 503, json.dumps(
                {"error": "proxy saturated"}).encode(),
                extra="Retry-After: 1\r\n")
            return
        self._queued += 1
        try:
            await self._sem.acquire()
        finally:
            self._queued -= 1
        self._inflight += 1
        try:
            arg = None
            if body:
                if headers.get("content-type", "").startswith(
                        "application/octet-stream"):
                    # raw-bytes passthrough (r14): binary payloads must
                    # not be lossily utf-8-decoded, and a large body
                    # handed to the handle as bytes rides the zero-copy
                    # by-ref ingress path end-to-end
                    arg = body
                else:
                    try:
                        arg = json.loads(body)
                    except json.JSONDecodeError:
                        arg = body.decode("utf-8", "replace")
            loop = asyncio.get_running_loop()
            handle = await loop.run_in_executor(
                self._pool, self._app_handle, app)
            if headers.get("x-serve-stream") == "1":
                await self._stream(handle, arg, writer)
                return
            # submission may block on routing metadata -> executor;
            # awaiting the response rides the ref's callback future. The
            # timeout frees the inflight slot if a replica hangs — a dead
            # replica must not eat the proxy's whole concurrency budget
            resp = await loop.run_in_executor(
                self._pool, lambda: handle.remote(arg))
            result = await asyncio.wait_for(resp, _REQUEST_TIMEOUT_S)
            if isinstance(result, bytes):
                await self._reply(writer, 200, result,
                                  "application/octet-stream")
            else:
                await self._reply(writer, 200, json.dumps(result).encode())
        finally:
            self._inflight -= 1
            self._sem.release()

    async def _stream(self, handle, arg, writer):
        """Chunked ndjson streaming (ref: StreamingResponse over a
        generator deployment, replica.py:339). The sync generator is
        consumed on an executor thread feeding an asyncio queue; client
        disconnects propagate back and release the replica slot."""
        loop = asyncio.get_running_loop()
        q: asyncio.Queue = asyncio.Queue(maxsize=32)
        done = object()
        stop = threading.Event()

        gen = await loop.run_in_executor(
            self._pool, lambda: handle.options(stream=True).remote(arg))
        headers_sent = False

        def put_item(item) -> bool:
            """Enqueue from the pump thread; abandons quickly once the
            consumer stopped (a slow/gone client must not pin this pool
            thread for a long blocking put)."""
            while not stop.is_set():
                fut = asyncio.run_coroutine_threadsafe(q.put(item), loop)
                try:
                    fut.result(timeout=1.0)
                    return True
                except TimeoutError:
                    # cancel() returning False means the put WON the race
                    # with the timeout and (is) completing — retrying
                    # then would enqueue the item twice, corrupting the
                    # stream; wait out its final state instead
                    if not fut.cancel():
                        try:
                            fut.result(timeout=5.0)
                            return True
                        except Exception:  # noqa: BLE001
                            return False
                except Exception:  # noqa: BLE001 — loop closing
                    return False
            return False

        def pump():
            try:
                for item in gen:
                    if not put_item(item):
                        return
                put_item(done)
            except Exception as e:  # noqa: BLE001
                put_item(e)
            finally:
                gen.close()  # releases the replica slot

        self._stream_pool.submit(pump)
        try:
            while True:
                # bounded inter-item gap: a hung replica generator must
                # not hold this inflight slot forever (mirrors the
                # non-stream path's request timeout)
                item = await asyncio.wait_for(q.get(),
                                              _REQUEST_TIMEOUT_S)
                if item is done:
                    break
                if isinstance(item, Exception):
                    raise item
                if not headers_sent:
                    writer.write(b"HTTP/1.1 200 OK\r\n"
                                 b"Content-Type: application/x-ndjson\r\n"
                                 b"Transfer-Encoding: chunked\r\n\r\n")
                    headers_sent = True
                chunk = (json.dumps(item) + "\n").encode()
                writer.write(f"{len(chunk):x}\r\n".encode()
                             + chunk + b"\r\n")
                await writer.drain()  # slow-client backpressure
            if not headers_sent:
                writer.write(b"HTTP/1.1 200 OK\r\n"
                             b"Content-Type: application/x-ndjson\r\n"
                             b"Transfer-Encoding: chunked\r\n\r\n")
            writer.write(b"0\r\n\r\n")
            await writer.drain()
        except (ConnectionResetError, BrokenPipeError):
            raise
        except Exception as e:  # noqa: BLE001
            if not headers_sent:
                stop.set()
                raise  # no bytes on the wire yet: a clean 500 is fine
            # mid-stream failure: a second status line would desync the
            # chunked encoding — emit an error record, terminate the
            # encoding, and close the connection
            try:
                chunk = (json.dumps({"error": repr(e)}) + "\n").encode()
                writer.write(f"{len(chunk):x}\r\n".encode()
                             + chunk + b"\r\n" + b"0\r\n\r\n")
                await writer.drain()
            except Exception:  # noqa: BLE001
                pass
            raise _CloseConnection() from e
        finally:
            stop.set()

    # ------------------------------------------------------------- helpers

    def _controller_handle(self):
        if self._controller is None:
            from .controller import CONTROLLER_NAME

            self._controller = ray_tpu.get_actor(CONTROLLER_NAME)
        return self._controller

    def _refresh_routes(self) -> dict:
        try:
            self._routes = ray_tpu.get(
                self._controller_handle().get_routes.remote(), timeout=10)
            self._routes_at = time.monotonic()
            # ingress handles share the routes' freshness window; a
            # redeploy that changes an app's ingress is picked up on the
            # next refresh
            self._handles = {}
        except Exception:  # noqa: BLE001 — keep serving the stale table
            pass
        return self._routes

    async def _route_table_async(self) -> dict:
        if time.monotonic() - self._routes_at > _ROUTES_TTL_S:
            # coalesce: at most ONE controller RPC in flight no matter
            # how many requests cross the TTL boundary together
            if self._refresh_fut is None:
                loop = asyncio.get_running_loop()
                self._refresh_fut = loop.run_in_executor(
                    self._pool, self._refresh_routes)
                try:
                    return await self._refresh_fut
                finally:
                    self._refresh_fut = None
            return await asyncio.shield(self._refresh_fut)
        return self._routes

    @staticmethod
    def _match(table: dict, path: str) -> Optional[str]:
        best, best_len = None, -1
        for prefix, app in table.items():
            norm = prefix.rstrip("/") or "/"
            if (path == norm or path.startswith(norm.rstrip("/") + "/")
                    or norm == "/") and len(norm) > best_len:
                best, best_len = app, len(norm)
        return best

    def _app_handle(self, app: str):
        from .handle import DeploymentHandle

        handle = self._handles.get(app)
        if handle is None:
            # one controller RPC per app per routes-refresh window — NOT
            # per request (the per-request RPC dominated proxy latency)
            ingress = ray_tpu.get(
                self._controller_handle().get_ingress.remote(app),
                timeout=10)
            handle = DeploymentHandle(ingress, app)
            self._handles[app] = handle
        return handle

    # -------------------------------------------------------------- public

    def port(self) -> int:
        return self._port

    def ready(self) -> bool:
        return True

    def stats(self) -> dict:
        return {"inflight": self._inflight, "queued": self._queued}

    def stop(self):
        def _shutdown():
            if self._server is not None:
                self._server.close()
            self._loop.stop()

        self._loop.call_soon_threadsafe(_shutdown)
        self._pool.shutdown(wait=False)
        self._stream_pool.shutdown(wait=False)
        return True
