"""Declarative Serve config: deploy applications from a YAML/dict spec.

Ref parity: ray.serve schema + REST config (python/ray/serve/schema.py:559
ServeDeploySchema / ServeApplicationSchema; `serve deploy config.yaml`).
Shape (a subset of the reference's, same field names)::

    applications:
      - name: app1
        import_path: my_module:app      # a Deployment or bound graph
        route_prefix: /app1
        args: {...}                     # optional, passed to a builder fn
        deployments:                    # per-deployment overrides
          - name: Model
            num_replicas: 2
            max_concurrent_queries: 8
            user_config: {...}
            autoscaling_config: {...}

``deploy_config`` imports each target, applies overrides via
Deployment.options, and serve.run()s it.
"""

from __future__ import annotations

import importlib
from typing import Any, Dict, List, Optional

_DEPLOYMENT_OVERRIDES = ("num_replicas", "max_concurrent_queries",
                         "user_config", "autoscaling_config",
                         "ray_actor_options", "health_check_period_s",
                         "health_check_timeout_s")


def load_config_file(path: str) -> Dict[str, Any]:
    with open(path) as f:
        text = f.read()
    try:
        import yaml

        return yaml.safe_load(text)
    except ImportError:
        import json

        return json.loads(text)


def _import_target(import_path: str):
    """'pkg.module:attr' -> the attribute (ref: import_attr)."""
    if ":" not in import_path:
        raise ValueError(
            f"import_path {import_path!r} must look like 'module:attr'")
    module_name, attr = import_path.split(":", 1)
    module = importlib.import_module(module_name)
    target = module
    for part in attr.split("."):
        target = getattr(target, part)
    return target


def _apply_overrides(app_target, overrides: List[Dict[str, Any]]):
    """Rebuild the deployment (or bound-graph root) with per-deployment
    option overrides from the config."""
    from .deployment import Application, Deployment

    by_name = {o["name"]: o for o in overrides or []}

    def rebuild(node):
        if isinstance(node, Application):
            d = node.deployment
            o = by_name.get(d.name)
            new_args = tuple(rebuild(a) if isinstance(a, Application) else a
                             for a in node.init_args)
            new_kwargs = {k: (rebuild(v) if isinstance(v, Application)
                              else v)
                          for k, v in node.init_kwargs.items()}
            if o:
                opts = {k: v for k, v in o.items()
                        if k in _DEPLOYMENT_OVERRIDES}
                d = d.options(**opts)
            return d.bind(*new_args, **new_kwargs)
        if isinstance(node, Deployment):
            o = by_name.get(node.name)
            if o:
                opts = {k: v for k, v in o.items()
                        if k in _DEPLOYMENT_OVERRIDES}
                node = node.options(**opts)
            return node
        return node

    return rebuild(app_target)


def deploy_config(config: Dict[str, Any] | str) -> List[str]:
    """Deploy every application in the config; returns their names.
    (ref: `serve deploy` against the REST schema)."""
    from . import api as serve_api

    if isinstance(config, str):
        config = load_config_file(config)
    apps = config.get("applications")
    if not apps:
        raise ValueError("config has no 'applications' list")
    deployed = []
    for app in apps:
        name = app.get("name") or "default"
        target = _import_target(app["import_path"])
        if callable(target) and not hasattr(target, "bind") and \
                not hasattr(target, "deployment"):
            # builder function taking the config args dict
            target = target(app.get("args") or {})
        target = _apply_overrides(target, app.get("deployments"))
        serve_api.run(target, name=name,
                      route_prefix=app.get("route_prefix", f"/{name}"))
        deployed.append(name)
    return deployed


def status_schema() -> Dict[str, Any]:
    """Cluster serve status in the REST schema's shape
    (ref: serve/schema.py ServeStatusSchema)."""
    from . import api as serve_api

    return {"applications": serve_api.status()}
