"""LLM serving: a batched autoregressive-generation deployment.

Ref analog: the reference's Serve LLM path (python/ray/serve + the
"Ray Serve: Llama-3 inference deployment (batched)" BASELINE.json
config, served there via vLLM-on-GPU workers). TPU-first re-design:
replicas hold jitted prefill/decode programs from
``ray_tpu.models.generate`` — the KV cache is preallocated at a static
``max_len`` so every batch shape compiles once — and ``@serve.batch``
coalesces concurrent single-prompt requests into one [B, P] generate
call that keeps the MXU busy. Prompts are right-aligned into a fixed
bucket (static shapes; XLA never recompiles per request).
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np

from ray_tpu.serve.deployment import deployment


class _LLMReplica:
    """Replica body: owns params + jitted generate for one model config.

    ``model`` is a config name from ``ray_tpu.models.config.get_config``
    (e.g. "gpt2-small", "llama3-1b") or a TransformerConfig; weights are
    randomly initialized unless ``checkpoint_dir`` (an orbax/pickle tree
    saved by train) is given — serving infrastructure is what's under
    test here, not weights.
    """

    def __init__(self, model="tiny", *, max_batch_size: int = 8,
                 max_prompt_len: int = 64, max_new_tokens: int = 32,
                 batch_wait_timeout_s: float = 0.02,
                 checkpoint_dir: Optional[str] = None,
                 greedy: bool = True, temperature: float = 1.0,
                 pad_id: int = 0, eos_id: int = -1, seed: int = 0):
        import jax

        from ray_tpu.models.config import TransformerConfig, get_config
        from ray_tpu.models.transformer import init_params

        cfg = (model if isinstance(model, TransformerConfig)
               else get_config(model))
        self.cfg = cfg
        self.max_new_tokens = int(max_new_tokens)
        self.max_prompt_len = int(max_prompt_len)
        self.greedy = greedy
        self.temperature = float(temperature)
        self.pad_id = int(pad_id)
        # -1 (never sampled for non-negative vocabularies) disables the
        # eos freeze; when set, generate() stops extending finished rows
        # and stream() ends at the model's natural stop
        self.eos_id = int(eos_id)
        import threading

        # stream() runs on caller threads while _generate runs on the
        # batcher's drainer thread: key handout must be atomic or two
        # concurrent sampling requests split the same key
        self._rng_lock = threading.Lock()
        self._rng = jax.random.key(seed)
        if checkpoint_dir is not None:
            import pickle

            with open(checkpoint_dir, "rb") as f:
                self.params = jax.tree.map(np.asarray, pickle.load(f))
        else:
            self.params = init_params(jax.random.key(seed), cfg)
        self._max_bs = int(max_batch_size)
        # the batcher cap and the compiled batch shape MUST be the same
        # number, so the batcher is built per-instance from the
        # constructor arg (a class-level @serve.batch would freeze its
        # own cap). Held on self — not the module-global registry — so
        # replica teardown releases the params it closes over.
        from ray_tpu.serve.batching import _Batcher

        self._batcher = _Batcher(self._max_bs, batch_wait_timeout_s)

    def _pad_batch(self, prompts: Sequence[Sequence[int]]):
        """Left-pad to the bucket so the last prompt token sits at the
        cache's write position for every row; returns (tokens [B,P],
        start [B]) where start marks each row's first real token (pad
        positions are masked out of attention by generate)."""
        P = self.max_prompt_len
        out = np.full((len(prompts), P), self.pad_id, np.int32)
        start = np.zeros(len(prompts), np.int32)
        for i, p in enumerate(prompts):
            p = list(p)  # oversized prompts were rejected in __call__
            out[i, P - len(p):] = p
            start[i] = P - len(p)
        return out, start

    def _next_rng(self):
        import jax

        with self._rng_lock:
            self._rng, sub = jax.random.split(self._rng)
        return sub

    def _generate(self, prompts: List[Sequence[int]]) -> List[dict]:
        from ray_tpu.models.generate import generate

        toks, start = self._pad_batch(prompts)
        # pad the BATCH to the compiled size too: one XLA program total
        B = toks.shape[0]
        if B < self._max_bs:
            toks_full = np.resize(toks, (self._max_bs, toks.shape[1]))
            start_full = np.resize(start, (self._max_bs,))
        else:
            toks_full, start_full = toks, start
        out = generate(self.params, toks_full, self.cfg,
                       max_new_tokens=self.max_new_tokens,
                       greedy=self.greedy, temperature=self.temperature,
                       eos_id=self.eos_id, rng=self._next_rng(),
                       start=start_full)
        out = np.asarray(out)[:B, toks.shape[1]:]
        # trim each row at its first eos so the batched contract matches
        # stream(): output ends AT the natural stop, no eos-padded tail
        results = []
        for row in out:
            ids = row.tolist()
            if self.eos_id in ids:
                ids = ids[:ids.index(self.eos_id) + 1]
            results.append({"token_ids": ids})
        return results

    def stream(self, prompt: Sequence[int]):
        """Token-by-token generation: a generator the router streams back
        chunk-wise (``handle.options(method_name='stream', stream=True)``
        or chunked HTTP). Per-request B=1 decode via the stepwise
        prefill/decode_step API — streaming trades the batched program
        for first-token latency, the same trade the reference's streaming
        LLM responses make (serve/_private/replica.py generator path)."""
        import jax
        import jax.numpy as jnp

        from ray_tpu.models.generate import decode_step, prefill

        if len(prompt) > self.max_prompt_len:
            raise ValueError(
                f"prompt length {len(prompt)} exceeds this deployment's "
                f"max_prompt_len={self.max_prompt_len}")
        # left-pad into the same fixed bucket as the batched path: ONE
        # compiled (prefill, decode) shape per deployment, not one per
        # distinct prompt length
        P = self.max_prompt_len
        toks = np.full((1, P), self.pad_id, np.int32)
        toks[0, P - len(prompt):] = list(prompt)
        start = jnp.asarray([P - len(prompt)], jnp.int32)
        toks = jnp.asarray(toks)
        max_len = P + self.max_new_tokens
        logits, cache = prefill(self.params, toks, self.cfg, max_len,
                                start)
        last = logits[:, -1]
        for i in range(self.max_new_tokens):
            if self.greedy:
                tok = jnp.argmax(last, axis=-1).astype(jnp.int32)
            else:
                tok = jax.random.categorical(
                    self._next_rng(), last / max(self.temperature, 1e-6)
                ).astype(jnp.int32)
            yield {"token_id": int(tok[0])}
            if int(tok[0]) == self.eos_id:  # natural stop
                return
            if i + 1 < self.max_new_tokens:  # last step has no consumer
                last, cache = decode_step(self.params, cache, tok,
                                          self.cfg, start)

    def __call__(self, prompt: Sequence[int]) -> dict:
        if len(prompt) > self.max_prompt_len:
            # refuse rather than silently conditioning on a clipped
            # prompt; the per-request check keeps one oversized prompt
            # from failing a whole coalesced batch
            raise ValueError(
                f"prompt length {len(prompt)} exceeds this deployment's "
                f"max_prompt_len={self.max_prompt_len}")
        return self._batcher.submit(self._generate, prompt)


class _ContinuousLLMReplica:
    """Continuous-batching replica: slot-level admission/eviction.

    Ref analog: the reference's request-cohort `@serve.batch`
    (python/ray/serve/batching.py:337) holds a batch until every member
    finishes decoding; this replica instead owns an
    `ray_tpu.models.engine.InferenceEngine` whose decode loop refills a
    finished sequence's slot on the very next step — one long generation
    no longer stalls its batchmates (the vLLM-style redesign, TPU-first:
    static slot shapes, one compiled decode program, on-device sampling).

    ``tensor_parallel`` > 1 shards the model over that many local devices
    (a `num_tpus=N`-class replica): params/cache carry tensor-axis
    shardings and the SAME engine program runs TP via GSPMD propagation.
    """

    def __init__(self, model="tiny", *, slots: int = 8,
                 max_prompt_len: int = 64, max_new_tokens: int = 32,
                 checkpoint_dir: Optional[str] = None,
                 greedy: bool = True, temperature: float = 1.0,
                 pad_id: int = 0, eos_id: int = -1, seed: int = 0,
                 tensor_parallel: int = 1, decode_chunk: int = 4,
                 fetch_every: int = 1):
        import jax

        from ray_tpu.models.config import TransformerConfig, get_config
        from ray_tpu.models.engine import InferenceEngine
        from ray_tpu.models.transformer import init_params

        cfg = (model if isinstance(model, TransformerConfig)
               else get_config(model))
        if checkpoint_dir is not None:
            import pickle

            with open(checkpoint_dir, "rb") as f:
                params = jax.tree.map(np.asarray, pickle.load(f))
        else:
            params = init_params(jax.random.key(seed), cfg)
        mesh = None
        if tensor_parallel > 1:
            from ray_tpu.parallel import MeshSpec

            devices = jax.devices()
            if len(devices) < tensor_parallel:
                raise ValueError(
                    f"tensor_parallel={tensor_parallel} but only "
                    f"{len(devices)} local devices")
            mesh = MeshSpec(data=1, fsdp=1, tensor=tensor_parallel) \
                .build(devices[:tensor_parallel])
        self.engine = InferenceEngine(
            params, cfg, slots=slots, max_prompt_len=max_prompt_len,
            max_new_tokens=max_new_tokens, greedy=greedy,
            temperature=temperature, eos_id=eos_id, pad_id=pad_id,
            mesh=mesh, seed=seed, decode_chunk=decode_chunk,
            fetch_every=fetch_every).serve_forever()

    def __call__(self, prompt: Sequence[int],
                 max_new_tokens: Optional[int] = None) -> dict:
        toks = self.engine.generate(prompt, max_new_tokens)
        return {"token_ids": toks}

    def stream(self, prompt: Sequence[int],
               max_new_tokens: Optional[int] = None):
        for tok in self.engine.submit_stream(prompt, max_new_tokens):
            yield {"token_id": tok}

    def engine_stats(self) -> dict:
        return dict(self.engine.stats)

    def __del__(self):
        eng = getattr(self, "engine", None)
        if eng is not None:
            eng.shutdown()


def build_continuous_llm_deployment(model="tiny", *, name: str = "llm",
                                    num_replicas: int = 1,
                                    max_concurrency: int = 32,
                                    **replica_kwargs):
    """-> an Application whose replicas continuously batch generations.

    ``max_concurrency`` lifts the replica's query cap (and with it the
    actor's thread cap) so many callers can block in ``__call__`` while
    the engine thread interleaves them — admission happens per decode
    step, not per cohort.
    """
    dep = deployment(_ContinuousLLMReplica, name=name) \
        .options(num_replicas=num_replicas,
                 max_concurrent_queries=max_concurrency)
    return dep.bind(model, **replica_kwargs)


def build_llm_deployment(model="tiny", *, name: str = "llm",
                         num_replicas: int = 1, **replica_kwargs):
    """-> an Application serving ``{prompt token ids} -> {token_ids}``.

    Usage::

        app = build_llm_deployment("gpt2-small", max_new_tokens=16)
        handle = serve.run(app, name="llm")
        out = handle.remote([1, 2, 3]).result()
    """
    dep = deployment(_LLMReplica, name=name) \
        .options(num_replicas=num_replicas)
    return dep.bind(model, **replica_kwargs)
