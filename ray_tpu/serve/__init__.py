"""ray_tpu.serve — model serving on the ray_tpu runtime.

TPU-first re-design of the reference's Serve library (SURVEY.md §2.4;
python/ray/serve/): a controller actor reconciles declarative app state
into replica actors; handles route requests client-side with
power-of-two-choices + max_concurrent_queries backpressure;
``@serve.batch`` coalesces concurrent requests into one XLA forward pass;
an HTTP proxy actor provides ingress. Deployments may request TPU chips
via ``ray_actor_options={"num_tpus": N}``.

Public API mirrors ``ray.serve``:

    @serve.deployment
    class Model:
        def __call__(self, x): ...

    handle = serve.run(Model.bind(), name="app")
    handle.remote(x).result()
"""

from .api import (
    delete,
    get_app_handle,
    get_deployment_handle,
    proxy_ports,
    run,
    shutdown,
    start,
    status,
)
from .batching import batch
from .config import AutoscalingConfig, DeploymentConfig, HTTPOptions
from .deployment import Application, Deployment, deployment
from .grpc_proxy import start_grpc, stop_grpc
from .handle import (DeploymentHandle, DeploymentResponse,
                     DeploymentResponseGenerator)
from .multiplex import get_multiplexed_model_id, multiplexed
from .schema import deploy_config

__all__ = [
    "deployment", "Deployment", "Application", "run", "delete", "status",
    "shutdown", "start", "start_grpc", "stop_grpc",
    "proxy_ports", "batch", "get_app_handle", "get_deployment_handle",
    "DeploymentHandle", "DeploymentResponse", "DeploymentResponseGenerator",
    "multiplexed", "get_multiplexed_model_id", "deploy_config",
    "AutoscalingConfig",
    "DeploymentConfig", "HTTPOptions",
]

from ray_tpu.usage_stats import record_library_usage as _rlu
_rlu("serve")
del _rlu
