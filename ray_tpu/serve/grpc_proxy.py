"""gRPC ingress proxy for Serve applications.

Ref analog: the reference's experimental gRPC ingress —
python/ray/serve/drivers.py (gRPCIngress) and
python/ray/serve/_private/grpc_util.py (RayServeAPIService wiring) —
re-designed without protoc codegen: the service is registered with
``grpc.method_handlers_generic_handler`` using identity (bytes)
serializers, so any gRPC client can call it by full method name with
JSON payloads.  Service surface:

  /ray.serve.ServeAPIService/Healthz           unary-unary
  /ray.serve.ServeAPIService/ListApplications  unary-unary
  /ray.serve.ServeAPIService/Predict           unary-unary
  /ray.serve.ServeAPIService/Streaming         unary-stream

Routing follows the reference's metadata convention: the target app is
the ``application`` entry in the call's invocation metadata, falling
back to the single deployed app when only one exists.  Request bytes
are JSON-decoded into the handle argument; responses are JSON bytes
(or raw bytes passthrough when the deployment returns ``bytes``).

Backpressure: ``maximum_concurrent_rpcs`` on the grpc server rejects
excess calls with RESOURCE_EXHAUSTED — the proxy-level saturation
semantics the HTTP proxy expresses with 503 + Retry-After.
"""

from __future__ import annotations

import json
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Optional

import ray_tpu

GRPC_PROXY_NAME = "SERVE_GRPC_PROXY"
SERVICE_NAME = "ray.serve.ServeAPIService"
_ROUTES_TTL_S = 1.0
_REQUEST_TIMEOUT_S = 60.0


def _ident(b: bytes) -> bytes:
    return b


class GrpcProxy:
    """Actor hosting the gRPC server (one per cluster by default)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 max_concurrent_rpcs: Optional[int] = None,
                 workers: int = 16):
        import grpc

        self._controller = None
        self._apps: dict = {}
        self._apps_at = 0.0
        self._handles: dict = {}
        self._refresh_lock = threading.Lock()
        self._loaded = False  # one cold-start route fetch has completed
        # rejection must be prompt: each handler can block its executor
        # thread up to the request timeout, so the RPC cap is tied to
        # the thread count (workers running + workers queued) — not an
        # arbitrary large constant that would let calls 17..N sit in the
        # executor queue until DEADLINE_EXCEEDED instead of failing fast
        # with RESOURCE_EXHAUSTED
        if max_concurrent_rpcs is None:
            max_concurrent_rpcs = workers * 2
        self._server = grpc.server(
            ThreadPoolExecutor(max_workers=workers,
                               thread_name_prefix="serve-grpc"),
            maximum_concurrent_rpcs=max_concurrent_rpcs)
        handlers = {
            "Healthz": grpc.unary_unary_rpc_method_handler(
                self._healthz, _ident, _ident),
            "ListApplications": grpc.unary_unary_rpc_method_handler(
                self._list_apps, _ident, _ident),
            "Predict": grpc.unary_unary_rpc_method_handler(
                self._predict, _ident, _ident),
            "Streaming": grpc.unary_stream_rpc_method_handler(
                self._streaming, _ident, _ident),
        }
        self._server.add_generic_rpc_handlers(
            (grpc.method_handlers_generic_handler(SERVICE_NAME, handlers),))
        self._port = self._server.add_insecure_port(f"{host}:{port}")
        self._server.start()

    # ------------------------------------------------------------- handlers

    def _healthz(self, request: bytes, context) -> bytes:
        return b'{"status": "ok"}'

    def _list_apps(self, request: bytes, context) -> bytes:
        return json.dumps(sorted(self._app_table())).encode()

    def _predict(self, request: bytes, context) -> bytes:
        handle, arg = self._resolve(request, context)
        resp = handle.remote(arg)
        result = resp.result(timeout_s=_REQUEST_TIMEOUT_S)
        if isinstance(result, bytes):
            return result
        return json.dumps(result).encode()

    def _streaming(self, request: bytes, context):
        handle, arg = self._resolve(request, context)
        for item in handle.options(stream=True).remote(arg):
            yield (item if isinstance(item, bytes)
                   else json.dumps(item).encode())

    # -------------------------------------------------------------- routing

    def _resolve(self, request: bytes, context):
        import grpc

        md = dict(context.invocation_metadata() or ())
        apps = self._app_table()
        app = md.get("application")
        if app is None and len(apps) == 1:
            app = next(iter(apps))
        if app is None or app not in apps:
            context.abort(
                grpc.StatusCode.NOT_FOUND,
                f"application {app!r} not found; deployed: {sorted(apps)}")
        arg = None
        if request:
            try:
                arg = json.loads(request)
            except json.JSONDecodeError:
                arg = request  # raw-bytes passthrough
        return self._app_handle(app), arg

    def _controller_handle(self):
        if self._controller is None:
            from .controller import CONTROLLER_NAME

            self._controller = ray_tpu.get_actor(CONTROLLER_NAME)
        return self._controller

    def _app_table(self) -> dict:
        """app name -> route prefix, with the same TTL/staleness policy
        as the HTTP proxy's route table. Refreshes are coalesced: one
        controller RPC per expiry no matter how many handler threads
        cross the TTL together (the HTTP proxy learned this the hard
        way — the per-request controller RPC dominated proxy latency)."""
        if time.monotonic() - self._apps_at > _ROUTES_TTL_S:
            # cold start (no completed load attempt) must BLOCK on the
            # lock: serving the initial empty table would turn a racing
            # first request into a spurious NOT_FOUND, which gRPC
            # clients don't retry. Afterwards, losers of the acquire
            # race serve the (possibly stale) table instead of stacking
            # up behind the RPC.
            if self._refresh_lock.acquire(blocking=not self._loaded):
                try:
                    if time.monotonic() - self._apps_at > _ROUTES_TTL_S:
                        routes = ray_tpu.get(
                            self._controller_handle().get_routes.remote(),
                            timeout=10)
                        old = self._apps
                        new = {app: prefix
                               for prefix, app in routes.items()}
                        # Invalidate ONLY handles whose app's route
                        # actually changed (redeploy/removal) — dropping
                        # the whole cache every 1s refresh made the next
                        # Predict per app pay a blocking get_ingress
                        # controller RPC every second under steady
                        # traffic (ADVICE.md finding).
                        self._handles = {
                            a: h for a, h in self._handles.items()
                            if a in new and new[a] == old.get(a)}
                        self._apps = new
                        self._apps_at = time.monotonic()
                except Exception:  # noqa: BLE001 — keep serving stale
                    pass
                finally:
                    # loaded marks "a cold-start attempt COMPLETED", not
                    # "it succeeded": if the controller is unreachable,
                    # later requests must fail fast on the empty table
                    # rather than serially repeating a 10s blocking RPC
                    # from every executor thread
                    self._loaded = True
                    self._refresh_lock.release()
        return self._apps

    def _app_handle(self, app: str):
        from .handle import DeploymentHandle

        handle = self._handles.get(app)
        if handle is None:
            ingress = ray_tpu.get(
                self._controller_handle().get_ingress.remote(app),
                timeout=10)
            handle = DeploymentHandle(ingress, app)
            self._handles[app] = handle
        return handle

    # -------------------------------------------------------------- public

    def port(self) -> int:
        return self._port

    def ready(self) -> bool:
        return True

    def stop(self):
        self._server.stop(grace=1.0)
        return True


def start_grpc(host: str = "127.0.0.1", port: int = 0) -> int:
    """Start the gRPC ingress (idempotent); returns the bound port.

    Like ``serve.start()``, host/port apply only on first start: if the
    proxy actor already exists its existing binding is returned (call
    ``stop_grpc()`` first to rebind)."""
    from .api import get_or_create_controller

    get_or_create_controller()
    try:
        proxy = ray_tpu.get_actor(GRPC_PROXY_NAME)
    except ValueError:
        proxy = ray_tpu.remote(GrpcProxy).options(
            name=GRPC_PROXY_NAME, num_cpus=0, max_concurrency=32).remote(
                host, port)
    return ray_tpu.get(proxy.port.remote(), timeout=30)


def stop_grpc():
    try:
        proxy = ray_tpu.get_actor(GRPC_PROXY_NAME)
    except ValueError:
        return
    try:
        ray_tpu.get(proxy.stop.remote(), timeout=10)
    except Exception:  # noqa: BLE001
        pass
    ray_tpu.kill(proxy)
