"""Replica actor: hosts one copy of a deployment's callable.

Ref analog: python/ray/serve/_private/replica.py:237 (handle_request) —
re-designed: the replica is a plain ``max_concurrency``-threaded actor
(queries run concurrently on its thread pool; ``@serve.batch`` coalesces
across those threads), and the XLA path is first-class: a deployment whose
``ray_actor_options`` request TPUs constructs its model inside the replica
process with the chip(s) already assigned.
"""

from __future__ import annotations

import threading
import time
from typing import Any

from .config import ReplicaMetrics


class HandleMarker:
    """Placeholder for a DeploymentHandle inside pickled init args."""

    def __init__(self, deployment_name: str, app_name: str):
        self.deployment_name = deployment_name
        self.app_name = app_name


def _resolve_markers(obj):
    from .handle import DeploymentHandle

    if isinstance(obj, HandleMarker):
        return DeploymentHandle(obj.deployment_name, obj.app_name)
    if isinstance(obj, (list, tuple)):
        return type(obj)(_resolve_markers(x) for x in obj)
    if isinstance(obj, dict):
        return {k: _resolve_markers(v) for k, v in obj.items()}
    return obj


class ServeReplica:
    """The actor class every replica runs (one per replica)."""

    def __init__(self, payload: bytes, replica_id: str):
        from ray_tpu.core.serialization import loads

        spec = loads(payload)
        self._replica_id = replica_id
        self._is_function = spec["is_function"]
        self._lock = threading.Lock()
        self._ongoing = 0
        self._completed = 0
        self._healthy = True
        self._draining = False
        self._streams = {}      # stream id -> (iterator, meta)
        init_args = _resolve_markers(spec["init_args"])
        init_kwargs = _resolve_markers(spec["init_kwargs"])
        if self._is_function:
            self._callable = spec["func_or_class"]
        else:
            self._callable = spec["func_or_class"](*init_args, **init_kwargs)
        user_config = spec.get("user_config")
        if user_config is not None:
            self.reconfigure(user_config)

    # ------------------------------------------------------------- serving

    def _resolve_fn(self, method_name: str):
        if self._is_function:
            return self._callable
        if method_name == "__call__":
            fn = self._callable
            if not callable(fn):
                raise TypeError(
                    f"deployment class {type(self._callable).__name__} "
                    "has no __call__; call a named method instead")
            return fn
        return getattr(self._callable, method_name)

    def handle_request(self, method_name: str, args: tuple, kwargs: dict,
                       meta: dict = None):
        from .multiplex import _set_request_model_id

        with self._lock:
            self._ongoing += 1
        _set_request_model_id((meta or {}).get("multiplexed_model_id", ""))
        try:
            return self._resolve_fn(method_name)(*args, **kwargs)
        finally:
            _set_request_model_id("")
            with self._lock:
                self._ongoing -= 1
                self._completed += 1

    # ------------------------------------------------------- streaming

    def start_stream(self, method_name: str, args: tuple, kwargs: dict,
                     meta: dict = None) -> str:
        """Begin a streaming response: run the (generator) callable, park
        its iterator, return a stream id the client drains with
        stream_next (ref: replica.py:339 streaming generator support).
        The stream counts as one ongoing request until it ends."""
        from ray_tpu.core.ids import _random_bytes

        from .multiplex import _set_request_model_id

        _set_request_model_id((meta or {}).get("multiplexed_model_id", ""))
        try:
            result = self._resolve_fn(method_name)(*args, **kwargs)
        finally:
            _set_request_model_id("")
        it = iter(result)
        sid = _random_bytes(8).hex()  # pooled entropy: per-request path
        with self._lock:
            self._ongoing += 1
            self._streams[sid] = (it, meta or {})
        return sid

    def cancel_stream(self, sid: str):
        """Abandoned stream (client gone): drop the parked iterator and
        free its request slot."""
        with self._lock:
            entry = self._streams.pop(sid, None)
            if entry is not None:
                self._ongoing -= 1
                self._completed += 1
        if entry is not None and hasattr(entry[0], "close"):
            try:
                entry[0].close()
            except Exception:  # noqa: BLE001 — generator cleanup
                pass

    def stream_next(self, sid: str, max_items: int = 1):
        """-> (items, done). Pulls up to max_items from the stream.

        Default 1: each chunk ships as soon as the generator produces
        it — a larger batch would delay time-to-first-token by the whole
        batch and time out slow producers. Callers wanting fewer RPCs on
        fast streams can raise max_items."""
        from .multiplex import _set_request_model_id

        with self._lock:
            entry = self._streams.get(sid)
        if entry is None:
            raise KeyError(f"no such stream {sid}")
        it, meta = entry
        items = []
        done = False
        # generator frames execute during next() — the request context
        # must be live HERE, not just in start_stream
        _set_request_model_id(meta.get("multiplexed_model_id", ""))
        try:
            for _ in range(max_items):
                items.append(next(it))
        except StopIteration:
            done = True
        finally:
            _set_request_model_id("")
        if done:
            with self._lock:
                # guard against a concurrent cancel_stream having already
                # released the slot
                if self._streams.pop(sid, None) is not None:
                    self._ongoing -= 1
                    self._completed += 1
        return items, done

    # ---------------------------------------------------------- management

    def reconfigure(self, user_config: Any):
        if not self._is_function and hasattr(self._callable, "reconfigure"):
            self._callable.reconfigure(user_config)

    def ping(self) -> bool:
        if not self._is_function and hasattr(self._callable, "check_health"):
            self._callable.check_health()
        return True

    def metrics(self) -> ReplicaMetrics:
        with self._lock:
            return ReplicaMetrics(
                replica_id=self._replica_id,
                num_ongoing_requests=self._ongoing,
                num_completed_requests=self._completed,
                healthy=self._healthy)

    def prepare_shutdown(self, timeout_s: float = 5.0) -> bool:
        """Graceful drain: wait for ongoing requests to finish."""
        self._draining = True
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            with self._lock:
                if self._ongoing == 0:
                    return True
            time.sleep(0.02)
        return False
