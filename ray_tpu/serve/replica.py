"""Replica actor: hosts one copy of a deployment's callable.

Ref analog: python/ray/serve/_private/replica.py:237 (handle_request) —
re-designed: the replica is a plain ``max_concurrency``-threaded actor
(queries run concurrently on its thread pool; ``@serve.batch`` coalesces
across those threads), and the XLA path is first-class: a deployment whose
``ray_actor_options`` request TPUs constructs its model inside the replica
process with the chip(s) already assigned.
"""

from __future__ import annotations

import threading
import time
from typing import Any

from .config import ReplicaMetrics


class HandleMarker:
    """Placeholder for a DeploymentHandle inside pickled init args."""

    def __init__(self, deployment_name: str, app_name: str):
        self.deployment_name = deployment_name
        self.app_name = app_name


def _resolve_markers(obj, _refs=None):
    """Rehydrate HandleMarkers and fetch by-ref init args.

    Weights-by-ref (r14): large init args are put() into the object
    store ONCE at serve.run() time (or passed as refs by the user) and
    fetched here through the object plane — concurrent replica
    cold-starts ride the cooperative pipelined broadcast tree (r9) and
    the zero-copy typed reducer (r13) instead of each unpickling a
    private copy shipped inside CREATE_ACTOR args. The controller
    pre-warms these refs onto nodes at scale-up decision time, so the
    fetch usually joins an in-flight pull or finds the bytes already
    local. All refs in the tree are fetched in ONE batched get (k
    weight shards overlap their pulls instead of paying k serial
    transfers)."""
    import ray_tpu
    from ray_tpu.core.object_ref import ObjectRef

    from .handle import DeploymentHandle

    if _refs is None:
        # pass 1: collect unique refs, batch-fetch, then substitute
        refs, seen = [], set()

        def collect(o):
            if isinstance(o, ObjectRef):
                if o.id not in seen:
                    seen.add(o.id)
                    refs.append(o)
            elif isinstance(o, (list, tuple)):
                for x in o:
                    collect(x)
            elif isinstance(o, dict):
                for v in o.values():
                    collect(v)
        collect(obj)
        _refs = {}
        if refs:
            for r, v in zip(refs, ray_tpu.get(refs)):
                _refs[r.id] = v
    if isinstance(obj, HandleMarker):
        return DeploymentHandle(obj.deployment_name, obj.app_name)
    if isinstance(obj, ObjectRef):
        return _refs[obj.id]
    if isinstance(obj, (list, tuple)):
        return type(obj)(_resolve_markers(x, _refs) for x in obj)
    if isinstance(obj, dict):
        return {k: _resolve_markers(v, _refs) for k, v in obj.items()}
    return obj


def _resolve_request_refs(args: tuple, kwargs: dict):
    """Shallow by-ref resolution for request payloads: top-level args
    arrive as real task args (the runtime already fetched ARG_REF
    entries zero-copy before dispatch), but refs nested one level down
    — kwargs values and DeploymentResponse composition through
    non-handle paths — still reach the replica as ObjectRefs. Resolve
    those here so user code always sees values. Shallow on purpose: a
    ref buried deeper inside user containers stays a ref, same as task
    semantics. All refs fetch in ONE batched get (overlapped pulls)."""
    import ray_tpu
    from ray_tpu.core.object_ref import ObjectRef

    refs = [a for a in args if isinstance(a, ObjectRef)]
    refs += [v for v in kwargs.values() if isinstance(v, ObjectRef)]
    if not refs:
        return args, kwargs
    vals = iter(ray_tpu.get(refs))
    args = tuple(next(vals) if isinstance(a, ObjectRef) else a
                 for a in args)
    kwargs = {k: next(vals) if isinstance(v, ObjectRef) else v
              for k, v in kwargs.items()}
    return args, kwargs


class ServeReplica:
    """The actor class every replica runs (one per replica)."""

    def __init__(self, payload: bytes, replica_id: str):
        from ray_tpu.core.serialization import loads

        spec = loads(payload)
        self._replica_id = replica_id
        self._is_function = spec["is_function"]
        self._lock = threading.Lock()
        self._ongoing = 0
        self._completed = 0
        self._healthy = True
        self._draining = False
        self._streams = {}      # stream id -> (iterator, meta)
        init_args = _resolve_markers(spec["init_args"])
        init_kwargs = _resolve_markers(spec["init_kwargs"])
        if self._is_function:
            self._callable = spec["func_or_class"]
        else:
            self._callable = spec["func_or_class"](*init_args, **init_kwargs)
        user_config = spec.get("user_config")
        if user_config is not None:
            self.reconfigure(user_config)

    # ------------------------------------------------------------- serving

    def _resolve_fn(self, method_name: str):
        if self._is_function:
            return self._callable
        if method_name == "__call__":
            fn = self._callable
            if not callable(fn):
                raise TypeError(
                    f"deployment class {type(self._callable).__name__} "
                    "has no __call__; call a named method instead")
            return fn
        return getattr(self._callable, method_name)

    def handle_request(self, method_name: str, kwargs: dict,
                       meta: dict = None, *args):
        """One request. Positional request args ride as REAL task args
        (``*args``) rather than nested in a tuple (r14): a large
        payload the handle converted to a by-ref arg is fetched by the
        worker runtime before dispatch — arena-backed zero-copy read,
        dispatch-time prefetch overlap, and the fetch shows up as the
        task's ``arg_fetch`` phase instead of hiding inside exec."""
        from .multiplex import _set_request_model_id

        # count the request BEFORE resolving by-ref payloads: fetching a
        # large kwarg over a slow link can take hundreds of ms, and a
        # replica saturated in fetches must not report idle to the
        # autoscaler's replica-side load signal
        with self._lock:
            self._ongoing += 1
        try:
            args, kwargs = _resolve_request_refs(args, kwargs or {})
            _set_request_model_id(
                (meta or {}).get("multiplexed_model_id", ""))
            try:
                return self._resolve_fn(method_name)(*args, **kwargs)
            finally:
                _set_request_model_id("")
        finally:
            with self._lock:
                self._ongoing -= 1
                self._completed += 1

    # ------------------------------------------------------- streaming

    def start_stream(self, method_name: str, kwargs: dict,
                     meta: dict = None, *args) -> str:
        """Begin a streaming response: run the (generator) callable, park
        its iterator, return a stream id the client drains with
        stream_next (ref: replica.py:339 streaming generator support).
        The stream counts as one ongoing request until it ends.
        Positional args ride as real task args (see handle_request)."""
        from ray_tpu.core.ids import _random_bytes

        from .multiplex import _set_request_model_id

        # count BEFORE resolving by-ref payloads, same invariant as
        # handle_request: a replica saturated fetching large request
        # args must not report idle to the autoscaler's replica signal
        with self._lock:
            self._ongoing += 1
        try:
            args, kwargs = _resolve_request_refs(args, kwargs or {})
            _set_request_model_id(
                (meta or {}).get("multiplexed_model_id", ""))
            try:
                result = self._resolve_fn(method_name)(*args, **kwargs)
            finally:
                _set_request_model_id("")
            it = iter(result)
            sid = _random_bytes(8).hex()  # pooled entropy: per-request
            with self._lock:
                self._streams[sid] = (it, meta or {})
            return sid
        except BaseException:
            with self._lock:
                self._ongoing -= 1
            raise

    def cancel_stream(self, sid: str):
        """Abandoned stream (client gone): drop the parked iterator and
        free its request slot."""
        with self._lock:
            entry = self._streams.pop(sid, None)
            if entry is not None:
                self._ongoing -= 1
                self._completed += 1
        if entry is not None and hasattr(entry[0], "close"):
            try:
                entry[0].close()
            except Exception:  # noqa: BLE001 — generator cleanup
                pass

    def stream_next(self, sid: str, max_items: int = 1):
        """-> (items, done). Pulls up to max_items from the stream.

        Default 1: each chunk ships as soon as the generator produces
        it — a larger batch would delay time-to-first-token by the whole
        batch and time out slow producers. Callers wanting fewer RPCs on
        fast streams can raise max_items."""
        from .multiplex import _set_request_model_id

        with self._lock:
            entry = self._streams.get(sid)
        if entry is None:
            raise KeyError(f"no such stream {sid}")
        it, meta = entry
        items = []
        done = False
        # generator frames execute during next() — the request context
        # must be live HERE, not just in start_stream
        _set_request_model_id(meta.get("multiplexed_model_id", ""))
        try:
            for _ in range(max_items):
                items.append(next(it))
        except StopIteration:
            done = True
        finally:
            _set_request_model_id("")
        if done:
            with self._lock:
                # guard against a concurrent cancel_stream having already
                # released the slot
                if self._streams.pop(sid, None) is not None:
                    self._ongoing -= 1
                    self._completed += 1
        return items, done

    # ---------------------------------------------------------- management

    def reconfigure(self, user_config: Any):
        if not self._is_function and hasattr(self._callable, "reconfigure"):
            self._callable.reconfigure(user_config)

    def ping(self) -> dict:
        """Liveness probe; carries the replica's node placement so the
        controller learns it at the STARTING->RUNNING transition (for
        slow-node-aware routing) instead of a metrics tick later."""
        if not self._is_function and hasattr(self._callable, "check_health"):
            self._callable.check_health()
        return {"node_idx": self._node_idx()}

    @staticmethod
    def _node_idx() -> int:
        from ray_tpu.core.context import get_context_if_exists

        ctx = get_context_if_exists()
        return ctx.node_idx if ctx is not None else -1

    def metrics(self) -> ReplicaMetrics:
        with self._lock:
            return ReplicaMetrics(
                replica_id=self._replica_id,
                num_ongoing_requests=self._ongoing,
                num_completed_requests=self._completed,
                healthy=self._healthy,
                node_idx=self._node_idx())

    def prepare_shutdown(self, timeout_s: float = 5.0) -> bool:
        """Graceful drain: wait for ongoing requests to finish."""
        self._draining = True
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            with self._lock:
                if self._ongoing == 0:
                    return True
            time.sleep(0.02)
        return False
