"""Serve configuration dataclasses.

Ref analogs: python/ray/serve/config.py (DeploymentConfig, AutoscalingConfig,
HTTPOptions) and python/ray/serve/schema.py:326 — re-designed as plain
dataclasses; TPU replicas declare ``num_tpus`` in ``ray_actor_options``.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional


@dataclasses.dataclass
class AutoscalingConfig:
    """Request-based autoscaling (ref: _private/autoscaling_policy.py:106)."""

    min_replicas: int = 1
    max_replicas: int = 4
    target_num_ongoing_requests_per_replica: float = 2.0
    upscale_delay_s: float = 0.5
    downscale_delay_s: float = 2.0
    # exponential smoothing applied to the raw desired-replica estimate
    smoothing_factor: float = 1.0

    def __post_init__(self):
        if self.min_replicas < 0 or self.max_replicas < self.min_replicas:
            raise ValueError(
                f"invalid autoscaling bounds [{self.min_replicas}, "
                f"{self.max_replicas}]")


@dataclasses.dataclass
class DeploymentConfig:
    num_replicas: int = 1
    max_concurrent_queries: int = 8
    user_config: Any = None
    autoscaling_config: Optional[AutoscalingConfig] = None
    ray_actor_options: Dict[str, Any] = dataclasses.field(default_factory=dict)
    health_check_period_s: float = 1.0
    health_check_timeout_s: float = 10.0
    graceful_shutdown_timeout_s: float = 5.0
    version: Optional[str] = None


@dataclasses.dataclass
class HTTPOptions:
    host: str = "127.0.0.1"
    port: int = 8000
    # "HeadOnly": one proxy on the head node; "EveryNode": one proxy per
    # alive node, pinned there (the reference's http_state.py proxy fleet
    # — ingress scales with the cluster, a pod LB fronts all of them)
    location: str = "HeadOnly"


@dataclasses.dataclass
class ReplicaMetrics:
    """What a replica reports to the controller each health-check tick."""

    replica_id: str = ""
    num_ongoing_requests: int = 0
    num_completed_requests: int = 0
    healthy: bool = True
