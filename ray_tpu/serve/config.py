"""Serve configuration dataclasses.

Ref analogs: python/ray/serve/config.py (DeploymentConfig, AutoscalingConfig,
HTTPOptions) and python/ray/serve/schema.py:326 — re-designed as plain
dataclasses; TPU replicas declare ``num_tpus`` in ``ray_actor_options``.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional


@dataclasses.dataclass
class AutoscalingConfig:
    """Signal-fused autoscaling (ref: _private/autoscaling_policy.py:106).

    The controller fuses three signal families each policy tick (r14):

    - **Concurrency/queue depth**: the max of replica-reported ongoing
      requests and the router-reported per-replica in-flight counts
      (queued + executing, summed across router processes). The raw
      replica estimate is ``ceil(smoothing_factor * load /
      target_num_ongoing_requests_per_replica)``.
    - **Latency SLO burn** (``latency_slo_ms``): when the head's
      per-func phase histogram p99 for the replica request method
      exceeds the SLO, the policy scales up one step per satisfied
      upscale window even if concurrency alone would not — latency
      degrades before queue depth explodes when requests get slower
      rather than more numerous.
    - **Node pressure** (``downscale_cpu_block_pct``): a scale-DOWN is
      held while every node hosting this deployment's replicas reports
      ``node.cpu_percent`` at or above the bound — shrinking a hot
      fleet just moves the queue.

    Hysteresis: upscale/downscale each need their signal to persist for
    their own delay window (``upscale_delay_s`` / ``downscale_delay_s``),
    and each direction additionally honors a cooldown measured from the
    LAST scale event in any direction (``upscale_cooldown_s`` /
    ``downscale_cooldown_s``) so a burst right after a shrink cannot
    flap the fleet. Decisions are emitted as rate-limited
    ``serve_autoscale`` cluster events carrying direction + reason;
    ``doctor_warnings()`` flags flapping.
    """

    min_replicas: int = 1
    max_replicas: int = 4
    target_num_ongoing_requests_per_replica: float = 2.0
    # how long the up/down signal must persist before acting
    upscale_delay_s: float = 0.5
    downscale_delay_s: float = 2.0
    # exponential smoothing applied to the raw desired-replica estimate
    smoothing_factor: float = 1.0
    # --- r14 signal fusion ---
    # p99 latency SLO on the replica request path, milliseconds; when
    # the head phase histogram's p99 for ``slo_phase`` exceeds it, the
    # policy scales up one step per upscale window (0 disables the
    # latency signal). The histograms aggregate per FUNC (the shared
    # replica entrypoint), so the signal is serve-wide: deployments
    # sharing a cluster see each other's burn — set the SLO on the
    # deployment(s) that own the latency budget.
    latency_slo_ms: float = 0.0
    # which lifecycle phase the SLO reads: "e2e" (submit -> result,
    # includes queueing + transport: the user-visible number) or "exec"
    # (replica compute only).
    slo_phase: str = "e2e"
    # minimum gap after the LAST scale event (either direction) before
    # scaling in this direction — the anti-flap floor on top of the
    # delay windows. 0 keeps the pre-r14 windows-only behavior.
    upscale_cooldown_s: float = 0.0
    downscale_cooldown_s: float = 0.0
    # hold scale-downs while every node hosting this deployment's
    # replicas reports node.cpu_percent >= this (0 disables the veto).
    downscale_cpu_block_pct: float = 0.0

    def __post_init__(self):
        if self.min_replicas < 0 or self.max_replicas < self.min_replicas:
            raise ValueError(
                f"invalid autoscaling bounds [{self.min_replicas}, "
                f"{self.max_replicas}]")
        if self.slo_phase not in ("e2e", "exec"):
            raise ValueError(
                f"slo_phase must be 'e2e' or 'exec', got {self.slo_phase!r}")


@dataclasses.dataclass
class DeploymentConfig:
    num_replicas: int = 1
    max_concurrent_queries: int = 8
    user_config: Any = None
    autoscaling_config: Optional[AutoscalingConfig] = None
    ray_actor_options: Dict[str, Any] = dataclasses.field(default_factory=dict)
    health_check_period_s: float = 1.0
    health_check_timeout_s: float = 10.0
    graceful_shutdown_timeout_s: float = 5.0
    version: Optional[str] = None


@dataclasses.dataclass
class HTTPOptions:
    host: str = "127.0.0.1"
    port: int = 8000
    # "HeadOnly": one proxy on the head node; "EveryNode": one proxy per
    # alive node, pinned there (the reference's http_state.py proxy fleet
    # — ingress scales with the cluster, a pod LB fronts all of them)
    location: str = "HeadOnly"


@dataclasses.dataclass
class ReplicaMetrics:
    """What a replica reports to the controller each health-check tick."""

    replica_id: str = ""
    num_ongoing_requests: int = 0
    num_completed_requests: int = 0
    healthy: bool = True
    # which node hosts this replica (r14: feeds slow-node-aware routing
    # and the node-pressure downscale veto); -1 until known
    node_idx: int = -1
