"""Deployment definition + ``@serve.deployment`` decorator.

Ref analog: python/ray/serve/api.py:243 (decorator), serve/deployment.py
(Deployment class), and the ``.bind()`` application-graph API
(serve/deployment_graph.py) — composition is kept, the DAG IR is not:
a bound deployment's init args may themselves be Applications, which the
controller deploys transitively and replicas receive as DeploymentHandles.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, Optional, Tuple

from .config import AutoscalingConfig, DeploymentConfig


@dataclasses.dataclass
class Deployment:
    """An undeployed deployment definition (callable + config)."""

    func_or_class: Any
    name: str
    config: DeploymentConfig

    def options(self, **kwargs) -> "Deployment":
        cfg = dataclasses.replace(self.config)
        name = kwargs.pop("name", self.name)
        for key, val in kwargs.items():
            if key == "autoscaling_config" and isinstance(val, dict):
                val = AutoscalingConfig(**val)
            if not hasattr(cfg, key):
                raise TypeError(f"unknown deployment option {key!r}")
            setattr(cfg, key, val)
        return Deployment(self.func_or_class, name, cfg)

    def bind(self, *args, **kwargs) -> "Application":
        return Application(self, args, kwargs)

    def __call__(self, *a, **k):
        raise TypeError(
            f"Deployment '{self.name}' cannot be called directly; deploy it "
            f"with serve.run(<dep>.bind(...)) and call the returned handle.")


@dataclasses.dataclass
class Application:
    """A deployment bound to its constructor args (possibly other Apps)."""

    deployment: Deployment
    init_args: Tuple = ()
    init_kwargs: Dict[str, Any] = dataclasses.field(default_factory=dict)

    @property
    def name(self) -> str:
        return self.deployment.name


def deployment(_func_or_class=None, *, name: Optional[str] = None,
               num_replicas: Optional[int] = None,
               max_concurrent_queries: Optional[int] = None,
               user_config: Any = None,
               autoscaling_config=None,
               ray_actor_options: Optional[dict] = None,
               health_check_period_s: Optional[float] = None,
               health_check_timeout_s: Optional[float] = None,
               graceful_shutdown_timeout_s: Optional[float] = None,
               version: Optional[str] = None) -> Any:
    """``@serve.deployment`` — wrap a class or function as a Deployment."""

    def decorate(obj) -> Deployment:
        cfg = DeploymentConfig()
        if num_replicas is not None:
            if num_replicas <= 0:
                raise ValueError("num_replicas must be positive")
            cfg.num_replicas = num_replicas
        if max_concurrent_queries is not None:
            cfg.max_concurrent_queries = max_concurrent_queries
        if user_config is not None:
            cfg.user_config = user_config
        if autoscaling_config is not None:
            cfg.autoscaling_config = (
                autoscaling_config if isinstance(
                    autoscaling_config, AutoscalingConfig)
                else AutoscalingConfig(**autoscaling_config))
        if ray_actor_options is not None:
            cfg.ray_actor_options = dict(ray_actor_options)
        if health_check_period_s is not None:
            cfg.health_check_period_s = health_check_period_s
        if health_check_timeout_s is not None:
            cfg.health_check_timeout_s = health_check_timeout_s
        if graceful_shutdown_timeout_s is not None:
            cfg.graceful_shutdown_timeout_s = graceful_shutdown_timeout_s
        cfg.version = version
        return Deployment(obj, name or getattr(obj, "__name__", "deployment"),
                          cfg)

    if _func_or_class is not None:
        return decorate(_func_or_class)
    return decorate
