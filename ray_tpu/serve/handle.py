"""DeploymentHandle — the Python-native way to call a deployment.

Ref analog: python/ray/serve/handle.py:92 (RayServeHandle /
DeploymentHandle). ``handle.remote(...)`` routes through the shared
per-process Router and returns a DeploymentResponse future; responses can
be passed straight into other handle calls (composition) — they convert to
ObjectRefs so the downstream replica fetches the value without a hop
through the caller.
"""

from __future__ import annotations

from typing import Any, Optional

import ray_tpu
from ray_tpu.core.config import get_config
from ray_tpu.core.object_ref import ObjectRef


class DeploymentResponse:
    """Future for one deployment request."""

    def __init__(self, ref: ObjectRef):
        self._ref = ref

    def result(self, timeout_s: Optional[float] = None) -> Any:
        return ray_tpu.get(self._ref, timeout=timeout_s)

    def _to_object_ref(self) -> ObjectRef:
        return self._ref

    def __await__(self):
        return self._ref.__await__()


class DeploymentResponseGenerator:
    """Iterator over a streaming deployment response (ref:
    handle.options(stream=True) -> DeploymentResponseGenerator). Chunks
    are pulled from the serving replica in small batches; the replica's
    concurrency slot is held until the stream is exhausted."""

    def __init__(self, router, rid: str, replica_handle, sid_ref):
        self._router = router
        self._rid = rid
        self._replica = replica_handle
        self._sid_ref = sid_ref
        self._sid: Optional[str] = None
        self._buf: list = []
        self._done = False
        self._released = False

    def __iter__(self):
        return self

    def __next__(self):
        try:
            while not self._buf:
                if self._done:
                    raise StopIteration
                if self._sid is None:
                    self._sid = ray_tpu.get(self._sid_ref, timeout=60)
                items, done = ray_tpu.get(
                    self._replica.stream_next.remote(self._sid),
                    timeout=60)
                self._buf.extend(items)
                if done:
                    self._done = True
                    self._release()
            return self._buf.pop(0)
        except StopIteration:
            raise
        except BaseException:
            # errored streams must not leak the replica's concurrency
            # slot or its parked iterator
            self.close()
            raise

    def _release(self):
        if not self._released:
            self._released = True
            self._router.release(self._rid)

    def close(self):
        """Abandon the stream: free the replica-side iterator + the
        router slot (also runs from __del__, so a consumer that stops
        iterating early — e.g. an HTTP client disconnect — cleans up)."""
        if self._done and self._released:
            return
        self._done = True
        if self._sid is None:
            # start_stream already ran on the replica even if nobody ever
            # pulled a chunk — resolve the id (best effort) or the
            # replica's slot + parked iterator leak forever
            try:
                self._sid = ray_tpu.get(self._sid_ref, timeout=10)
            except Exception:  # noqa: BLE001 — start_stream itself failed
                pass
        if self._sid is not None:
            try:
                self._replica.cancel_stream.remote(self._sid)
            except Exception:  # noqa: BLE001 — replica may be gone
                pass
        self._release()

    def __del__(self):
        try:
            self.close()
        except BaseException:  # noqa: BLE001 — interpreter teardown
            pass


class DeploymentHandle:
    def __init__(self, deployment_name: str, app_name: str = "default",
                 method_name: str = "__call__", stream: bool = False,
                 multiplexed_model_id: str = ""):
        self.deployment_name = deployment_name
        self.app_name = app_name
        self.method_name = method_name
        self.stream = stream
        self.multiplexed_model_id = multiplexed_model_id

    def __getattr__(self, name: str) -> "DeploymentHandle":
        if name.startswith("_"):
            raise AttributeError(name)
        return DeploymentHandle(self.deployment_name, self.app_name, name,
                                self.stream, self.multiplexed_model_id)

    def options(self, *, method_name: Optional[str] = None,
                stream: Optional[bool] = None,
                multiplexed_model_id: Optional[str] = None
                ) -> "DeploymentHandle":
        return DeploymentHandle(
            self.deployment_name, self.app_name,
            method_name or self.method_name,
            self.stream if stream is None else stream,
            self.multiplexed_model_id if multiplexed_model_id is None
            else multiplexed_model_id)

    def _meta(self) -> Optional[dict]:
        if self.multiplexed_model_id:
            return {"multiplexed_model_id": self.multiplexed_model_id}
        return None

    def remote(self, *args, **kwargs):
        from .router import get_router

        args = tuple(_to_ref(a) for a in args)
        kwargs = {k: _to_ref(v) for k, v in kwargs.items()}
        router = get_router(self.app_name, self.deployment_name)
        if self.stream:
            rid, handle, sid_ref = router.assign_stream(
                self.method_name, args, kwargs, meta=self._meta())
            return DeploymentResponseGenerator(router, rid, handle,
                                               sid_ref)
        ref = router.assign(self.method_name, args, kwargs,
                            meta=self._meta())
        return DeploymentResponse(ref)

    def __reduce__(self):
        return (DeploymentHandle,
                (self.deployment_name, self.app_name, self.method_name,
                 self.stream, self.multiplexed_model_id))

    def __repr__(self):
        return (f"DeploymentHandle({self.app_name}/{self.deployment_name}"
                f".{self.method_name})")


def _to_ref(x):
    """Arg normalization for the handle path. DeploymentResponses pass
    as their ObjectRefs (composition: the downstream replica fetches the
    value without a hop through the caller). Large binary payloads —
    bytes / bytearray / anything with an integer ``nbytes`` (ndarray,
    jax.Array) — of at least ``serve_request_by_ref_min_bytes`` are
    put() into the object store and passed BY REFERENCE (r14 zero-copy
    ingress): the put writes frames straight into the mapped arena (r8),
    the replica-side fetch is an arena-backed zero-copy read via the
    typed reducer (r13), and the dispatch-time prefetch hint overlaps
    the transfer with dispatch. Positional args ride as real task args
    (router.assign), so the runtime resolves the refs before user code
    runs."""
    if isinstance(x, DeploymentResponse):
        return x._to_object_ref()
    thr = get_config().serve_request_by_ref_min_bytes
    if thr > 0:
        if isinstance(x, (bytes, bytearray)):
            nbytes = len(x)
        else:
            nbytes = getattr(x, "nbytes", None)
        if isinstance(nbytes, int) and nbytes >= thr:
            return ray_tpu.put(x)
    return x
