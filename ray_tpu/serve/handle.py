"""DeploymentHandle — the Python-native way to call a deployment.

Ref analog: python/ray/serve/handle.py:92 (RayServeHandle /
DeploymentHandle). ``handle.remote(...)`` routes through the shared
per-process Router and returns a DeploymentResponse future; responses can
be passed straight into other handle calls (composition) — they convert to
ObjectRefs so the downstream replica fetches the value without a hop
through the caller.
"""

from __future__ import annotations

from typing import Any, Optional

import ray_tpu
from ray_tpu.core.object_ref import ObjectRef


class DeploymentResponse:
    """Future for one deployment request."""

    def __init__(self, ref: ObjectRef):
        self._ref = ref

    def result(self, timeout_s: Optional[float] = None) -> Any:
        return ray_tpu.get(self._ref, timeout=timeout_s)

    def _to_object_ref(self) -> ObjectRef:
        return self._ref

    def __await__(self):
        return self._ref.__await__()


class DeploymentHandle:
    def __init__(self, deployment_name: str, app_name: str = "default",
                 method_name: str = "__call__"):
        self.deployment_name = deployment_name
        self.app_name = app_name
        self.method_name = method_name

    def __getattr__(self, name: str) -> "DeploymentHandle":
        if name.startswith("_"):
            raise AttributeError(name)
        return DeploymentHandle(self.deployment_name, self.app_name, name)

    def options(self, *, method_name: Optional[str] = None
                ) -> "DeploymentHandle":
        return DeploymentHandle(self.deployment_name, self.app_name,
                                method_name or self.method_name)

    def remote(self, *args, **kwargs) -> DeploymentResponse:
        from .router import get_router

        args = tuple(_to_ref(a) for a in args)
        kwargs = {k: _to_ref(v) for k, v in kwargs.items()}
        router = get_router(self.app_name, self.deployment_name)
        ref = router.assign(self.method_name, args, kwargs)
        return DeploymentResponse(ref)

    def __reduce__(self):
        return (DeploymentHandle,
                (self.deployment_name, self.app_name, self.method_name))

    def __repr__(self):
        return (f"DeploymentHandle({self.app_name}/{self.deployment_name}"
                f".{self.method_name})")


def _to_ref(x):
    return x._to_object_ref() if isinstance(x, DeploymentResponse) else x
