"""Model multiplexing: many models share one replica pool.

Ref parity: ray.serve.multiplexed (python/ray/serve/multiplex.py
_ModelMultiplexWrapper + api.py multiplexed/get_multiplexed_model_id):
a replica lazy-loads models by id with LRU eviction, requests carry
``multiplexed_model_id`` through ``handle.options(...)``, and routing
prefers replicas that already hold the model (client-side affinity cache
here; the reference pushes replica model sets through its long-poll
broker). The TPU payoff is the same as the reference's GPU one: N small
models share one chip-holding replica instead of each pinning a chip.
"""

from __future__ import annotations

import functools
import threading
from collections import OrderedDict
from typing import Any, Callable, Optional

_request_ctx = threading.local()


def get_multiplexed_model_id() -> str:
    """Inside a replica: the model id of the CURRENT request (ref:
    serve.get_multiplexed_model_id)."""
    return getattr(_request_ctx, "model_id", "")


def _set_request_model_id(model_id: str):
    _request_ctx.model_id = model_id


class _ModelMultiplexWrapper:
    """LRU model cache living on the replica (one per decorated loader)."""

    def __init__(self, load_fn: Callable, self_obj: Optional[Any],
                 max_models: int):
        self._load_fn = load_fn
        self._self = self_obj
        self._max = max_models
        self._lock = threading.Lock()
        self._models: "OrderedDict[str, Any]" = OrderedDict()
        self._loading: dict = {}  # model_id -> Event (single-flight load)

    def load_model(self, model_id: str) -> Any:
        while True:
            with self._lock:
                if model_id in self._models:
                    self._models.move_to_end(model_id)
                    return self._models[model_id]
                ev = self._loading.get(model_id)
                if ev is None:
                    ev = self._loading[model_id] = threading.Event()
                    i_load = True
                else:
                    i_load = False
            if not i_load:
                ev.wait()
                continue  # loaded (or failed) — re-check the cache
            try:
                model = self._load_fn(self._self, model_id) \
                    if self._self is not None else self._load_fn(model_id)
                with self._lock:
                    self._models[model_id] = model
                    while len(self._models) > self._max:
                        old_id, old = self._models.popitem(last=False)
                        self._unload(old)
                return model
            finally:
                with self._lock:
                    self._loading.pop(model_id, None)
                ev.set()

    @staticmethod
    def _unload(model):
        """Evicted models get a chance to free accelerator memory
        (ref: __del__-based release in multiplex.py)."""
        for attr in ("__serve_multiplex_unload__", "unload"):
            fn = getattr(model, attr, None)
            if callable(fn):
                try:
                    fn()
                except Exception:  # noqa: BLE001 — eviction best-effort
                    pass
                return

    def loaded_model_ids(self):
        with self._lock:
            return list(self._models)


def multiplexed(max_num_models_per_replica: int = 3):
    """Decorator on a replica's model-loader method (ref:
    serve.multiplexed)::

        @serve.deployment
        class Multi:
            @serve.multiplexed(max_num_models_per_replica=2)
            def get_model(self, model_id: str):
                return load_from_store(model_id)

            def __call__(self, x):
                model = self.get_model(serve.get_multiplexed_model_id())
                return model(x)
    """
    if max_num_models_per_replica <= 0:
        raise ValueError("max_num_models_per_replica must be positive")

    def decorate(fn: Callable):
        # the wrapper lives on the replica INSTANCE (or on the function
        # object for plain loaders) — closure state would make the
        # deployment class unpicklable
        attr = f"__serve_mux_{fn.__name__}"

        @functools.wraps(fn)
        def method(self_or_id, maybe_id=None):
            if maybe_id is None:  # plain function loader
                holder, self_obj, model_id = method, None, self_or_id
            else:
                holder, self_obj, model_id = \
                    self_or_id, self_or_id, maybe_id
            w = holder.__dict__.get(attr)
            if w is None:
                w = holder.__dict__.setdefault(
                    attr, _ModelMultiplexWrapper(
                        fn, self_obj, max_num_models_per_replica))
            return w.load_model(model_id)

        method.__serve_multiplexed__ = True
        return method

    return decorate
