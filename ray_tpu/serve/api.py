"""Serve public API: run/status/delete/shutdown + handles + HTTP start.

Ref analogs: python/ray/serve/api.py:437 (serve.run), :243 (@serve
.deployment via deployment.py), serve/controller.py:696 (declarative
deploy_apps). The application graph is walked here: every Application found
in a bound deployment's init args is deployed into the same app and replaced
by a HandleMarker that the replica rehydrates into a DeploymentHandle.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional

import ray_tpu
from ray_tpu.core.serialization import dumps

from .config import HTTPOptions
from .controller import (
    CONTROLLER_NAME,
    DEPLOY_HEALTHY,
    get_or_create_controller,
)
from .deployment import Application, Deployment
from .handle import DeploymentHandle
from .http_proxy import HTTPProxy, PROXY_NAME

# Weights-by-ref put cache: content digest -> live ObjectRef. A repeat
# serve.run() with the SAME weight bytes (the common redeploy: bump
# num_replicas, tweak a config) reuses the prior ref, so the pickled
# payload — and therefore the sha1-derived deployment version — stays
# stable and the redeploy scales instead of rolling-restarting every
# replica (and the store keeps ONE copy, not one per run). Changed
# bytes change the digest -> new ref -> new version -> rolling update,
# as intended. Bounded: evicted entries just drop this driver's pin
# (the controller still holds refs for live deployments).
_WEIGHTS_CACHE_MAX = 8
_weights_ref_cache: "Dict[str, Any]" = {}
_weights_cache_session: Optional[str] = None  # cluster the refs belong to


def _weights_digest(obj) -> str:
    """Content fingerprint of a large init arg (dtype/shape-aware for
    arrays; hashlib reads the buffer without copying when contiguous)."""
    import hashlib

    h = hashlib.sha1()
    if isinstance(obj, (bytes, bytearray)):
        h.update(b"raw")
        h.update(obj)
    else:
        import numpy as np

        a = np.ascontiguousarray(np.asarray(obj))
        h.update(f"{a.dtype.str}{a.shape}".encode())
        h.update(memoryview(a).cast("B"))
    return h.hexdigest()


def _put_weights(obj):
    """put() a large init arg through the digest cache (see above)."""
    from ray_tpu.core.context import get_context

    # Cached refs are only valid within the cluster that minted them: a
    # shutdown()/init() cycle in the same process would otherwise hand a
    # redeploy a ref into the dead cluster's object store.
    global _weights_cache_session
    session = get_context().session_dir
    if session != _weights_cache_session:
        _weights_ref_cache.clear()
        _weights_cache_session = session
    dig = _weights_digest(obj)
    ref = _weights_ref_cache.pop(dig, None)  # pop+reinsert = LRU bump
    if ref is None:
        ref = ray_tpu.put(obj)
    _weights_ref_cache[dig] = ref
    while len(_weights_ref_cache) > _WEIGHTS_CACHE_MAX:
        del _weights_ref_cache[next(iter(_weights_ref_cache))]
    return ref


def _collect_app(app: Application) -> List[dict]:
    """Flatten the application graph into replica-spec payloads.

    Weights-by-ref (r14): init args that are large arrays/blobs (an
    integer ``nbytes`` >= ``serve_weights_by_ref_min_bytes``, or
    bytes/bytearray of that size) are put() into the object store ONCE
    here and replaced by their ObjectRef in the payload — replicas
    fetch them through the object plane (cooperative broadcast under
    concurrent cold-starts, zero-copy typed reducer) instead of each
    unpickling a private copy out of CREATE_ACTOR args. Explicit
    ObjectRef init args ride the same path. The live refs are ALSO
    returned per deployment (``weights_refs``) so the controller can
    hold them (outliving this driver's locals) and pre-warm them at
    scale-up decision time."""
    import inspect

    from ray_tpu.core.config import get_config
    from ray_tpu.core.object_ref import ObjectRef

    from .replica import HandleMarker

    out: Dict[str, dict] = {}
    thr = get_config().serve_weights_by_ref_min_bytes

    def mark(obj, app_name: str, weights: list):
        if isinstance(obj, Application):
            visit(obj, app_name)
            return HandleMarker(obj.deployment.name, app_name)
        if isinstance(obj, Deployment):
            raise TypeError(
                f"pass '{obj.name}.bind(...)' (an Application), not the "
                f"bare Deployment, as an init arg")
        if isinstance(obj, ObjectRef):
            weights.append(obj)
            return obj
        if thr > 0:
            if isinstance(obj, (bytes, bytearray)):
                nbytes = len(obj)
            else:
                nbytes = getattr(obj, "nbytes", None)
            if isinstance(nbytes, int) and nbytes >= thr:
                ref = _put_weights(obj)
                weights.append(ref)
                return ref
        if isinstance(obj, (list, tuple)):
            return type(obj)(mark(x, app_name, weights) for x in obj)
        if isinstance(obj, dict):
            return {k: mark(v, app_name, weights)
                    for k, v in obj.items()}
        return obj

    def visit(node: Application, app_name: str):
        dep = node.deployment
        if dep.name in out:
            return
        out[dep.name] = {}  # reserve before recursing (cycle guard)
        weights: list = []
        init_args = tuple(mark(a, app_name, weights)
                          for a in node.init_args)
        init_kwargs = {k: mark(v, app_name, weights)
                       for k, v in node.init_kwargs.items()}
        spec = {
            "func_or_class": dep.func_or_class,
            "is_function": not inspect.isclass(dep.func_or_class),
            "init_args": init_args,
            "init_kwargs": init_kwargs,
            "user_config": dep.config.user_config,
        }
        out[dep.name] = {"name": dep.name, "payload": dumps(spec),
                         "config": dep.config, "weights_refs": weights}

    # app_name resolved by caller; placeholder substituted there
    visit(app, "__APP__")
    return list(out.values())


def run(target: Application, *, name: str = "default",
        route_prefix: Optional[str] = "/", _blocking: bool = True,
        timeout_s: float = 60.0) -> DeploymentHandle:
    """Deploy an application and return a handle to its ingress."""
    if isinstance(target, Deployment):
        target = target.bind()
    if not isinstance(target, Application):
        raise TypeError(f"serve.run expects an Application, got {target}")
    ctrl = get_or_create_controller()

    # markers live inside pickled payloads; rebuild with the real app name
    from ray_tpu.core.serialization import loads

    from .replica import HandleMarker

    def walk(o):
        if isinstance(o, HandleMarker):
            if o.app_name == "__APP__":
                o.app_name = name
            return o
        if isinstance(o, (list, tuple)):
            return type(o)(walk(x) for x in o)
        if isinstance(o, dict):
            return {k: walk(v) for k, v in o.items()}
        return o

    deployments = []
    for d in _collect_app(target):
        spec = loads(d["payload"])
        spec["init_args"] = walk(spec["init_args"])
        spec["init_kwargs"] = walk(spec["init_kwargs"])
        deployments.append({"name": d["name"], "payload": dumps(spec),
                            "config": d["config"],
                            "weights_refs": d.get("weights_refs") or []})

    ray_tpu.get(ctrl.deploy_app.remote(
        name, route_prefix, target.deployment.name, deployments),
        timeout=30)

    from .router import reset_routers

    reset_routers()

    if _blocking:
        _wait_healthy(ctrl, name, timeout_s)
    return DeploymentHandle(target.deployment.name, name)


def _wait_healthy(ctrl, app_name: str, timeout_s: float):
    deadline = time.monotonic() + timeout_s
    last = {}
    while time.monotonic() < deadline:
        last = ray_tpu.get(ctrl.status.remote(), timeout=30)
        app = last.get(app_name, {})
        if app.get("status") == "RUNNING":
            return
        if app.get("status") == "UNHEALTHY":
            msgs = {d: s.get("message") for d, s in
                    app.get("deployments", {}).items()
                    if s.get("status") != DEPLOY_HEALTHY}
            raise RuntimeError(f"app '{app_name}' unhealthy: {msgs}")
        time.sleep(0.05)
    raise TimeoutError(
        f"app '{app_name}' not healthy after {timeout_s}s: {last}")


def status() -> dict:
    try:
        ctrl = ray_tpu.get_actor(CONTROLLER_NAME)
    except ValueError:
        return {"applications": {}}
    return {"applications": ray_tpu.get(ctrl.status.remote(), timeout=30)}


def get_app_handle(name: str = "default") -> DeploymentHandle:
    ctrl = ray_tpu.get_actor(CONTROLLER_NAME)
    ingress = ray_tpu.get(ctrl.get_ingress.remote(name), timeout=30)
    if ingress is None:
        raise ValueError(f"no serve application named '{name}'")
    return DeploymentHandle(ingress, name)


def get_deployment_handle(deployment_name: str,
                          app_name: str = "default") -> DeploymentHandle:
    return DeploymentHandle(deployment_name, app_name)


def delete(name: str, _blocking: bool = True):
    try:
        ctrl = ray_tpu.get_actor(CONTROLLER_NAME)
    except ValueError:
        return
    ray_tpu.get(ctrl.delete_app.remote(name), timeout=30)
    from .router import reset_routers

    reset_routers()


def _proxy_name(node_idx: int) -> str:
    return PROXY_NAME if node_idx == 0 else f"{PROXY_NAME}_{node_idx}"


def start(http_options: Optional[HTTPOptions] = None) -> int:
    """Start HTTP ingress (idempotent); returns the head proxy's port.

    With ``HTTPOptions(location="EveryNode")`` a proxy actor is pinned to
    EVERY alive node (the reference's per-node proxy fleet,
    serve/_private/http_state.py) — each serves the same route table, so
    an external load balancer can front all of them. ``proxy_ports()``
    lists the fleet."""
    get_or_create_controller()
    http_options = http_options or HTTPOptions()
    if http_options.location == "EveryNode":
        from ray_tpu.core.api import NodeAffinitySchedulingStrategy

        # create the whole fleet first, then collect ports (a blocking
        # get per node would serialize startup at N x actor-boot time)
        proxies = {}
        for node in ray_tpu.nodes():
            if not node.get("alive", True):
                continue
            idx = node["node_idx"]
            name = _proxy_name(idx)
            try:
                proxies[idx] = ray_tpu.get_actor(name)
            except ValueError:
                proxies[idx] = ray_tpu.remote(HTTPProxy).options(
                    name=name, num_cpus=0, max_concurrency=32,
                    scheduling_strategy=NodeAffinitySchedulingStrategy(
                        idx)).remote(
                    http_options.host,
                    http_options.port + idx if http_options.port else 0)
        port_refs = {idx: p.port.remote() for idx, p in proxies.items()}
        ports = {idx: ray_tpu.get(r, timeout=60)
                 for idx, r in port_refs.items()}
        return ports[0]
    try:
        proxy = ray_tpu.get_actor(PROXY_NAME)
    except ValueError:
        proxy = ray_tpu.remote(HTTPProxy).options(
            name=PROXY_NAME, num_cpus=0, max_concurrency=32).remote(
                http_options.host, http_options.port)
    return ray_tpu.get(proxy.port.remote(), timeout=30)


def proxy_ports() -> dict:
    """node_idx -> bound HTTP port for every live proxy actor."""
    out = {}
    for node in ray_tpu.nodes():
        idx = node["node_idx"]
        try:
            proxy = ray_tpu.get_actor(_proxy_name(idx))
        except ValueError:
            continue
        out[idx] = ray_tpu.get(proxy.port.remote(), timeout=30)
    return out


def shutdown():
    """Tear down all applications, the proxies, and the controller."""
    from .grpc_proxy import stop_grpc
    from .router import reset_routers

    try:
        stop_grpc()
    except Exception:
        pass

    proxy_names = [_proxy_name(n["node_idx"]) for n in ray_tpu.nodes()]
    if PROXY_NAME not in proxy_names:
        proxy_names.append(PROXY_NAME)  # head proxy of a shrunken cluster
    for name in proxy_names:
        try:
            proxy = ray_tpu.get_actor(name)
            try:
                ray_tpu.get(proxy.stop.remote(), timeout=10)
            except Exception:
                pass
            ray_tpu.kill(proxy)
        except ValueError:
            pass
    try:
        ctrl = ray_tpu.get_actor(CONTROLLER_NAME)
    except ValueError:
        reset_routers()
        return
    try:
        # Generous timeout: shutdown_serve joins every in-flight replica
        # drain (graceful_shutdown_timeout_s each, run concurrently) before
        # returning; killing the controller early would orphan them.
        ray_tpu.get(ctrl.shutdown_serve.remote(), timeout=120)
    except Exception:
        pass
    ray_tpu.kill(ctrl)
    reset_routers()
