"""Comm-aware trace analysis over timeline() events (r19).

Input: the chrome-trace event list ``tracing.timeline()`` produces —
complete ("X") events where cat "task" is compute (task bodies,
``stage{k}r{rep}.fwd/bwd`` pipeline ops), cat "comm" is communication
(``comm.*`` spans: collective hops, object-plane transfers, pipeline
grad all-reduce), cat "span" is user annotation and cat "phase" is the
lifecycle sub-slice layer (skipped here: phases shadow their task's
interval and would double-count busy time).

Output (all durations in seconds):

- per-lane utilization — a lane is one (pid, tid) Perfetto row, i.e.
  one worker thread on one node;
- **exposed-comm** — communication time NOT hidden under compute:
  per comm span, its overlap fraction with the union of ALL compute
  intervals cluster-wide (a late stage's batch-end all-reduce is
  hidden if ANY lane is computing under it — that is exactly the
  overlap the MPMD schedule buys); per lane and in total, the comm
  that no compute anywhere covered;
- per-(stage, replica) **bubble breakdown** for pipeline runs parsed
  from ``stage{k}r{rep}.fwd/bwd`` task names: busy vs idle inside each
  stage's active window, plus its attributed ``comm.ar.stage{k}r{rep}``
  all-reduce time;
- the **critical path**: the latest-finishing event walked backward
  through latest-ending predecessors — the chain of intervals that
  bounds the run's makespan (a heuristic over wall-clock order, not a
  dataflow proof, but it names the lanes/ops to shorten first).

Everything is pure function over the event list so tests can feed
hand-built traces with known answers.
"""

from __future__ import annotations

import re
from typing import Any, Dict, List, Optional, Tuple

_STAGE_RE = re.compile(r"^(.*?)stage(\d+)(?:r(\d+))?\.(fwd|bwd)$")
_AR_RE = re.compile(r"^comm\.ar\.stage(\d+)r(\d+)$")


# ------------------------------------------------------- interval math


def merge_intervals(ivals: List[Tuple[float, float]]
                    ) -> List[Tuple[float, float]]:
    """Union of [start, end) intervals, sorted and coalesced."""
    out: List[Tuple[float, float]] = []
    for s, e in sorted(i for i in ivals if i[1] > i[0]):
        if out and s <= out[-1][1]:
            if e > out[-1][1]:
                out[-1] = (out[-1][0], e)
        else:
            out.append((s, e))
    return out


def total_len(ivals: List[Tuple[float, float]]) -> float:
    return sum(e - s for s, e in ivals)


def overlap_len(s: float, e: float,
                merged: List[Tuple[float, float]]) -> float:
    """Length of [s, e) covered by a MERGED (sorted, disjoint) union."""
    cov = 0.0
    for a, b in merged:
        if b <= s:
            continue
        if a >= e:
            break
        cov += min(e, b) - max(s, a)
    return cov


# ------------------------------------------------------------ analysis


def _lane(ev: dict) -> str:
    return f"{ev.get('pid', '?')}/{ev.get('tid', '?')}"


def analyze(events: List[Dict[str, Any]]) -> Dict[str, Any]:
    """See the module docstring for semantics and the result shape."""
    compute = []   # (start_s, end_s, name, lane)
    comm = []
    for ev in events:
        if ev.get("ph") != "X":
            continue
        cat = ev.get("cat")
        if cat not in ("task", "comm", "span"):
            continue
        s = ev["ts"] / 1e6
        e = s + ev.get("dur", 0.0) / 1e6
        row = (s, e, ev.get("name", ""), _lane(ev))
        if cat == "comm":
            comm.append(row)
        elif cat == "task":
            compute.append(row)
        # cat "span" (user annotations) is neither compute nor comm —
        # it overlays task intervals and would double-count busy time

    all_compute = merge_intervals([(s, e) for s, e, _, _ in compute])
    all_comm = merge_intervals([(s, e) for s, e, _, _ in comm])

    # per-lane busy/exposed accounting
    lanes: Dict[str, dict] = {}
    by_lane_compute: Dict[str, list] = {}
    by_lane_comm: Dict[str, list] = {}
    for s, e, _, lane in compute:
        by_lane_compute.setdefault(lane, []).append((s, e))
    for s, e, _, lane in comm:
        by_lane_comm.setdefault(lane, []).append((s, e))
    bounds = [(s, e) for s, e, _, _ in compute + comm]
    t0 = min((s for s, _ in bounds), default=0.0)
    t1 = max((e for _, e in bounds), default=0.0)
    wall = max(0.0, t1 - t0)
    for lane in sorted(set(by_lane_compute) | set(by_lane_comm)):
        cu = merge_intervals(by_lane_compute.get(lane, []))
        mu = merge_intervals(by_lane_comm.get(lane, []))
        busy = merge_intervals(cu + mu)
        exposed = total_len(mu) - sum(
            overlap_len(s, e, cu) for s, e in mu)
        lanes[lane] = {
            "compute_s": total_len(cu),
            "comm_s": total_len(mu),
            "busy_s": total_len(busy),
            # comm in this lane not under this lane's own compute
            "exposed_comm_s": max(0.0, exposed),
            "utilization": total_len(busy) / wall if wall > 0 else 0.0,
        }

    # per-comm-span exposure vs compute ANYWHERE: overlap_frac > 0
    # means some lane's compute ran under this transfer (the overlap
    # a pipeline schedule exists to create)
    comm_spans = []
    for s, e, name, lane in sorted(comm):
        dur = e - s
        cov = overlap_len(s, e, all_compute)
        comm_spans.append({
            "name": name, "lane": lane,
            "start_s": s - t0, "dur_s": dur,
            "exposed_s": max(0.0, dur - cov),
            "overlap_frac": (cov / dur) if dur > 0 else 0.0,
        })
    total_exposed = total_len(all_comm) - sum(
        overlap_len(s, e, all_compute) for s, e in all_comm)
    total_comm = total_len(all_comm)

    # per-(stage, replica) bubble breakdown
    stages: Dict[str, dict] = {}
    for s, e, name, lane in compute:
        m = _STAGE_RE.match(name)
        if not m:
            continue
        key = f"stage{int(m.group(2))}r{int(m.group(3) or 0)}"
        st = stages.setdefault(key, {
            "fwd_s": 0.0, "bwd_s": 0.0, "ar_s": 0.0,
            "first_s": s, "last_s": e})
        st[m.group(4) + "_s"] += e - s
        st["first_s"] = min(st["first_s"], s)
        st["last_s"] = max(st["last_s"], e)
    for s, e, name, lane in comm:
        m = _AR_RE.match(name)
        if not m:
            continue
        key = f"stage{int(m.group(1))}r{int(m.group(2))}"
        st = stages.get(key)
        if st is not None:
            st["ar_s"] += e - s
            st["last_s"] = max(st["last_s"], e)
    for key, st in stages.items():
        span = max(0.0, st["last_s"] - st["first_s"])
        busy = st["fwd_s"] + st["bwd_s"] + st["ar_s"]
        st["window_s"] = span
        st["bubble_s"] = max(0.0, span - busy)
        st["bubble_frac"] = st["bubble_s"] / span if span > 0 else 0.0
        st["first_s"] -= t0
        st["last_s"] -= t0

    crit = _critical_path(compute + comm)

    return {
        "wall_s": wall,
        "lanes": lanes,
        "total": {
            "compute_s": total_len(all_compute),
            "comm_s": total_comm,
            "exposed_comm_s": max(0.0, total_exposed),
            "exposed_comm_frac": (max(0.0, total_exposed) / total_comm)
            if total_comm > 0 else 0.0,
            # mean lane utilization over the run's wall window
            "utilization": (sum(r["busy_s"] for r in lanes.values())
                            / (wall * len(lanes)))
            if wall > 0 and lanes else 0.0,
        },
        "comm_spans": comm_spans,
        "stages": stages,
        "critical_path": crit,
        "critical_path_s": (crit[-1]["end_s"] - crit[0]["start_s"])
        if crit else 0.0,
    }


def _critical_path(rows: List[Tuple[float, float, str, str]],
                   eps: float = 1e-7) -> List[dict]:
    """Backward walk from the latest-finishing interval: each step
    picks the latest-ENDING interval that ends at/before the current
    one starts (the tightest wall-clock predecessor — the thing the
    current op was most plausibly waiting on). Returns oldest-first."""
    if not rows:
        return []
    by_end = sorted(rows, key=lambda r: r[1])
    cur = by_end[-1]
    path = [cur]
    idx = len(by_end) - 1
    while True:
        # binary-search-free scan: by_end is sorted, walk left to the
        # latest interval ending <= cur start
        pred = None
        for j in range(idx - 1, -1, -1):
            if by_end[j][1] <= cur[0] + eps:
                pred = by_end[j]
                idx = j
                break
        if pred is None:
            break
        path.append(pred)
        cur = pred
    t0 = min(r[0] for r in rows)
    return [{
        "name": r[2], "lane": r[3],
        "start_s": r[0] - t0, "end_s": r[1] - t0,
        "dur_s": r[1] - r[0],
    } for r in reversed(path)]
