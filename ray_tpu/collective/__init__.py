"""ray_tpu.collective — host-plane collective communication.

TPU-first split of the reference's ray.util.collective (SURVEY.md §2.3):
tensor-plane collectives are XLA programs (jax.lax.psum et al. over ICI —
see ray_tpu.parallel); this module covers the host plane the reference
used NCCL/Gloo groups for: gang barriers, broadcasts, gradient
allreduce/reduce_scatter/allgather between data-parallel actors. The
coordination plane is a per-group rendezvous actor; the data plane (r18)
is the object plane — chunked ring / halving-doubling tree collectives
moving bytes store-to-store — with the pre-r18 rendezvous transport
preserved behind ``collective_transport="rendezvous"``.
"""

from .collective import (
    CollectiveError,
    Rendezvous,
    allgather,
    allreduce,
    barrier,
    broadcast,
    create_collective_group,
    destroy_collective_group,
    get_collective_group_size,
    get_rank,
    init_collective_group,
    is_group_initialized,
    reduce,
    reduce_scatter,
)

__all__ = [
    "init_collective_group", "destroy_collective_group", "allreduce",
    "reduce_scatter", "allgather", "broadcast", "barrier", "reduce",
    "get_rank", "get_collective_group_size", "is_group_initialized",
    "create_collective_group", "Rendezvous", "CollectiveError",
]
