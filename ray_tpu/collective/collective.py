"""Host-level collective groups over the runtime control plane.

Ref analog: python/ray/util/collective/collective.py (GroupManager :40,
init_collective_group :120, allreduce :258) — with the TPU-first split
(SURVEY.md §2.3): *tensor* collectives live inside compiled XLA programs
(psum/all_gather over ICI; see ray_tpu.parallel), so this module only
provides the *host-plane* collectives the reference used NCCL/Gloo for —
gang barriers, config broadcast, gradient allreduce/allgather between
data-parallel actors.

Two transports (r18):

- **ring / tree (default)** — the data plane is the object plane: each
  rank ``put()``s its chunk into its LOCAL arena, peers pull it
  store-to-store over the striped-pull / zero-copy path (r13 typed
  reducer — the driver and the coordinator never touch payload bytes),
  and the rendezvous actor carries only per-hop *ref exchanges* (small
  control dicts). Large payloads ride a chunked ring
  (reduce-scatter + allgather, 2·(R-1)/R·nbytes moved per rank, each
  hop's pull warmed ahead so it overlaps the previous chunk's reduce);
  small payloads ride a halving-doubling (recursive-doubling) tree —
  log2(R) hops instead of 2(R-1), the standard small-message trade.
- **rendezvous (escape hatch)** — the pre-r18 implementation, preserved
  verbatim behind ``collective_transport="rendezvous"`` (or per-call
  ``transport="rendezvous"/"inline"/"object"``): payloads flow through
  the coordinator inline, or as the two-round slice-exchange for sized
  arrays.

Every collective runs a fixed number of rendezvous rounds for a given
(algorithm, world size), and each ring/tree round is tagged with its
algorithm + hop index, so ranks that accidentally disagree on the
algorithm fail with a clean ``CollectiveError`` instead of wedging the
group.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional

import numpy as np

#: reduce ufuncs by op name (also the incremental fold the coordinator
#: applies as contributions land — satellite r18: O(1) payloads held)
_UFUNCS = {
    "sum": np.add,
    "prod": np.multiply,
    "max": np.maximum,
    "min": np.minimum,
}

_REDUCE_OPS = {
    name: (lambda xs, _u=u: _tree_reduce(xs, _u))
    for name, u in _UFUNCS.items()
}


def _tree_reduce(xs: List[Any], op):
    out = xs[0]
    for x in xs[1:]:
        out = op(out, x)
    return out


class CollectiveError(RuntimeError):
    """A collective operation failed as a GROUP: a rank died mid-ring,
    a round timed out, or ranks disagreed on the algorithm. The error
    surfaces on every surviving rank within the op's ``timeout`` bound
    (plus the get() margin) — the group is never silently wedged, and
    the failed round's coordinator state is dropped so later operations
    on the surviving group are not poisoned."""


class Rendezvous:
    """Coordinator actor: one per group; collects one contribution per
    rank per round, computes the result, hands it back to every caller.

    Create with max_concurrency >= world_size + 1 so all ranks can block
    inside ``contribute`` concurrently.

    For the reduce kinds (``allreduce`` / ``reduce``) contributions are
    FOLDED INCREMENTALLY as they land (r18): the coordinator holds one
    running accumulator instead of every rank's payload, so its peak
    memory is O(1) payloads rather than O(world) — the escape-hatch
    inline transport stays honest for large gradients. The fold order is
    arrival order (ops are commutative; float rounding may differ
    run-to-run but is identical across ranks within one round, since the
    result is computed once and shared). ``allgather`` / ``exchange`` /
    ``broadcast`` inherently need the per-rank parts and keep them.
    """

    def __init__(self, world_size: int):
        self.world_size = world_size
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._rounds: Dict[tuple, dict] = {}

    def contribute(self, kind: str, seq: int, rank: int, payload,
                   op: str = "sum", src_rank: int = 0,
                   timeout: float = 300.0):
        key = (kind, seq)
        with self._cond:
            state = self._rounds.setdefault(
                key, {"parts": {}, "acc": None, "arrived": 0,
                      "result": None, "done": False, "claimed": 0})
            state["arrived"] += 1
            if kind in ("allreduce", "reduce"):
                # incremental fold: never hold more than the running
                # accumulator (+ the payload being folded)
                acc = state["acc"]
                state["acc"] = payload if acc is None \
                    else _UFUNCS[op](acc, payload)
            else:
                state["parts"][rank] = payload
            if state["arrived"] == self.world_size:
                state["result"] = self._finish(kind, state, op, src_rank)
                state["done"] = True
                state["acc"] = None
                state["parts"] = {}
                self._cond.notify_all()
            else:
                ok = self._cond.wait_for(lambda: state["done"],
                                         timeout=timeout)
                if not ok:
                    # drop the wedged round so a retry (or the next
                    # operation) on the surviving group starts clean
                    # instead of rendezvousing with stale arrivals
                    if self._rounds.get(key) is state:
                        del self._rounds[key]
                    raise TimeoutError(
                        f"collective {kind}#{seq}: only "
                        f"{state['arrived']}/{self.world_size} ranks "
                        f"arrived within {timeout}s")
            result = state["result"]
            state["claimed"] += 1
            if state["claimed"] == self.world_size:
                self._rounds.pop(key, None)
        return result

    def _finish(self, kind: str, state: dict, op: str, src_rank: int):
        if kind == "barrier":
            return True
        if kind in ("allreduce", "reduce"):
            return state["acc"]
        parts = state["parts"]
        if kind == "broadcast":
            return parts[src_rank]
        ordered = [parts[r] for r in sorted(parts)]
        if kind == "exchange":
            # control-plane-only round for the object-plane transports:
            # payloads are OBJECT REFS (+ small metadata), never tensor
            # bytes — every rank gets the full rank->payload picture and
            # the bulk data moves store-to-store
            return ordered
        if kind == "allgather":
            return ordered
        raise ValueError(f"unknown collective kind {kind}")

    def ping(self) -> bool:
        return True


class _GroupState:
    def __init__(self, name: str, world_size: int, rank: int, handle):
        self.name = name
        self.world_size = world_size
        self.rank = rank
        self.handle = handle
        self.seq = 0
        self.lock = threading.Lock()

    def next_seq(self) -> int:
        with self.lock:
            self.seq += 1
            return self.seq


_groups: Dict[str, _GroupState] = {}
_groups_lock = threading.Lock()
#: groups this process was a MEMBER of and has already left — a repeat
#: destroy from a departed non-zero rank must be a no-op, not a
#: driver-style coordinator kill out from under the surviving ranks
_departed: set = set()


def _coordinator_name(group_name: str) -> str:
    return f"__collective_{group_name}"


def init_collective_group(world_size: int, rank: int,
                          backend: str = "host",
                          group_name: str = "default"):
    """Join this process to a named group (call once per member).

    Rank 0 creates the rendezvous coordinator actor; other ranks look it
    up by name (ref: collective.py:120 + the named-store rendezvous
    :40-118).
    """
    import ray_tpu

    if backend not in ("host", "jax"):
        raise ValueError(f"unsupported backend {backend!r}")
    if not 0 <= rank < world_size:
        raise ValueError(f"rank {rank} out of range for {world_size}")
    name = _coordinator_name(group_name)
    handle = None
    if rank == 0:
        try:
            handle = ray_tpu.remote(Rendezvous).options(
                name=name, num_cpus=0,
                max_concurrency=world_size + 2).remote(world_size)
        except Exception:
            handle = None
    if handle is None:
        import time as _time

        deadline = _time.monotonic() + 60
        while _time.monotonic() < deadline:
            try:
                handle = ray_tpu.get_actor(name)
                break
            except ValueError:
                _time.sleep(0.05)
        else:
            raise TimeoutError(f"collective group {group_name} never "
                               "materialized")
    with _groups_lock:
        _groups[group_name] = _GroupState(group_name, world_size, rank,
                                          handle)


def is_group_initialized(group_name: str = "default") -> bool:
    with _groups_lock:
        return group_name in _groups


def get_rank(group_name: str = "default") -> int:
    return _get(group_name).rank


def get_collective_group_size(group_name: str = "default") -> int:
    return _get(group_name).world_size


def destroy_collective_group(group_name: str = "default"):
    """Leave the group; rank 0 (or a NON-member — e.g. the driver that
    gang-created the group on actors and owns its lifecycle) also kills
    the coordinator actor. A repeat call from a rank that already left
    is a no-op (it must not kill a coordinator its surviving siblings
    still rendezvous through)."""
    import ray_tpu

    with _groups_lock:
        st = _groups.pop(group_name, None)
        if st is None and group_name in _departed:
            return  # former member, already left: nothing to do
        if st is not None:
            _departed.add(group_name)
    if st is None or st.rank == 0:
        try:
            ray_tpu.kill(ray_tpu.get_actor(_coordinator_name(group_name)))
        except Exception:
            pass


def _get(group_name: str) -> _GroupState:
    with _groups_lock:
        st = _groups.get(group_name)
    if st is None:
        raise RuntimeError(
            f"collective group {group_name!r} not initialized; call "
            "init_collective_group first")
    return st


def _run(kind: str, group_name: str, payload, timeout: float = 300.0,
         **kw):
    import ray_tpu

    st = _get(group_name)
    seq = st.next_seq()
    return ray_tpu.get(
        st.handle.contribute.remote(kind, seq, st.rank, payload,
                                    timeout=timeout, **kw),
        timeout=timeout + 30)


# ---------------------------------------------------------- transports

# Payloads at or above this ride the OBJECT PLANE (store-to-store
# transfer) with the coordinator carrying refs only; below it, inline
# through the coordinator. The choice is PER RANK and cannot
# desynchronize the group within one algorithm family: every rendezvous
# algorithm runs a fixed number of "exchange" rounds regardless of
# inline-vs-object, and each round's payload self-describes. The
# ALGORITHM (rendezvous vs ring vs tree) must agree across ranks; it is
# a pure function of (nbytes, transport arg, config), and ring/tree
# rounds are tagged so a disagreement raises instead of wedging.
OBJECT_TRANSPORT_THRESHOLD = 256 * 1024

#: auto transport: payloads below this use the halving-doubling tree
#: (log2(R) hops) when the world size is a power of two; above it, the
#: bandwidth-optimal chunked ring
TREE_MAX_BYTES = 4 * 1024 * 1024

_TRANSPORTS = ("auto", "inline", "object", "rendezvous", "ring", "tree")


def _resolve_algorithm(arr: np.ndarray, transport: str,
                       world: int) -> str:
    """Pick the wire algorithm: "inline" / "object" (rendezvous scheme)
    or "ring" / "tree" (object-plane, r18). Validation happens even for
    world==1 so a typo'd transport fails everywhere identically."""
    if transport not in _TRANSPORTS:
        raise ValueError(f"transport must be one of {_TRANSPORTS}, "
                         f"got {transport!r}")
    if world <= 1:
        return "local"
    if transport == "inline":
        return "inline"
    if transport == "object":
        return "object"
    if transport == "rendezvous":
        # the rendezvous-actor DATA plane: every rank ships its full
        # payload to the coordinator, which folds incrementally and
        # hands the result back — the O(R·nbytes)-through-one-node
        # baseline, and the only transport with ZERO object-plane
        # involvement (the true escape hatch)
        return "rendezvous"
    if transport == "tree":
        if world & (world - 1):
            raise ValueError(
                f"tree transport needs a power-of-two world size, got "
                f"{world} (use transport='ring' or 'auto')")
        return "tree"
    if transport == "ring":
        return "ring"
    # auto: config decides the family, size decides within it
    from ray_tpu.core.config import get_config

    if get_config().collective_transport == "rendezvous":
        return ("object" if arr.nbytes >= OBJECT_TRANSPORT_THRESHOLD
                else "inline")
    if arr.nbytes < OBJECT_TRANSPORT_THRESHOLD:
        return "inline"  # a put + R pulls costs more than it saves
    if arr.nbytes < TREE_MAX_BYTES and not (world & (world - 1)):
        return "tree"
    return "ring"


def _use_object_plane(arr: np.ndarray, transport: str) -> bool:
    """Rendezvous-scheme payload choice (broadcast / legacy paths).
    Ring-family transports map to the object plane — for broadcast the
    single-source object path IS the r9 cooperative relay tree, so
    there is nothing extra a ring would add; "rendezvous" forces the
    inline funnel (zero object-plane involvement)."""
    if transport not in _TRANSPORTS:
        raise ValueError(f"transport must be one of {_TRANSPORTS}, "
                         f"got {transport!r}")
    if transport in ("inline", "rendezvous"):
        return False
    if transport in ("object", "ring", "tree"):
        return True
    return arr.nbytes >= OBJECT_TRANSPORT_THRESHOLD


# ----------------------------------------------------------- telemetry

_METRICS: Optional[Dict[str, Any]] = None
_metrics_lock = threading.Lock()

#: per-hop latency spans sub-ms local folds to paced multi-second pulls
HOP_BOUNDARIES = (0.001, 0.005, 0.01, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5,
                  5.0, 15.0, 60.0)


def _m() -> Dict[str, Any]:
    """Lazily-created ``collective.*`` counters; pushed from each rank
    process over the normal metrics channel, merged on the head (the
    ``object_plane`` state row summarizes them, Prometheus exports them
    verbatim)."""
    global _METRICS
    if _METRICS is None:
        with _metrics_lock:
            if _METRICS is None:
                from ray_tpu import metrics as _mm

                _METRICS = {
                    "ops": _mm.Counter(
                        "collective.ops",
                        "Completed collective operations, by algorithm "
                        "and kind",
                        tag_keys=("algorithm", "kind")),
                    "bytes_sent": _mm.Counter(
                        "collective.bytes_sent",
                        "Payload bytes this rank published for peers "
                        "(arena puts), by algorithm",
                        tag_keys=("algorithm",)),
                    "bytes_recv": _mm.Counter(
                        "collective.bytes_recv",
                        "Payload bytes this rank pulled from peers, by "
                        "algorithm",
                        tag_keys=("algorithm",)),
                    "hop_s": _mm.Histogram(
                        "collective.hop_s",
                        "Per-hop wall time (publish + ref exchange + "
                        "pull + fold), seconds",
                        boundaries=HOP_BOUNDARIES,
                        tag_keys=("algorithm",)),
                }
    return _METRICS


# --------------------------------------------- object-plane primitives


def _put_chunks(arr: np.ndarray, chunk_bytes: int):
    """Publish a 1-D array into the LOCAL arena as ~chunk_bytes pieces;
    returns ([refs], nbytes). Peers pull each chunk store-to-store, so
    chunking bounds per-pull latency and lets a consumer's later-chunk
    pulls overlap its earlier chunks' reduce compute."""
    import ray_tpu

    flat = np.ascontiguousarray(arr).reshape(-1)
    n = max(1, -(-flat.nbytes // max(1, int(chunk_bytes))))
    parts = np.array_split(flat, n) if n > 1 else [flat]
    return ([ray_tpu.put(np.ascontiguousarray(p)) for p in parts],
            flat.nbytes)


def _warm_refs(refs) -> None:
    """Start the store-to-store pulls for chunks this rank is about to
    consume (the dispatch-time PREFETCH_HINT analog, riding the same
    r13 prefetch machinery via OBJECT_WARM): the transfers run under
    whatever compute precedes the ``get`` — a failure only loses the
    overlap, never the data (the get demand-pulls)."""
    import ray_tpu
    from ray_tpu.core.context import get_context_if_exists

    ctx = get_context_if_exists()
    if ctx is None:
        return
    for r in refs:
        try:
            ray_tpu.warm_object(r, node_idx=ctx.node_idx)
        except Exception:  # noqa: BLE001 — speculation only
            pass


def _fetch_flat(refs, timeout: float):
    """Pull a peer's chunk list (warmed pulls are joined in flight) and
    return (1-D array, nbytes). Chunks may come back as readonly
    arena-aliasing views; every consumer below produces a fresh array
    (ufunc output / concatenate), so the views die with this scope and
    the borrow ledger releases the slots."""
    import ray_tpu

    vals = ray_tpu.get(list(refs), timeout=timeout)
    arrs = [np.asarray(v).reshape(-1) for v in vals]
    nb = sum(a.nbytes for a in arrs)
    if len(arrs) == 1:
        return arrs[0], nb
    return np.concatenate(arrs), nb


def _fold_chunks(dst: np.ndarray, refs, ufunc, timeout: float) -> int:
    """Pull a peer's chunk list and fold it into ``dst`` IN PLACE,
    chunk by chunk: later chunks' (warmed) pulls overlap earlier
    chunks' folds, and — deliberately — NOTHING is allocated. Fresh
    multi-MiB allocations are exactly what the hot path must avoid:
    first-touch page faults on this class of sandboxed host cost
    ~20 ms/MiB under arena pressure (see object_store._populate_bg),
    which at 64 MiB payloads was costing more than a paced 16 MiB
    transfer. The pulled values stay readonly arena views; each is
    read once into the accumulator segment and dropped."""
    import ray_tpu

    off = 0
    nb = 0
    for ref in refs:
        a = np.asarray(ray_tpu.get(ref, timeout=timeout)).reshape(-1)
        n = a.size
        if off + n > dst.size:
            raise CollectiveError(
                f"peer chunk overruns the slice: {off + n} > "
                f"{dst.size} elements (mismatched chunk_bytes across "
                "ranks?)")
        seg = dst[off:off + n]
        ufunc(seg, a, out=seg)
        off += n
        nb += a.nbytes
        del a
    if off != dst.size:
        raise CollectiveError(
            f"peer chunks cover {off} of {dst.size} slice elements "
            "(mismatched chunk_bytes across ranks?)")
    return nb


def _copy_chunks(dst: np.ndarray, refs, timeout: float) -> int:
    """Pull a peer's chunk list straight into ``dst`` (allgather
    assembly) — same zero-allocation discipline as ``_fold_chunks``."""
    import ray_tpu

    off = 0
    nb = 0
    for ref in refs:
        a = np.asarray(ray_tpu.get(ref, timeout=timeout)).reshape(-1)
        n = a.size
        if off + n > dst.size:
            raise CollectiveError(
                f"peer chunk overruns the slice: {off + n} > "
                f"{dst.size} elements")
        dst[off:off + n] = a
        off += n
        nb += a.nbytes
        del a
    if off != dst.size:
        raise CollectiveError(
            f"peer chunks cover {off} of {dst.size} slice elements")
    return nb


def _work_buffer(arr: np.ndarray) -> np.ndarray:
    """Flat 1-D accumulator for the in-place ring/tree fold. A
    writable contiguous input is used DIRECTLY (the API's in-place
    contract already mutates it at the end; starting early saves the
    output allocation + final copy — on this host class, page-fault
    cost rivals transfer cost). Otherwise one private copy is made.
    On a failed collective the buffer (and thus a writable caller
    tensor) may hold partial sums — same contract as an aborted NCCL
    op."""
    flat = np.ascontiguousarray(arr).reshape(-1)
    if not flat.flags.writeable:
        flat = np.array(flat, copy=True)
    return flat


def _check_round(grid, alg: str, hop: int, meta) -> None:
    """Every rank must have contributed the same (algorithm, hop) —
    and, when ``meta`` is given, the same shape/dtype."""
    for q, p in enumerate(grid):
        if not isinstance(p, dict) or p.get("alg") != alg \
                or p.get("hop") != hop:
            got = p.get("alg") if isinstance(p, dict) else type(p).__name__
            raise CollectiveError(
                f"collective round desync at {alg} hop {hop}: rank {q} "
                f"contributed {got!r} — every rank must choose the same "
                "transport/algorithm (auto resolves identically only "
                "when ranks share config and shapes)")
        if meta is not None and p.get("meta") != meta:
            raise CollectiveError(
                f"collective requires identical shape/dtype on every "
                f"rank; rank {q} sent {p.get('meta')}, expected {meta}")


#: per-process trace of the LAST object-plane collective's hops:
#: (label, seconds) tuples — ("put"/"exchange"/"pull+fold" per hop,
#: "ag_pull", "barrier"). Introspection for benches/tests; overwritten
#: per op. Not thread-safe (one collective per process at a time is
#: the supported pattern).
LAST_OP_TRACE: List[tuple] = []


def _trace(label: str, t0: float) -> float:
    now = time.monotonic()
    LAST_OP_TRACE.append((label, round(now - t0, 4)))
    return now


def _comm_span(name: str, t0_mono: float):
    """Retroactively stamp [t0_mono, now) as a ``comm.<name>`` timeline
    span (r19): collective hops land in the same Perfetto lanes as the
    compute that should hide them, and trace_analysis.analyze() reads
    the exposed remainder. No-op outside a CoreContext."""
    from ray_tpu import tracing

    now_m, now_w = time.monotonic(), time.time()
    tracing.record_comm_span(name, now_w - (now_m - t0_mono), now_w,
                             t0_mono, now_m)


def _ring_chunk_bytes(chunk_bytes: Optional[int]) -> int:
    if chunk_bytes is not None:
        return int(chunk_bytes)
    from ray_tpu.core.config import get_config

    return get_config().collective_ring_chunk_bytes


def _ring_collective(arr: np.ndarray, st: _GroupState, op: str,
                     timeout: float, chunk_bytes: Optional[int],
                     allgather_phase: bool):
    """Chunked ring reduce-scatter (+ allgather) on the object plane.

    Reduce-scatter: R-1 hops. At hop s rank r publishes its current
    partial for slice (r-1-s) mod R into its LOCAL arena (chunked), one
    control-only exchange round spreads the refs, and r pulls its
    predecessor's partial for slice (r-2-s) mod R — the pull warmed
    ahead so chunks stream in while earlier chunks fold — and reduces
    it into its accumulator. Completing hop s's exchange PROVES every
    rank consumed hop s-1's chunks, so each rank drops its previous
    hop's refs there (eager free: O(1) hops of chunks live per rank).
    After R-1 hops rank r holds the fully-reduced slice r.

    Allgather: each rank publishes its completed slice ONCE; a single
    exchange round spreads the refs and everyone pulls the other R-1
    slices — concurrent pulls of one slice form the r9 cooperative
    relay tree, giving ring-like link utilization without R-1 more
    rounds. A final barrier round lets every rank free its published
    slice eagerly.

    Per-rank traffic ~2·(R-1)/R·nbytes, none of it through the
    coordinator or the driver (counter-asserted in BENCH_dp_r18).
    """
    m = _m()
    t_setup = time.monotonic()
    W, r = st.world_size, st.rank
    ufunc = _UFUNCS[op]
    chunk_bytes = _ring_chunk_bytes(chunk_bytes)
    # the fold runs IN PLACE over contiguous segments of one flat
    # buffer (the writable caller tensor itself when possible): each
    # hop publishes a segment (put() snapshots it into the arena) then
    # folds the predecessor's partial into the next segment — no
    # per-hop allocations, no final concatenate
    flat = _work_buffer(arr)
    views = np.array_split(flat, W)
    meta = (tuple(arr.shape), str(arr.dtype))
    kind = "allreduce" if allgather_phase else "reduce_scatter"
    sent = recv = 0
    prev_refs = None
    LAST_OP_TRACE.clear()
    try:
        for s in range(W - 1):
            if s == 0:
                _trace("setup", t_setup)
            t_hop = t = time.monotonic()
            out_idx = (r - 1 - s) % W
            in_idx = (r - 2 - s) % W
            refs, nb = _put_chunks(views[out_idx], chunk_bytes)
            sent += nb
            t = _trace(f"h{s}.put", t)
            grid = _run("exchange", st.name,
                        {"alg": "ring", "hop": s, "meta": meta,
                         "chunks": refs}, timeout=timeout)
            t = _trace(f"h{s}.exchange", t)
            _check_round(grid, "ring", s, meta)
            # hop s's exchange completing proves hop s-1's chunks were
            # consumed everywhere: drop them now (owner free)
            prev_refs = None  # noqa: F841 — eager free via refcount
            pred = grid[(r - 1) % W]
            _warm_refs(pred["chunks"])
            recv += _fold_chunks(views[in_idx], pred["chunks"], ufunc,
                                 timeout)
            prev_refs = refs
            _trace(f"h{s}.pull_fold", t)
            _comm_span(f"{kind}.ring.h{s}", t_hop)
            m["hop_s"].observe(time.monotonic() - t_hop,
                               {"algorithm": "ring"})
        # rank r now holds the fully-reduced slice r. Publish it only
        # when someone will pull it: a reduce_scatter's slices have no
        # consumers, so its hop W-1 round exists purely for round-
        # structure symmetry and carries no chunks.
        t_hop = t = time.monotonic()
        my_refs = None
        if allgather_phase:
            my_refs, nb = _put_chunks(views[r], chunk_bytes)
            sent += nb
        t = _trace("ag.put", t)
        grid = _run("exchange", st.name,
                    {"alg": "ring", "hop": W - 1, "meta": meta,
                     "chunks": my_refs}, timeout=timeout)
        t = _trace("ag.exchange", t)
        _check_round(grid, "ring", W - 1, meta)
        prev_refs = None
        if allgather_phase:
            # rotated order — rank r starts at its successor — so the
            # R-1 concurrent pullers spread their demand across every
            # host instead of convoying on slice 0's (the warm above
            # already races the background pulls; the demand order
            # decides who serves whom first)
            order = [(r + off) % W for off in range(1, W)]
            for q in order:
                _warm_refs(grid[q]["chunks"])
            for q in order:
                recv += _copy_chunks(views[q], grid[q]["chunks"],
                                     timeout)
            t = _trace("ag.pull", t)
        _comm_span(f"{kind}.ring.ag", t_hop)
        m["hop_s"].observe(time.monotonic() - t_hop,
                           {"algorithm": "ring"})
        if allgather_phase:
            # completion barrier: every rank pulled what it needs, so
            # the published slice refs can be dropped eagerly on
            # return. reduce_scatter needs no extra round — its hop
            # W-1 exchange already proved every published partial was
            # consumed.
            _run("exchange", st.name, {"alg": "ring", "hop": W,
                                       "meta": None, "chunks": None},
                 timeout=timeout)
            _trace("barrier", t)
            del my_refs
            _comm_span(f"{kind}.ring", t_setup)
            return flat.reshape(arr.shape)
        # reduce_scatter hands the slice out as an independent array
        # (the flat buffer may alias the caller's tensor)
        _comm_span(f"{kind}.ring", t_setup)
        return np.array(views[r], copy=True)
    except CollectiveError:
        raise
    except Exception as e:  # noqa: BLE001 — group failure surface
        raise CollectiveError(
            f"ring {kind} failed on rank {r}/{W} of group "
            f"{st.name!r}: {e!r}") from e
    finally:
        m["bytes_sent"].inc(float(sent), {"algorithm": "ring"})
        m["bytes_recv"].inc(float(recv), {"algorithm": "ring"})
        m["ops"].inc(1.0, {"algorithm": "ring", "kind": kind})


def _tree_allreduce(arr: np.ndarray, st: _GroupState, op: str,
                    timeout: float, chunk_bytes: Optional[int]):
    """Halving-doubling (recursive-doubling) allreduce for small
    payloads on the object plane: log2(R) pairwise hops — at hop t rank
    r publishes its full accumulator and pulls partner ``r ^ 2^t``'s,
    folding it in; after every hop each rank's accumulator covers a
    2^(t+1)-rank block, so log2(R) hops reach the global sum. Moves
    nbytes·log2(R) per rank (more than the ring's 2·nbytes for large
    payloads, far fewer latency-bound hops for small ones). Power-of-two
    world sizes only; ``auto`` falls back to the ring otherwise."""
    m = _m()
    t_setup = time.monotonic()
    W, r = st.world_size, st.rank
    ufunc = _UFUNCS[op]
    chunk_bytes = _ring_chunk_bytes(chunk_bytes)
    # same in-place discipline as the ring: each round publishes the
    # accumulator (put() snapshots it) then folds the partner's copy
    # into it — zero per-round allocations
    acc = _work_buffer(arr)
    meta = (tuple(arr.shape), str(arr.dtype))
    rounds = W.bit_length() - 1
    sent = recv = 0
    prev_refs = None
    LAST_OP_TRACE.clear()
    try:
        for t in range(rounds):
            t_hop = time.monotonic()
            partner = r ^ (1 << t)
            refs, nb = _put_chunks(acc, chunk_bytes)
            sent += nb
            grid = _run("exchange", st.name,
                        {"alg": "tree", "hop": t, "meta": meta,
                         "chunks": refs}, timeout=timeout)
            _check_round(grid, "tree", t, meta)
            prev_refs = None  # noqa: F841 — consumed everywhere by now
            _warm_refs(grid[partner]["chunks"])
            recv += _fold_chunks(acc, grid[partner]["chunks"], ufunc,
                                 timeout)
            prev_refs = refs
            _trace(f"t{t}.hop", t_hop)
            _comm_span(f"allreduce.tree.t{t}", t_hop)
            m["hop_s"].observe(time.monotonic() - t_hop,
                               {"algorithm": "tree"})
        _run("exchange", st.name, {"alg": "tree", "hop": rounds,
                                   "meta": None, "chunks": None},
             timeout=timeout)
        prev_refs = None
        _comm_span("allreduce.tree", t_setup)
        return acc.reshape(arr.shape)
    except CollectiveError:
        raise
    except Exception as e:  # noqa: BLE001 — group failure surface
        raise CollectiveError(
            f"tree allreduce failed on rank {r}/{W} of group "
            f"{st.name!r}: {e!r}") from e
    finally:
        m["bytes_sent"].inc(float(sent), {"algorithm": "tree"})
        m["bytes_recv"].inc(float(recv), {"algorithm": "tree"})
        m["ops"].inc(1.0, {"algorithm": "tree", "kind": "allreduce"})


def _object_allgather(arr: np.ndarray, st: _GroupState, timeout: float,
                      chunk_bytes: Optional[int]) -> List[np.ndarray]:
    """Store-to-store allgather: each rank publishes its (chunked)
    tensor once, one exchange round spreads the refs, everyone pulls
    the other R-1 tensors (concurrent pulls of one tensor form the r9
    relay tree), a barrier round gates the eager free. Per-rank shapes
    may differ (each entry carries its own meta)."""
    import ray_tpu  # noqa: F401 — symmetry with the ring path

    m = _m()
    W, r = st.world_size, st.rank
    chunk_bytes = _ring_chunk_bytes(chunk_bytes)
    flat = np.ascontiguousarray(arr).reshape(-1)
    sent = recv = 0
    try:
        t_hop = time.monotonic()
        refs, nb = _put_chunks(flat, chunk_bytes)
        sent += nb
        grid = _run("exchange", st.name,
                    {"alg": "gather", "hop": 0,
                     "meta": (tuple(arr.shape), str(arr.dtype)),
                     "chunks": refs}, timeout=timeout)
        _check_round(grid, "gather", 0, None)
        for q in range(W):
            if q != r:
                _warm_refs(grid[q]["chunks"])
        out: List[np.ndarray] = []
        for q in range(W):
            if q == r:
                out.append(np.asarray(arr))
                continue
            shape, _dtype = grid[q]["meta"]
            piece, nb_in = _fetch_flat(grid[q]["chunks"], timeout)
            recv += nb_in
            # the typed reducer preserved the dtype; copy detaches the
            # result from any arena-aliasing view before the free
            out.append(np.array(piece, copy=True).reshape(shape))
        m["hop_s"].observe(time.monotonic() - t_hop,
                           {"algorithm": "ring"})
        _run("exchange", st.name, {"alg": "gather", "hop": 1,
                                   "meta": None, "chunks": None},
             timeout=timeout)
        del refs
        _comm_span("allgather.object", t_hop)
        return out
    except CollectiveError:
        raise
    except Exception as e:  # noqa: BLE001 — group failure surface
        raise CollectiveError(
            f"object-plane allgather failed on rank {r}/{W} of group "
            f"{st.name!r}: {e!r}") from e
    finally:
        m["bytes_sent"].inc(float(sent), {"algorithm": "ring"})
        m["bytes_recv"].inc(float(recv), {"algorithm": "ring"})
        m["ops"].inc(1.0, {"algorithm": "ring", "kind": "allgather"})


def _rendezvous_allreduce(arr: np.ndarray, st: _GroupState, op: str,
                          timeout: float):
    """The rendezvous-actor data plane: every rank ships its FULL
    payload to the coordinator, which folds contributions incrementally
    as they land (O(1) payloads held) and hands every rank the result —
    O(R·nbytes) through the coordinator's node per operation. The
    pre-exchange baseline the ring exists to beat, preserved as the
    zero-object-plane escape hatch (transport="rendezvous") and the
    bench_pipeline collective phase's A."""
    m = _m()
    t0 = time.monotonic()
    out = _run("allreduce", st.name, np.ascontiguousarray(arr), op=op,
               timeout=timeout)
    _comm_span("allreduce.rendezvous", t0)
    m["hop_s"].observe(time.monotonic() - t0,
                       {"algorithm": "rendezvous"})
    m["ops"].inc(1.0, {"algorithm": "rendezvous", "kind": "allreduce"})
    return np.asarray(out).reshape(arr.shape).astype(arr.dtype,
                                                     copy=False)


# ------------------------------------------- rendezvous-scheme payloads


def _wrap(arr: Optional[np.ndarray], use_object: bool) -> Optional[dict]:
    """Self-describing round payload: inline value or nested ref (a
    BARE ref argument would be resolved to its value at the callee —
    exactly the byte funnel the object path exists to avoid)."""
    if arr is None:
        return None
    if use_object:
        import ray_tpu

        return {"ref": [ray_tpu.put(np.ascontiguousarray(arr))]}
    return {"val": np.asarray(arr)}


def _unwrap(payload: dict) -> np.ndarray:
    if "val" in payload:
        return payload["val"]
    import ray_tpu

    return np.asarray(ray_tpu.get(payload["ref"][0], timeout=300))


def _allreduce_exchange(arr: np.ndarray, st: _GroupState, op: str,
                        use_object: bool, timeout: float = 300.0):
    """Reduce-scatter + allgather by slices over TWO exchange rounds —
    the preserved pre-r18 rendezvous object path (the
    ``collective_transport="rendezvous"`` baseline and escape hatch).

    Each rank publishes W slices of its flattened tensor (refs when
    sized, inline when small), the first round spreads the W x W
    payload grid, every rank resolves COLUMN r (one slice from each
    peer), reduces it, publishes the reduced slice, and the second
    round lets everyone assemble the result — ~2x nbytes moved per
    rank, none of it through the coordinator when refs are used. The
    r18 ring improves on this with per-hop pipelining, warmed pulls and
    eager chunk frees; this path survives verbatim as the baseline. The
    round structure is IDENTICAL for both payload styles, so ranks
    choosing inline vs object still rendezvous."""
    W = st.world_size
    flat = np.ascontiguousarray(arr).reshape(-1)
    slices = np.array_split(flat, W)
    mine = {"meta": (arr.shape, str(arr.dtype)),
            "slices": [_wrap(s, use_object) for s in slices]}
    grid = _run("exchange", st.name, mine,
                timeout=timeout)  # [rank] -> payload dict
    for q, p in enumerate(grid):
        if not isinstance(p, dict) or "slices" not in p:
            raise CollectiveError(
                f"collective round desync: rank {q} did not contribute "
                "a rendezvous slice grid — every rank must choose the "
                "same transport/algorithm")
    metas = {p["meta"] for p in grid}
    if len(metas) != 1:
        raise ValueError(
            f"allreduce requires identical shape/dtype on every rank; "
            f"got {sorted(metas)}")
    r = st.rank
    column = [_unwrap(grid[q]["slices"][r]) for q in range(W)]
    reduced = _REDUCE_OPS[op](column)
    round2 = _run("exchange", st.name,
                  _wrap(reduced, use_object), timeout=timeout)
    pieces = [np.asarray(_unwrap(p)).reshape(-1) for p in round2]
    out = np.concatenate(pieces)
    _m()["ops"].inc(1.0, {"algorithm": "rendezvous",
                          "kind": "allreduce"})
    return out.reshape(arr.shape).astype(arr.dtype, copy=False)


# ------------------------------------------------------------- the API


def allreduce(tensor, group_name: str = "default", op: str = "sum",
              transport: str = "auto", timeout: float = 300.0,
              chunk_bytes: Optional[int] = None):
    """Reduce across the group; returns the reduced array (and copies it
    into ``tensor`` in place when it's a writable ndarray, matching the
    reference's in-place contract, collective.py:258 — the ring/tree
    transports fold INTO the writable tensor as hops complete, so after
    a failed op its contents are undefined, like an aborted NCCL op).

    ``transport``: "auto" (config ``collective_transport`` picks the
    family; the default ring family uses the chunked ring for sized
    payloads, the halving-doubling tree below ``TREE_MAX_BYTES`` on
    power-of-two worlds, and the inline coordinator for tiny ones;
    config "rendezvous" restores the pre-r18 auto split of inline
    under 256 KiB / slice-exchange above), "ring" / "tree" (force the
    object-plane algorithm), "rendezvous" (the rendezvous-actor DATA
    plane: full payloads through the coordinator, which folds them
    incrementally — the O(R·nbytes)-through-one-node baseline, and the
    only transport with zero object-plane involvement), "inline" /
    "object" (force a pre-r18 slice-exchange payload style). Every
    rank must resolve the SAME algorithm (auto does, given shared
    config and identical shapes — which are validated).
    ``chunk_bytes`` overrides ``collective_ring_chunk_bytes`` for the
    ring/tree chunking and must agree across ranks.
    """
    arr = np.asarray(tensor)
    st = _get(group_name)
    if st.world_size > 1:
        alg = _resolve_algorithm(arr, transport, st.world_size)
        if alg == "ring":
            result = _ring_collective(arr, st, op, timeout, chunk_bytes,
                                      allgather_phase=True)
        elif alg == "tree":
            result = _tree_allreduce(arr, st, op, timeout, chunk_bytes)
        elif alg == "rendezvous":
            result = _rendezvous_allreduce(arr, st, op, timeout)
        else:
            result = _allreduce_exchange(arr, st, op, alg == "object",
                                         timeout)
    else:
        _resolve_algorithm(arr, transport, 1)  # validate the argument
        result = arr
    if isinstance(tensor, np.ndarray) and tensor.flags.writeable \
            and not np.may_share_memory(tensor, result):
        np.copyto(tensor, result)
    return result


def reduce_scatter(tensor, group_name: str = "default", op: str = "sum",
                   transport: str = "auto", timeout: float = 300.0,
                   chunk_bytes: Optional[int] = None):
    """Reduce across the group and return THIS rank's slice of the
    result (``np.array_split(flat, world)[rank]`` of the flattened
    reduce — the reference's reduce_scatter contract, and the first
    half of the ring allreduce exposed directly: rank r pays only
    (R-1)/R·nbytes of pulls and never materializes the full result).
    Rendezvous-family transports compute the full allreduce and slice
    it (the escape hatch is correct, just not slimmer). A writable
    ``tensor`` is used as the ring fold's scratch buffer — its
    contents are undefined afterwards (pass a copy to keep the
    input)."""
    arr = np.asarray(tensor)
    st = _get(group_name)
    W, r = st.world_size, st.rank
    if W <= 1:
        _resolve_algorithm(arr, transport, 1)
        return arr.reshape(-1)
    alg = _resolve_algorithm(arr, transport, W)
    if alg in ("ring", "tree"):
        # the tree has no natural scatter half at these sizes; the ring
        # reduce-scatter is the algorithm either way
        return _ring_collective(arr, st, op, timeout, chunk_bytes,
                                allgather_phase=False)
    if alg == "rendezvous":
        full = _rendezvous_allreduce(arr, st, op, timeout)
    else:
        full = _allreduce_exchange(arr, st, op, alg == "object",
                                   timeout)
    return np.array_split(np.asarray(full).reshape(-1), W)[r]


def allgather(tensor, group_name: str = "default",
              transport: str = "auto", timeout: float = 300.0,
              chunk_bytes: Optional[int] = None) -> List[Any]:
    """Gather every rank's tensor, in rank order. Unlike allreduce,
    per-rank SHAPES may differ — so the algorithm choice must not
    depend on this rank's payload size (ranks straddling a size
    threshold would desync the round structure): "auto" resolves from
    the config family alone — object-plane gather under "ring",
    the pre-r18 per-rank inline/object wrap under "rendezvous" (whose
    single-round structure is payload-style-agnostic by design)."""
    arr = np.asarray(tensor)
    st = _get(group_name)
    if st.world_size == 1:
        _resolve_algorithm(arr, transport, 1)
        return [arr]
    alg = _resolve_algorithm(arr, transport, st.world_size)
    if transport == "auto" and alg in ("ring", "tree", "inline"):
        # size-independent re-resolution (see docstring): the family
        # decides, never this rank's nbytes
        from ray_tpu.core.config import get_config

        alg = ("legacy" if get_config().collective_transport ==
               "rendezvous" else "ring")
    if alg in ("ring", "tree"):
        return _object_allgather(arr, st, timeout, chunk_bytes)
    if alg == "rendezvous":
        # the coordinator gathers and re-ships every payload (the
        # allgather kind inherently holds all parts)
        parts = _run("allgather", group_name,
                     np.ascontiguousarray(arr), timeout=timeout)
        _m()["ops"].inc(1.0, {"algorithm": "rendezvous",
                              "kind": "allgather"})
        return [np.asarray(p) for p in parts]
    # pre-r18 single-round wrap: "legacy" keeps the per-rank
    # inline-vs-ref choice (safe — the round structure is identical
    # for both payload styles)
    use_object = (arr.nbytes >= OBJECT_TRANSPORT_THRESHOLD
                  if alg == "legacy" else alg == "object")
    parts = _run("exchange", group_name,
                 _wrap(arr, use_object), timeout=timeout)
    return [_unwrap(p) for p in parts]


def broadcast(tensor, src_rank: int = 0, group_name: str = "default",
              transport: str = "auto"):
    """One exchange round for any world size: only the SOURCE's local
    tensor decides the payload style (receivers pass placeholders whose
    size must not influence the round structure), so ranks can never
    rendezvous on mismatched kinds. The object payload IS already the
    cooperative relay-tree broadcast (r9) — ring-family transports map
    onto it."""
    arr = np.asarray(tensor)
    st = _get(group_name)
    if st.world_size > 1:
        if st.rank == src_rank:
            mine = _wrap(arr, _use_object_plane(arr, transport))
        else:
            _use_object_plane(arr, transport)  # validate the argument
            mine = None
        parts = _run("exchange", group_name, mine)
        result = arr if st.rank == src_rank else _unwrap(parts[src_rank])
    else:
        _use_object_plane(arr, transport)
        result = arr
    if isinstance(tensor, np.ndarray) and tensor.flags.writeable:
        np.copyto(tensor, result)
    return result


def barrier(group_name: str = "default", timeout: float = 300.0):
    _run("barrier", group_name, None, timeout=timeout)


def reduce(tensor, dst_rank: int = 0, group_name: str = "default",
           op: str = "sum"):
    """All ranks contribute; only dst_rank gets the result (others get
    their input back, matching the reference's semantics loosely)."""
    st = _get(group_name)
    result = _run("reduce", group_name, np.asarray(tensor), op=op)
    return result if st.rank == dst_rank else tensor


def create_collective_group(actors: list, world_size: int,
                            ranks: List[int],
                            backend: str = "host",
                            group_name: str = "default"):
    """Declarative form: initialize the group on a list of actor handles
    (each must expose ``init_collective(world_size, rank, group_name)``;
    ref: collective.py:151)."""
    import ray_tpu

    if len(actors) != len(ranks):
        raise ValueError("actors and ranks must align")
    refs = [a.init_collective.remote(world_size, r, group_name)
            for a, r in zip(actors, ranks)]
    ray_tpu.get(refs, timeout=120)
