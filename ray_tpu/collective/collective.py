"""Host-level collective groups over the runtime control plane.

Ref analog: python/ray/util/collective/collective.py (GroupManager :40,
init_collective_group :120, allreduce :258) — with the TPU-first split
(SURVEY.md §2.3): *tensor* collectives live inside compiled XLA programs
(psum/all_gather over ICI; see ray_tpu.parallel), so this module only
provides the *host-plane* collectives the reference used NCCL/Gloo for —
gang barriers, config broadcast, small-array allreduce/allgather between
actors — implemented with a rendezvous coordinator actor per group.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional

import numpy as np

_REDUCE_OPS = {
    "sum": lambda xs: _tree_reduce(xs, np.add),
    "prod": lambda xs: _tree_reduce(xs, np.multiply),
    "max": lambda xs: _tree_reduce(xs, np.maximum),
    "min": lambda xs: _tree_reduce(xs, np.minimum),
}


def _tree_reduce(xs: List[Any], op):
    out = xs[0]
    for x in xs[1:]:
        out = op(out, x)
    return out


class Rendezvous:
    """Coordinator actor: one per group; collects one contribution per rank
    per round, computes the result, hands it back to every caller.

    Create with max_concurrency >= world_size + 1 so all ranks can block
    inside ``contribute`` concurrently.
    """

    def __init__(self, world_size: int):
        self.world_size = world_size
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._rounds: Dict[tuple, dict] = {}

    def contribute(self, kind: str, seq: int, rank: int, payload,
                   op: str = "sum", src_rank: int = 0,
                   timeout: float = 300.0):
        key = (kind, seq)
        with self._cond:
            state = self._rounds.setdefault(
                key, {"parts": {}, "result": None, "done": False,
                      "claimed": 0})
            state["parts"][rank] = payload
            if len(state["parts"]) == self.world_size:
                state["result"] = self._finish(kind, state["parts"], op,
                                               src_rank)
                state["done"] = True
                self._cond.notify_all()
            else:
                ok = self._cond.wait_for(lambda: state["done"],
                                         timeout=timeout)
                if not ok:
                    raise TimeoutError(
                        f"collective {kind}#{seq}: only "
                        f"{len(state['parts'])}/{self.world_size} ranks "
                        f"arrived within {timeout}s")
            result = state["result"]
            state["claimed"] += 1
            if state["claimed"] == self.world_size:
                del self._rounds[key]
        if kind == "allgather":
            return result
        if kind == "barrier":
            return True
        if kind == "broadcast":
            return result
        return result

    def _finish(self, kind: str, parts: Dict[int, Any], op: str,
                src_rank: int):
        if kind == "barrier":
            return True
        if kind == "broadcast":
            return parts[src_rank]
        ordered = [parts[r] for r in sorted(parts)]
        if kind == "exchange":
            # control-plane-only round for the object-plane transport:
            # payloads are OBJECT REFS (+ small metadata), never tensor
            # bytes — every rank gets the full rank->payload picture and
            # the bulk data moves store-to-store
            return ordered
        if kind == "allgather":
            return ordered
        if kind == "allreduce" or kind == "reduce":
            return _REDUCE_OPS[op](ordered)
        raise ValueError(f"unknown collective kind {kind}")

    def ping(self) -> bool:
        return True


class _GroupState:
    def __init__(self, name: str, world_size: int, rank: int, handle):
        self.name = name
        self.world_size = world_size
        self.rank = rank
        self.handle = handle
        self.seq = 0
        self.lock = threading.Lock()

    def next_seq(self) -> int:
        with self.lock:
            self.seq += 1
            return self.seq


_groups: Dict[str, _GroupState] = {}
_groups_lock = threading.Lock()


def _coordinator_name(group_name: str) -> str:
    return f"__collective_{group_name}"


def init_collective_group(world_size: int, rank: int,
                          backend: str = "host",
                          group_name: str = "default"):
    """Join this process to a named group (call once per member).

    Rank 0 creates the rendezvous coordinator actor; other ranks look it
    up by name (ref: collective.py:120 + the named-store rendezvous
    :40-118).
    """
    import ray_tpu

    if backend not in ("host", "jax"):
        raise ValueError(f"unsupported backend {backend!r}")
    if not 0 <= rank < world_size:
        raise ValueError(f"rank {rank} out of range for {world_size}")
    name = _coordinator_name(group_name)
    handle = None
    if rank == 0:
        try:
            handle = ray_tpu.remote(Rendezvous).options(
                name=name, num_cpus=0,
                max_concurrency=world_size + 2).remote(world_size)
        except Exception:
            handle = None
    if handle is None:
        import time

        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            try:
                handle = ray_tpu.get_actor(name)
                break
            except ValueError:
                time.sleep(0.05)
        else:
            raise TimeoutError(f"collective group {group_name} never "
                               "materialized")
    with _groups_lock:
        _groups[group_name] = _GroupState(group_name, world_size, rank,
                                          handle)


def is_group_initialized(group_name: str = "default") -> bool:
    with _groups_lock:
        return group_name in _groups


def get_rank(group_name: str = "default") -> int:
    return _get(group_name).rank


def get_collective_group_size(group_name: str = "default") -> int:
    return _get(group_name).world_size


def destroy_collective_group(group_name: str = "default"):
    import ray_tpu

    with _groups_lock:
        st = _groups.pop(group_name, None)
    if st is not None and st.rank == 0:
        try:
            ray_tpu.kill(ray_tpu.get_actor(_coordinator_name(group_name)))
        except Exception:
            pass


def _get(group_name: str) -> _GroupState:
    with _groups_lock:
        st = _groups.get(group_name)
    if st is None:
        raise RuntimeError(
            f"collective group {group_name!r} not initialized; call "
            "init_collective_group first")
    return st


def _run(kind: str, group_name: str, payload, **kw):
    import ray_tpu

    st = _get(group_name)
    seq = st.next_seq()
    return ray_tpu.get(
        st.handle.contribute.remote(kind, seq, st.rank, payload, **kw),
        timeout=kw.get("timeout", 300.0) + 30)


# Payloads at or above this ride the OBJECT PLANE (store-to-store
# transfer) with the coordinator carrying refs only; below it, inline
# through the coordinator. The choice is PER RANK and cannot
# desynchronize the group: every collective runs a fixed number of
# "exchange" rendezvous rounds regardless of transport, and each round's
# payload self-describes as an inline value or a (nested) ref that the
# receiving ranks resolve. Override per call with transport=.
OBJECT_TRANSPORT_THRESHOLD = 256 * 1024

_TRANSPORTS = ("auto", "inline", "object")


def _use_object_plane(arr: np.ndarray, transport: str) -> bool:
    if transport not in _TRANSPORTS:
        raise ValueError(f"transport must be one of {_TRANSPORTS}, "
                         f"got {transport!r}")
    if transport == "inline":
        return False
    if transport == "object":
        return True
    return arr.nbytes >= OBJECT_TRANSPORT_THRESHOLD


def _wrap(arr: Optional[np.ndarray], use_object: bool) -> Optional[dict]:
    """Self-describing round payload: inline value or nested ref (a
    BARE ref argument would be resolved to its value at the callee —
    exactly the byte funnel the object path exists to avoid)."""
    if arr is None:
        return None
    if use_object:
        import ray_tpu

        return {"ref": [ray_tpu.put(np.ascontiguousarray(arr))]}
    return {"val": np.asarray(arr)}


def _unwrap(payload: dict) -> np.ndarray:
    if "val" in payload:
        return payload["val"]
    import ray_tpu

    return np.asarray(ray_tpu.get(payload["ref"][0], timeout=300))


def _allreduce_exchange(arr: np.ndarray, st: _GroupState, op: str,
                        use_object: bool):
    """Reduce-scatter + allgather by slices over TWO exchange rounds.

    Ring-class asymptotics without per-step rendezvous chatter: each
    rank publishes W slices of its flattened tensor (refs when sized,
    inline when small), the first round spreads the W x W payload grid,
    every rank resolves COLUMN r (one slice from each peer, ~nbytes/W
    each, sources spread across all stores), reduces it, publishes the
    reduced slice, and the second round lets everyone assemble the
    result — ~2x nbytes moved per rank, none of it through the
    coordinator when refs are used. This replaces funneling
    O(world x nbytes) of tensor bytes through one actor (round-4
    review, Weak #7); the reference's analog is the NCCL ring under
    collective.py:258. The round structure is IDENTICAL for both
    transports, so ranks choosing differently still rendezvous."""
    W = st.world_size
    flat = np.ascontiguousarray(arr).reshape(-1)
    slices = np.array_split(flat, W)
    mine = {"meta": (arr.shape, str(arr.dtype)),
            "slices": [_wrap(s, use_object) for s in slices]}
    grid = _run("exchange", st.name, mine)  # [rank] -> payload dict
    metas = {p["meta"] for p in grid}
    if len(metas) != 1:
        raise ValueError(
            f"allreduce requires identical shape/dtype on every rank; "
            f"got {sorted(metas)}")
    r = st.rank
    column = [_unwrap(grid[q]["slices"][r]) for q in range(W)]
    reduced = _REDUCE_OPS[op](column)
    round2 = _run("exchange", st.name,
                  _wrap(reduced, use_object))
    pieces = [np.asarray(_unwrap(p)).reshape(-1) for p in round2]
    out = np.concatenate(pieces)
    return out.reshape(arr.shape).astype(arr.dtype, copy=False)


def allreduce(tensor, group_name: str = "default", op: str = "sum",
              transport: str = "auto"):
    """Reduce across the group; returns the reduced array (and copies it
    into ``tensor`` in place when it's a writable ndarray, matching the
    reference's in-place contract, collective.py:258).

    ``transport``: "auto" (object plane for payloads >= 256 KiB),
    "inline" (through the coordinator), "object" (force object plane).
    All ranks must pass identically-shaped/dtyped tensors (validated).
    """
    arr = np.asarray(tensor)
    st = _get(group_name)
    if st.world_size > 1:
        result = _allreduce_exchange(
            arr, st, op, _use_object_plane(arr, transport))
    else:
        _use_object_plane(arr, transport)  # validate the argument
        result = arr
    if isinstance(tensor, np.ndarray) and tensor.flags.writeable:
        np.copyto(tensor, result)
    return result


def allgather(tensor, group_name: str = "default",
              transport: str = "auto") -> List[Any]:
    arr = np.asarray(tensor)
    st = _get(group_name)
    if st.world_size == 1:
        _use_object_plane(arr, transport)
        return [arr]
    parts = _run("exchange", group_name,
                 _wrap(arr, _use_object_plane(arr, transport)))
    return [_unwrap(p) for p in parts]


def broadcast(tensor, src_rank: int = 0, group_name: str = "default",
              transport: str = "auto"):
    """One exchange round for any world size: only the SOURCE's local
    tensor decides the transport (receivers pass placeholders whose
    size must not influence the round structure), so ranks can never
    rendezvous on mismatched kinds."""
    arr = np.asarray(tensor)
    st = _get(group_name)
    if st.world_size > 1:
        if st.rank == src_rank:
            mine = _wrap(arr, _use_object_plane(arr, transport))
        else:
            _use_object_plane(arr, transport)  # validate the argument
            mine = None
        parts = _run("exchange", group_name, mine)
        result = arr if st.rank == src_rank else _unwrap(parts[src_rank])
    else:
        _use_object_plane(arr, transport)
        result = arr
    if isinstance(tensor, np.ndarray) and tensor.flags.writeable:
        np.copyto(tensor, result)
    return result


def barrier(group_name: str = "default"):
    _run("barrier", group_name, None)


def reduce(tensor, dst_rank: int = 0, group_name: str = "default",
           op: str = "sum"):
    """All ranks contribute; only dst_rank gets the result (others get
    their input back, matching the reference's semantics loosely)."""
    st = _get(group_name)
    result = _run("reduce", group_name, np.asarray(tensor), op=op)
    return result if st.rank == dst_rank else tensor


def create_collective_group(actors: list, world_size: int,
                            ranks: List[int],
                            backend: str = "host",
                            group_name: str = "default"):
    """Declarative form: initialize the group on a list of actor handles
    (each must expose ``init_collective(world_size, rank, group_name)``;
    ref: collective.py:151)."""
    import ray_tpu

    if len(actors) != len(ranks):
        raise ValueError("actors and ranks must align")
    refs = [a.init_collective.remote(world_size, r, group_name)
            for a, r in zip(actors, ranks)]
    ray_tpu.get(refs, timeout=120)
