"""Declarative (batching) node provider + GKE-style TPU node pools.

Ref analogs: python/ray/autoscaler/batching_node_provider.py:63
(BatchingNodeProvider — create/terminate coalesce into ONE ScaleRequest
submitted per autoscaler update, the KubeRay pattern of PATCHing a
workerGroup's replica count) and _private/gcp/node_provider.py:19
(GCPTPU — TPU pod-slice node pools with accelerator topology labels).

Re-design: the cloud side is an injectable ``CloudAPI`` with a single
``submit_scale_request`` method. ``FakeGkeTpuCloud`` implements it for
tests and single-host clusters by provisioning "VMs" as local node-agent
processes that join the head over TCP carrying TPU resources + topology
labels — the same join path a real GKE pool's pods would take, including
asynchronous provisioning delay.
"""

from __future__ import annotations

import subprocess
import sys
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from .autoscaler import PROVIDER_LABEL, NodeProvider


@dataclass
class ScaleRequest:
    """One declarative resize (ref: batching_node_provider.ScaleRequest).

    ``desired_num_workers`` is the target pool size; ``workers_to_delete``
    names nodes the autoscaler chose to drain (cloud must honor the
    specific picks, not just the count — KubeRay's
    workersToDelete field)."""

    desired_num_workers: int = 0
    workers_to_delete: List[str] = field(default_factory=list)


class CloudAPI:
    """What a cloud integration must provide."""

    def list_nodes(self) -> List[str]:
        """Provider ids of non-terminated pool nodes."""
        raise NotImplementedError

    def submit_scale_request(self, req: ScaleRequest):
        raise NotImplementedError


class BatchingNodeProvider(NodeProvider):
    """Coalesces the autoscaler's per-node calls into one ScaleRequest.

    The autoscaler keeps calling ``create_node``/``terminate_node`` like
    any provider; nothing touches the cloud until ``post_process()``
    (invoked once at the end of each autoscaler update), which submits a
    single declarative resize iff something changed — ref
    batching_node_provider.py:63 (same three-method reuse + post_process
    hook).
    """

    declarative = True

    def __init__(self, cloud: CloudAPI):
        self.cloud = cloud
        self.scale_request = ScaleRequest()
        self._changed = False
        self.num_scale_requests = 0

    @property
    def num_cpus(self) -> int:  # demand -> node-count sizing
        return getattr(self.cloud, "num_cpus", 1)

    def non_terminated_nodes(self) -> List[str]:
        nodes = self.cloud.list_nodes()
        # each update cycle starts from observed reality (ref:
        # non_terminated_nodes resets the ScaleRequest)
        self.scale_request = ScaleRequest(desired_num_workers=len(nodes))
        self._changed = False
        return nodes

    def create_node(self) -> str:
        self.scale_request.desired_num_workers += 1
        self._changed = True
        # id is assigned by the cloud when the node materializes; the
        # autoscaler matches it via the PROVIDER_LABEL contract
        return f"pending-{self.scale_request.desired_num_workers}"

    def terminate_node(self, provider_id: str):
        if provider_id.startswith("pending-"):
            self.scale_request.desired_num_workers = max(
                0, self.scale_request.desired_num_workers - 1)
        else:
            self.scale_request.workers_to_delete.append(provider_id)
            self.scale_request.desired_num_workers = max(
                0, self.scale_request.desired_num_workers - 1)
        self._changed = True

    def post_process(self):
        if self._changed:
            self.cloud.submit_scale_request(self.scale_request)
            self.num_scale_requests += 1
            self._changed = False


class FakeGkeTpuCloud(CloudAPI):
    """A fake GKE TPU node pool (ref: the reference's
    FakeMultiNodeProvider test cloud + GCPTPU node semantics).

    ``submit_scale_request`` resizes the pool: grow provisions node-agent
    processes (after ``provision_delay_s``, emulating VM boot) that join
    the head over TCP with ``num_tpus`` chips and a TPU topology label;
    shrink honors ``workers_to_delete`` first, then trims newest-first.
    """

    def __init__(self, head_tcp_addr: str, *, num_tpus_per_node: int = 4,
                 num_cpus_per_node: int = 4,
                 accelerator: str = "tpu-v5e-4",
                 provision_delay_s: float = 0.0):
        import os

        self.addr = head_tcp_addr
        self.num_tpus = num_tpus_per_node
        self.num_cpus = num_cpus_per_node
        self.accelerator = accelerator
        self.provision_delay_s = provision_delay_s
        self._procs: Dict[str, subprocess.Popen] = {}
        self._next = 0
        self._lock = threading.Lock()
        self.scale_requests: List[ScaleRequest] = []
        import ray_tpu as _pkg

        self._pythonpath = os.path.dirname(os.path.dirname(
            os.path.abspath(_pkg.__file__)))

    # ------------------------------------------------------------- CloudAPI

    def list_nodes(self) -> List[str]:
        with self._lock:
            return [pid for pid, p in self._procs.items()
                    if p.poll() is None]

    def submit_scale_request(self, req: ScaleRequest):
        self.scale_requests.append(req)
        threading.Thread(target=self._reconcile, args=(req,),
                         daemon=True, name="fake-gke").start()

    # ------------------------------------------------------------ internals

    def _reconcile(self, req: ScaleRequest):
        if self.provision_delay_s:
            time.sleep(self.provision_delay_s)
        with self._lock:
            for pid in req.workers_to_delete:
                self._kill(pid)
            alive = [pid for pid, p in self._procs.items()
                     if p.poll() is None]
            # trim newest-first beyond the declared size
            while len(alive) > req.desired_num_workers:
                self._kill(alive.pop())
            while len(alive) < req.desired_num_workers:
                alive.append(self._boot())

    def _kill(self, pid: str):
        proc = self._procs.pop(pid, None)
        if proc is not None and proc.poll() is None:
            proc.terminate()

    def _boot(self) -> str:
        import os

        pid = f"gke-{self.accelerator}-{self._next}"
        self._next += 1
        env = dict(os.environ)
        env["PYTHONPATH"] = self._pythonpath + os.pathsep + \
            env.get("PYTHONPATH", "")
        proc = subprocess.Popen(
            [sys.executable, "-m", "ray_tpu.core.node_agent",
             "--address", self.addr,
             "--num-cpus", str(self.num_cpus),
             "--num-tpus", str(self.num_tpus),
             "--label", f"{PROVIDER_LABEL}={pid}",
             "--label", f"accelerator={self.accelerator}"],
            env=env, stdout=subprocess.DEVNULL,
            stderr=subprocess.STDOUT, start_new_session=True)
        self._procs[pid] = proc
        return pid

    def shutdown(self):
        with self._lock:
            for pid in list(self._procs):
                self._kill(pid)
