"""GKE TPU node-pool cloud: real Container/Compute REST calls.

Ref analogs: python/ray/autoscaler/_private/gcp/node_provider.py:19
(GCPCompute/GCPTPU split — resource-specific REST clients behind one
provider interface) and the KubeRay path batching_node_provider.py
models (one declarative resize per update).

Re-design: everything cloud-specific lives behind ``CloudAPI``'s two
methods, and everything network-specific behind an injectable
``transport`` callable, so the reconciler logic is fully testable on a
sealed image (tests inject an in-memory GKE emulation; production uses
``RestTransport``). The REST surface used:

  GET  {container}/v1/projects/{p}/locations/{l}/clusters/{c}/nodePools/{np}
       -> {"initialNodeCount", "instanceGroupUrls": [...]}
  POST .../nodePools/{np}:setSize          {"nodeCount": N} -> Operation
  GET  {container}/v1/projects/{p}/locations/{l}/operations/{op}
  POST {ig}/deleteInstances  {"instances": [url, ...]} (targeted drain)
  POST {ig}/listManagedInstances -> {"managedInstances": [...]}

TPU-specific bits ride node-pool config (machine type ct5lp-hightpu-4t
etc. and the tpu-topology placement label), which this module treats as
pre-provisioned pool properties — resizing never changes slice shape.
"""

from __future__ import annotations

import json
import time
from typing import Callable, Dict, List, Optional, Tuple

from .batching_provider import CloudAPI, ScaleRequest

# transport(method, url, body_dict_or_None, headers) -> (status, json_dict)
Transport = Callable[[str, str, Optional[dict], Dict[str, str]],
                     Tuple[int, dict]]

CONTAINER_API = "https://container.googleapis.com"


class RestTransport:
    """urllib-based default transport (production path).

    Kept import-light and dependency-free: the sealed test image has no
    google-cloud SDK, and the reference's discovery-client dependency is
    exactly what the injectable-transport design avoids.
    """

    def __init__(self, timeout_s: float = 30.0):
        self.timeout_s = timeout_s

    def __call__(self, method: str, url: str, body: Optional[dict],
                 headers: Dict[str, str]) -> Tuple[int, dict]:
        import urllib.error
        import urllib.request

        data = json.dumps(body).encode() if body is not None else None
        req = urllib.request.Request(url, data=data, method=method,
                                     headers={
                                         "Content-Type": "application/json",
                                         **headers})
        try:
            with urllib.request.urlopen(req, timeout=self.timeout_s) as r:
                payload = r.read()
                return r.status, json.loads(payload) if payload else {}
        except urllib.error.HTTPError as e:
            try:
                return e.code, json.loads(e.read())
            except Exception:
                return e.code, {}


def metadata_token_provider(transport: Optional[Transport] = None
                            ) -> Callable[[], str]:
    """OAuth token from the GCE metadata server (how in-cluster pods and
    VMs authenticate; no SDK needed)."""
    tr = transport or RestTransport()

    def token() -> str:
        status, body = tr(
            "GET",
            "http://metadata.google.internal/computeMetadata/v1/"
            "instance/service-accounts/default/token",
            None, {"Metadata-Flavor": "Google"})
        if status != 200:
            raise RuntimeError(f"metadata token fetch failed: {status}")
        return body["access_token"]
    return token


class GkeTpuNodePoolCloud(CloudAPI):
    """CloudAPI over one GKE TPU node pool.

    ``submit_scale_request`` performs the real reconcile:
      1. targeted drains via the pool's instance group's
         ``deleteInstances`` (KubeRay's workersToDelete semantics — the
         autoscaler's specific picks are honored, not just a count);
      2. ``nodePools:setSize`` to the declared size;
      3. bounded polling of the returned Operations.
    """

    def __init__(self, project: str, location: str, cluster: str,
                 node_pool: str, *,
                 transport: Optional[Transport] = None,
                 token_provider: Optional[Callable[[], str]] = None,
                 api_base: str = CONTAINER_API,
                 operation_timeout_s: float = 600.0,
                 poll_interval_s: float = 2.0):
        self.project, self.location = project, location
        self.cluster, self.node_pool = cluster, node_pool
        self.transport: Transport = transport or RestTransport()
        self._token = token_provider or metadata_token_provider()
        self.api_base = api_base.rstrip("/")
        self.operation_timeout_s = operation_timeout_s
        self.poll_interval_s = poll_interval_s

    # ------------------------------------------------------------ plumbing

    def _call(self, method: str, url: str,
              body: Optional[dict] = None) -> dict:
        status, payload = self.transport(
            method, url, body,
            {"Authorization": f"Bearer {self._token()}"})
        if status // 100 != 2:
            raise RuntimeError(
                f"{method} {url} -> {status}: "
                f"{payload.get('error', payload)}")
        return payload

    @property
    def _pool_url(self) -> str:
        return (f"{self.api_base}/v1/projects/{self.project}/locations/"
                f"{self.location}/clusters/{self.cluster}/nodePools/"
                f"{self.node_pool}")

    def _wait_operation(self, op: dict):
        """Poll an Operation until DONE (bounded). Compute Engine ops
        (returned by instance-group deleteInstances) carry a selfLink and
        must be polled THERE — they do not exist in the Container API's
        operations collection; Container ops are polled by name."""
        name = op.get("name")
        if not name or op.get("status") == "DONE":
            return
        url = op.get("selfLink") or (
            f"{self.api_base}/v1/projects/{self.project}/locations/"
            f"{self.location}/operations/{name}")
        deadline = time.monotonic() + self.operation_timeout_s
        while time.monotonic() < deadline:
            cur = self._call("GET", url)
            if cur.get("status") == "DONE":
                if cur.get("error"):
                    raise RuntimeError(
                        f"operation {name} failed: {cur['error']}")
                return
            time.sleep(self.poll_interval_s)
        raise TimeoutError(f"operation {name} not DONE after "
                           f"{self.operation_timeout_s}s")

    def _instance_groups(self) -> List[str]:
        pool = self._call("GET", self._pool_url)
        return list(pool.get("instanceGroupUrls", []))

    def _managed_instances(self, ig_url: str) -> List[dict]:
        out = self._call("POST", f"{ig_url}/listManagedInstances")
        return list(out.get("managedInstances", []))

    # ------------------------------------------------------------ CloudAPI

    def list_nodes(self) -> List[str]:
        """Non-terminated node names across the pool's instance groups
        (the node name doubles as the PROVIDER_LABEL value kubelet sets)."""
        nodes = []
        for ig in self._instance_groups():
            for inst in self._managed_instances(ig):
                if inst.get("instanceStatus") not in ("STOPPING",
                                                     "TERMINATED"):
                    nodes.append(inst["instance"].rsplit("/", 1)[-1])
        return nodes

    def submit_scale_request(self, req: ScaleRequest):
        if req.workers_to_delete:
            # targeted drain: map node names back to instance URLs per
            # instance group and deleteInstances (resizes the group too)
            wanted = set(req.workers_to_delete)
            for ig in self._instance_groups():
                urls = [inst["instance"]
                        for inst in self._managed_instances(ig)
                        if inst["instance"].rsplit("/", 1)[-1] in wanted]
                if urls:
                    op = self._call("POST", f"{ig}/deleteInstances",
                                    {"instances": urls})
                    self._wait_operation(op)
        op = self._call("POST", f"{self._pool_url}:setSize",
                        {"nodeCount": int(req.desired_num_workers)})
        self._wait_operation(op)
