"""Autoscaler: resource-demand-driven node scaling over a provider.

Ref parity: the reference's StandardAutoscaler
(python/ray/autoscaler/_private/autoscaler.py:166 update() loop over a
NodeProvider, resource_demand_scheduler.py bin-packing of pending demand,
idle-node termination). TPU re-design: nodes are whole hosts joining over
TCP (node agents); bin-packing is simpler because TPU fleets are
homogeneous per node type.
"""

from ray_tpu.autoscaler.autoscaler import (Autoscaler, AutoscalingPolicy,
                                           LocalNodeProvider, NodeProvider)
from ray_tpu.autoscaler.batching_provider import (BatchingNodeProvider,
                                                  CloudAPI,
                                                  FakeGkeTpuCloud,
                                                  ScaleRequest)

__all__ = ["Autoscaler", "AutoscalingPolicy", "NodeProvider",
           "LocalNodeProvider", "BatchingNodeProvider", "CloudAPI",
           "FakeGkeTpuCloud", "ScaleRequest"]
