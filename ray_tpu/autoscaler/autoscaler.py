"""Autoscaler core loop + node providers.

Ref analogs: python/ray/autoscaler/_private/autoscaler.py:166
(StandardAutoscaler.update: read load metrics -> compute target ->
launch/terminate via NodeProvider), node_provider.py (the provider
interface), resource_demand_scheduler.py (demand -> node count).

The demand signal comes straight from the head: pending lease requests
(queued because no node can grant them) plus infeasible placement
groups. Upscale adds ceil(missing/node_size) nodes up to max_workers;
downscale terminates nodes idle longer than idle_timeout_s. The
LocalNodeProvider launches REAL node-agent processes joining over TCP —
the same join path a cloud provider implementation would drive on VMs.
"""

from __future__ import annotations

import math
import subprocess
import sys
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional


# node label marking autoscaler-launched nodes (value = provider node id)
PROVIDER_LABEL = "ray_tpu_autoscaler_id"


class NodeProvider:
    """Minimal provider surface (ref: autoscaler/node_provider.py).

    CONTRACT: ``create_node`` must arrange for the launched node agent to
    register with the head carrying the label
    ``PROVIDER_LABEL=<returned id>`` (pass
    ``--label ray_tpu_autoscaler_id=<id>`` to the agent) — the autoscaler
    matches registered nodes to its launches by that label; unlabeled
    nodes are never adopted (so it cannot scale down somebody else's
    node) and therefore never scale down either. The autoscaler logs a
    warning when a launch stays unmatched past the grace period."""

    #: declarative providers (BatchingNodeProvider) return transient ids
    #: from create_node; the autoscaler adopts the materialized nodes by
    #: label instead of tracking the launch ids
    declarative: bool = False

    def create_node(self) -> str:
        """Launch one node; returns a provider node id (see the label
        contract above)."""
        raise NotImplementedError

    def terminate_node(self, provider_id: str):
        raise NotImplementedError

    def non_terminated_nodes(self) -> List[str]:
        raise NotImplementedError


class LocalNodeProvider(NodeProvider):
    """Node agents as local processes (tests / single-host elasticity;
    the multi-host path is identical — agents join the head over TCP)."""

    def __init__(self, head_tcp_addr: str, *, num_cpus_per_node: int = 1,
                 num_tpus_per_node: int = 0):
        import os

        self.addr = head_tcp_addr
        self.num_cpus = num_cpus_per_node
        self.num_tpus = num_tpus_per_node
        self._procs: Dict[str, subprocess.Popen] = {}
        self._next = 0
        import ray_tpu as _pkg

        self._pythonpath = os.path.dirname(os.path.dirname(
            os.path.abspath(_pkg.__file__)))

    def create_node(self) -> str:
        import os

        pid = f"local-{self._next}"
        self._next += 1
        env = dict(os.environ)
        env["PYTHONPATH"] = self._pythonpath + os.pathsep + \
            env.get("PYTHONPATH", "")
        proc = subprocess.Popen(
            [sys.executable, "-m", "ray_tpu.core.node_agent",
             "--address", self.addr, "--num-cpus", str(self.num_cpus),
             "--num-tpus", str(self.num_tpus),
             # the label lets the autoscaler match registered nodes to
             # ITS launches (a remote driver or hand-joined agent must
             # not be adopted and later scaled down)
             "--label", f"{PROVIDER_LABEL}={pid}"],
            env=env, stdout=subprocess.DEVNULL,
            stderr=subprocess.STDOUT, start_new_session=True)
        self._procs[pid] = proc
        return pid

    def terminate_node(self, provider_id: str):
        proc = self._procs.pop(provider_id, None)
        if proc is not None and proc.poll() is None:
            proc.terminate()

    def non_terminated_nodes(self) -> List[str]:
        return [pid for pid, p in self._procs.items() if p.poll() is None]


@dataclass
class AutoscalingPolicy:
    """Knobs (ref: cluster-config max_workers / idle_timeout_minutes /
    upscaling_speed)."""

    min_workers: int = 0
    max_workers: int = 4
    idle_timeout_s: float = 60.0
    update_interval_s: float = 1.0
    # launch at most this many nodes per update (ref: upscaling_speed)
    max_launch_batch: int = 2


@dataclass
class _TrackedNode:
    provider_id: str
    node_idx: Optional[int] = None      # filled once it registers
    launched_at: float = field(default_factory=time.monotonic)
    idle_since: Optional[float] = None
    warned: bool = False                # label-contract warning emitted


class Autoscaler:
    """The update loop (ref: StandardAutoscaler.update)."""

    def __init__(self, head, provider: NodeProvider,
                 policy: Optional[AutoscalingPolicy] = None):
        self._head = head
        self._provider = provider
        self.policy = policy or AutoscalingPolicy()
        self._tracked: List[_TrackedNode] = []
        self._known_idxs: set = set()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.num_launches = 0
        self.num_terminations = 0

    # ------------------------------------------------------------ lifecycle

    def start(self):
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="autoscaler")
        self._thread.start()

    def stop(self):
        self._stop.set()

    def _loop(self):
        while not self._stop.wait(self.policy.update_interval_s):
            try:
                self.update()
            except Exception:  # noqa: BLE001 — scaling must not die
                pass

    # --------------------------------------------------------------- update

    def pending_demand(self) -> int:
        """Lease requests the cluster cannot currently grant (the
        reference reads per-raylet resource_load; our head already queues
        exactly the unsatisfiable requests)."""
        with self._head._lock:
            return len(self._head._pending_leases) + \
                len(self._head._pending_pg)

    def update(self):
        # ONE provider poll per update: a declarative provider resets its
        # pending ScaleRequest here (ref: batching_node_provider's
        # non_terminated_nodes contract)
        alive = list(self._provider.non_terminated_nodes())
        declarative = getattr(self._provider, "declarative", False)
        self._reconcile_membership(alive if declarative else None)
        demand = self.pending_demand()
        count = len(alive)
        if demand > 0:
            per_node = max(self._provider_cpus_per_node(), 1)
            want = math.ceil(demand / per_node)
            capacity = self.policy.max_workers - count
            n = min(want, self.policy.max_launch_batch, max(capacity, 0))
            for _ in range(n):
                pid = self._provider.create_node()
                if not declarative:
                    self._tracked.append(_TrackedNode(pid))
                self.num_launches += 1
                count += 1
        else:
            count -= self._scale_down(count)
        # honor min_workers
        for _ in range(max(self.policy.min_workers - count, 0)):
            pid = self._provider.create_node()
            if not declarative:
                self._tracked.append(_TrackedNode(pid))
            self.num_launches += 1
            count += 1
        # declarative providers flush all of the above as ONE request
        post = getattr(self._provider, "post_process", None)
        if post is not None:
            post()

    def _provider_cpus_per_node(self) -> int:
        return getattr(self._provider, "num_cpus", 1)

    def _reconcile_membership(self, provider_ids=None):
        """Match provider nodes to registered head nodes (by the launch
        label — adopting ANY new node would let scale-down evict remote
        drivers or hand-joined agents) + track idleness.

        ``provider_ids`` (declarative providers only): the cloud's
        current node list — ids the cloud materialized that we aren't
        tracking yet are adopted, and tracked ids the cloud no longer
        reports are dropped."""
        if provider_ids is not None:
            tracked_ids = {t.provider_id for t in self._tracked}
            for pid in provider_ids:
                if pid not in tracked_ids:
                    self._tracked.append(_TrackedNode(pid))
            gone = set(tracked_ids) - set(provider_ids)
            for t in list(self._tracked):
                if t.provider_id in gone:
                    self._tracked.remove(t)
                    self._known_idxs.discard(t.node_idx)
        with self._head._lock:
            remote = {idx: n for idx, n in self._head.nodes.items()
                      if n.is_remote and n.alive}
        by_provider_id = {
            n.resources.labels.get(PROVIDER_LABEL): idx
            for idx, n in remote.items()
            if n.resources.labels.get(PROVIDER_LABEL)}
        now_mono = time.monotonic()
        for t in self._tracked:
            if t.node_idx is None:
                idx = by_provider_id.get(t.provider_id)
                if idx is not None and idx not in self._known_idxs:
                    t.node_idx = idx
                    self._known_idxs.add(idx)
                elif now_mono - t.launched_at > 120 and not t.warned:
                    t.warned = True
                    import sys

                    print(
                        f"ray_tpu autoscaler: launch {t.provider_id} has "
                        f"not registered with label {PROVIDER_LABEL}="
                        f"{t.provider_id} after 120s — the provider must "
                        f"pass it or the node can never be scaled down "
                        f"(see NodeProvider docstring)", file=sys.stderr)
        now = time.monotonic()
        for t in self._tracked:
            node = remote.get(t.node_idx)
            if node is None:
                continue
            busy = any(w.state in ("leased", "actor", "starting")
                       for w in node.workers.values())
            if busy:
                t.idle_since = None
            elif t.idle_since is None:
                t.idle_since = now

    def _scale_down(self, alive: int) -> int:
        """Terminate idle tracked nodes; returns how many were removed."""
        now = time.monotonic()
        floor = self.policy.min_workers
        removed = 0
        for t in list(self._tracked):
            if alive <= floor:
                break
            if t.node_idx is None or t.idle_since is None:
                continue
            if now - t.idle_since < self.policy.idle_timeout_s:
                continue
            # drain head-side first, then the provider process
            try:
                self._head.remove_node(t.node_idx)
            except Exception:  # noqa: BLE001
                pass
            self._provider.terminate_node(t.provider_id)
            self._tracked.remove(t)
            self._known_idxs.discard(t.node_idx)
            self.num_terminations += 1
            alive -= 1
            removed += 1
        return removed
