"""Usage stats: opt-out feature-usage telemetry (collection side).

Ref parity: ray._private.usage.usage_lib (usage_lib.py:92
UsageStatsToReport, record_library_usage :190, report generation :455):
libraries record which features a cluster exercised; a periodic reporter
assembles a schema'd payload. Redesign notes: collection and transport
are split — this sealed-image build has zero egress, so the transport is
a file sink under the session dir (plus an injectable reporter hook for
deployments that have one), while the collection API and report schema
match the reference's shape. Opt-out via RAY_TPU_USAGE_STATS_ENABLED=0,
same default-on-with-notice policy as the reference.
"""

from __future__ import annotations

import json
import os
import platform
import threading
import time
from typing import Callable, Dict, Optional

_lock = threading.Lock()
_libraries: Dict[str, float] = {}   # name -> first-use unix time
_tags: Dict[str, str] = {}
_notice_printed = [False]


def usage_stats_enabled() -> bool:
    return os.environ.get("RAY_TPU_USAGE_STATS_ENABLED", "1") not in (
        "0", "false", "False")


def print_usage_stats_notice(out=None) -> None:
    """One-line collection notice on cluster start (ref prints the same
    from usage_lib's head-node hook)."""
    if _notice_printed[0] or not usage_stats_enabled():
        return
    _notice_printed[0] = True
    import sys

    print("Usage stats collection is enabled (local file sink only on "
          "this build). Disable with RAY_TPU_USAGE_STATS_ENABLED=0.",
          file=out or sys.stderr)


def record_library_usage(name: str) -> None:
    """Mark a library/feature as used (ref: record_library_usage).
    Cheap and always safe to call; a no-op when disabled."""
    if not usage_stats_enabled():
        return
    with _lock:
        _libraries.setdefault(name, time.time())


def record_extra_usage_tag(key: str, value: str) -> None:
    if not usage_stats_enabled():
        return
    with _lock:
        _tags[str(key)] = str(value)


def _cluster_metadata() -> dict:
    from ray_tpu._version import __version__

    meta = {
        "ray_tpu_version": __version__,
        "python_version": platform.python_version(),
        "os": platform.system().lower(),
    }
    try:  # backend info without forcing device init
        import jax

        meta["jax_version"] = jax.__version__
    except Exception:
        pass
    return meta


def generate_report() -> dict:
    """Assemble the report payload (ref: generate_report's
    UsageStatsToReport schema, trimmed to what exists here)."""
    with _lock:
        libs = sorted(_libraries)
        tags = dict(_tags)
    return {
        "schema_version": "0.1",
        "collected_at": int(time.time()),
        "library_usages": libs,
        "extra_usage_tags": tags,
        **_cluster_metadata(),
    }


def write_report(session_dir: str) -> Optional[str]:
    """File sink: usage_stats.json under the session dir. Returns the
    path, or None when disabled/unwritable."""
    if not usage_stats_enabled():
        return None
    try:
        os.makedirs(session_dir, exist_ok=True)
        path = os.path.join(session_dir, "usage_stats.json")
        with open(path, "w") as f:
            json.dump(generate_report(), f, indent=1)
        return path
    except OSError:
        return None


def report_via(reporter: Callable[[dict], None]) -> bool:
    """Injectable transport (the seam a network uploader would fill;
    ref posts to a usage server — zero-egress builds pass a collector).
    Returns False when disabled, True after the reporter ran."""
    if not usage_stats_enabled():
        return False
    reporter(generate_report())
    return True


def reset_for_testing() -> None:
    with _lock:
        _libraries.clear()
        _tags.clear()
    _notice_printed[0] = False
