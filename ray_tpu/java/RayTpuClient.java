// Java task-submission frontend for the ray_tpu head.
//
// Ref analog: the reference's Java runtime
// (java/runtime/.../RayNativeRuntime.java:38) drives the shared
// CoreWorker over JNI (~32k LoC). Re-design: no JNI and no native
// library — this client speaks the head's framed wire protocol directly,
// exactly like the C++ frontend (native/task_client.cc): it emits the
// one fixed pickle shape the protocol needs (a (msg_type, request_id,
// bytes) tuple; core/protocol.py XLANG_CALL=67) and receives replies as
// RAW frames of JSON, so no pickle parser exists on the Java side.
// Submission is by function descriptor ("module:qualname"),
// python/ray/cross_language.py:15's pattern.
//
//   javac RayTpuClient.java
//   java RayTpuClient <host:port> <module:qualname> '[1, 2]'
//
// NOTE: this image ships no JDK, so unlike task_client.cc this file is
// not compiled in CI here; the wire contract it uses IS covered by
// tests/test_cpp_client.py (same two frame shapes).

import java.io.DataInputStream;
import java.io.DataOutputStream;
import java.io.IOException;
import java.net.Socket;
import java.nio.ByteBuffer;
import java.nio.ByteOrder;
import java.nio.charset.StandardCharsets;

public final class RayTpuClient implements AutoCloseable {
    private static final long RAW_BIT = 1L << 63;
    private static final int XLANG_CALL = 67; // core/protocol.py

    private final Socket sock;
    private final DataInputStream in;
    private final DataOutputStream out;

    public RayTpuClient(String host, int port) throws IOException {
        this.sock = new Socket(host, port);
        this.in = new DataInputStream(sock.getInputStream());
        this.out = new DataOutputStream(sock.getOutputStream());
    }

    /** Send one XLANG_CALL request (JSON body) and block for the reply. */
    public String call(String reqJson) throws IOException {
        sendFrame(pickleCall(XLANG_CALL, 1,
                             reqJson.getBytes(StandardCharsets.UTF_8)));
        byte[] raw = readRawFrame();
        return new String(raw, StandardCharsets.UTF_8);
    }

    /** Submit module:qualname(argsJson...) and block for the JSON reply. */
    public String submit(String function, String argsJson, String optionsJson)
            throws IOException {
        return call("{\"op\":\"submit\",\"function\":\"" + function
                + "\",\"args\":" + argsJson + ",\"options\":"
                + optionsJson + "}");
    }

    /** Create a named actor from module:Class; returns the JSON reply
     *  whose result carries the registered actor name. */
    public String actorCreate(String cls, String argsJson,
                              String optionsJson) throws IOException {
        return call("{\"op\":\"actor_create\",\"class\":\"" + cls
                + "\",\"args\":" + argsJson + ",\"options\":"
                + optionsJson + "}");
    }

    public String actorCall(String actor, String method, String argsJson)
            throws IOException {
        return call("{\"op\":\"actor_call\",\"actor\":\"" + actor
                + "\",\"method\":\"" + method + "\",\"args\":"
                + argsJson + "}");
    }

    public String actorKill(String actor) throws IOException {
        return call("{\"op\":\"actor_kill\",\"actor\":\"" + actor + "\"}");
    }

    // (int, int, bytes) tuple, pickle protocol 3 — see task_client.cc
    // for the opcode walkthrough (PROTO, BININT, SHORT_BINBYTES/BINBYTES,
    // TUPLE3, STOP).
    static byte[] pickleCall(int msgType, int requestId, byte[] payload) {
        int head = 2 + 5 + 5 + (payload.length < 256 ? 2 : 5);
        ByteBuffer buf = ByteBuffer.allocate(head + payload.length + 2)
                .order(ByteOrder.LITTLE_ENDIAN);
        buf.put((byte) 0x80).put((byte) 3);
        buf.put((byte) 'J').putInt(msgType);
        buf.put((byte) 'J').putInt(requestId);
        if (payload.length < 256) {
            buf.put((byte) 'C').put((byte) payload.length);
        } else {
            buf.put((byte) 'B').putInt(payload.length);
        }
        buf.put(payload);
        buf.put((byte) 0x87).put((byte) '.');
        byte[] outBytes = new byte[buf.position()];
        buf.flip();
        buf.get(outBytes);
        return outBytes;
    }

    private void sendFrame(byte[] payload) throws IOException {
        ByteBuffer hdr = ByteBuffer.allocate(8)
                .order(ByteOrder.LITTLE_ENDIAN);
        hdr.putLong(payload.length);
        out.write(hdr.array());
        out.write(payload);
        out.flush();
    }

    /** Skip pickled frames; return the first RAW frame's bytes. */
    private byte[] readRawFrame() throws IOException {
        while (true) {
            byte[] hdr = new byte[8];
            in.readFully(hdr);
            long len = ByteBuffer.wrap(hdr)
                    .order(ByteOrder.LITTLE_ENDIAN).getLong();
            boolean raw = (len & RAW_BIT) != 0;
            len &= ~RAW_BIT;
            byte[] body = new byte[(int) len];
            in.readFully(body);
            if (raw) {
                return body;
            }
            // pickled frame for some other consumer (pubsub etc.) — skip
        }
    }

    @Override
    public void close() throws IOException {
        sock.close();
    }

    public static void main(String[] args) throws Exception {
        if (args.length < 2) {
            System.err.println(
                "usage: RayTpuClient <host:port> <module:qualname> "
                + "[json-args] [json-options]\n"
                + "       RayTpuClient <host:port> actor-create "
                + "<module:Class> [json-args] [json-options]\n"
                + "       RayTpuClient <host:port> actor-call "
                + "<actor> <method> [json-args]\n"
                + "       RayTpuClient <host:port> actor-kill <actor>");
            System.exit(2);
        }
        String[] hp = args[0].replaceFirst("^tcp:", "").split(":");
        try (RayTpuClient client =
                 new RayTpuClient(hp[0], Integer.parseInt(hp[1]))) {
            String reply;
            switch (args[1]) {
                case "actor-create":
                    if (args.length < 3) {
                        System.err.println(
                            "actor-create needs <module:Class>");
                        System.exit(2);
                    }
                    reply = client.actorCreate(
                        args[2],
                        args.length > 3 ? args[3] : "[]",
                        args.length > 4 ? args[4] : "{}");
                    break;
                case "actor-call":
                    if (args.length < 4) {
                        System.err.println(
                            "actor-call needs <actor> <method>");
                        System.exit(2);
                    }
                    reply = client.actorCall(
                        args[2], args[3],
                        args.length > 4 ? args[4] : "[]");
                    break;
                case "actor-kill":
                    if (args.length < 3) {
                        System.err.println("actor-kill needs <actor>");
                        System.exit(2);
                    }
                    reply = client.actorKill(args[2]);
                    break;
                default:
                    reply = client.submit(
                        args[1],
                        args.length > 2 ? args[2] : "[]",
                        args.length > 3 ? args[3] : "{}");
            }
            System.out.println(reply);
            System.exit(reply.contains("\"status\": \"ok\"")
                        || reply.contains("\"status\":\"ok\"") ? 0 : 1);
        }
    }
}
