"""Runtime environments: per-job/task/actor execution environment.

Ref parity: ray runtime_env (python/ray/_private/runtime_env/ — the agent
at runtime_env_agent.py:159 materializes env_vars / working_dir /
py_modules / pip per task). Re-designed for a pre-baked TPU image:

- ``env_vars``: dict applied around task execution (saved/restored).
- ``working_dir``: a local directory, packed and shipped through the
  head KV store (the reference uploads to GCS the same way); workers
  extract once per content digest and chdir into it for the task.
- ``py_modules``: list of local package dirs shipped the same way and
  prepended to sys.path.
- ``pip``: a venv-overlay (ref: runtime_env/pip.py). A virtualenv with
  ``--system-site-packages`` is created per requirements digest; unmet
  requirements are installed **offline** with ``pip install --no-index
  --find-links <RAY_TPU_WHEEL_DIRS>`` (colon-separated local wheel
  dirs). Requirements already satisfied by the baked image are
  verified, not reinstalled. The venv's site-packages is prepended to
  ``sys.path`` around task execution. No-network installs only: a
  requirement that is neither baked in nor available as a local wheel
  fails with a clear error (this is a sealed TPU image — there is no
  package index at runtime).
- ``conda``: rejected with a clear error — no conda on the image.

Size cap: packed archives ride the control-plane KV, so each is capped
(default 64 MiB) — big data belongs in the object store, not the env.
"""

from __future__ import annotations

import hashlib
import io
import os
import sys
import zipfile
from typing import Any, Dict, List, Optional

MAX_ARCHIVE_BYTES = 64 * 1024 * 1024
_EXCLUDE_DIRS = {"__pycache__", ".git", ".venv", "node_modules"}

KV_NS = "runtime_env"


def validate(env: Optional[Dict[str, Any]]) -> Optional[Dict[str, Any]]:
    if not env:
        return None
    known = {"env_vars", "working_dir", "py_modules", "pip", "conda"}
    unknown = set(env) - known
    if unknown:
        raise ValueError(f"unknown runtime_env keys: {sorted(unknown)}")
    if env.get("conda"):
        raise ValueError(
            "runtime_env conda is not supported on this sealed image; "
            "use pip (offline venv overlay) or pre-bake dependencies")
    pip = env.get("pip")
    if pip is not None:
        if isinstance(pip, dict):
            pip = pip.get("packages", [])
        if not (isinstance(pip, list) and
                all(isinstance(r, str) for r in pip)):
            raise ValueError(
                "runtime_env pip must be a list of requirement strings "
                "or {'packages': [...]}")
    ev = env.get("env_vars")
    if ev is not None and not (
            isinstance(ev, dict) and
            all(isinstance(k, str) and isinstance(v, str)
                for k, v in ev.items())):
        raise ValueError("env_vars must be a Dict[str, str]")
    return env


def _pack_dir(path: str) -> bytes:
    buf = io.BytesIO()
    with zipfile.ZipFile(buf, "w", zipfile.ZIP_DEFLATED) as zf:
        for root, dirs, files in os.walk(path):
            dirs[:] = [d for d in dirs if d not in _EXCLUDE_DIRS]
            for f in files:
                full = os.path.join(root, f)
                zf.write(full, os.path.relpath(full, path))
    blob = buf.getvalue()
    if len(blob) > MAX_ARCHIVE_BYTES:
        raise ValueError(
            f"runtime_env directory {path!r} packs to "
            f"{len(blob) >> 20} MiB (cap {MAX_ARCHIVE_BYTES >> 20} MiB); "
            f"ship large data through the object store instead")
    return blob


def upload(ctx, env: Dict[str, Any]) -> Dict[str, Any]:
    """Driver side: pack local dirs into the head KV, rewrite the env to
    digest URIs (reference: working_dir upload + GCS URIs)."""
    out = dict(env)
    for key in ("working_dir", "py_modules"):
        val = env.get(key)
        if not val:
            continue
        paths: List[str] = [val] if isinstance(val, str) else list(val)
        uris = []
        for p in paths:
            if p.startswith("kv://"):
                uris.append(p)
                continue
            if not os.path.isdir(p):
                raise ValueError(f"runtime_env {key}: {p!r} is not a "
                                 f"directory")
            blob = _pack_dir(p)
            digest = hashlib.sha256(blob).hexdigest()[:16]
            uri = f"kv://{digest}"
            ctx.kv_put(KV_NS, digest, blob, overwrite=False)
            uris.append(uri)
        out[key] = uris[0] if key == "working_dir" else uris
    return out


def _materialize(ctx, uri: str) -> str:
    """Worker side: fetch + extract an archive once per digest."""
    digest = uri[len("kv://"):]
    dest = os.path.join(ctx.session_dir, "runtime_envs", digest)
    if os.path.isdir(dest):
        return dest
    blob = ctx.kv_get(KV_NS, digest)
    if blob is None:
        raise ValueError(f"runtime_env archive {uri} not found in KV")
    # per-process tmp dir: concurrent workers materializing the same
    # digest must not extract into one shared staging path
    tmp = f"{dest}.tmp.{os.getpid()}"
    os.makedirs(tmp, exist_ok=True)
    with zipfile.ZipFile(io.BytesIO(blob)) as zf:
        zf.extractall(tmp)
    try:
        os.rename(tmp, dest)
    except OSError:  # raced with another worker — theirs is identical
        import shutil

        shutil.rmtree(tmp, ignore_errors=True)
    return dest


def _pip_requirements(env: Dict[str, Any]) -> List[str]:
    pip = env.get("pip")
    if not pip:
        return []
    if isinstance(pip, dict):
        pip = pip.get("packages", [])
    return list(pip)


def _satisfied(req: str, _depth: int = 0) -> bool:
    """True when the baked image already satisfies the requirement —
    including environment markers (a marker-excluded requirement is
    vacuously satisfied, not an install failure) and extras (each
    extra's own dependency set must be present too)."""
    from importlib import metadata

    from packaging.requirements import InvalidRequirement, Requirement

    try:
        r = Requirement(req)
    except InvalidRequirement:
        return False
    if r.marker is not None and not r.marker.evaluate():
        return True  # requirement does not apply on this platform
    try:
        installed = metadata.version(r.name)
    except metadata.PackageNotFoundError:
        return False
    if not r.specifier.contains(installed, prereleases=True):
        return False
    if r.extras and _depth < 4:
        for dep in metadata.requires(r.name) or []:
            try:
                d = Requirement(dep)
            except InvalidRequirement:
                continue
            if d.marker is None:
                continue  # base dep, already present with the package
            for extra in r.extras:
                if d.marker.evaluate({"extra": extra}):
                    base = str(d).split(";", 1)[0].strip()
                    if not _satisfied(base, _depth + 1):
                        return False
    return True


def _ensure_venv(ctx, reqs: List[str]) -> str:
    """Worker side: build (once per digest) the venv overlay for a pip
    requirements list; returns its site-packages dir.

    Offline by design: unmet requirements install from local wheel dirs
    (``RAY_TPU_WHEEL_DIRS``, colon-separated) with ``--no-index``."""
    import subprocess
    import venv as venv_mod

    digest = hashlib.sha256(
        ("\n".join(sorted(reqs))).encode()).hexdigest()[:16]
    dest = os.path.join(ctx.session_dir, "runtime_envs",
                        f"venv-{digest}")
    site = os.path.join(
        dest, "lib",
        f"python{sys.version_info.major}.{sys.version_info.minor}",
        "site-packages")
    if os.path.isdir(dest):
        return site
    unmet = [r for r in reqs if not _satisfied(r)]
    tmp = f"{dest}.tmp.{os.getpid()}"
    # with_pip (ensurepip) costs seconds; skip it when nothing installs
    venv_mod.EnvBuilder(system_site_packages=True, with_pip=bool(unmet),
                        symlinks=True).create(tmp)
    if unmet:
        wheel_dirs = [d for d in
                      os.environ.get("RAY_TPU_WHEEL_DIRS", "").split(":")
                      if d]
        cmd = [os.path.join(tmp, "bin", "python"), "-m", "pip",
               "install", "--quiet", "--no-index"]
        for d in wheel_dirs:
            cmd += ["--find-links", d]
        cmd += unmet
        try:
            proc = subprocess.run(cmd, capture_output=True, text=True,
                                  timeout=300)
            err, rc = proc.stderr, proc.returncode
        except subprocess.TimeoutExpired as e:
            err, rc = f"pip timed out after {e.timeout}s", -1
        if rc != 0:
            import shutil

            # a half-built tmp venv must not survive: a same-pid retry
            # would EnvBuilder.create() over it and cache the corruption
            shutil.rmtree(tmp, ignore_errors=True)
            raise RuntimeError(
                f"runtime_env pip: cannot satisfy {unmet} offline — not "
                f"baked into the image and no matching wheel under "
                f"RAY_TPU_WHEEL_DIRS={wheel_dirs or '(unset)'}; this is "
                f"a sealed image with no package index.\n"
                f"{(err or '').strip()[-2000:]}")
    try:
        os.rename(tmp, dest)
    except OSError:  # raced with another worker — theirs is identical
        import shutil

        shutil.rmtree(tmp, ignore_errors=True)
    return site


def _overlay_top_level(site: str) -> List[str]:
    """Top-level importable names a pip venv overlay provides: the
    package dirs and modules pip installed into its site-packages
    (``--system-site-packages`` venvs start empty, so everything present
    was installed for THIS requirements digest), refined by each
    dist-info's ``top_level.txt`` when present."""
    names = set()
    try:
        entries = os.listdir(site)
    except OSError:
        return []
    for entry in entries:
        path = os.path.join(site, entry)
        if entry.endswith(".dist-info"):
            try:
                with open(os.path.join(path, "top_level.txt")) as f:
                    names.update(ln.strip() for ln in f if ln.strip())
            except OSError:
                pass
        elif entry.endswith(".py") and not entry.startswith("_"):
            names.add(entry[:-3])
        elif os.path.isdir(path) and not entry.startswith("_") \
                and "." not in entry:
            names.add(entry)
    return sorted(names)


def _purge_shadowed_modules(site: str):
    """Drop sys.modules entries for names the overlay provides whose
    cached import came from OUTSIDE the overlay (the baked image): the
    next import inside the task resolves through the overlay's
    site-packages at the head of sys.path, so the requested version
    actually loads."""
    root = os.path.abspath(site) + os.sep
    tops = set(_overlay_top_level(site))
    if not tops:
        return
    purged = []
    for name, mod in list(sys.modules.items()):
        if name.split(".", 1)[0] not in tops:
            continue
        f = getattr(mod, "__file__", None)
        under = bool(f and os.path.abspath(f).startswith(root))
        if not under:
            for p in list(getattr(mod, "__path__", None) or []):
                if os.path.abspath(p).startswith(root):
                    under = True
                    break
        if not under:
            del sys.modules[name]
            purged.append(name)
    if purged:
        roots = sorted({n.split('.', 1)[0] for n in purged})
        print(f"[ray_tpu] runtime_env pip overlay: purged "
              f"{len(purged)} cached baked-image modules shadowing the "
              f"requested versions ({', '.join(roots[:5])}"
              f"{'...' if len(roots) > 5 else ''})", file=sys.stderr)


class applied:
    """Context manager applying a runtime_env around one task execution
    (the reference applies per worker-process; our workers are pooled
    per scheduling class, so env application is scoped to the task)."""

    def __init__(self, ctx, env: Optional[Dict[str, Any]]):
        self._ctx = ctx
        self._env = env or {}
        self._saved_environ: Dict[str, Optional[str]] = {}
        self._saved_cwd: Optional[str] = None
        self._added_paths: List[str] = []

    def __enter__(self):
        # a failure mid-application (e.g. an unsatisfiable pip env) must
        # roll back what was already applied: the with-statement will not
        # call __exit__ after a raising __enter__, and a pooled worker
        # would otherwise keep the partial env forever
        try:
            env = self._env
            for k, v in (env.get("env_vars") or {}).items():
                self._saved_environ[k] = os.environ.get(k)
                os.environ[k] = v
            wd = env.get("working_dir")
            if wd:
                path = _materialize(self._ctx, wd)
                self._saved_cwd = os.getcwd()
                os.chdir(path)
                sys.path.insert(0, path)
                self._added_paths.append(path)
            for uri in env.get("py_modules") or []:
                path = _materialize(self._ctx, uri)
                sys.path.insert(0, path)
                self._added_paths.append(path)
            reqs = _pip_requirements(env)
            if reqs:
                site = _ensure_venv(self._ctx, reqs)
                sys.path.insert(0, site)
                self._added_paths.append(site)
                # Evict already-imported BAKED modules the overlay
                # provides: workers are pooled, so an earlier task may
                # have imported package X from the image — without this
                # a task requesting pip=['X==2.0'] silently keeps
                # running the cached baked version (sys.path order only
                # decides FUTURE imports). The __exit__ purge below then
                # removes the overlay-origin modules, so the next task
                # re-imports the baked ones cleanly.
                _purge_shadowed_modules(site)
        except BaseException:
            self.__exit__(*sys.exc_info())
            raise
        return self

    def __exit__(self, *exc):
        # purge modules imported from overlay paths: workers are pooled,
        # so a cached import would leak this env's packages into later
        # tasks that did not request them
        if self._added_paths:
            roots = tuple(os.path.abspath(p) + os.sep
                          for p in self._added_paths)

            def _under(mod) -> bool:
                f = getattr(mod, "__file__", None)
                if f and os.path.abspath(f).startswith(roots):
                    return True
                # namespace packages have __file__=None but carry the
                # overlay in __path__
                for p in list(getattr(mod, "__path__", None) or []):
                    if os.path.abspath(p).startswith(roots):
                        return True
                return False

            for name, mod in list(sys.modules.items()):
                if _under(mod):
                    del sys.modules[name]
        for p in self._added_paths:
            try:
                sys.path.remove(p)
            except ValueError:
                pass
        if self._saved_cwd is not None:
            try:
                os.chdir(self._saved_cwd)
            except OSError:
                pass
        for k, old in self._saved_environ.items():
            if old is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = old
        return False
