"""Runtime environments: per-job/task/actor execution environment.

Ref parity: ray runtime_env (python/ray/_private/runtime_env/ — the agent
at runtime_env_agent.py:159 materializes env_vars / working_dir /
py_modules / pip per task). Re-designed for a pre-baked TPU image:

- ``env_vars``: dict applied around task execution (saved/restored).
- ``working_dir``: a local directory, packed and shipped through the
  head KV store (the reference uploads to GCS the same way); workers
  extract once per content digest and chdir into it for the task.
- ``py_modules``: list of local package dirs shipped the same way and
  prepended to sys.path.
- ``pip`` / ``conda``: rejected with a clear error — this environment is
  a sealed image with no package index; dependencies must be pre-baked
  (matching how TPU pod images are operated).

Size cap: packed archives ride the control-plane KV, so each is capped
(default 64 MiB) — big data belongs in the object store, not the env.
"""

from __future__ import annotations

import hashlib
import io
import os
import sys
import zipfile
from typing import Any, Dict, List, Optional

MAX_ARCHIVE_BYTES = 64 * 1024 * 1024
_EXCLUDE_DIRS = {"__pycache__", ".git", ".venv", "node_modules"}

KV_NS = "runtime_env"


def validate(env: Optional[Dict[str, Any]]) -> Optional[Dict[str, Any]]:
    if not env:
        return None
    known = {"env_vars", "working_dir", "py_modules", "pip", "conda"}
    unknown = set(env) - known
    if unknown:
        raise ValueError(f"unknown runtime_env keys: {sorted(unknown)}")
    if env.get("pip") or env.get("conda"):
        raise ValueError(
            "runtime_env pip/conda are not supported on this sealed image "
            "(no package index at runtime); pre-bake dependencies into "
            "the image instead")
    ev = env.get("env_vars")
    if ev is not None and not (
            isinstance(ev, dict) and
            all(isinstance(k, str) and isinstance(v, str)
                for k, v in ev.items())):
        raise ValueError("env_vars must be a Dict[str, str]")
    return env


def _pack_dir(path: str) -> bytes:
    buf = io.BytesIO()
    with zipfile.ZipFile(buf, "w", zipfile.ZIP_DEFLATED) as zf:
        for root, dirs, files in os.walk(path):
            dirs[:] = [d for d in dirs if d not in _EXCLUDE_DIRS]
            for f in files:
                full = os.path.join(root, f)
                zf.write(full, os.path.relpath(full, path))
    blob = buf.getvalue()
    if len(blob) > MAX_ARCHIVE_BYTES:
        raise ValueError(
            f"runtime_env directory {path!r} packs to "
            f"{len(blob) >> 20} MiB (cap {MAX_ARCHIVE_BYTES >> 20} MiB); "
            f"ship large data through the object store instead")
    return blob


def upload(ctx, env: Dict[str, Any]) -> Dict[str, Any]:
    """Driver side: pack local dirs into the head KV, rewrite the env to
    digest URIs (reference: working_dir upload + GCS URIs)."""
    out = dict(env)
    for key in ("working_dir", "py_modules"):
        val = env.get(key)
        if not val:
            continue
        paths: List[str] = [val] if isinstance(val, str) else list(val)
        uris = []
        for p in paths:
            if p.startswith("kv://"):
                uris.append(p)
                continue
            if not os.path.isdir(p):
                raise ValueError(f"runtime_env {key}: {p!r} is not a "
                                 f"directory")
            blob = _pack_dir(p)
            digest = hashlib.sha256(blob).hexdigest()[:16]
            uri = f"kv://{digest}"
            ctx.kv_put(KV_NS, digest, blob, overwrite=False)
            uris.append(uri)
        out[key] = uris[0] if key == "working_dir" else uris
    return out


def _materialize(ctx, uri: str) -> str:
    """Worker side: fetch + extract an archive once per digest."""
    digest = uri[len("kv://"):]
    dest = os.path.join(ctx.session_dir, "runtime_envs", digest)
    if os.path.isdir(dest):
        return dest
    blob = ctx.kv_get(KV_NS, digest)
    if blob is None:
        raise ValueError(f"runtime_env archive {uri} not found in KV")
    # per-process tmp dir: concurrent workers materializing the same
    # digest must not extract into one shared staging path
    tmp = f"{dest}.tmp.{os.getpid()}"
    os.makedirs(tmp, exist_ok=True)
    with zipfile.ZipFile(io.BytesIO(blob)) as zf:
        zf.extractall(tmp)
    try:
        os.rename(tmp, dest)
    except OSError:  # raced with another worker — theirs is identical
        import shutil

        shutil.rmtree(tmp, ignore_errors=True)
    return dest


class applied:
    """Context manager applying a runtime_env around one task execution
    (the reference applies per worker-process; our workers are pooled
    per scheduling class, so env application is scoped to the task)."""

    def __init__(self, ctx, env: Optional[Dict[str, Any]]):
        self._ctx = ctx
        self._env = env or {}
        self._saved_environ: Dict[str, Optional[str]] = {}
        self._saved_cwd: Optional[str] = None
        self._added_paths: List[str] = []

    def __enter__(self):
        env = self._env
        for k, v in (env.get("env_vars") or {}).items():
            self._saved_environ[k] = os.environ.get(k)
            os.environ[k] = v
        wd = env.get("working_dir")
        if wd:
            path = _materialize(self._ctx, wd)
            self._saved_cwd = os.getcwd()
            os.chdir(path)
            sys.path.insert(0, path)
            self._added_paths.append(path)
        for uri in env.get("py_modules") or []:
            path = _materialize(self._ctx, uri)
            sys.path.insert(0, path)
            self._added_paths.append(path)
        return self

    def __exit__(self, *exc):
        for p in self._added_paths:
            try:
                sys.path.remove(p)
            except ValueError:
                pass
        if self._saved_cwd is not None:
            try:
                os.chdir(self._saved_cwd)
            except OSError:
                pass
        for k, old in self._saved_environ.items():
            if old is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = old
        return False
