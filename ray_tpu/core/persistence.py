"""Head control-plane persistence: a write-ahead log for GCS-lite tables.

The reference keeps its GCS tables (KV, named/detached actors, placement
groups, job table) in an external Redis so a restarted GCS recovers the
control plane (`/root/reference/src/ray/gcs/store_client/redis_store_client.h`,
`gcs_server.cc` RaySyncer bootstrap). A TPU-pod head has no Redis; instead
the head appends every durable mutation to a length-prefixed pickle WAL in
the session directory and replays it on construction. Compaction rewrites
the log as one snapshot record when it grows past a threshold.

Durable records (everything else — leases, object directory, transient
worker state — is rebuilt by the live cluster re-registering):

- ``("kv_put", ns, key, value)`` / ``("kv_del", ns, key)``
- ``("actor", spec_bytes)``        named (detached) actor created
- ``("actor_gone", actor_id_bin)`` named actor permanently dead/killed
- ``("pg", spec_bytes)``           placement group created
- ``("pg_gone", pg_id_bin)``       placement group removed
- ``("dedupe", client_id, rid)``   a client request that produced one of
                                   the durable mutations above was
                                   applied — a restarted head re-acks a
                                   retried copy instead of applying it
                                   twice (GCS-FT request dedupe)
- ``("snapshot", state_dict)``     compaction record (always first after
                                   a compaction; replay starts from it)
"""

from __future__ import annotations

import os
import pickle
import struct
import threading
from typing import Any, Dict, List, Optional, Tuple

_LEN = struct.Struct("<Q")

WAL_NAME = "head_state.wal"


class HeadStore:
    """Append-only durable log for the head's control-plane tables."""

    def __init__(self, session_dir: str,
                 compact_threshold_bytes: int = 8 * 1024 * 1024):
        self.path = os.path.join(session_dir, WAL_NAME)
        self._lock = threading.Lock()
        self._compact_threshold = compact_threshold_bytes
        # Exclusive advisory lock: two live heads appending to one WAL
        # from separate handles would interleave length-prefix/payload
        # writes and corrupt the log. Held for the head's lifetime.
        self._lockfile = open(self.path + ".lock", "a+")
        try:
            import fcntl

            fcntl.flock(self._lockfile, fcntl.LOCK_EX | fcntl.LOCK_NB)
        except OSError:
            self._lockfile.close()
            raise RuntimeError(
                f"another head already owns session dir "
                f"{session_dir!r} (WAL lock held)")
        self._records: List[tuple] = []
        if os.path.exists(self.path):
            self._records = _read_all(self.path)
        self._f = open(self.path, "ab")

    # ------------------------------------------------------------- write

    def append(self, record: tuple):
        blob = pickle.dumps(record, protocol=5)
        with self._lock:
            self._f.write(_LEN.pack(len(blob)))
            self._f.write(blob)
            self._f.flush()
            if self._f.tell() > self._compact_threshold:
                self._compact_locked()

    def _compact_locked(self):
        state = replay(_read_all(self.path))
        tmp = self.path + ".tmp"
        blob = pickle.dumps(("snapshot", state), protocol=5)
        with open(tmp, "wb") as f:
            f.write(_LEN.pack(len(blob)))
            f.write(blob)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.path)
        self._f.close()
        self._f = open(self.path, "ab")

    def close(self):
        with self._lock:
            try:
                self._f.close()
            except OSError:
                pass
            try:
                self._lockfile.close()  # releases the flock
            except OSError:
                pass

    # ------------------------------------------------------------- read

    def restore(self) -> Optional[Dict[str, Any]]:
        """State replayed from the records found on disk at open time
        (i.e. a previous head's writes), or None for a fresh session."""
        if not self._records:
            return None
        return replay(self._records)


def _read_all(path: str) -> List[tuple]:
    records: List[tuple] = []
    try:
        with open(path, "rb") as f:
            data = f.read()
    except OSError:
        return records
    off = 0
    n = len(data)
    while off + 8 <= n:
        (ln,) = _LEN.unpack_from(data, off)
        off += 8
        if off + ln > n:  # torn tail write from a crashed head — drop it
            break
        try:
            records.append(pickle.loads(data[off:off + ln]))
        except Exception:  # noqa: BLE001 — corrupt record ends the log
            break
        off += ln
    return records


def replay(records: List[tuple]) -> Dict[str, Any]:
    """Fold the WAL into the durable-state dict.

    Returns ``{"kv": {ns: {key: value}}, "actors": {actor_id_bin:
    spec_bytes}, "pgs": {pg_id_bin: spec_bytes}, "dedupe":
    [(client_id, rid), ...]}``.
    """
    kv: Dict[Any, Dict[Any, Any]] = {}
    actors: Dict[bytes, bytes] = {}
    pgs: Dict[bytes, bytes] = {}
    dedupe: Dict[Tuple[str, int], None] = {}  # insertion-ordered set
    for rec in records:
        kind = rec[0]
        if kind == "snapshot":
            state = rec[1]
            kv = {ns: dict(t) for ns, t in state.get("kv", {}).items()}
            actors = dict(state.get("actors", {}))
            pgs = dict(state.get("pgs", {}))
            dedupe = dict.fromkeys(
                tuple(k) for k in state.get("dedupe", ()))
        elif kind == "dedupe":
            dedupe[(rec[1], rec[2])] = None
        elif kind == "kv_put":
            _, ns, key, value = rec
            kv.setdefault(ns, {})[key] = value
        elif kind == "kv_del":
            _, ns, key = rec
            kv.get(ns, {}).pop(key, None)
        elif kind == "actor":
            spec_bytes = rec[1]
            actors[_actor_key(spec_bytes)] = spec_bytes
        elif kind == "actor_gone":
            actors.pop(rec[1], None)
        elif kind == "pg":
            spec_bytes = rec[1]
            pgs[_pg_key(spec_bytes)] = spec_bytes
        elif kind == "pg_gone":
            pgs.pop(rec[1], None)
    # bound what a snapshot / restore carries: only recent request ids
    # matter (a client retries within head_reconnect_timeout_s)
    keys = list(dedupe)[-4096:]
    return {"kv": kv, "actors": actors, "pgs": pgs, "dedupe": keys}


def _actor_key(spec_bytes: bytes) -> bytes:
    from .serialization import loads

    return loads(spec_bytes).actor_id.binary()


def _pg_key(spec_bytes: bytes) -> bytes:
    from .serialization import loads

    return loads(spec_bytes).pg_id.binary()
