"""Wire protocol: framed messages over unix-domain sockets.

Analog role: the reference's gRPC services (src/ray/rpc/, 25 protos). On a
TPU pod the control plane is host-to-host over DCN; here we implement the
same message surface over length-prefixed pickled frames on unix/TCP sockets
— a head connection per process (GCS+raylet client) plus direct
worker-to-worker connections for task/actor push (the reference's
CoreWorkerService PushTask, core_worker.proto:415).

Messages are tuples ``(msg_type, request_id, *fields)``. ``request_id`` > 0
means a reply is expected (RPC); 0 means one-way.
"""

from __future__ import annotations

import itertools
import pickle
import select
import socket
import struct
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Tuple

# 8-byte length prefix: the top bit marks RAW frames, and pickled frames of
# several GiB (relay fallback of large spilled objects) must still fit.
_LEN = struct.Struct("<Q")

# --- message types ---------------------------------------------------------
# worker <-> head (GCS + raylet services)
REGISTER = 1            # (worker_id_hex, pid, listen_addr, node_idx)
REGISTER_REPLY = 2
LEASE_REQUEST = 3       # (sched_class_key, resources_dict, job_id_hex, strategy)
LEASE_REPLY = 4         # (ok, worker_id_hex, listen_addr, lease_id, err)
RETURN_WORKER = 5       # (lease_id, worker_id_hex)
CREATE_ACTOR = 6        # (actor_spec_bytes)
CREATE_ACTOR_REPLY = 7
GET_ACTOR = 8           # (actor_id_binary)
GET_ACTOR_REPLY = 9     # (state, listen_addr)
KV_PUT = 10             # (ns, key, value, overwrite)
KV_GET = 11             # (ns, key)
KV_DEL = 12
KV_KEYS = 13
SUBSCRIBE = 14          # (channel,)
PUBLISH = 15            # (channel, payload)
OBJECT_SEALED = 16      # (object_id_bin, node_idx, size, owner_hex
#                         [, job_id_hex]) — the trailing job id (memory
#                         observatory) is optional for wire compat with
#                         pre-r20 senders; the handler defaults it to "".
OBJECT_LOCATE = 17      # (object_id_bin)
OBJECT_LOCATE_REPLY = 18  # (node_idx or -1, size, spilled_url)
OBJECT_FREE = 19        # (object_id_bins,)
BORROW_ADD = 20         # (object_id_bin, borrower_hex)
BORROW_REMOVE = 21
CREATE_PG = 22          # (pg_spec_bytes)
CREATE_PG_REPLY = 23
REMOVE_PG = 24
ACTOR_DEAD = 25         # notification (actor_id_bin, err)
KILL_ACTOR = 26         # (actor_id_bin, no_restart)
NODE_INFO = 27          # request cluster node table
NODE_INFO_REPLY = 28
DRAIN_NODE = 29         # (node_idx,) -> ok — graceful drain (r16): the
#                         head excludes the node from lease grants /
#                         placements / prefetch targets, replicates its
#                         sole-copy objects off via the pull machinery,
#                         publishes "node_draining" so workloads migrate
#                         proactively (pipeline stage migration), waits
#                         for in-flight leases up to drain_deadline_s,
#                         then fires the deliberate SHUTDOWN_NODE
#                         removal (drain_forced past the deadline).
#                         Reference: NodeManager::HandleDrainNode, the
#                         autoscaler's planned-scale-down path.
OBJECT_TRANSFER = 30    # (object_id_bin, to_node_idx) - ask head to arrange
OBJECT_CHUNK = 31       # (object_id_bin, chunk_idx, n_chunks, payload)
WORKER_EXIT = 32        # worker announces clean exit
CANCEL_TASK = 33        # (task_id_bin, force)
ERROR_REPLY = 34
TASK_EVENTS = 35        # (events_list,) buffered task state events -> GCS
JOB_SUBMIT = 36
PING = 37
OK = 38

# head <-> node agent (remote-host membership; the reference's raylet
# registration over gRPC, src/ray/gcs/gcs_server gcs_node_manager)
REGISTER_NODE = 39        # (node_resources, store_name, node_ip, session_dir)
REGISTER_NODE_REPLY = 40  # (node_idx, session_name)
SPAWN_WORKER = 41         # head->agent: (worker_id,)
KILL_WORKER = 42          # head->agent: (worker_id,)
AGENT_OBJ_GET = 43        # head->agent: (oid_bin) -> (payload, meta) | error
AGENT_OBJ_PUT = 44        # head->agent: (oid_bin, payload, meta)
AGENT_OBJ_FREE = 45       # head->agent: (oid_bins,)

# worker <-> worker (direct transport)
PUSH_TASK = 50          # (task_spec_bytes, seqno)
TASK_REPLY = 51         # (task_id_bin, status, result_meta, err)  [rpc reply]
STEAL_BACK = 52
PUSH_CANCEL = 53        # (task_id_bin, force)
PUSH_TASK_BATCH = 54    # ([task_specs],) one frame, one pickle, one syscall
TASK_DONE_BATCH = 55    # ([(task_id_bin, status, result_meta, err)],) the
#                         return-side mirror of PUSH_TASK_BATCH: a worker
#                         that finished several tasks between io-loop
#                         ticks acks them all in ONE frame (small inline
#                         returns ride along), collapsing the async
#                         return flood from one pickle + one locked
#                         syscall per task to a handful per drain

# peer-to-peer object transfer (object_transfer.py; the reference's
# ObjectManagerService chunked pull, object_manager.proto:61)
PULL_OBJECT = 56        # head->agent: (oid_bin, [holder_addrs], size[,
#                         max_sources, [relay_addrs]]) -> ok (a single
#                         addr string is accepted for compat).
#                         max_sources caps the stripe width (0 = config
#                         default); relay_addrs marks which of the
#                         holder addrs are IN-PROGRESS pullers serving
#                         partial objects (cooperative broadcast) — the
#                         puller waits for those instead of failing fast
OBJ_PULL = 57           # puller->server, one-way: (oid_bin, start,
#                         length[, wait_s]); length -1 = "through end of
#                         object". Disjoint ranges of one object may be
#                         requested from different holders concurrently
#                         (striped pull, the reference's PullManager
#                         chunk fan-out). wait_s > 0: the server may
#                         serve a PARTIALLY present object, waiting up
#                         to wait_s for it to appear / for each next
#                         chunk to land (relay of an in-progress pull)
OBJ_PULL_CHUNK = 58     # server->puller header: (oid_bin, offset);
#                         the chunk bytes follow as ONE raw frame
OBJ_PULL_DONE = 59      # server->puller: (oid_bin, start, length) — the
#                         requested range has been fully streamed
RAW_FRAME = 60          # synthetic msg type for raw frames: (RAW_FRAME, 0, bytes)
OBJ_PULL_META = 61      # server->puller: (oid_bin, size|-1, meta_bytes)
OBJECT_RECOVERING = 62  # owner->head: ([oid_bins],) lineage re-execution began
RECOVER_OBJECT = 63     # borrower->head->owner: (oid_bin, owner_hex) please
                        # reconstruct — the lineage lives with the owner
STATE_QUERY = 64        # (kind, limit) -> ([rows],) observability state API
SEAL_ABORTED = 65       # owner->head: ([oid_bins],) the creating task failed
                        # permanently — these ids will never seal; fail any
                        # blocked locate waiters instead of hanging them
METRICS_REPORT = 66     # ([(kind, name, desc, meta, tags_key, value)],)
                        # per-process metric deltas -> head aggregate
XLANG_CALL = 67         # (json_bytes,) cross-language frontend (C++ task
                        # submission): {"op": "submit", "function":
                        # "module:qualname", "args": [...]} — the head
                        # executes on behalf of the client and replies
                        # with a RAW frame of JSON {"rid", "status",
                        # "result"|"error"} (raw so non-Python clients
                        # never parse pickle)
OBJ_LOCATION_ADD = 68   # (oid_bin, node_idx, size) a node gained a copy
                        # (pull completion / replica creation) — the head
                        # adds it to the object directory's holder set
                        # (reference: ObjectDirectory location updates,
                        # src/ray/object_manager/object_directory.h)
OBJ_LOCATION_REMOVE = 69  # ([oid_bins], node_idx) a node dropped copies
                        # (eviction/deletion) — remove from holder sets;
                        # batched: one message per eviction sweep
OBJ_LOCATION_LOOKUP = 70  # (oid_bin) -> ([holder_idxs], [transfer_addrs],
                        # size, spilled_url) full holder-set query
CLUSTER_EVENT = 71      # ([(ts, severity, source, node_idx, entity_id,
                        # type, message, extra)], dropped) severity-tagged
                        # cluster events -> head ring buffer (reference:
                        # the GCS cluster event log behind
                        # `ray list cluster-events`); one-way from any
                        # process, mirroring the task-event channel
LEASE_GRANT_BATCH = 73  # head->driver, one-way: ([(rid, worker_id,
                        # listen_addr, lease_id, tpu_ids)],) — the
                        # request-side mirror of TASK_DONE_BATCH: one
                        # batched dispatch pass that granted several of a
                        # driver's queued LEASE_REQUESTs acks them all in
                        # ONE frame (one pickle, one syscall) instead of
                        # a LEASE_REPLY per lease; the driver completes
                        # each rid's blocked call from the batch
SHUTDOWN_NODE = 75      # head->agent, one-way: () — the head is
#                         DELIBERATELY cutting this node loose (cluster
#                         shutdown, eviction): the agent must exit
#                         instead of treating the coming socket close as
#                         a head outage and re-dialing for the whole
#                         reconnect window (reference: an evicted raylet
#                         kills itself on learning of its eviction)
CLIENT_HELLO = 74       # client->head, one-way: (client_id, reattach) —
#                         sent first on every (re)connect of a
#                         reconnecting head channel. The head stamps the
#                         connection with the client's stable id so
#                         retried mutations can be deduped by
#                         (client_id, request_id), and counts reattaches
#                         (reattach=True on every connect after the
#                         first — the GCS-FT analog of a raylet
#                         re-establishing its GCS RPC channel)
PULL_ABORT = 76         # head->agent, one-way: (oid_bin,) — abort the
#                         in-flight PREFETCH pull of this object (its
#                         task was cancelled / retried elsewhere / its
#                         lease died before any worker asked for the
#                         arg). The agent's puller only honors it for
#                         prefetch-flagged pulls no demand get() has
#                         joined — a pull real work is waiting on is
#                         never killed by stale speculation.
PREFETCH_RESULT = 77    # agent->head, one-way: (oid_bin, node_idx, ok)
#                         — a prefetch-flagged pull finished (either
#                         way). The head releases the broadcast-planner
#                         source charges it registered at issue time and
#                         marks the entry done (ok) or drops it.
PREFETCH_HINT = 78      # driver->head, one-way: (lease_id,
#                         [arg_id_bins][, [inline_id_bins]]) —
#                         dispatch-time companion to
#                         the grant-time prefetch: leases are long-lived
#                         and serve many tasks, so when the submitter
#                         pushes a task batch with by-ref args it names
#                         them for the lease's node; the head applies
#                         the same holder check / caps / dedupe and
#                         fires prefetch-flagged PULL_OBJECTs while the
#                         batch is still in flight to the worker. The
#                         optional third field (r16) tags the subset of
#                         the ids that are INLINE-PROMOTED objects, so
#                         the head books their pulls outside the
#                         speculation waste ratio; sent only when
#                         non-empty (common frames stay r15-identical).
PREFETCH_HINT_BATCH = 80  # driver->head, one-way: ([(lease_key,
#                         [arg_id_bins][, [inline_id_bins]])],) — r15
#                         coalesced form of
#                         PREFETCH_HINT: a pipeline/actor hot loop
#                         pushing many small batches with FRESH by-ref
#                         args (per-microbatch activations defeat the
#                         r14 dedupe window — every id is novel) buffers
#                         hints per (lease | actor:<hex>) destination
#                         and the submitter's next wakeup ships ALL
#                         pending destinations in this one frame instead
#                         of one frame per pushed batch. The head
#                         unrolls it through the PREFETCH_HINT path
#                         (same caps / holder checks / dedupe).
OBJECT_WARM = 79        # client->head: (oid_bin, node_idx) — warm an
#                         object onto a node BEFORE any task/actor that
#                         needs it is even placed (r14 serve cold-start:
#                         the controller warms deployment weights at
#                         scale-up decision time so replica construction
#                         finds the bytes local or joins the in-flight
#                         pull). node_idx = -1 warms every alive remote
#                         node missing the object. Rides the r13
#                         prefetch machinery (same caps / pacing /
#                         dedupe / PREFETCH_RESULT accounting) under the
#                         reserved WARM lease, and the pulls register as
#                         in-progress locations, so N concurrent warms
#                         form the r9 cooperative broadcast tree.
#                         Replied (pull count issued) when sent as a
#                         call; also valid one-way.
OBJ_TAG = 81            # client->head, one-way: ([oid_bins], tag) —
#                         stamp a reference-class tag onto directory
#                         entries (memory observatory: "checkpoint" for
#                         pipeline checkpoint refs). Purely advisory
#                         accounting metadata: `ray_tpu memory`'s class
#                         breakdown splits resident bytes by it.
OBJ_PULL_FAIL = 72      # server->puller: (oid_bin, offset) — the server
                        # cannot complete the requested range past
                        # `offset` (its own in-progress pull aborted, or
                        # a promised object never materialized); the
                        # puller fails over ONLY this object's ranges on
                        # this connection to the remaining candidate
                        # sources (the root holder set), crediting what
                        # already arrived

# High bit of the length prefix marks a RAW frame: the payload is
# unpickled bytes (bulk data follows its pickled header message). Sending
# side writes straight from a memoryview (e.g. an shm arena slice) with
# zero serialization copies.
_RAW_BIT = 1 << 63

# Max buffers per sendmsg call. POSIX guarantees IOV_MAX >= 16 and Linux
# gives 1024; staying well below keeps one vectored write's worst-case
# kernel work bounded even when a drain coalesces many queued frames.
_IOV_MAX = 64


class WireStats:
    """Process-wide data/return-plane counters (one instance, ``WIRE``).

    Plain int attributes bumped from the send hot paths — a racy lost
    increment under free-threading is acceptable for observability
    counters; taking a lock per frame is not. Snapshotted by
    ``metrics.wire_metrics_snapshot`` (delta push to the head aggregate)
    and surfaced raw through the head's ``io_loop`` state query.
    """

    __slots__ = ("frames_sent", "sendmsg_calls", "frames_coalesced",
                 "coalesced_flushes", "zero_copy_bytes", "bytes_sent",
                 "task_done_batches", "task_done_batched",
                 "backpressure_hits")

    def __init__(self):
        self.frames_sent = 0        # framed messages handed to the wire
        self.sendmsg_calls = 0      # vectored write syscalls issued
        self.frames_coalesced = 0   # frames that shared a sendmsg with
        #                             at least one other frame
        self.coalesced_flushes = 0  # sendmsg calls carrying > 1 frame
        self.zero_copy_bytes = 0    # raw-frame bytes sent without an
        #                             intermediate copy (send_with_raw)
        self.bytes_sent = 0         # total payload+prefix bytes written
        self.task_done_batches = 0  # TASK_DONE_BATCH frames sent
        self.task_done_batched = 0  # completions that rode those frames
        self.backpressure_hits = 0  # write queue reached its bound

    def snapshot(self) -> dict:
        return {k: getattr(self, k) for k in self.__slots__}


WIRE = WireStats()

# Optional backpressure notifier: ``cb(peer, queued_frames, queued_bytes)``
# invoked (off the send path, rate-limited per connection) when a
# connection's write queue hits its bound — the runtime wires this to the
# cluster event log so wire saturation shows up on the events page
# instead of failing silently.
_backpressure_cb: Optional[Callable[[str, int, int], None]] = None


def set_backpressure_callback(cb: Optional[Callable[[str, int, int], None]]):
    global _backpressure_cb
    _backpressure_cb = cb


class ConnectionLost(Exception):
    """Raised by writes/calls on a dead connection. ``conn`` identifies
    WHICH connection died — a handler touching several peers needs it to
    tell "my requester vanished" apart from "some third party's socket
    broke mid-fanout" (the latter must not abort the handler)."""

    def __init__(self, msg, conn=None):
        super().__init__(msg)
        self.conn = conn

    def __reduce__(self):
        # conn holds a live socket + locks: unpicklable, and meaningless
        # in another process anyway — error replies ship the message only
        return (ConnectionLost, (str(self),))


class Connection:
    """A framed, thread-safe duplex connection.

    Reads are driven by the owning IOLoop (or a dedicated thread); writes may
    come from any thread. Supports request/reply with blocking ``call``.
    """

    _req_counter = itertools.count(1)

    def __init__(self, sock: socket.socket, peer: str = ""):
        self.sock = sock
        self.peer = peer
        self._wlock = threading.Lock()
        # Coalescing write queue: senders append their frame's buffer list
        # (a GIL-atomic deque op — no lock needed to enqueue), then the
        # sender that wins ``_wlock`` drains EVERYTHING queued in one
        # vectored write. Uncontended sends find the queue holding only
        # their own item and flush immediately — the latency path is
        # unchanged. Items are ``[bufs, nbytes, error, done]``; a sender
        # blocks on ``_wlock`` until its item is marked done (possibly by
        # another sender's drain), preserving synchronous ConnectionLost
        # semantics for every caller.
        self._wq: deque = deque()
        self._coalesce_max_bytes = 0   # lazily read from config
        self._coalesce_max_frames = 0
        self._backpressure_ts = 0.0
        self._pending: Dict[int, "_Waiter"] = {}
        self._pending_lock = threading.Lock()
        self._rbuf = bytearray()
        self.closed = False
        self.on_close: Optional[Callable[["Connection"], None]] = None
        self._ioloop: Optional["IOLoop"] = None
        self._on_message_cb = None  # set by IOLoop.add_connection
        sock.setblocking(True)

    def is_attached(self) -> bool:
        """True when a send would not park. Plain connections never park
        (a dead socket raises ConnectionLost immediately);
        ReconnectingConnection overrides this with its reattach gate.
        Fire-and-forget senders that must NEVER block on a head outage
        (speculative hints, warm requests, event emits) check this and
        skip the send instead."""
        return True

    # -- send side --

    def send(self, msg_type: int, *fields, request_id: int = 0):
        payload = pickle.dumps((msg_type, request_id, *fields), protocol=5)
        if len(payload) >= _RAW_BIT:
            # the high length bit marks RAW frames — a >=2 GiB pickled
            # frame would be misparsed by the receiver; move such data in
            # chunks (e.g. via the transfer plane) instead
            raise ValueError(
                f"frame too large ({len(payload)} bytes); chunk it")
        # vectored: the length prefix and payload ship as one iovec — no
        # prefix+payload concatenation copy
        self._send_frames((_LEN.pack(len(payload)), payload),
                          _LEN.size + len(payload))

    def send_with_raw(self, msg_type: int, *fields, raw) -> None:
        """Send a pickled header message immediately followed by a RAW
        frame (bytes/memoryview, no pickling) — atomic with respect to
        other senders on this connection, so concurrent streams can never
        interleave between a header and its raw payload. The receiver sees
        the raw frame as ``(RAW_FRAME, 0, bytes)`` right after the header.

        Zero-copy: the raw buffer rides the iovec straight into sendmsg —
        a multi-GiB arena slice is never copied into a Python bytes
        object. Atomicity is structural: the header and raw frame are one
        write-queue item, and a drain never splits an item across
        vectored writes."""
        n = len(raw)
        if n >= _RAW_BIT:
            raise ValueError("raw frame too large")
        header = pickle.dumps((msg_type, 0, *fields), protocol=5)
        WIRE.zero_copy_bytes += n
        self._send_frames(
            (_LEN.pack(len(header)), header, _LEN.pack(n | _RAW_BIT), raw),
            2 * _LEN.size + len(header) + n)

    def _send_frames(self, bufs: tuple, nbytes: int):
        """Queue one frame (or an atomic header+raw frame pair) and flush.

        The append is lock-free; whichever sender holds ``_wlock`` drains
        the whole queue, so under contention frames from concurrent
        senders coalesce into one sendmsg while each sender still
        observes its own frame's outcome synchronously."""
        if self.closed:
            raise ConnectionLost(self.peer, conn=self)
        item = [bufs, nbytes, None, False]
        wq = self._wq
        wq.append(item)
        # bound check honors wire_coalesce_max_frames exactly once a
        # drain has loaded the config; only the first-ever sends on a
        # connection fall back to the compile-time default
        if len(wq) >= (self._coalesce_max_frames or 64):
            self._note_backpressure()
        with self._wlock:
            if not item[3]:
                self._drain_wlocked()
        err = item[2]
        if err is not None:
            raise err

    def _note_backpressure(self):
        """The wire is saturated — the write queue hit its bound, or a
        single write sat blocked on an undrained socket for seconds.
        Count it and (rate-limited, off the hot path via a short-lived
        thread) tell the cluster event log — wire saturation must be
        observable, not silent."""
        WIRE.backpressure_hits += 1
        now = time.monotonic()
        if now - self._backpressure_ts < 5.0:
            return
        self._backpressure_ts = now
        cb = _backpressure_cb
        if cb is None:
            return
        # count the write in flight too (the stalled-single-sender case
        # has an empty queue — the blocked frame IS the backlog)
        frames = len(self._wq) + 1
        nbytes = sum(it[1] for it in list(self._wq))
        threading.Thread(target=cb, args=(self.peer, frames, nbytes),
                         daemon=True).start()

    def _drain_wlocked(self):
        """Flush every queued item. Caller holds ``_wlock``.

        Items are grouped into vectored writes bounded by the
        ``wire_coalesce_*`` knobs and ``_IOV_MAX``; an item's buffers are
        never split across groups, so a send_with_raw header always
        shares a write with its raw payload."""
        wq = self._wq
        items: List[list] = []
        while wq:
            try:
                items.append(wq.popleft())
            except IndexError:
                break
        if not items:
            return
        if self.closed:
            err = ConnectionLost(self.peer, conn=self)
            for it in items:
                it[2] = err
                it[3] = True
            return
        max_bytes = self._coalesce_max_bytes
        if not max_bytes:
            from .config import get_config

            cfg = get_config()
            max_bytes = self._coalesce_max_bytes = \
                max(1, cfg.wire_coalesce_max_bytes)
            self._coalesce_max_frames = max(1, cfg.wire_coalesce_max_frames)
        max_frames = self._coalesce_max_frames
        try:
            i, n = 0, len(items)
            while i < n:
                bufs: List = list(items[i][0])
                total = items[i][1]
                j = i + 1
                while (j < n and j - i < max_frames
                       and total + items[j][1] <= max_bytes
                       and len(bufs) + len(items[j][0]) <= _IOV_MAX):
                    bufs.extend(items[j][0])
                    total += items[j][1]
                    j += 1
                self._send_all_vectored(bufs)
                WIRE.frames_sent += j - i
                WIRE.bytes_sent += total
                if j - i > 1:
                    WIRE.frames_coalesced += j - i
                    WIRE.coalesced_flushes += 1
                for k in range(i, j):
                    items[k][3] = True
                i = j
        except OSError as e:
            err = ConnectionLost(f"{self.peer}: {e}", conn=self)
            err.__cause__ = e
            for it in items:
                if not it[3]:
                    it[2] = err
                    it[3] = True

    def _send_all_vectored(self, bufs: List, stall_timeout: float = 60.0):
        """sendmsg that survives a non-blocking socket (IOLoop
        registration sets O_NONBLOCK) and partial writes ACROSS iovec
        boundaries: under send-buffer pressure the kernel may accept any
        byte count — fully-sent buffers are dropped from the head of the
        vector and the first partially-sent one is resliced. Caller
        holds ``_wlock``.

        The stall timeout counts time with NO progress (reset on every
        accepted byte). On stall the connection is shut down before
        raising — a partial frame is already on the wire, so any later
        send on this socket would land mid-frame and permanently desync
        the peer."""
        # Fast path: one direct sendmsg of the caller's buffers — no
        # memoryview wrapping (measured ~2x the per-call overhead for
        # small frames). Small control frames virtually always fit the
        # socket buffer whole, so this is THE hot path; any partial or
        # blocked write falls through to the resumable slow path.
        if len(bufs) <= _IOV_MAX:
            want = sum(b.nbytes if type(b) is memoryview else len(b)
                       for b in bufs)
            try:
                sent = self.sock.sendmsg(bufs)
                WIRE.sendmsg_calls += 1
                if sent == want:
                    return
            except (BlockingIOError, InterruptedError):
                sent = 0
        else:
            sent = 0
        mvs: List[memoryview] = []
        for b in bufs:
            m = memoryview(b)
            if m.ndim != 1 or m.itemsize != 1:
                m = m.cast("B")
            if len(m):  # zero-length iovec (empty raw frame) would make
                mvs.append(m)  # the progress loop spin on sendmsg()==0
        idx, total = 0, len(mvs)
        # skip what the first attempt already put on the wire
        while sent and idx < total:
            first = mvs[idx]
            ln = len(first)
            if sent >= ln:
                sent -= ln
                idx += 1
            else:
                mvs[idx] = first[sent:]
                sent = 0
        deadline = time.monotonic() + stall_timeout
        # a write blocked this long is saturation even with a single
        # sender (queue depth never grows past 1 for synchronous
        # senders) — surface it before the 60s stall kill does
        bp_deadline = time.monotonic() + 1.0
        while idx < total:
            try:
                n = self.sock.sendmsg(mvs[idx:idx + _IOV_MAX])
                WIRE.sendmsg_calls += 1
            except BlockingIOError:
                now = time.monotonic()
                if bp_deadline is not None and now > bp_deadline:
                    bp_deadline = None
                    self._note_backpressure()
                if now > deadline:
                    # A partial frame is on the wire; any later send would
                    # land mid-frame and desync the peer. Kill the stream —
                    # the IO loop sees EOF and runs the full close path
                    # (fail pending calls, fire on_close).
                    try:
                        self.sock.shutdown(socket.SHUT_RDWR)
                    except OSError:
                        pass
                    raise OSError("send stalled: peer not draining")
                try:
                    select.select([], [self.sock], [], 1.0)
                except (OSError, ValueError) as e:
                    # Connection closed concurrently (fd now -1/invalid):
                    # surface as a normal send failure, not a ValueError
                    # that would escape callers' ConnectionLost handling.
                    raise OSError(f"connection closed during send: {e}")
                continue
            except InterruptedError:
                continue
            if n:
                deadline = time.monotonic() + stall_timeout
            while n and idx < total:
                first = mvs[idx]
                ln = len(first)
                if n >= ln:
                    n -= ln
                    idx += 1
                else:
                    mvs[idx] = first[n:]
                    n = 0

    def call(self, msg_type: int, *fields, timeout: Optional[float] = None):
        """Send a request and block for its reply; returns reply fields."""
        rid = next(self._req_counter)
        w = _Waiter()
        with self._pending_lock:
            self._pending[rid] = w
        try:
            self.send(msg_type, *fields, request_id=rid)
            if not w.event.wait(timeout):
                raise TimeoutError(f"RPC {msg_type} to {self.peer} timed out")
            if w.error is not None:
                raise w.error
            return w.value
        finally:
            with self._pending_lock:
                self._pending.pop(rid, None)

    def reply(self, request_id: int, *fields, msg_type: int = OK):
        self.send(msg_type, *fields, request_id=-request_id)

    def reply_error(self, request_id: int, err: BaseException):
        self.send(ERROR_REPLY, err, request_id=-request_id)

    # -- receive side --

    def feed(self, data: bytes):
        """Feed raw bytes; yields complete messages.

        Fast path: when no partial frame is buffered, frames are parsed
        straight out of ``data`` with zero copies — RAW frame payloads are
        then memoryviews into ``data`` and are only valid until the caller
        finishes iterating the returned list (the transfer plane consumes
        them synchronously).
        """
        hdr = _LEN.size
        msgs = []
        if not self._rbuf:
            src = memoryview(data)
            pos, n = 0, len(src)
            while n - pos >= hdr:
                (ln,) = _LEN.unpack_from(src, pos)
                raw = bool(ln & _RAW_BIT)
                ln &= ~_RAW_BIT
                if n - pos - hdr < ln:
                    break
                payload = src[pos + hdr:pos + hdr + ln]
                msgs.append((RAW_FRAME, 0, payload) if raw
                            else pickle.loads(payload))
                pos += hdr + ln
            if pos < n:
                self._rbuf += src[pos:]
            return msgs
        # slow path: a partial frame spans recv() calls — buffer and copy
        self._rbuf += data
        while True:
            if len(self._rbuf) < hdr:
                break
            (ln,) = _LEN.unpack_from(self._rbuf)
            raw = bool(ln & _RAW_BIT)
            ln &= ~_RAW_BIT
            if len(self._rbuf) < hdr + ln:
                break
            payload = bytes(self._rbuf[hdr:hdr + ln])
            del self._rbuf[:hdr + ln]
            msgs.append((RAW_FRAME, 0, payload) if raw
                        else pickle.loads(payload))
        return msgs

    def complete_reply(self, rid: int, fields: tuple) -> bool:
        """Complete a pending call() as if a normal reply for ``rid``
        arrived — the delivery path for BATCHED replies (e.g.
        LEASE_GRANT_BATCH), where one frame carries many requests'
        results and the receiver fans them out. Returns False when no
        call is waiting (requester gave up)."""
        with self._pending_lock:
            w = self._pending.get(rid)
        if w is None:
            return False
        w.value = tuple(fields)
        w.event.set()
        return True

    def dispatch_reply(self, msg) -> bool:
        """If msg is a reply to a pending call, complete it. Returns True."""
        request_id = msg[1]
        if request_id >= 0:
            return False
        rid = -request_id
        with self._pending_lock:
            w = self._pending.get(rid)
        if w is None:
            return True  # stale reply
        if msg[0] == ERROR_REPLY:
            w.error = msg[2]
        else:
            w.value = msg[2:]
        w.event.set()
        return True

    def _io_eof(self, sock=None):
        """IO loop saw EOF/error on this socket. Plain connections die;
        a ReconnectingConnection overrides this to begin reattachment
        instead of failing its waiters (``sock`` identifies WHICH socket
        died, so a stale EOF from a replaced socket is ignored)."""
        self.close()

    def close(self):
        if self.closed:
            return
        self.closed = True
        # Unregister from the IO loop BEFORE closing the fd — once closed the
        # fd number can be recycled by a new socket.
        if self._ioloop is not None:
            self._ioloop.remove(self.sock)
        try:
            self.sock.close()
        except OSError:
            pass
        with self._pending_lock:
            pending = list(self._pending.values())
            self._pending.clear()
        for w in pending:
            w.error = ConnectionLost(self.peer, conn=self)
            w.event.set()
        if self.on_close:
            try:
                self.on_close(self)
            except Exception:
                pass


class _Waiter:
    __slots__ = ("event", "value", "error")

    def __init__(self):
        self.event = threading.Event()
        self.value = None
        self.error = None


def backoff_delay(attempt: int, base: float = 0.05, cap: float = 2.0,
                  rng=None) -> float:
    """Reconnect backoff schedule: exponential from ``base`` capped at
    ``cap``, with +/-50% jitter so a fleet of agents losing one head
    does not reconnect in lockstep (the reference's
    gcs_rpc_server_reconnect backoff role). ``rng`` is a 0..1 callable
    (tests inject a deterministic one)."""
    import random

    d = min(cap, base * (2.0 ** attempt))
    r = rng() if rng is not None else random.random()
    return d * (0.5 + r)


class ReconnectingConnection(Connection):
    """A head channel that survives the head dying and coming back.

    The GCS-FT client analog: the reference's raylets/workers keep their
    GCS RPC channel alive across a gcs_server restart, retrying for
    ``gcs_rpc_server_reconnect_timeout_s`` before giving up. Here the
    Connection object is PERSISTENT — on socket loss only the socket
    underneath is replaced, so every caller-held reference (and every
    parked ``call()`` waiter in ``_pending``) survives the outage:

    * writes during an outage park (block) until reattach, then retry;
    * in-flight ``call()``s keep their waiters — after reattach their
      requests are re-sent verbatim with the SAME request id, and the
      head's (client_id, request_id) dedupe map keeps a retried
      mutation that already landed from applying twice;
    * ``on_reattach(conn)`` runs on the reconnector thread after the new
      socket registers (and after CLIENT_HELLO), BEFORE parked senders
      resume — the re-registration protocol (REGISTER_NODE with prior
      node id + holder report, driver/worker REGISTER) runs there, so
      nothing races ahead of it;
    * past ``head_reconnect_timeout_s`` of failed attempts the channel
      closes for real: parked senders and waiters get the ordinary
      fail-fast ``ConnectionLost``, and ``on_close`` fires exactly once
      (agents shut down, workers exit — the pre-reconnect semantics).
    """

    def __init__(self, addr: str, *, client_id: str, peer: str = "head",
                 reconnect_timeout_s: Optional[float] = None,
                 on_reattach: Optional[Callable[["Connection"], None]]
                 = None):
        sock = connect_addr(addr)
        super().__init__(sock, peer=peer)
        self.addr = addr
        self.client_id = client_id
        self.on_reattach = on_reattach
        self._timeout_s = reconnect_timeout_s
        self._attached = threading.Event()
        self._attached.set()
        self._final = False
        self._reconnect_lock = threading.Lock()
        self._reconnecting = False
        self._reconnector: Optional[threading.Thread] = None
        self._give_up_at: Optional[float] = None
        # rid -> (msg_type, fields): requests whose reply is still
        # pending, re-sent verbatim after a reattach
        self._inflight_reqs: Dict[int, tuple] = {}
        self._inflight_lock = threading.Lock()
        self.reconnects = 0          # successful reattachments
        self.reconnect_attempts = 0  # dial attempts (incl. failures)
        # identify ourselves so the head can dedupe retried requests
        self.send(CLIENT_HELLO, client_id, False)

    def is_attached(self) -> bool:
        return self._attached.is_set()

    def _reconnect_window_s(self) -> float:
        if self._timeout_s is not None:
            return self._timeout_s
        from .config import get_config

        return get_config().head_reconnect_timeout_s

    # -- send/call overrides -------------------------------------------

    def _wait_attached(self):
        if self._final:
            raise ConnectionLost(
                f"{self.peer}: head unreachable past reconnect window",
                conn=self)
        if self._attached.is_set():
            return
        if threading.current_thread() is self._reconnector:
            return  # re-registration traffic bypasses the gate
        if self._ioloop is not None and \
                threading.current_thread() is self._ioloop._thread:
            # NEVER park the IO loop: it must stay live to deliver the
            # re-registration replies the reattach handshake blocks on.
            # Handlers sending on the head channel during an outage get
            # the ordinary ConnectionLost (they all tolerate it).
            raise ConnectionLost(f"{self.peer}: reconnecting", conn=self)
        while not self._attached.wait(0.5):
            if self._final:
                break
        if self._final:
            raise ConnectionLost(
                f"{self.peer}: head unreachable past reconnect window",
                conn=self)

    def _send_frames(self, bufs: tuple, nbytes: int):
        while True:
            self._wait_attached()
            failed_sock = self.sock
            try:
                return super()._send_frames(bufs, nbytes)
            except ConnectionLost:
                if self._final or self.closed:
                    raise
                if threading.current_thread() is self._reconnector:
                    # a reattach-handshake send failed (head died again
                    # mid-handshake): surface to _reconnect_loop, which
                    # discards the half-attached socket and retries with
                    # backoff — retrying HERE would spin on the same
                    # dead socket forever (_socket_dead no-ops while
                    # _reconnecting is set)
                    raise
                # socket died under us: begin (or join) reattachment and
                # retry the frame on the next socket — a partially-sent
                # frame is harmless, the new head reads a fresh stream
                self._socket_dead(failed_sock)

    def call(self, msg_type: int, *fields,
             timeout: Optional[float] = None):
        """Like Connection.call, but the request is recorded so a
        reattach can replay it (same rid — the head dedupes)."""
        rid = next(self._req_counter)
        w = _Waiter()
        with self._pending_lock:
            self._pending[rid] = w
        with self._inflight_lock:
            self._inflight_reqs[rid] = (msg_type, fields)
        try:
            self.send(msg_type, *fields, request_id=rid)
            if not w.event.wait(timeout):
                raise TimeoutError(
                    f"RPC {msg_type} to {self.peer} timed out")
            if w.error is not None:
                raise w.error
            return w.value
        finally:
            with self._pending_lock:
                self._pending.pop(rid, None)
            with self._inflight_lock:
                self._inflight_reqs.pop(rid, None)

    # -- detach / reattach ---------------------------------------------

    def _io_eof(self, sock=None):
        self._socket_dead(sock)

    def _socket_dead(self, dead_sock=None):
        """The given socket is gone. Start one reconnector; concurrent
        callers (IO-loop EOF racing a failed send) just return — their
        own retry loops block on ``_attached``. A STALE report about an
        already-replaced socket (a late EOF event or a send failure that
        lost the race with a completed reattach) must not kill the new
        healthy socket."""
        with self._reconnect_lock:
            if self._final or self.closed or self._reconnecting:
                return
            if dead_sock is not None and dead_sock is not self.sock:
                return  # stale report about a replaced socket
            self._reconnecting = True
            self._attached.clear()
            if self._give_up_at is None:
                self._give_up_at = (time.monotonic()
                                    + self._reconnect_window_s())
            if self._ioloop is not None:
                self._ioloop.remove(self.sock)
            try:
                self.sock.close()
            except OSError:
                pass
            self._reconnector = threading.Thread(
                target=self._reconnect_loop, daemon=True,
                name=f"reconnect-{self.peer}")
            self._reconnector.start()

    def _reconnect_loop(self):
        attempt = 0
        while not self._final:
            deadline = self._give_up_at
            now = time.monotonic()
            if deadline is not None and now >= deadline:
                self._give_up()
                return
            self.reconnect_attempts += 1
            try:
                budget = 5.0 if deadline is None else \
                    max(0.2, min(5.0, deadline - now))
                sock = connect_addr(self.addr, timeout=budget)
            except OSError:
                time.sleep(backoff_delay(attempt))
                attempt += 1
                continue
            try:
                self._attach(sock)
            except ConnectionLost:
                # head answered then died again mid-handshake: next round
                self._discard_half_attached(sock)
                time.sleep(backoff_delay(attempt))
                attempt += 1
                continue
            except Exception:
                import traceback

                traceback.print_exc()
                self._discard_half_attached(sock)
                time.sleep(backoff_delay(attempt))
                attempt += 1
                continue
            return

    def _discard_half_attached(self, sock: socket.socket):
        """A reattach handshake failed after the socket may already have
        been registered with the IO loop — unregister FIRST (a closed fd
        left in the selector would make the loop spin on EBADF), then
        close."""
        if self._ioloop is not None:
            self._ioloop.remove(sock)
        try:
            sock.close()
        except OSError:
            pass

    def _attach(self, sock: socket.socket):
        """Swap the new socket in, re-register with the IO loop, run the
        re-registration hook, replay in-flight requests, release parked
        senders. Runs on the reconnector thread."""
        self.sock = sock
        self._rbuf = bytearray()
        if self._ioloop is not None and self._on_message_cb is not None:
            self._ioloop.add_connection(self, self._on_message_cb)
        else:
            sock.setblocking(True)
        self.send(CLIENT_HELLO, self.client_id, True)
        if self.on_reattach is not None:
            self.on_reattach(self)
        # replay requests whose replies died with the old head — same
        # rids, so the head's dedupe map absorbs true duplicates
        with self._inflight_lock:
            replay = sorted(self._inflight_reqs.items())
        for rid, (mt, fields) in replay:
            with self._pending_lock:
                if rid not in self._pending:
                    continue  # caller gave up while we were away
            self.send(mt, *fields, request_id=rid)
        self.reconnects += 1
        with self._reconnect_lock:
            self._reconnecting = False
            self._give_up_at = None
        self._attached.set()

    def _give_up(self):
        """Reconnect window expired: fail fast exactly like a plain
        connection dying — waiters get ConnectionLost, on_close fires."""
        with self._reconnect_lock:
            self._final = True
            self._reconnecting = False
        self._attached.set()  # release parked senders into the raise
        super().close()

    def close(self):
        """Deliberate, final close (shutdown paths)."""
        self._final = True
        self._attached.set()
        super().close()


class IOLoop:
    """Single IO thread multiplexing all connections of a process.

    Mirrors the reference's per-process ``instrumented_io_context`` asio loop
    (src/ray/common/asio/instrumented_io_context.h).
    """

    # a handler occupying the IO thread longer than this is logged —
    # the analog of the reference's event-loop lag tracking (every
    # handler on instrumented_io_context is timed; event_stats.h)
    SLOW_HANDLER_S = 0.1

    def __init__(self, name: str = "io"):
        import selectors

        self.name = name
        self.sel = selectors.DefaultSelector()
        self._lock = threading.Lock()
        self._thread = threading.Thread(target=self._run, name=name, daemon=True)
        self._stopped = threading.Event()
        self._wakeup_r, self._wakeup_w = socket.socketpair()
        self._wakeup_r.setblocking(False)
        self.sel.register(self._wakeup_r, 1, ("wakeup", None, None))
        self._started = False
        # loop-lag accounting, exposed via stats(): total busy seconds,
        # handled events, count + worst of slow handler episodes
        self._busy_s = 0.0
        self._events = 0
        self._slow_events = 0
        self._max_handler_s = 0.0
        # self-probe loop lag (probe_lag()/lag_stats()): a timestamped
        # wakeup measures how long a new event waits for this thread —
        # the direct "is the loop off the hot path" gauge (analog:
        # instrumented_io_context's queued-time stats). One probe in
        # flight at a time; samples ring-buffered for the quantiles.
        self._lag_probe_t: Optional[float] = None
        self._lag_samples: deque = deque(maxlen=256)

    def start(self):
        if not self._started:
            self._started = True
            self._thread.start()

    def add_listener(self, sock: socket.socket,
                     on_accept: Callable[[socket.socket, Any], None]):
        sock.setblocking(False)
        with self._lock:
            self.sel.register(sock, 1, ("listen", on_accept, None))
        self._wake()

    def add_connection(self, conn: Connection,
                       on_message: Callable[[Connection, Tuple], None]):
        conn.sock.setblocking(False)
        conn._ioloop = self
        # remembered so a ReconnectingConnection can re-register its
        # replacement socket with the same handler after a reattach
        conn._on_message_cb = on_message
        with self._lock:
            self.sel.register(conn.sock, 1, ("conn", on_message, conn))
        self._wake()

    def remove(self, sock):
        with self._lock:
            try:
                self.sel.unregister(sock)
            except (KeyError, ValueError):
                pass

    def _wake(self):
        try:
            self._wakeup_w.send(b"x")
        except OSError:
            pass

    def _run(self):
        while not self._stopped.is_set():
            try:
                events = self.sel.select(timeout=0.5)
            except OSError:
                continue
            for key, _ in events:
                kind, cb, conn = key.data
                t0 = time.perf_counter()
                if kind == "wakeup":
                    try:
                        self._wakeup_r.recv(4096)
                    except OSError:
                        pass
                    sent = self._lag_probe_t
                    if sent is not None:
                        self._lag_probe_t = None
                        self._lag_samples.append(
                            time.perf_counter() - sent)
                elif kind == "listen":
                    try:
                        client, addr = key.fileobj.accept()
                        if client.family == socket.AF_INET:
                            # connect_addr sets TCP_NODELAY on the dialing
                            # side only; without it here every server->
                            # client reply is at the mercy of Nagle +
                            # delayed-ack interplay on cross-host links
                            try:
                                client.setsockopt(socket.IPPROTO_TCP,
                                                  socket.TCP_NODELAY, 1)
                            except OSError:
                                pass
                        cb(client, addr)
                    except OSError:
                        pass
                elif kind == "conn":
                    self._service_conn(key.fileobj, cb, conn)
                dt = time.perf_counter() - t0
                self._busy_s += dt
                self._events += 1
                if dt > self._max_handler_s:
                    self._max_handler_s = dt
                if dt > self.SLOW_HANDLER_S:
                    # every connection on this loop stalled behind this
                    # handler — the single-threaded-loop failure mode the
                    # reference instruments (instrumented_io_context)
                    self._slow_events += 1
                    import sys

                    print(f"[ray_tpu] io loop '{self.name}' handler "
                          f"({kind}) blocked the loop {dt * 1e3:.0f} ms",
                          file=sys.stderr)

    def stats(self) -> dict:
        """Loop-lag counters (analog: event_stats.h per-handler stats)."""
        return {"events": self._events,
                "busy_s": round(self._busy_s, 3),
                "slow_events": self._slow_events,
                "max_handler_s": round(self._max_handler_s, 4)}

    def probe_lag(self):
        """Launch one loop-lag probe: stamp now, wake the loop, and let
        the wakeup handler record how long the wake waited. No-op while
        a probe is already in flight (a wedged loop then simply keeps
        its worst sample instead of stacking probes)."""
        if self._lag_probe_t is None and self._started:
            self._lag_probe_t = time.perf_counter()
            self._wake()

    def lag_stats(self) -> dict:
        """p50/p99/max of the recent self-probe lag samples, in ms."""
        samples = sorted(self._lag_samples)
        n = len(samples)
        if not n:
            return {"loop_lag_samples": 0, "loop_lag_ms_p50": 0.0,
                    "loop_lag_ms_p99": 0.0, "loop_lag_ms_max": 0.0}
        return {
            "loop_lag_samples": n,
            "loop_lag_ms_p50": round(samples[n // 2] * 1e3, 3),
            "loop_lag_ms_p99": round(
                samples[min(n - 1, (n * 99) // 100)] * 1e3, 3),
            "loop_lag_ms_max": round(samples[-1] * 1e3, 3),
        }

    def _service_conn(self, sock, on_message, conn: Connection):
        try:
            data = sock.recv(1 << 22)
        except BlockingIOError:
            return
        except OSError:
            data = b""
        if not data:
            self.remove(sock)
            conn._io_eof(sock)
            return
        for msg in conn.feed(data):
            if conn.dispatch_reply(msg):
                continue
            try:
                on_message(conn, msg)
            except Exception:
                import traceback

                traceback.print_exc()

    def stop(self):
        self._stopped.set()
        self._wake()
        if self._started:
            self._thread.join(timeout=2)
        try:
            self.sel.close()
        except Exception:
            pass


def listen_unix(path: str) -> socket.socket:
    import os

    try:
        os.unlink(path)
    except FileNotFoundError:
        pass
    s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    s.bind(path)
    s.listen(128)
    return s


def listen_tcp(host: str = "0.0.0.0", port: int = 0) -> socket.socket:
    """TCP listener for cross-host membership (DCN control plane)."""
    s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    s.bind((host, port))
    s.listen(128)
    return s


def local_ip() -> str:
    """Best-effort outward-facing IP (no packets sent; UDP connect only)."""
    s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    try:
        s.connect(("8.8.8.8", 80))
        return s.getsockname()[0]
    except OSError:
        return "127.0.0.1"
    finally:
        s.close()


def connect_addr(addr: str, timeout: float = 10.0) -> socket.socket:
    """addr: 'unix:<path>' or 'tcp:<host>:<port>'."""
    if addr.startswith("unix:"):
        s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        s.settimeout(timeout)
        s.connect(addr[5:])
    else:
        _, host, port = addr.split(":")
        s = socket.create_connection((host, int(port)), timeout=timeout)
        s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    s.settimeout(None)
    return s
