"""Worker process entrypoint.

Analog of python/ray/_private/workers/default_worker.py in the reference:
spawned by the head's worker pool, registers back over the head socket, then
runs the executor loop (the reference's run_task_loop, _raylet.pyx:2984).
"""

from __future__ import annotations

import os
import sys


def main():
    head_addr = os.environ["RAY_TPU_HEAD_ADDR"]
    session_dir = os.environ["RAY_TPU_SESSION_DIR"]
    node_idx = int(os.environ["RAY_TPU_NODE_IDX"])
    worker_id = os.environ["RAY_TPU_WORKER_ID"]

    from ray_tpu.profiling import install_profile_handler

    # SIGUSR1 -> on-demand stack sampling (ref analog: the dashboard's
    # py-spy-on-PID profiling; profiling.py)
    install_profile_handler(session_dir, worker_id)

    from .context import CoreContext, set_context

    ctx = CoreContext(head_addr=head_addr, session_dir=session_dir,
                      node_idx=node_idx, worker_id=worker_id,
                      is_driver=False)
    set_context(ctx)
    try:
        ctx.run_executor()
    except KeyboardInterrupt:
        pass
    sys.exit(0)


if __name__ == "__main__":
    main()
