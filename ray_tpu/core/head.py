"""Head service: GCS-lite control plane + per-node raylet-lite.

Analog of the reference's GCS server (src/ray/gcs/gcs_server/gcs_server.h:78
— node/actor/job/PG/KV/pubsub/health managers) fused with the raylet's local
managers (worker_pool.h:156 WorkerPool, local_task_manager.h dispatch,
local_object_manager.h:41 spilling). On a TPU cluster this is the per-cluster
control plane over DCN; within one host it runs embedded in the driver
process. Virtual multi-node (the reference's ray.cluster_utils.Cluster,
python/ray/cluster_utils.py:99) is first-class: one head can host N logical
nodes, each with its own resource view, worker pool, and shm object store —
the workhorse for scheduling/failover tests without real hosts.

Data plane note: tensor traffic never flows through here — within a slice it
is XLA/ICI inside compiled programs; this plane carries control messages,
small objects, and checkpoint/object logistics only, mirroring how the
reference keeps NCCL traffic out of its object store.
"""

from __future__ import annotations

import itertools
import os
import queue
import statistics
import subprocess
import sys
import threading
import time
from collections import OrderedDict, deque
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from . import events as E
from . import protocol as P
from .protocol import local_ip as _local_ip
from .config import get_config
from .ids import ActorID, ObjectID, PlacementGroupID, _random_bytes
from .obj_directory import ObjectDirectory, _ObjLoc  # noqa: F401 — _ObjLoc
#   re-exported: planner tests and older callers import it from here
from .object_store import ShmObjectStore
from .persistence import HeadStore
from .resources import NodeResources, ResourceSet, detect_node_resources
from .scheduler import ClusterResourceScheduler
from .serialization import dumps, loads
from .task_spec import ARG_REF, PlacementGroupSpec, TaskSpec
from .timeseries import FlightRecorder


@dataclass
class WorkerInfo:
    worker_id: str
    node_idx: int
    pid: int = 0
    listen_addr: str = ""
    conn: Optional[P.Connection] = None
    proc: Optional[subprocess.Popen] = None
    state: str = "starting"  # starting | idle | leased | actor | dead
    sched_class: Optional[tuple] = None
    lease_id: Optional[str] = None
    actor_id: Optional[ActorID] = None
    idle_since: float = 0.0
    spawned_at: float = 0.0


@dataclass
class ActorInfo:
    actor_id: ActorID
    spec: TaskSpec
    state: str = "PENDING"  # PENDING | ALIVE | RESTARTING | DEAD
    listen_addr: str = ""
    worker_id: str = ""
    restarts_used: int = 0
    name: str = ""
    death_cause: str = ""
    pending_get_replies: List[Tuple[P.Connection, int]] = field(default_factory=list)


@dataclass
class PgInfo:
    spec: PlacementGroupSpec
    placement: List[int] = field(default_factory=list)
    # Per-bundle remaining resources (tasks scheduled into a bundle consume
    # from here, not from the node's free pool — the reference's
    # CPU_group_<pgid> shadow-resource mechanism).
    bundle_available: List[ResourceSet] = field(default_factory=list)
    state: str = "PENDING"


@dataclass
class NodeState:
    idx: int
    resources: NodeResources
    store: Optional[ShmObjectStore]  # None for remote nodes (agent owns it)
    store_name: str
    workers: Dict[str, WorkerInfo] = field(default_factory=dict)
    idle_by_class: Dict[tuple, List[str]] = field(default_factory=dict)
    alive: bool = True
    # per-chip assignment pool (lazily built from the TPU resource total)
    tpu_free: Optional[List[int]] = None
    # remote-node plumbing (multi-host over TCP; the reference's raylet)
    agent_conn: Optional[P.Connection] = None
    node_ip: str = ""
    session_dir: str = ""
    # the host's peer-to-peer object TransferServer (object_transfer.py)
    transfer_addr: str = ""
    # periodic health probing (remote nodes; gcs_health_check_manager.h:39)
    health_failures: int = 0
    last_ping: float = 0.0
    ping_inflight: bool = False
    # graceful drain (r16): set by drain_node() — excluded from lease
    # grants / placements / prefetch targets while its in-flight leases
    # complete and sole-copy objects replicate off; past
    # ``drain_deadline_s`` the removal force-escalates (drain_forced)
    draining: bool = False
    drain_started: float = 0.0     # monotonic, when the drain began
    drain_replicated: bool = False   # last replication pass was clean
    drain_replicating: bool = False  # a replication pass is in flight
    drain_last_pass: float = 0.0     # when the last pass ended
    # RTT-midpoint estimate of (agent monotonic clock - head monotonic
    # clock), sampled at registration and refreshed by every health
    # probe; applied when folding this node's task-event stamps into the
    # head's timebase so cross-node phase math cannot go negative.
    clock_offset_s: float = 0.0
    clock_rtt_s: float = 0.0

    @property
    def is_remote(self) -> bool:
        return self.agent_conn is not None


@dataclass
class _TaskTimeline:
    """Folded per-task lifecycle row (reference: GcsTaskManager's
    per-task state aggregation over task_event_buffer flushes). Events
    arrive out of order across connections; the fold is commutative —
    first stamp per state wins, display state is the highest-ranked one
    seen, and each phase is observed into the histograms exactly once,
    the moment both its endpoints are present."""

    task_id: str
    name: str = ""
    state: str = ""
    worker_id: str = ""
    node_idx: int = -1
    ts: float = 0.0
    error: str = ""
    trace_id: str = ""
    state_ts: Dict[str, float] = field(default_factory=dict)
    # state -> monotonic stamp, already folded into the HEAD's timebase
    # (remote stamps have their node's clock offset subtracted)
    state_mono: Dict[str, float] = field(default_factory=dict)
    observed: Set[str] = field(default_factory=set)  # phases histogrammed
    straggler: bool = False
    straggler_ms: float = 0.0


@dataclass
class _PrefetchState:
    """One speculative arg pull (r13): fired at lease grant or dispatch
    hint, keyed (oid_bin, node_idx). ``charged`` is the broadcast
    planner's source-load registration, released exactly once — by the
    agent's PREFETCH_RESULT or the TTL sweep. ``consumed`` flips when a
    demand fetch for the same (object, node) arrives (the overlap the
    feature exists for); unconsumed in-flight entries at lease teardown
    are aborted and counted wasted."""

    oid_bin: bytes
    node_idx: int
    lease_id: str
    size: int
    ts: float
    charged: list = field(default_factory=list)
    state: str = "inflight"  # inflight | done | aborted
    consumed: bool = False
    # r16: the driver tagged this arg as an INLINE-PROMOTED object (a
    # tiny value materialized into the store only so a borrower could
    # fetch it, e.g. a pipeline backward cotangent) — its pull is
    # counted in the *_inline counters, outside the issued/wasted
    # ratio the doctor waste check judges
    inline: bool = False


# inflight/aborted prefetch entries whose agent never answered (died,
# or the frame was lost) are swept — charges released — after this long;
# completed entries linger briefly so a late demand fetch still counts
# as satisfied-by-prefetch before the record is dropped.
_PREFETCH_SWEEP_S = 180.0
_PREFETCH_DONE_TTL_S = 60.0
# Reserved lease id for OBJECT_WARM prefetches (r14 serve cold-start):
# not a real lease, so the lease-liveness gate is skipped and teardown
# never aborts them — warm entries age out via the normal done-TTL /
# sweep paths instead.
_WARM_LEASE = "__warm__"


# task.phase_ms / task.node_phase_ms bucket bounds (milliseconds): task
# phases span sub-ms dispatch hops to multi-minute training steps.
TASK_PHASE_MS_BOUNDARIES = (1.0, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0,
                            500.0, 1000.0, 2500.0, 5000.0, 15000.0,
                            60000.0, 300000.0)


def _hist_quantile(bounds, value, q: float) -> float:
    """Estimate the q-quantile of a [bucket counts..., +inf, sum, n]
    histogram row by linear interpolation inside the holding bucket
    (the standard Prometheus histogram_quantile estimator); the +Inf
    bucket clamps to the last finite bound."""
    n = value[-1]
    if n <= 0:
        return 0.0
    target = q * n
    acc, lo = 0.0, 0.0
    for i, b in enumerate(bounds):
        c = value[i]
        if c > 0 and acc + c >= target:
            return lo + (b - lo) * max(0.0, min(1.0, (target - acc) / c))
        acc += c
        lo = b
    return float(bounds[-1])


# Request types whose retried copies the head dedupes by
# (client_id, request_id): the control-plane MUTATIONS a reconnecting
# channel may replay after a reattach. Reads are naturally idempotent
# and lease requests have their own orphan-grant return path.
_DEDUPE_TYPES = frozenset((
    P.KV_PUT, P.KV_DEL, P.CREATE_ACTOR, P.CREATE_PG, P.REMOVE_PG,
    P.KILL_ACTOR,
))
# WAL-durable subset: their dedupe keys persist alongside the mutation,
# so a retry that crosses a head CRASH is re-acked, not re-applied.
_DEDUPE_DURABLE = frozenset((
    P.KV_PUT, P.KV_DEL, P.CREATE_ACTOR, P.CREATE_PG, P.REMOVE_PG,
))
# Generic success acks for WAL-restored dedupe entries (the mutation
# landed before the crash; the original reply's exact content is gone).
_DEDUPE_GENERIC = {
    P.KV_PUT: (P.OK, (True,)),
    P.KV_DEL: (P.OK, (True,)),
    P.CREATE_ACTOR: (P.CREATE_ACTOR_REPLY, (True,)),
    P.CREATE_PG: (P.CREATE_PG_REPLY, ("CREATED",)),
    P.REMOVE_PG: (P.OK, (True,)),
    P.KILL_ACTOR: (P.OK, (True,)),
}
_DEDUPE_CAP = 4096


class _DedupeRecorder:
    """Connection proxy handed to deduped handlers: success replies are
    recorded under the request's (client_id, rid) key — and, for
    durable mutations, a ``("dedupe", ...)`` WAL record rides along —
    before forwarding to the real connection. Error replies are NOT
    recorded (a retry may legitimately succeed)."""

    __slots__ = ("_head", "_conn", "_key", "_mt")

    def __init__(self, head: "Head", conn, key, mt: int):
        self._head = head
        self._conn = conn
        self._key = key
        self._mt = mt

    def __getattr__(self, name):
        return getattr(self._conn, name)

    def reply(self, request_id, *fields, msg_type=P.OK):
        self._head._record_dedupe(self._key, self._mt,
                                  (msg_type, fields))
        self._conn.reply(request_id, *fields, msg_type=msg_type)

    def reply_error(self, request_id, err):
        self._conn.reply_error(request_id, err)


class Head:
    def __init__(self, session_dir: str, session_name: str):
        self.session_dir = session_dir
        self.session_name = session_name
        self.addr = f"unix:{session_dir}/head.sock"
        self.scheduler = ClusterResourceScheduler()
        self.nodes: Dict[int, NodeState] = {}
        self.actors: Dict[ActorID, ActorInfo] = {}
        self.named_actors: Dict[str, ActorID] = {}
        self.pgs: Dict[PlacementGroupID, PgInfo] = {}
        self.kv: Dict[str, Dict[str, bytes]] = {}
        self.subs: Dict[str, Set[P.Connection]] = {}
        # Sharded object directory (obj_directory.py): holder sets,
        # blocked-locate waiters, broadcast in-progress locations — all
        # under per-shard locks, OFF the head lock, so directory traffic
        # never convoys behind lease granting or the event fold.
        self.objects = ObjectDirectory()
        self.leases: Dict[str, Tuple[int, ResourceSet, str, Optional[tuple]]] = {}
        self._lock = threading.RLock()
        # Control-plane lock split (r11): the head lock now guards ONLY
        # the node/worker/lease/actor/PG/kv tables. Observability state
        # has its own locks so a dashboard poll or a metrics merge can
        # never stall a lease grant (ordering, outermost first:
        # _lock -> _timeline_lock -> _metrics_lock; _cev_lock is a leaf).
        self._timeline_lock = threading.RLock()
        self._metrics_lock = threading.RLock()
        self._cev_lock = threading.Lock()
        self._pending_pg: List[PlacementGroupID] = []
        # lease requests waiting for a worker/resources:
        # (conn, request_id, sched_class, request, strategy_bytes, job)
        self._pending_leases: List[tuple] = []
        self.io = P.IOLoop("head-io")
        self._listener = P.listen_unix(f"{session_dir}/head.sock")
        self.io.add_listener(self._listener, self._on_accept)
        self._tcp_listener = None
        self.tcp_addr: str = ""
        self._next_node_idx = 0
        self._driver_conn: Optional[P.Connection] = None
        self._shutdown = False
        # P2P object plane for the head's in-process nodes (lazy, multi-host
        # only): serves local arenas to remote agents and pulls from them.
        self._transfer_server = None
        self._pullers: Dict[int, object] = {}  # local node idx -> ObjectPuller
        # bytes relayed through head memory on the legacy path — the P2P
        # tests assert this stays 0 for host<->host transfers
        self.relay_bytes = 0
        # locality-aware leasing counters (hit = a task landed on a node
        # already holding its args; miss = locality applied but no holder
        # was feasible/available and the hybrid policy decided instead)
        self.locality_hits = 0
        self.locality_misses = 0
        # cooperative-broadcast planner counters (object_plane state row):
        # how many pulls were pointed at a sealed root vs an in-progress
        # relay, and how often every candidate source was already at its
        # broadcast_fanout bound (the planner then reuses the least-
        # loaded root and emits the rate-limited saturation event)
        self.broadcast_root_assignments = 0
        self.broadcast_relay_assignments = 0
        self.broadcast_fanout_saturations = 0
        self._last_saturation_event_ts = 0.0
        # Speculative arg prefetch (r13, the reference PullManager's
        # prefetch role): (oid_bin, node_idx) -> _PrefetchState for
        # pulls fired at lease grant / dispatch hint, ahead of worker
        # demand. Entries hold the broadcast-planner source charges
        # until the agent's PREFETCH_RESULT (or the TTL sweep) releases
        # them; lease teardown aborts unconsumed in-flight entries
        # through PULL_ABORT (counted wasted).
        self._prefetches: Dict[Tuple[bytes, int], _PrefetchState] = {}
        self._prefetch_by_lease: Dict[str, List[Tuple[bytes, int]]] = {}
        # caps pace, they don't drop (the reference PullManager's
        # bounded pull activation): requests denied by the
        # inflight/byte caps queue per node and activate as
        # PREFETCH_RESULTs free slots. Bounded FIFO; entries re-check
        # holders/caps/lease liveness at activation time.
        self._prefetch_pending: Dict[int, "deque"] = {}
        self._prefetch_draining: Set[int] = set()
        self._prefetch_lock = threading.Lock()
        self.prefetch_issued = 0     # speculative pulls fired
        self.prefetch_joined = 0     # demand fetches that overlapped one
        self.prefetch_completed = 0  # pulls that landed their copy
        self.prefetch_wasted = 0     # aborted: task cancelled/retried
        self.prefetch_bytes_issued = 0
        # r16: pulls of INLINE-PROMOTED objects (tiny values an owner
        # materialized into the store only so borrowers could fetch
        # them — e.g. pipeline backward cotangents) are counted apart:
        # they are real pulls but not the speculation the waste-ratio
        # doctor check judges, and on this 2-vCPU class of host they
        # were padding prefetch_issued by one per microbatch
        self.prefetch_issued_inline = 0
        self.prefetch_completed_inline = 0
        self.prefetch_wasted_inline = 0
        # graceful node drain (r16): counters behind the io_loop state
        # row — migrated = leases released off a draining node while it
        # was still alive (work moved, nothing died)
        self.drains_started = 0
        self.drains_completed = 0
        self.drains_forced = 0
        self.drain_migrated_leases = 0
        self.drain_objects_replicated = 0
        # Worker spawner queue (drained by the spawner thread, started in
        # start()): created here so _try_grant can enqueue spawns even on
        # heads that are never start()ed (unit tests drive handlers
        # directly).
        self._spawn_q: "queue.Queue" = queue.Queue()
        # Batched lease dispatch (r11): LEASE_REQUESTs queue here and a
        # dedicated dispatcher thread grants them in ONE pass over node
        # state per tick (one lock hold, strategies pre-parsed), replying
        # per-connection in LEASE_GRANT_BATCH frames. Handlers that free
        # resources just signal the event — the O(pending^2) re-grant
        # loop the IO thread used to run per message (measured 60-190 ms
        # per REGISTER/RETURN_WORKER at burst) is gone.
        self._dispatch_event = threading.Event()
        self._dispatcher: Optional[threading.Thread] = None
        self.lease_grant_batches = 0   # LEASE_GRANT_BATCH frames sent
        self.lease_grants_batched = 0  # grants that rode those frames
        self._lease_seq = itertools.count(1)
        self._lease_prefix = _random_bytes(8).hex()
        # Task-event ring buffer feeding the state API (reference:
        # GcsTaskManager over task_event_buffer.h flushes).
        self.task_events: "deque" = deque(
            maxlen=get_config().task_event_buffer_size)
        self.task_events_dropped = 0
        # Total events ever ingested into the ring — the absolute
        # sequence base for the paged task_events query (r19): ring
        # position i holds sequence (task_events_seq - len(ring) + i).
        self.task_events_seq = 0
        # Flight recorder (r19): periodic() folds the merged metric
        # table into bounded ring-buffer series every
        # timeseries_sample_s (STATE_QUERY "metrics_history" /
        # /api/timeseries read them back).
        self.recorder = FlightRecorder(
            get_config().timeseries_sample_s,
            get_config().timeseries_window_s)
        self._ts_last_sample = 0.0
        # Off-loop event folding (r11): TASK_EVENTS batches from the wire
        # land in this bounded queue and a dedicated fold thread does the
        # timeline/histogram work — the commutative fold makes the move
        # safe, and the IO loop goes back to being a router. Flush-acks
        # (rid > 0) are issued by the fold thread AFTER ingesting the
        # batch, preserving the ordering barrier timeline() relies on.
        # Overflow sheds the batch (observability must never backpressure
        # the control plane) and counts it: fold_queue_drops is surfaced
        # through io_loop state + doctor_warnings().
        self._fold_q: "deque" = deque()
        self._fold_event = threading.Event()
        self._fold_thread: Optional[threading.Thread] = None
        self.fold_queue_drops = 0
        # Folded per-task lifecycle timelines (bounded, FIFO-evicted;
        # reference: GcsTaskManager task aggregation): state_ts /
        # phase_ms for list_tasks, the task.phase_ms{func,phase} +
        # task.node_phase_ms{node,phase} histograms for
        # summarize_tasks()/Prometheus, and the straggler flags.
        self.task_timelines: "OrderedDict[str, _TaskTimeline]" = \
            OrderedDict()
        # node idx -> latest (remote_mono - head_mono) estimate; kept
        # outside NodeState so stamps from already-dead nodes still fold
        self.node_clock_offsets: Dict[int, float] = {}
        self.stragglers_flagged = 0
        self.slow_nodes_flagged = 0
        self._last_slow_node_event: Dict[tuple, float] = {}
        # node idx -> monotonic deadline while the slow_node detector's
        # skew flag is ROUTABLE-AROUND (r14): refreshed on every
        # detection, surfaced as `slow` in the nodes state rows so
        # serve routers can deprioritize the host's replicas. Written
        # under _metrics_lock (the detector holds it); TTL'd reads are
        # GIL-atomic dict gets.
        self._slow_node_until: Dict[int, float] = {}
        # (node, phase) -> cumulative bucket vector at the last detector
        # sweep: the skew check judges the DELTA since then (recent
        # behavior), not the lifetime histogram — a node's early stall
        # would otherwise keep its cumulative p95 skewed, re-stamping
        # the routing flag long after the host recovered.
        self._node_phase_prev: Dict[tuple, list] = {}
        # Structured cluster event log (reference: the GCS event
        # aggregator behind `ray list cluster-events`): severity-tagged
        # records from head-side emitters and any process's
        # CLUSTER_EVENT pushes, bounded with drop counting.
        self.cluster_events: "deque" = deque(
            maxlen=get_config().cluster_event_buffer_size)
        self.cluster_events_dropped = 0
        # last node.* telemetry gauges per node (reporter.py rows),
        # mirrored into list_nodes() rows
        self.node_telemetry: Dict[int, dict] = {}
        self._telemetry = None  # NodeTelemetryReporter, started in start()
        # cluster-merged metrics: (name, tags_key) -> row dict
        self.metrics: Dict[tuple, dict] = {}
        # auto-names for actors created by non-Python frontends
        self._xlang_actor_seq = itertools.count()
        self._log_monitor = None
        # --- head fault tolerance (r12, the GCS-FT analog) ---
        # (client_id, request_id) -> cached success reply for retried
        # mutations: a reconnecting channel replays in-flight requests
        # after reattach with their ORIGINAL rids, and a mutation that
        # already landed must be re-acked, not re-applied. None values
        # are WAL-restored entries ("applied before the crash, reply
        # unknown") answered with the generic per-type ack.
        self._dedupe: "OrderedDict[tuple, Optional[tuple]]" = OrderedDict()
        self._dedupe_lock = threading.Lock()
        self.dedupe_hits = 0
        self.client_reconnects = 0   # CLIENT_HELLO reattach=True count
        self._reconnect_clients: set = set()  # distinct reattaching ids
        self.node_reattaches = 0     # REGISTER_NODE with a prior node id
        self.actor_reclaims = 0      # surviving actor workers re-claimed
        # bootstrap grace window of a restarted head: set below when the
        # WAL shows a previous incarnation; while active, lease granting
        # and the detectors hold so re-registrations can stream in
        self._grace_until = 0.0
        self._grace_reported = False
        self._last_node_reg_ts = time.monotonic()
        # Durable control-plane WAL (reference: GCS Redis store client).
        self._persist: Optional[HeadStore] = None
        self._wal_backlog: List[tuple] = []  # records queued under _lock
        self._restored_actor_specs: List[bytes] = []
        self._restored_pg_specs: List[bytes] = []
        if get_config().head_persistence:
            self._persist = HeadStore(session_dir)
            state = self._persist.restore()
            if state:
                self.kv = {ns: dict(t) for ns, t in state["kv"].items()}
                self._restored_actor_specs = list(state["actors"].values())
                self._restored_pg_specs = list(state["pgs"].values())
                for key in state.get("dedupe", ()):
                    self._dedupe[tuple(key)] = None
                self._grace_until = (time.monotonic()
                                     + get_config().head_restart_grace_s)
                self.emit_event(
                    "WARNING", "head", "head_restarted",
                    f"head restarted from WAL in {session_dir} "
                    f"(holding scheduling up to "
                    f"{get_config().head_restart_grace_s:g}s for "
                    "re-registrations)",
                    extra={"restored_kv_namespaces": len(self.kv),
                           "restored_actors":
                               len(self._restored_actor_specs),
                           "restored_pgs": len(self._restored_pg_specs)})

    def start(self):
        self.io.start()
        # Wire-saturation events from this process's connections land in
        # the ring directly (a CoreContext created later in the same
        # process re-targets the callback at its head connection — same
        # ring either way).
        from .events import wire_backpressure_fields

        def _on_wire_backpressure(peer, frames, nbytes):
            sev, src, etype, msg, extra = \
                wire_backpressure_fields(peer, frames, nbytes)
            self.emit_event(sev, src, etype, msg, extra=extra)

        P.set_backpressure_callback(_on_wire_backpressure)
        # Tail worker log files -> "logs" pubsub channel; drivers mirror
        # them when log_to_driver=True (reference: log_monitor.py:103).
        from .log_monitor import LogMonitor

        self._log_monitor = LogMonitor(
            self.session_dir,
            lambda ch, data: self._publish(ch, dumps(data)))
        self._log_monitor.start()
        # OOM control: kill the newest busy worker under memory pressure
        # (reference: memory_monitor.h:52 + retriable-LIFO kill policy)
        from .memory_monitor import MemoryMonitor

        self._memory_monitor = MemoryMonitor(self)
        self._memory_monitor.start()
        # Housekeeping loop: pending-PG retries and idle-worker reaping
        # must not depend on any client calling in — a placement group
        # that couldn't be placed at creation (resources transiently held
        # by leases) would otherwise stay pending forever.
        self._housekeeper = threading.Thread(
            target=self._housekeeping_loop, daemon=True, name="head-keeper")
        self._housekeeper.start()
        # Physical telemetry for the head host, published per local
        # logical node (reference: reporter_agent.py; remote hosts run
        # their own reporter inside the node agent).
        from .reporter import NodeTelemetryReporter

        def _local_nodes():
            with self._lock:
                return [(n.idx, n.store) for n in self.nodes.values()
                        if n.alive and not n.is_remote]

        self._telemetry = NodeTelemetryReporter(
            lambda batch: self._h_metrics_report(None, 0, batch),
            _local_nodes)
        self._telemetry.start()
        # Straggler detector: periodically compare each RUNNING task's
        # current exec time against its func's completed-exec p95 and
        # per-node phase p95s against the cluster median (reference
        # motivation: one straggler gates every synchronous TPU step).
        if get_config().straggler_detect_period_s > 0:
            self._straggler_thread = threading.Thread(
                target=self._straggler_loop, daemon=True,
                name="head-straggler")
            self._straggler_thread.start()
        # Worker spawner thread: fork+exec of an interpreter costs
        # 20-300 ms of syscalls — measured blocking the head IO loop
        # (and the head lock) for exactly that long per spawn when run
        # inline in a lease handler. _spawn_worker records the starting
        # WorkerInfo synchronously (stampede accounting) and hands the
        # Popen to this thread. (reference: worker_pool.cc forks from
        # the raylet main loop but the raylet is not also the GCS)
        self._spawner = threading.Thread(
            target=self._spawn_loop, daemon=True, name="head-spawner")
        self._spawner.start()
        # Task-event fold thread: folds TASK_EVENTS batches into the
        # timeline table off the IO loop (handlers just enqueue). Started
        # here so unstarted unit-test heads keep folding inline.
        self._fold_thread = threading.Thread(
            target=self._fold_loop, daemon=True, name="head-fold")
        self._fold_thread.start()
        # Lease dispatcher thread: batched grant passes off the IO loop.
        self._dispatcher = threading.Thread(
            target=self._dispatch_loop, daemon=True, name="head-dispatch")
        self._dispatcher.start()
        # Prestart the worker pool (reference: WorkerPool prestart,
        # worker_pool.cc num_prestarted_python_workers): interpreter
        # startup costs O(seconds); forking CPU-count workers now means a
        # first burst of tasks finds idle workers instead of paying the
        # spawn storm mid-workload.
        cfg = get_config()
        if cfg.prestart_workers:
            with self._lock:
                for node in self.nodes.values():
                    if node.is_remote:
                        continue
                    n = int(node.resources.total.to_dict().get("CPU", 0))
                    n = min(n, cfg.max_workers_per_node)
                    for _ in range(n):
                        self._spawn_worker(node, ("prestart",))

    def enable_tcp(self, host: str = "0.0.0.0", port: int = 0,
                   advertise_ip: str = "") -> str:
        """Open the TCP control-plane listener so other hosts can join
        (the reference's gRPC GcsServer port; SURVEY.md §5 DCN plane)."""
        if self.tcp_addr:
            return self.tcp_addr
        self._tcp_listener = P.listen_tcp(host, port)
        bound_port = self._tcp_listener.getsockname()[1]
        ip = advertise_ip or (host if host not in ("0.0.0.0", "") else
                              _local_ip())
        self.tcp_addr = f"tcp:{ip}:{bound_port}"
        self.io.add_listener(self._tcp_listener, self._on_accept)
        # Multi-host session: serve the head's local arenas to peers.
        from .object_transfer import TransferServer

        self._transfer_server = TransferServer(
            self.io, self._read_local_object, advertise_ip=ip,
            partial_fn=self._partial_local_object)
        return self.tcp_addr

    def _read_local_object(self, oid: ObjectID):
        """TransferServer read_fn over every in-process node store: any
        local holder in the directory can serve the pull (primary first)."""
        with self.objects.lock_for(oid):
            loc = self.objects.get(oid)
            if loc is None:
                return None
            nodes = self._holder_nodes(loc)
        for node in nodes:
            if node.store is None:
                continue
            got = node.store.get(oid)
            if got is None:
                continue
            data_v, meta_v = got
            return (data_v, bytes(meta_v),
                    lambda n=node: n.store.release(oid))
        return None

    def _partial_local_object(self, oid: ObjectID):
        """TransferServer partial_fn over every in-process node store:
        an in-progress pull into any head-local arena can relay its
        chunks to downstream pullers (cooperative broadcast)."""
        with self._lock:
            stores = [n.store for n in self.nodes.values()
                      if n.store is not None and n.alive]
        for s in stores:
            part = s.partial(oid)
            if part is not None:
                return part
        return None

    def _puller_for(self, node: NodeState):
        from .object_transfer import ObjectPuller

        p = self._pullers.get(node.idx)
        if p is None:
            p = self._pullers[node.idx] = ObjectPuller(self.io, node.store)
        return p

    # ------------------------------------------------------------- nodes

    def add_node(self, num_cpus=None, num_tpus=None, memory=None,
                 object_store_memory=None, resources=None, labels=None,
                 tpu_topology=None) -> int:
        cfg = get_config()
        with self._lock:
            idx = self._next_node_idx
            self._next_node_idx += 1
        store_name = f"rtpu_{self.session_name}_{idx}"
        cap = object_store_memory or cfg.object_store_memory
        store = ShmObjectStore(store_name, cap, create=True)
        # head-driven writes into this arena (relay _node_store_write,
        # _puller_for pulls) can evict LRU objects: keep the object
        # directory honest for those too. Workers attached to the same
        # arena report their own evictions via context's hook. _lock is
        # an RLock, so firing inside a locked head path is safe.
        store.on_evict = lambda oids, _i=idx: self._on_local_evictions(
            _i, oids)
        nr = detect_node_resources(num_cpus=num_cpus, num_tpus=num_tpus,
                                   memory=memory,
                                   object_store_memory=cap,
                                   resources=resources, labels=labels)
        if tpu_topology is not None:
            nr.tpu = tpu_topology
        node = NodeState(idx=idx, resources=nr, store=store,
                         store_name=store_name)
        with self._lock:
            self.nodes[idx] = node
            self.scheduler.add_node(idx, nr)
            self._last_node_reg_ts = time.monotonic()
        self.emit_event("INFO", "head", "node_registered",
                        f"local node {idx} registered", node_idx=idx,
                        extra={"resources": nr.total.to_dict()})
        self._flush_restored()
        return idx

    def _grace_active(self) -> bool:
        """Restarted head's SCHEDULING holdback: True while lease
        granting and the detectors must wait for re-registrations.
        Lifts at ``head_restart_grace_s``, or EARLY once at least one
        node is registered and no node/worker registration has landed
        for 0.5s (reattaches arrive in a burst — the quiet period marks
        the stream's end, so an embedded restart pays ~0.5s instead of
        the full window). The restored-entity flush holdback
        (``_flush_restored``) deliberately does NOT lift early: a
        surviving actor worker's reclaim may trail the node burst by a
        couple of backoff rounds, and a WAL reschedule racing it would
        fork a fresh actor that shadows the live one."""
        gu = self._grace_until
        if not gu:
            return False
        now = time.monotonic()
        if now >= gu:
            self._grace_until = 0.0
            self._report_grace_end()
            return False
        if self.nodes and now - self._last_node_reg_ts >= 0.5:
            # scheduling resumes early; _grace_until stays set so the
            # restored-entity flush still waits out the full window.
            # No dispatcher kick here: this is routinely observed from
            # INSIDE a dispatch pass (via _try_grant_locked), and the
            # dispatcher's 0.25s tick resumes queued leases anyway.
            self._report_grace_end()
            return False
        return True

    def _report_grace_end(self):
        if self._grace_reported:
            return
        self._grace_reported = True
        self.emit_event(
            "INFO", "head", "head_grace_ended",
            f"restart grace window ended with {len(self.nodes)} nodes "
            f"({self.node_reattaches} reattached); scheduling resumed",
            extra={"nodes": len(self.nodes),
                   "node_reattaches": self.node_reattaches})

    def _flush_restored(self):
        """Reschedule durable entities replayed from a previous head's WAL,
        now that a node exists to place them on (reference: GCS failover
        reschedules detached actors / placement groups from the Redis
        tables — gcs_actor_manager.cc, gcs_placement_group_manager.cc).
        Held back for the FULL restart grace window: a surviving actor
        worker re-claiming its actor must win over a fresh reschedule of
        the same WAL spec (the reclaim empties the spec from the
        restored list, making this a no-op for it)."""
        if self._grace_until and time.monotonic() < self._grace_until:
            return  # periodic() retries once the window expires
        with self._lock:
            pg_specs, self._restored_pg_specs = self._restored_pg_specs, []
            a_specs, self._restored_actor_specs = \
                self._restored_actor_specs, []
        for sb in pg_specs:
            spec: PlacementGroupSpec = loads(sb)
            with self._lock:
                if spec.pg_id in self.pgs:
                    continue
                placement = self.scheduler.place_bundles(spec)
                if placement is None:
                    self.pgs[spec.pg_id] = PgInfo(spec=spec)
                    self._pending_pg.append(spec.pg_id)
                else:
                    self._commit_pg(spec, placement)
        for sb in a_specs:
            spec = loads(sb)
            info = ActorInfo(actor_id=spec.actor_id, spec=spec,
                             name=spec.name or "")
            with self._lock:
                if spec.actor_id in self.actors or (
                        info.name and info.name in self.named_actors):
                    continue
                self.actors[spec.actor_id] = info
                if info.name:
                    self.named_actors[info.name] = spec.actor_id
            self._schedule_actor(info)

    # scheduling class assigned to workers recreated from an agent's
    # re-registration report: they re-enter the idle pool under it when
    # their own REGISTER lands, so the repurpose-across-classes path can
    # lease them again instead of forking fresh interpreters
    REATTACH_CLASS = ("_reattached",)

    def register_remote_node(self, conn: P.Connection, resources,
                             store_name: str, node_ip: str,
                             session_dir: str,
                             transfer_addr: str = "",
                             prior_idx: int = -1, worker_ids=(),
                             holder_report=()) -> int:
        """A node agent on another host joins over TCP (the reference's
        raylet registration with the GCS, gcs_node_manager.cc).

        Re-registration (GCS-FT analog: raylets re-register after a
        gcs_server restart): a reattaching agent sends its PRIOR node
        id, its live worker set, and a full object-store holder report.
        The head keeps (or recreates) the node under the same index,
        recreates the reported workers as ``starting`` entries (each
        flips to a leasable idle worker when its own REGISTER arrives),
        and rebuilds the — deliberately non-WAL'd — object directory
        from holder truth."""
        reattached = False
        with self._lock:
            # Idempotent per connection: a reconnecting agent's reattach
            # hook re-registers AND its original in-flight REGISTER_NODE
            # (no prior idx yet) may be replayed afterwards on the same
            # socket — the second request must return the same node, not
            # mint a ghost entry that double-counts the host's resources.
            prev = getattr(conn, "_registered_node_idx", None)
            if prev is not None:
                existing = self.nodes.get(prev)
                if existing is not None and \
                        existing.store_name == store_name:
                    return prev
            node = None
            if prior_idx >= 0:
                existing = self.nodes.get(prior_idx)
                if existing is not None and \
                        existing.store_name == store_name:
                    # brief socket loss, head never evicted the node:
                    # swap the channel in place
                    node = existing
                    old = node.agent_conn
                    if old is not None and old is not conn:
                        old.on_close = None
                        old.close()
                    node.agent_conn = conn
                    node.alive = True
                    node.health_failures = 0
                    idx = prior_idx
                    reattached = True
                elif existing is None:
                    # restarted head: the table died with it — recreate
                    # the node under its prior index so worker env vars
                    # and directory reports stay coherent
                    idx = prior_idx
                    self._next_node_idx = max(self._next_node_idx,
                                              prior_idx + 1)
                    reattached = True
                # else: index collision with a different store (prior
                # idx recycled) — fall through to a fresh index
            if node is None:
                if not reattached:
                    idx = self._next_node_idx
                    self._next_node_idx += 1
                node = NodeState(idx=idx, resources=resources, store=None,
                                 store_name=store_name, agent_conn=conn,
                                 node_ip=node_ip, session_dir=session_dir,
                                 transfer_addr=transfer_addr)
                self.nodes[idx] = node
                self.scheduler.add_node(idx, resources)
            now = time.monotonic()
            self._last_node_reg_ts = now
            conn._registered_node_idx = idx
            if reattached:
                self.node_reattaches += 1
                for wid in worker_ids:
                    if wid in node.workers:
                        continue
                    node.workers[wid] = WorkerInfo(
                        worker_id=wid, node_idx=idx,
                        sched_class=self.REATTACH_CLASS, spawned_at=now)
        conn.peer = f"agent:node{idx}"
        conn.on_close = lambda c, i=idx: self._on_agent_close(i)
        # holder truth -> object directory (off the head lock: the
        # directory has its own shard locks). Answers any locates that
        # were already parked by reconnected drivers.
        for ob, size in holder_report:
            self._directory_add(ObjectID(ob), idx, int(size))
        if reattached:
            self.emit_event(
                "INFO", "head", "node_reattached",
                f"node {idx} re-registered from {node_ip} "
                f"({len(worker_ids)} live workers, "
                f"{len(holder_report)} held objects reported)",
                node_idx=idx,
                extra={"node_ip": node_ip,
                       "live_workers": len(worker_ids),
                       "held_objects": len(holder_report)})
        else:
            self.emit_event("INFO", "head", "node_registered",
                            f"remote node {idx} joined from {node_ip}",
                            node_idx=idx,
                            extra={"node_ip": node_ip,
                                   "resources":
                                       resources.total.to_dict()})
        self._publish("node_added", dumps(idx))
        self._flush_restored()
        return idx

    def _on_agent_close(self, idx: int):
        """Agent connection lost => the host is gone (failure detection)."""
        if not self._shutdown:
            self.remove_node(idx, kill_workers=True)

    def _h_register_node(self, conn, rid, resources, store_name, node_ip,
                         session_dir, transfer_addr="", prior_idx=-1,
                         worker_ids=(), holder_report=()):
        idx = self.register_remote_node(conn, resources, store_name,
                                        node_ip, session_dir, transfer_addr,
                                        prior_idx=prior_idx,
                                        worker_ids=worker_ids,
                                        holder_report=holder_report)
        conn.reply(rid, idx, self.session_name,
                   msg_type=P.REGISTER_NODE_REPLY)
        # Handshake clock-offset probe: sample (agent_mono - head_mono)
        # NOW rather than waiting for the first health-check period, so
        # the node's very first task events already fold into the head
        # timebase. Off-thread: the agent's PING reply rides this same
        # IO thread.
        node = self.nodes.get(idx)
        if node is not None:
            threading.Thread(target=self._ping_node, args=(node,),
                             daemon=True, name="clock-probe").start()
        self._try_fulfill_pending()

    # --------------------------------------------------- graceful drain

    def drain_node(self, idx: int) -> bool:
        """Begin a GRACEFUL drain (r16; reference: the NodeManager
        ``DrainNode`` RPC the autoscaler uses for planned scale-down —
        node_manager.cc HandleDrainNode — vs the kill path chaos
        exercises). The node is immediately excluded from lease grants,
        placements and prefetch/warm targets (``scheduler.drain_node``
        pulls it from the schedulable set); its sole-copy objects
        replicate off via the existing pull machinery; and once every
        in-flight lease has completed — or ``drain_deadline_s`` passes,
        whichever first — the deliberate r12 ``SHUTDOWN_NODE`` removal
        fires. A ``node_draining`` event + pubsub frame lets workloads
        (the pipeline's stage migration) move their work off BEFORE the
        shutdown instead of eating a crash. Idempotent; False when the
        node is unknown/dead — or the BOOTSTRAP node (idx 0): that is
        the head host's own node, whose arena the driver puts into and
        whose removal the drain would escalate to, bricking the
        cluster from one CLI command (the reference likewise never
        drains the head node)."""
        if idx == 0:
            return False
        with self._lock:
            node = self.nodes.get(idx)
            if node is None or not node.alive:
                return False
            if node.draining:
                return True
            node.draining = True
            node.drain_started = time.monotonic()
            node.drain_replicating = True  # first pass spawns below
            self.scheduler.drain_node(idx)
            self.drains_started += 1
            live_leases = sum(1 for l in self.leases.values()
                              if l[0] == idx)
        # speculative pulls aimed at a departing host are wasted work
        # (and would re-create copies the drain is moving off)
        self._purge_node_prefetches(idx)
        deadline_s = get_config().drain_deadline_s
        self.emit_event(
            "WARNING", "head", "node_draining",
            f"node {idx} draining: {live_leases} in-flight leases, "
            f"deadline {deadline_s:g}s",
            node_idx=idx,
            extra={"live_leases": live_leases,
                   "drain_deadline_s": deadline_s})
        self._publish("node_draining", dumps(idx))
        threading.Thread(target=self._replicate_off_node, args=(idx,),
                         daemon=True, name=f"drain-replicate-{idx}")\
            .start()
        return True

    class _ReplySink:
        """Throwaway conn stand-in for internal reuse of reply-shaped
        helpers (the drain replication pass drives _do_object_transfer
        with no requester to answer)."""

        def __init__(self):
            self.ok = False
            self.err = None

        def reply(self, rid, *fields, **kw):
            self.ok = True

        def reply_error(self, rid, err):
            self.err = err

    def _replicate_off_node(self, idx: int):
        """Drain replication pass: every object whose ONLY arena copy
        lives on the draining node is copied to a surviving node
        through the normal transfer machinery (store-to-store for
        remote targets, arena memcpy for head-local ones), so the
        eventual SHUTDOWN_NODE loses no data. Spilled objects already
        survive on disk. Sets ``drain_replicated`` when done — the
        drain completion check waits for it (up to the deadline)."""
        moved = failed = 0
        aborted = False
        assigned_bytes: Dict[int, int] = {}  # spread across survivors
        # ONE survivor snapshot per pass — re-scanning the node table
        # under the head lock per object would serialize a large drain
        # against the grant path O(objects) times. The per-object
        # failover below tolerates a stale entry (a dying target just
        # fails that transfer; the _check_drains retry re-snapshots).
        with self._lock:
            all_targets = [n for n in self.nodes.values()
                           if n.alive and not n.draining
                           and n.idx != idx
                           and (n.store is not None
                                or n.agent_conn is not None)]
        for oid, loc in self.objects.items_snapshot():
            with self.objects.lock_for(oid):
                sole = self._is_sole_copy(idx, loc)
            if not sole:
                continue
            if not all_targets:
                aborted = True
                break  # nowhere to put copies: deadline escalation
            # least-loaded-first over the bytes THIS pass already
            # assigned (tie -> lowest idx), with the rest as failover —
            # funneling everything at one survivor would fill its
            # arena and fail the replication the drain exists for
            targets = sorted(
                all_targets,
                key=lambda n: (assigned_bytes.get(n.idx, 0), n.idx))
            ok = False
            for dst in targets:
                sink = self._ReplySink()
                try:
                    self._do_object_transfer(sink, 0, oid, loc, dst)
                except Exception:  # noqa: BLE001 — try the next target
                    sink.err = sink.err or True
                if sink.ok:
                    ok = True
                    assigned_bytes[dst.idx] = \
                        assigned_bytes.get(dst.idx, 0) + loc.size
                    break
            if ok:
                moved += 1
                self.drain_objects_replicated += 1
            else:
                failed += 1
        # the clean-finish path requires EVERY sole copy safely moved:
        # an aborted or partly-failed pass leaves the flag unset, so
        # the drain waits out the deadline and escalates with the
        # honest drain_forced WARNING instead of reporting "copies
        # replicated" over silent data loss
        with self._lock:
            node = self.nodes.get(idx)
            if node is not None:
                node.drain_replicating = False
                node.drain_last_pass = time.monotonic()
                if not aborted and failed == 0:
                    node.drain_replicated = True
        if moved or failed:
            self.emit_event(
                "INFO", "head", "node_draining",
                f"node {idx} drain replication: {moved} sole-copy "
                f"objects moved off" + (f", {failed} failed" if failed
                                        else ""),
                node_idx=idx,
                extra={"replicated": moved, "failed": failed})

    @staticmethod
    def _is_sole_copy(idx: int, loc: _ObjLoc) -> bool:
        """The ONE sole-copy predicate drain replication and its
        completion re-scan must agree on (caller holds the object's
        shard lock) — two drifting copies would either finish a drain
        over unreplicated objects or loop passes forever."""
        return (idx in loc.holders and len(loc.holders) == 1
                and not loc.spilled_path and loc.size > 0)

    def _sole_copy_count(self, idx: int) -> int:
        """Objects whose ONLY arena copy lives on node ``idx`` (the
        drain completion check re-verifies this right before removal —
        a lease still running during the replication pass may have
        put() fresh sole copies after the pass scanned)."""
        count = 0
        for oid, loc in self.objects.items_snapshot():
            with self.objects.lock_for(oid):
                if self._is_sole_copy(idx, loc):
                    count += 1
        return count

    def _check_drains(self):
        """Housekeeping: complete or escalate in-progress drains. A
        drain completes — ``node_drained`` + the deliberate removal
        (SHUTDOWN_NODE to the agent) — once the node holds no live
        leases, the replication pass finished clean, AND a final
        sole-copy re-scan comes back empty (objects created on the
        node AFTER the pass re-run it rather than dying with the
        removal); past ``drain_deadline_s`` it force-escalates
        (``drain_forced``) instead of wedging, and surviving work
        rides the normal lineage/retry machinery."""
        deadline_s = get_config().drain_deadline_s
        now = time.monotonic()
        candidates: List[int] = []
        repass: List[int] = []
        force: List[Tuple[int, int, bool]] = []
        with self._lock:
            for node in self.nodes.values():
                if not node.draining or not node.alive:
                    continue
                left = sum(1 for l in self.leases.values()
                           if l[0] == node.idx)
                if now - node.drain_started > deadline_s:
                    force.append((node.idx, left,
                                  node.drain_replicated))
                elif left == 0 and node.drain_replicated \
                        and not node.drain_replicating:
                    candidates.append(node.idx)
                elif not node.drain_replicated \
                        and not node.drain_replicating \
                        and now - node.drain_last_pass > 1.0:
                    # the last pass failed (transient transfer error,
                    # or momentarily no target) — keep retrying inside
                    # the deadline rather than letting one hiccup turn
                    # into a forced escalation
                    node.drain_replicating = True
                    repass.append(node.idx)
        for idx in repass:
            threading.Thread(target=self._replicate_off_node,
                             args=(idx,), daemon=True,
                             name=f"drain-replicate-{idx}").start()
        finish: List[int] = []
        for idx in candidates:
            if self._sole_copy_count(idx) == 0:
                finish.append(idx)
                continue
            # fresh sole copies landed after the replication pass (a
            # then-live lease put() them): run another pass before
            # declaring the drain clean
            with self._lock:
                node = self.nodes.get(idx)
                if node is None or node.drain_replicating:
                    continue
                node.drain_replicated = False
                node.drain_replicating = True
            threading.Thread(target=self._replicate_off_node,
                             args=(idx,), daemon=True,
                             name=f"drain-replicate-{idx}").start()
        for idx in finish:
            self.drains_completed += 1
            self.emit_event(
                "INFO", "head", "node_drained",
                f"node {idx} drained: all leases migrated, copies "
                "replicated; shutting it down",
                node_idx=idx,
                extra={"forced": False,
                       "migrated_leases": self.drain_migrated_leases})
            self._publish("node_drained", dumps(idx))
            self.remove_node(idx)
        for idx, left, replicated in force:
            self.drains_forced += 1
            self.emit_event(
                "WARNING", "head", "drain_forced",
                f"node {idx} drain deadline ({deadline_s:g}s) passed "
                f"with {left} leases still live"
                + ("" if replicated
                   else " and sole-copy replication incomplete")
                + " — force-removing (surviving work retries via "
                "lineage)",
                node_idx=idx,
                extra={"leases_killed": left,
                       "replication_done": replicated,
                       "drain_deadline_s": deadline_s})
            self._publish("node_drained", dumps(idx))
            self.remove_node(idx)

    def remove_node(self, idx: int, kill_workers: bool = True):
        """Node failure (chaos testing / scale-down / agent loss)."""
        with self._lock:
            node = self.nodes.pop(idx, None)
            self.scheduler.remove_node(idx)
        with self._metrics_lock:
            self.node_telemetry.pop(idx, None)
            self._slow_node_until.pop(idx, None)
            for phase in ("dispatch", "arg_fetch"):
                self._node_phase_prev.pop((idx, phase), None)
            # prune the node's telemetry gauges from the merged metric
            # table too — a dead host must not keep exporting
            # fresh-looking node_cpu_percent rows to scrapers forever
            # (match on the reserved {"node": idx} tag shape so user
            # metrics merely named node.* are untouched)
            for key in [k for k, row in self.metrics.items()
                        if k[0].startswith("node.")
                        and row["tags"] == {"node": str(idx)}]:
                del self.metrics[key]
            # ... and its per-node phase histograms: a removed node's
            # frozen dispatch/arg_fetch distribution must not keep
            # feeding the slow_node skew detector (or the exposition)
            for key in [k for k, row in self.metrics.items()
                        if k[0] == "task.node_phase_ms"
                        and row["tags"].get("node") == str(idx)]:
                del self.metrics[key]
        if node is None:
            return
        node.alive = False
        # prefetches aimed at the dead host can never land: drop them
        # and release their source charges (no waste counting — host
        # loss, not task churn)
        self._purge_node_prefetches(idx)
        # a drained node's removal is the PLANNED end of a graceful
        # drain, not a failure — keep severity-based alerting honest
        self.emit_event(
            "INFO" if node.draining else "ERROR", "head", "node_dead",
            f"node {idx} removed"
            + (" after graceful drain" if node.draining else "")
            + (" (agent lost/evicted)"
               if node.is_remote and not node.draining else ""),
            node_idx=idx,
            extra={"is_remote": node.is_remote,
                   "drained": node.draining,
                   "workers_killed": len(node.workers)
                   if kill_workers else 0})
        if kill_workers:
            doomed = list(node.workers.values())
            for w in doomed:
                self._kill_worker_process(w)
            for w in doomed:
                if w.actor_id is not None:
                    # _kill_worker_process pre-marks the worker "dead",
                    # which SUPPRESSES the conn-close death path — so a
                    # node removal used to leave its actors ALIVE with a
                    # dead address and pending callers hung to their
                    # timeout. Route the death explicitly: restartable
                    # actors reschedule elsewhere, the rest go DEAD and
                    # every pending caller gets a prompt ActorDiedError
                    # (the surface the r16 pipeline repair planner
                    # relies on).
                    self._on_actor_worker_death(w.actor_id)
        # objects whose ONLY copy lived on this node are lost: answer any
        # blocked locates with the LOST sentinel (-2) and remember the ids
        # so later locates fail fast — owners react by re-executing the
        # creating task (lineage reconstruction; reference:
        # object_recovery_manager.h:41). Objects with surviving replicas
        # in the directory just fail over to another holder.
        # broadcast bookkeeping for the dead host: it can no longer be a
        # relay (in-progress location) nor serve its assigned downstream
        # pulls — drop both so the planner stops routing at it (its
        # in-flight downstream pullers fail over via connection loss)
        dead_addr = node.transfer_addr if node.is_remote else ""
        self._reply_lost(self.objects.purge_node(idx, dead_addr))
        if node.store is not None:
            node.store.close()
        if node.agent_conn is not None:
            node.agent_conn.on_close = None
            try:
                # deliberate eviction: tell the agent to die now rather
                # than reconnect-and-re-register off the socket close
                node.agent_conn.send(P.SHUTDOWN_NODE)
            except P.ConnectionLost:
                pass  # agent already gone (the usual removal cause)
            node.agent_conn.close()
        self._publish("node_removed", dumps(idx))

    def _kill_worker_process(self, w: WorkerInfo):
        w.state = "dead"
        if w.conn:
            if w.sched_class is not None or w.actor_id is not None:
                # r12: workers hold RECONNECTING head channels — a bare
                # close reads as a head outage and the worker would
                # linger for head_reconnect_timeout_s re-dialing the
                # live head and retrying registration. Send the
                # explicit die-now frame first (the context's
                # KILL_ACTOR handler os._exit(0)s) so deliberate kills
                # stay instant even when no agent/proc handle can
                # deliver a signal (e.g. node removal after its agent
                # died). Never sent to drivers (sched_class None,
                # no actor).
                try:
                    w.conn.send(P.KILL_ACTOR, b"", True)
                except P.ConnectionLost:
                    pass
            w.conn.close()
        if w.proc and w.proc.poll() is None:
            try:
                w.proc.kill()
            except OSError:
                pass
        elif w.proc is None:
            # remote worker: ask its node agent to kill the process
            node = self.nodes.get(w.node_idx)
            if node is not None and node.agent_conn is not None:
                try:
                    node.agent_conn.send(P.KILL_WORKER, w.worker_id)
                except P.ConnectionLost:
                    pass

    # --------------------------------------------------------- accept/IO

    def _on_accept(self, sock, addr):
        conn = P.Connection(sock, peer="incoming")
        conn.on_close = self._on_conn_close
        self.io.add_connection(conn, self._on_message)

    def _on_conn_close(self, conn: P.Connection):
        with self._lock:
            dead = None
            for node in self.nodes.values():
                for w in node.workers.values():
                    if w.conn is conn and w.state != "dead":
                        dead = w
                        break
        if dead is not None:
            self._handle_worker_death(dead)
        for chan_subs in self.subs.values():
            chan_subs.discard(conn)

    def _on_message(self, conn: P.Connection, msg):
        mt, rid = msg[0], msg[1]
        try:
            handler = self._HANDLERS[mt]
        except KeyError:
            if rid > 0:
                conn.reply_error(rid, ValueError(f"unknown msg {mt}"))
            return
        # Request dedupe (GCS-FT analog): a reconnecting channel replays
        # in-flight requests after reattach with their original rids. A
        # mutation that already landed is re-ACKED from the cache (or
        # the generic per-type ack for WAL-restored keys), never
        # re-applied; first-time requests run under a recording proxy.
        target = conn
        if rid > 0 and mt in _DEDUPE_TYPES:
            cid = getattr(conn, "client_id", None)
            if cid is not None:
                key = (cid, rid)
                hit, cached = self._dedupe_lookup(key)
                if hit:
                    self.dedupe_hits += 1
                    if cached is None:
                        cached = _DEDUPE_GENERIC[mt]
                    reply_mt, fields = cached
                    try:
                        conn.send(reply_mt, *fields, request_id=-rid)
                    except P.ConnectionLost:
                        pass
                    return
                target = _DedupeRecorder(self, conn, key, mt)
        try:
            handler(self, target, rid, *msg[2:])
        except P.ConnectionLost as e:
            # Swallow ONLY "the requester itself vanished mid-request"
            # (e.g. a worker killed during a shutdown wave): nobody to
            # answer, and replying would raise on the same dead socket.
            # Anything else — another peer's socket breaking inside a
            # handler's fan-out, or a ConnectionLost UNPICKLED from a
            # remote error reply (``__reduce__`` strips ``conn``, so it
            # arrives with conn=None) — is a real handler failure: the
            # requester is alive and must hear it, not block to its RPC
            # timeout.
            if e.conn is not conn:
                if rid > 0:
                    try:
                        conn.reply_error(rid, e)
                    except P.ConnectionLost:
                        pass
                else:
                    import traceback

                    traceback.print_exc()
        except Exception as e:  # noqa: BLE001
            if rid > 0:
                try:
                    conn.reply_error(rid, e)
                except P.ConnectionLost:
                    pass
            else:
                import traceback

                traceback.print_exc()

    def _dedupe_lookup(self, key):
        """-> (hit, cached_reply_or_None)."""
        with self._dedupe_lock:
            if key in self._dedupe:
                return True, self._dedupe[key]
        return False, None

    def _record_dedupe(self, key, mt: int, reply: tuple):
        with self._dedupe_lock:
            self._dedupe[key] = reply
            while len(self._dedupe) > _DEDUPE_CAP:
                self._dedupe.popitem(last=False)
        if mt in _DEDUPE_DURABLE and self._persist is not None:
            # the dedupe key must survive a crash ALONGSIDE the durable
            # mutation it acks — a retry crossing a restart is then
            # re-acked generically instead of re-applied
            self._enqueue_wal(("dedupe", key[0], key[1]))

    def _h_client_hello(self, conn, rid, client_id, reattach=False):
        """A reconnecting head channel identifies itself (first frame on
        every connect). The id keys the request-dedupe map; reattaches
        are counted for the reconnect-storm doctor warning — alongside
        the DISTINCT reattaching clients, so one clean restart of a
        large cluster (one reattach per client) is distinguishable from
        a flapping head (many reattaches per client)."""
        conn.client_id = client_id
        if reattach:
            self.client_reconnects += 1
            if len(self._reconnect_clients) < 8192:
                self._reconnect_clients.add(client_id)

    # ----------------------------------------------------- worker registry

    def _h_register(self, conn, rid, worker_id, pid, listen_addr, node_idx,
                    actor_spec_bytes=None):
        """Worker/driver registration. ``actor_spec_bytes`` (GCS-FT
        re-registration): a surviving ACTOR worker reconnecting after a
        head restart ships its creation TaskSpec so the restarted head
        rebuilds the actor table from worker truth — the actor keeps its
        state and address instead of being rescheduled from the WAL (the
        reference's gcs_actor_manager rebuilding from reports after
        failover)."""
        reclaim_info = None
        with self._lock:
            node = self.nodes.get(node_idx)
            if node is None:
                conn.reply_error(rid, RuntimeError(f"no node {node_idx}"))
                return
            w = node.workers.get(worker_id)
            if w is None:
                w = WorkerInfo(worker_id=worker_id, node_idx=node_idx)
                node.workers[worker_id] = w
            w.pid = pid
            w.listen_addr = listen_addr
            w.conn = conn
            conn.peer = f"worker:{worker_id[:8]}"
            if self._grace_until:
                # worker re-registrations extend the quiet window the
                # early scheduling lift waits on — they trail their
                # node's burst by a backoff round or two
                self._last_node_reg_ts = time.monotonic()
            stale_duplicate = False
            if actor_spec_bytes is not None:
                reclaim_info = self._reclaim_actor_locked(
                    node, w, actor_spec_bytes)
                if reclaim_info is None:
                    # the actor was already rescheduled onto another
                    # live worker while this one was away: this
                    # surviving instance is a stale duplicate — it must
                    # die, not linger as a second copy of the actor's
                    # state (and not sit in "starting" feeding the
                    # stuck-re-registering doctor warning)
                    stale_duplicate = True
                    w.actor_id = None
            elif w.state == "starting":
                w.state = "idle"
                w.idle_since = time.monotonic()
                if w.sched_class is not None:
                    node.idle_by_class.setdefault(w.sched_class, []).append(
                        worker_id)
        conn.reply(rid, node.store_name,
                   node.session_dir or self.session_dir)
        if stale_duplicate:
            with self._lock:
                # ensure the die-now poison is sent (it is gated off
                # drivers by sched_class/actor_id)
                w.sched_class = w.sched_class or self.REATTACH_CLASS
                self._kill_worker_process(w)
                node.workers.pop(worker_id, None)
            return
        if reclaim_info is not None:
            info, waiters = reclaim_info
            self.emit_event(
                "INFO", "head", "actor_reclaimed",
                f"actor {info.actor_id.hex()[:8]}"
                + (f" '{info.name}'" if info.name else "")
                + f" re-claimed by surviving worker {worker_id[:8]}",
                node_idx=node_idx, entity_id=info.actor_id.hex())
            for wconn, wrid in waiters:
                try:
                    wconn.reply(wrid, "ALIVE", info.listen_addr,
                                msg_type=P.GET_ACTOR_REPLY)
                except P.ConnectionLost:
                    pass
            self._publish(f"actor:{info.actor_id.hex()}",
                          dumps(("ALIVE", info.listen_addr)))
        self._try_fulfill_pending()

    def _reclaim_actor_locked(self, node: NodeState, w: WorkerInfo,
                              actor_spec_bytes: bytes):
        """Rebuild an actor's table entry from its surviving worker's
        re-registration (caller holds the head lock). Returns
        (ActorInfo, pending_get waiters) or None when another live
        worker already owns the actor id."""
        spec: TaskSpec = loads(actor_spec_bytes)
        aid = spec.actor_id
        info = self.actors.get(aid)
        if info is not None and info.state == "ALIVE" and \
                info.worker_id and info.worker_id != w.worker_id:
            # the current owner may live on ANY node (e.g. the WAL
            # reschedule picked a different host after this worker's
            # reattach outlasted the grace window) — checking only this
            # node's table would let the stale instance steal back
            for n in self.nodes.values():
                other = n.workers.get(info.worker_id)
                if other is not None and other.state != "dead":
                    return None  # already rescheduled onto a live worker
        w.state = "actor"
        w.actor_id = aid
        w.sched_class = spec.scheduling_class()
        if info is None:
            info = ActorInfo(actor_id=aid, spec=spec,
                             name=spec.name or "")
            self.actors[aid] = info
        info.state = "ALIVE"
        info.listen_addr = w.listen_addr
        info.worker_id = w.worker_id
        if info.name:
            self.named_actors[info.name] = aid
        waiters = list(info.pending_get_replies)
        info.pending_get_replies.clear()
        # the WAL-restored spec (if any) must not ALSO be rescheduled
        # when the grace window lifts — the reclaim wins
        aid_bin = aid.binary()
        self._restored_actor_specs = [
            sb for sb in self._restored_actor_specs
            if loads(sb).actor_id.binary() != aid_bin]
        # re-anchor resource accounting: the old lease died with the old
        # head — mint a fresh one so the actor's resources are held and
        # released on death like any scheduled actor's (best-effort: an
        # oversubscribed post-restart node just skips the allocation)
        req = ResourceSet(spec.resources)
        if w.lease_id is None:
            if node.resources.is_available(req):
                node.resources.allocate(req)
            else:
                req = ResourceSet({})
            lease_id = f"{self._lease_prefix}{next(self._lease_seq):x}"
            self.leases[lease_id] = (node.idx, req, w.worker_id, None,
                                     None)
            w.lease_id = lease_id
        self.actor_reclaims += 1
        return info, waiters

    def register_driver(self, conn: Optional[P.Connection] = None):
        self._driver_conn = conn

    # ----------------------------------------------------------- leases

    def _h_lease_request(self, conn, rid, sched_class, resources, job_id_hex,
                         strategy_bytes, arg_ids=None):
        """``arg_ids`` — binary ObjectIDs of the sample task's by-reference
        args (the reference ships the same hint with lease requests so the
        raylet can score locality, LocalityAwareLeasePolicy)."""
        self._queue_lease(conn, rid, sched_class, resources, job_id_hex,
                          strategy_bytes, arg_ids)
        self._try_fulfill_pending()

    def _queue_lease(self, conn, rid, sched_class, resources, job_id_hex,
                     strategy_bytes, arg_ids=None):
        # the strategy is parsed ONCE at enqueue — the old per-pass
        # loads() re-parsed every queued request on every dispatch retry
        strategy = loads(strategy_bytes)
        with self._lock:
            self._pending_leases.append(
                (conn, rid, tuple(sched_class), ResourceSet(resources),
                 job_id_hex, strategy_bytes, arg_ids, strategy))

    def _try_fulfill_pending(self):
        """Kick the lease dispatcher (reference:
        ClusterTaskManager::ScheduleAndDispatchTasks). With the
        dispatcher thread running (start()ed heads) this only signals
        it — callers on the IO loop return immediately; unstarted
        unit-test heads run the batched pass inline."""
        d = self._dispatcher
        if d is not None and d.is_alive():
            self._dispatch_event.set()
        else:
            self._dispatch_pass()

    def _dispatch_loop(self):
        while not self._shutdown:
            self._dispatch_event.wait(0.25)
            self._dispatch_event.clear()
            if self._shutdown:
                return
            try:
                self._dispatch_pass()
            except Exception:
                if not self._shutdown:
                    import traceback

                    traceback.print_exc()

    def _dispatch_pass(self):
        """ONE batched grant pass: every pending lease is tried under a
        single head-lock hold, and the grants are replied per-connection
        afterwards — many grants to one driver ride a single
        LEASE_GRANT_BATCH frame (the request-side mirror of r8's
        TASK_DONE_BATCH). Requests that stay ungrantable remain queued;
        anything that frees resources re-signals the dispatcher."""
        by_conn: Dict[P.Connection, list] = {}
        prefetch_jobs: List[tuple] = []
        with self._lock:
            if not self._pending_leases:
                return
            pending = list(self._pending_leases)
            demand: dict = {}
            for item in pending:
                demand[item[2]] = demand.get(item[2], 0) + 1
            # ONE cluster-wide starting-workers scan per pass (the
            # spawn gate reads it per attempt; rescanning nodes x
            # workers per pending lease would put O(pending * workers)
            # back under the head-lock hold)
            spawn_budget = [self._count_starting(time.monotonic())]
            for item in pending:
                (conn, rid, sched_class, request, _job_hex, _sb,
                 arg_ids, strategy) = item
                grant = self._try_grant_locked(
                    sched_class, request, strategy,
                    demand=demand.get(sched_class, 1), arg_ids=arg_ids,
                    spawn_budget=spawn_budget)
                if grant is None:
                    continue
                try:
                    self._pending_leases.remove(item)
                except ValueError:
                    continue
                worker, lease_id = grant
                tpu_ids = self.leases[lease_id][4]
                by_conn.setdefault(conn, []).append(
                    (rid, worker.worker_id, worker.listen_addr, lease_id,
                     tpu_ids))
                if arg_ids:
                    # speculative arg prefetch (r13): issued AFTER the
                    # lock drops, in this same pass, so the pull runs
                    # while the lease reply / driver dispatch / worker
                    # wakeup are still in flight
                    prefetch_jobs.append(
                        (lease_id, worker.node_idx, arg_ids))
        if not by_conn:
            return
        batch_max = get_config().lease_grant_batch_max
        for conn, grants in by_conn.items():
            try:
                if batch_max > 1 and len(grants) > 1:
                    for i in range(0, len(grants), batch_max):
                        chunk = grants[i:i + batch_max]
                        conn.send(P.LEASE_GRANT_BATCH, chunk)
                        self.lease_grant_batches += 1
                        self.lease_grants_batched += len(chunk)
                else:
                    for rid, wid, addr, lease_id, tpu_ids in grants:
                        conn.reply(rid, True, wid, addr, lease_id, None,
                                   tpu_ids, msg_type=P.LEASE_REPLY)
            except P.ConnectionLost:
                # Requester (driver) died while its lease request was
                # queued — undo the grants so the workers and resources
                # return to the pool instead of leaking.
                for _rid, wid, _addr, lease_id, _tpu in grants:
                    self._h_return_worker(conn, 0, lease_id, wid)
        for lease_id, node_idx, arg_ids in prefetch_jobs:
            self._maybe_prefetch_args(lease_id, node_idx, arg_ids)

    def _try_grant(self, sched_class, request: ResourceSet, strategy,
                   demand: int = 1, arg_ids=None
                   ) -> Optional[Tuple[object, str]]:
        with self._lock:
            return self._try_grant_locked(sched_class, request, strategy,
                                          demand=demand, arg_ids=arg_ids)

    def _count_starting(self, now: float) -> int:
        """Cluster-wide count of workers still forking/importing
        (caller holds the lock)."""
        return sum(1 for n in self.nodes.values()
                   for w in n.workers.values()
                   if w.state == "starting" and now - w.spawned_at < 60.0)

    def _try_grant_locked(self, sched_class, request: ResourceSet, strategy,
                          demand: int = 1, arg_ids=None, spawn_budget=None
                          ) -> Optional[Tuple[object, str]]:
        """Try to allocate resources + a worker. Returns (WorkerInfo,
        lease_id) on success, or None (possibly after kicking off a
        worker spawn — the request stays queued and re-tries once the
        worker registers).

        ``spawn_budget`` — one-element list holding the cluster-wide
        count of starting workers, shared across one dispatch pass so
        the gate below reads (and bumps) it instead of rescanning every
        node's worker table per pending lease; None (direct callers,
        e.g. actor scheduling) computes it fresh.

        ``demand`` caps the spawn stampede: if at least that many workers of
        any class are already starting on the node, no new process is forked
        (the round-1 bug was the actor-creation retry timer forking a fresh
        interpreter every 50ms, starving the CPU so *no* worker ever finished
        importing; ref: WorkerPool pending-registration accounting,
        src/ray/raylet/worker_pool.cc).

        ``arg_ids`` (binary ObjectIDs of the sample task's by-ref args)
        turns on locality-aware placement: when those args' directory
        sizes total at least ``locality_min_arg_bytes``, the node already
        holding the most argument bytes is preferred over the hybrid
        policy — the bytes then never move at all (reference:
        LocalityAwareLeasePolicy over the object directory).

        Callers hold the head lock (the RLock re-entry below costs a
        counter bump and keeps direct callers safe)."""
        cfg = get_config()
        if self._grace_active():
            # restarted head, re-registrations still streaming in:
            # granting now would schedule against a half-empty node
            # table — requests stay queued; the dispatcher's 0.25s tick
            # retries until the window lifts
            return None
        with self._lock:
            loc_choice = None
            pg_id = strategy.placement_group_id
            if pg_id is not None:
                node_idx = self._pg_node_for(pg_id, strategy.bundle_index,
                                             request)
                if node_idx is None:
                    return None
            else:
                node_idx = None
                # hit/miss is counted only when the lease is actually
                # granted (below) — a queued lease re-runs this branch on
                # every dispatch retry while its worker spawns, and
                # counting attempts would inflate the placement counters
                # the object_plane endpoint reports by the retry rate
                if (arg_ids and cfg.scheduler_locality_enabled
                        and strategy.kind == "DEFAULT"):
                    scores, total = self.objects.locality_scores(arg_ids)
                    if total >= cfg.locality_min_arg_bytes:
                        node_idx = self.scheduler.best_locality_node(
                            request, scores)
                        loc_choice = "hit" if node_idx is not None \
                            else "miss"
                if node_idx is None:
                    node_idx = self.scheduler.best_node(request, strategy)
                if node_idx is None:
                    return None
            node = self.nodes[node_idx]
            if (pg_id is None and loc_choice is None
                    and strategy.kind == "DEFAULT"
                    and not any(node.idle_by_class.values())):
                # The policy's pick would have to FORK an interpreter
                # (20-300 ms of syscalls plus seconds of imports) while
                # another feasible node already holds a warm idle worker
                # — retarget there (reference analog: the WorkerPool's
                # idle-worker reuse preference). The scale bench measured
                # 16 mid-wave forks with 20 idle workers sitting on
                # unchosen nodes before this.
                alt = self._node_with_idle_worker(sched_class, request)
                if alt is not None:
                    node_idx, node = alt
            # Affinity may target a feasible-but-busy node: stay queued.
            if pg_id is None and not node.resources.is_available(request):
                return None
            # allocate resources
            if pg_id is not None:
                self._pg_allocate(pg_id, strategy.bundle_index, request)
            else:
                node.resources.allocate(request)
            # pooled-entropy lease ids: uuid4 hits os.urandom per call
            # (~34 us on the deployment kernel) and a burst pass mints
            # one per grant ATTEMPT, rolled back or not
            lease_id = f"{self._lease_prefix}{next(self._lease_seq):x}"
            tpu_ids = self._allocate_tpu_chips(node, request)
            pg_binding = pg_id and (pg_id, strategy.bundle_index)
            self.leases[lease_id] = (node_idx, request, "", pg_binding,
                                     tpu_ids)
            # find idle worker of this class
            idle = node.idle_by_class.get(sched_class)
            if idle:
                wid = idle.pop(0)
                w = node.workers[wid]
                w.state = "leased"
                w.lease_id = lease_id
                self.leases[lease_id] = (node_idx, request, wid,
                                         pg_binding, tpu_ids)
                self._count_locality(loc_choice)
                return w, lease_id
            # reuse any idle worker (repurpose across scheduling classes)
            for cls, lst in node.idle_by_class.items():
                if lst:
                    wid = lst.pop(0)
                    w = node.workers[wid]
                    w.state = "leased"
                    w.sched_class = sched_class
                    w.lease_id = lease_id
                    self.leases[lease_id] = (node_idx, request, wid,
                                             pg_binding, tpu_ids)
                    self._count_locality(loc_choice)
                    return w, lease_id
            # spawn a new worker (unless enough are already starting),
            # re-queue the lease until it registers. The gate is bounded
            # by what THIS NODE can actually run concurrently for this
            # request — ``demand`` is the CLASS-wide pending count, and
            # gating on it alone let every node the scheduler touched
            # fork up to ``demand`` interpreters (measured: the worker
            # population grew 15 -> 74 across two identical task waves
            # while throughput halved; reference analog: WorkerPool
            # caps prestarts by available concurrency slots).
            now = time.monotonic()
            starting = sum(1 for w in node.workers.values()
                           if w.state == "starting"
                           and now - w.spawned_at < 60.0)
            req_cpu_fp = request.get_fp("CPU")
            if req_cpu_fp > 0:
                node_cap = max(1, node.resources.total.get_fp("CPU")
                               // req_cpu_fp)
            else:
                node_cap = get_config().max_workers_per_node
            # NOT gated on total live workers: leased workers may belong
            # to long-lived actors of other classes (counting them
            # starved gang creation on busy nodes); bounding STARTING
            # forks per node at its request-concurrency stops the
            # per-node storm. ALSO gated CLUSTER-WIDE at ``demand``:
            # the hybrid policy's randomized pick lands each retry pass
            # on fresh nodes, and the per-node gate alone let a 100-node
            # table fork up to 10 interpreters per pass on
            # never-before-touched nodes until ~100 were importing at
            # once on 2 cores (measured: head loop-lag p99 2.4s during
            # the scale wave from fork+import CPU alone). We never need
            # more forks in flight than ungranted requests exist.
            if spawn_budget is None:
                spawn_budget = [self._count_starting(now)]
            if starting < min(demand, node_cap) and \
                    spawn_budget[0] < demand:
                if self._spawn_worker(node, sched_class) is not None:
                    spawn_budget[0] += 1
            # roll back allocation; the pending lease will re-acquire
            if pg_id is not None:
                self._pg_release(pg_id, strategy.bundle_index, request)
            else:
                node.resources.release(request)
            self._release_tpu_chips(node, tpu_ids)
            del self.leases[lease_id]
            return None

    def _node_with_idle_worker(self, sched_class, request: ResourceSet
                               ) -> Optional[Tuple[int, NodeState]]:
        """A schedulable node that can take ``request`` right now AND
        already holds an idle worker — exact scheduling class preferred,
        any-class repurpose otherwise. Caller holds the lock."""
        fallback = None
        for idx in self.scheduler.schedulable_nodes():
            n = self.nodes.get(idx)
            if n is None or not n.alive or \
                    not n.resources.is_available(request):
                continue
            if n.idle_by_class.get(sched_class):
                return idx, n
            if fallback is None and any(n.idle_by_class.values()):
                fallback = (idx, n)
        return fallback

    def _count_locality(self, loc_choice: Optional[str]):
        """Locality placement counters, bumped only on a completed grant
        (caller holds the lock)."""
        if loc_choice == "hit":
            self.locality_hits += 1
        elif loc_choice == "miss":
            self.locality_misses += 1

    def _allocate_tpu_chips(self, node: NodeState, request: ResourceSet):
        """Assign specific chip indices for a TPU lease — the reference's
        CUDA_VISIBLE_DEVICES assignment (worker.py:888 get_gpu_ids,
        resource-instance ids); workers export TPU_VISIBLE_CHIPS.

        Caller holds the lock (called from _try_grant after allocation).
        """
        n = int(request.to_dict().get("TPU", 0))
        if n <= 0:
            return None
        if node.tpu_free is None:
            total = int(node.resources.total.to_dict().get("TPU", 0))
            node.tpu_free = list(range(total))
        chips = node.tpu_free[:n]
        del node.tpu_free[:n]
        return chips

    def _release_tpu_chips(self, node: NodeState, tpu_ids):
        if tpu_ids and node.tpu_free is not None:
            node.tpu_free.extend(tpu_ids)
            node.tpu_free.sort()

    def _spawn_worker(self, node: NodeState, sched_class) -> WorkerInfo:
        """Record the starting worker and hand the fork to the spawner
        thread — callers hold the head lock inside IO handlers, and a
        synchronous fork+exec here measurably stalled the whole control
        plane per spawn."""
        cfg = get_config()
        if len([w for w in node.workers.values() if w.state != "dead"]) >= \
                cfg.max_workers_per_node:
            return None  # type: ignore[return-value]
        worker_id = _random_bytes(16).hex()
        w = WorkerInfo(worker_id=worker_id, node_idx=node.idx,
                       sched_class=sched_class,
                       spawned_at=time.monotonic())
        node.workers[worker_id] = w
        if node.is_remote:
            # delegated fork: the node agent on the remote host Popens the
            # worker (the reference's raylet WorkerPool::StartWorkerProcess)
            try:
                node.agent_conn.send(P.SPAWN_WORKER, worker_id)
            except P.ConnectionLost:
                node.workers.pop(worker_id, None)
                return None  # type: ignore[return-value]
            return w
        self._spawn_q.put((node, w))
        return w

    def _spawn_loop(self):
        while not self._shutdown:
            try:
                item = self._spawn_q.get(timeout=0.5)
            except queue.Empty:
                continue
            node, w = item
            try:
                with self._lock:
                    if w.state != "starting":
                        continue  # killed/cleaned while queued
                self._popen_worker(node, w)
                # TOCTOU: a ghost-sweep/shutdown may have declared this
                # worker dead between the check and the fork — an
                # untracked interpreter would register with no
                # sched_class and pin a worker slot until head shutdown
                with self._lock:
                    if w.state != "starting" and w.proc is not None \
                            and w.proc.poll() is None:
                        try:
                            w.proc.kill()
                        except OSError:
                            pass
            except Exception as e:  # noqa: BLE001 — mark dead, don't die
                with self._lock:
                    w.state = "dead"
                    # drop the record too: persistent fork failure +
                    # the 0.25s lease retry would otherwise grow
                    # node.workers by a dead entry per attempt forever
                    node.workers.pop(w.worker_id, None)
                print(f"[ray_tpu] worker spawn failed: {e!r}",
                      file=sys.stderr)

    def _popen_worker(self, node: NodeState, w: WorkerInfo):
        worker_id = w.worker_id
        env = dict(os.environ)
        # Ship the driver's full sys.path to workers (the reference does the
        # same via its runtime env / worker setup, worker.py): functions and
        # classes pickled *by reference* (module-level defs, e.g. in pytest
        # test modules whose dir pytest inserted into sys.path) must be
        # importable where they execute.
        import ray_tpu

        pkg_parent = os.path.dirname(os.path.dirname(
            os.path.abspath(ray_tpu.__file__)))
        pp = env.get("PYTHONPATH", "")
        entries = [p for p in sys.path if p] + [pkg_parent]
        have = set(pp.split(os.pathsep)) if pp else set()
        add = [p for p in entries if p not in have]
        if add:
            env["PYTHONPATH"] = os.pathsep.join(
                add + ([pp] if pp else []))
        env.update({
            "RAY_TPU_WORKER_ID": worker_id,
            "RAY_TPU_HEAD_ADDR": self.addr,
            "RAY_TPU_NODE_IDX": str(node.idx),
            "RAY_TPU_SESSION_DIR": self.session_dir,
            # Workers must not grab the TPU: the driver/trainer owns devices
            # unless a task explicitly requests TPU resources.
            "JAX_PLATFORMS": env_jax_platform(node),
        })
        if env["JAX_PLATFORMS"] == "cpu":
            # The host sitecustomize force-registers the axon (tunneled TPU)
            # PJRT backend whenever this var is set, overriding JAX_PLATFORMS
            # and clobbering jax.distributed state — CPU-only workers must
            # not load it.
            env.pop("PALLAS_AXON_POOL_IPS", None)
        log_dir = os.path.join(self.session_dir, "logs")
        os.makedirs(log_dir, exist_ok=True)
        out = open(os.path.join(log_dir, f"worker-{worker_id[:8]}.out"), "ab")
        w.proc = subprocess.Popen(
            [sys.executable, "-m", "ray_tpu.core.worker_main"],
            env=env, stdout=out, stderr=subprocess.STDOUT,
            start_new_session=True)
        return w

    def _h_return_worker(self, conn, rid, lease_id, worker_id, dispose=False):
        try:
            self._return_worker_inner(lease_id, worker_id, dispose)
        finally:
            # runs even when the lease or its node is already gone
            # (node death raced the return): the lease's unconsumed
            # prefetches are stale speculation either way, and their
            # per-lease records must not accumulate across churn
            self._abort_lease_prefetches(lease_id)

    def _return_worker_inner(self, lease_id, worker_id, dispose):
        with self._lock:
            lease = self.leases.pop(lease_id, None)
            if lease is None:
                return
            node_idx, request, _, pg_binding, tpu_ids = lease
            node = self.nodes.get(node_idx)
            if node is None:
                return
            if node.draining and node.alive:
                # the lease ended while its node drains: work moved off
                # cleanly instead of dying with the shutdown
                self.drain_migrated_leases += 1
            if pg_binding:
                self._pg_release(pg_binding[0], pg_binding[1], request)
            else:
                node.resources.release(request)
            self._release_tpu_chips(node, tpu_ids)
            w = node.workers.get(worker_id)
            if w is not None and w.state == "leased":
                if dispose:
                    self._kill_worker_process(w)
                    node.workers.pop(worker_id, None)
                else:
                    w.state = "idle"
                    w.lease_id = None
                    w.idle_since = time.monotonic()
                    node.idle_by_class.setdefault(w.sched_class, []).append(
                        worker_id)
        self._try_fulfill_pending()
        # freed resources may unblock a pending placement group too
        self._retry_pending_pgs()

    def _handle_worker_death(self, w: WorkerInfo):
        with self._lock:
            # already "dead" => a deliberate kill (_kill_worker_process
            # ran first: kill(), OOM policy); only UNEXPECTED deaths log
            # a worker_died event — deliberate paths log their own
            # (actor_dead, worker_oom_kill), and a duplicate WARNING
            # here would false-alarm severity-based alerting
            unexpected = w.state != "dead"
            w.state = "dead"
            node = self.nodes.get(w.node_idx)
            if node:
                for lst in node.idle_by_class.values():
                    if w.worker_id in lst:
                        lst.remove(w.worker_id)
                if w.lease_id and w.lease_id in self.leases:
                    node_idx, request, _, pg_binding, tpu_ids = \
                        self.leases.pop(w.lease_id)
                    if node.draining and node.alive and not unexpected:
                        # deliberate kill during a drain (e.g. the
                        # pipeline retiring its migrated stage actor):
                        # the lease moved off, nothing failed
                        self.drain_migrated_leases += 1
                    if pg_binding:
                        self._pg_release(pg_binding[0], pg_binding[1], request)
                    else:
                        node.resources.release(request)
                    self._release_tpu_chips(node, tpu_ids)
            actor_id = w.actor_id
        if w.lease_id:
            self._abort_lease_prefetches(w.lease_id)
        if unexpected:
            self.emit_event("WARNING", "head", "worker_died",
                            f"worker {w.worker_id[:8]} died",
                            node_idx=w.node_idx, entity_id=w.worker_id)
        if actor_id is not None:
            self._on_actor_worker_death(actor_id)
        self._publish("worker_failed", dumps(w.worker_id))
        self._try_fulfill_pending()

    # ----------------------------------------------------------- actors

    def _h_create_actor(self, conn, rid, spec_bytes):
        spec: TaskSpec = loads(spec_bytes)
        info = ActorInfo(actor_id=spec.actor_id, spec=spec,
                         name=spec.name or "")
        with self._lock:
            self.actors[spec.actor_id] = info
            if info.name:
                if info.name in self.named_actors:
                    conn.reply_error(rid, ValueError(
                        f"actor name '{info.name}' already taken"))
                    return
                self.named_actors[info.name] = spec.actor_id
        if info.name and self._persist is not None:
            # named == detached: survives head restart (reference: GCS
            # actor table; detached actors rescheduled after failover)
            self._enqueue_wal(("actor", spec_bytes))
        self._schedule_actor(info)
        conn.reply(rid, True, msg_type=P.CREATE_ACTOR_REPLY)

    def _schedule_actor(self, info: ActorInfo):
        """Lease a worker and push the creation task (reference:
        GcsActorScheduler::ScheduleByGcs, gcs_actor_scheduler.cc:60)."""
        spec = info.spec
        request = ResourceSet(spec.resources)
        deadline = time.monotonic() + get_config().actor_creation_timeout_s
        # actors benefit from arg locality too: a big by-ref constructor
        # arg (e.g. sharded weights) anchors the actor next to the bytes
        # (same dedup + 32-arg hint cap as the task lease path)
        arg_ids = list(dict.fromkeys(
            enc[1] for enc in spec.args if enc[0] == ARG_REF))[:32]

        def attempt():
            if self._shutdown:
                return
            grant = self._try_grant(spec.scheduling_class(), request,
                                    spec.strategy, arg_ids=arg_ids)
            if grant is None:
                if time.monotonic() > deadline:
                    self._mark_actor_dead(info, "creation timed out (no "
                                          "feasible node/worker)")
                    return
                t = threading.Timer(0.05, attempt)
                t.daemon = True
                t.start()
                return
            w, lease_id = grant
            with self._lock:
                w.state = "actor"
                w.actor_id = spec.actor_id
                info.worker_id = w.worker_id
                info.listen_addr = w.listen_addr
                tpu_ids = self.leases[lease_id][4]
            try:
                push_spec = loads(dumps(spec))
                push_spec.tpu_ids = tpu_ids
                w.conn.send(P.PUSH_TASK, push_spec, 0)
            except P.ConnectionLost:
                self._on_actor_worker_death(spec.actor_id)
                return
            # ALIVE is announced only once the worker confirms the
            # constructor ran (TASK_REPLY on its registration conn).

        attempt()

    def _h_creation_reply(self, conn, rid, task_id_bin, status, result_meta,
                          err):
        """Actor-creation completion from the actor's worker."""
        with self._lock:
            w = None
            for node in self.nodes.values():
                for cand in node.workers.values():
                    if cand.conn is conn:
                        w = cand
                        break
            if w is None or w.actor_id is None:
                return
            info = self.actors.get(w.actor_id)
            if info is None:
                return
            if status != "ok":
                info.state = "DEAD"
                info.death_cause = f"creation failed: {err}"
                self._release_actor_name(info)
                waiters = list(info.pending_get_replies)
                info.pending_get_replies.clear()
                state, payload = "DEAD", info.death_cause
            else:
                info.state = "ALIVE"
                info.listen_addr = w.listen_addr
                waiters = list(info.pending_get_replies)
                info.pending_get_replies.clear()
                state, payload = "ALIVE", info.listen_addr
        if state == "ALIVE":
            self.emit_event(
                "INFO", "head", "actor_created",
                f"actor {info.spec.class_name or '?'} "
                f"{w.actor_id.hex()[:8]} alive",
                node_idx=w.node_idx, entity_id=w.actor_id.hex())
        else:
            self.emit_event("ERROR", "head", "actor_dead", payload,
                            node_idx=w.node_idx,
                            entity_id=w.actor_id.hex())
        for wconn, wrid in waiters:
            try:
                wconn.reply(wrid, state, payload,
                            msg_type=P.GET_ACTOR_REPLY)
            except P.ConnectionLost:
                pass  # that waiter died; the rest must still hear
        self._publish(f"actor:{w.actor_id.hex()}", dumps((state, payload)))

    def _h_actor_dead(self, conn, rid, actor_id_bin, cause):
        aid = ActorID(actor_id_bin)
        with self._lock:
            info = self.actors.get(aid)
        if info is not None:
            self._mark_actor_dead(info, cause)

    def _on_actor_worker_death(self, actor_id: ActorID):
        waiters: List[Tuple[P.Connection, int]] = []
        with self._lock:
            info = self.actors.get(actor_id)
            if info is None or info.state == "DEAD":
                return
            spec = info.spec
            can_restart = (spec.max_restarts == -1
                           or info.restarts_used < spec.max_restarts)
            if can_restart:
                info.restarts_used += 1
                info.state = "RESTARTING"
            else:
                info.state = "DEAD"
                info.death_cause = "worker died"
                self._release_actor_name(info)
                # GET_ACTOR waiters queued while the actor was
                # PENDING/RESTARTING must hear the death — the pubsub
                # channel alone leaves their blocking calls (and the
                # head-side waiter entries) stranded forever
                waiters = list(info.pending_get_replies)
                info.pending_get_replies.clear()
        for wconn, wrid in waiters:
            try:
                wconn.reply(wrid, "DEAD", info.death_cause,
                            msg_type=P.GET_ACTOR_REPLY)
            except P.ConnectionLost:
                pass  # that waiter died; the rest must still hear
        if info.state == "RESTARTING":
            self.emit_event(
                "WARNING", "head", "actor_restarted",
                f"actor {actor_id.hex()[:8]} restarting "
                f"({info.restarts_used} used)",
                entity_id=actor_id.hex(),
                extra={"restarts_used": info.restarts_used})
            self._publish(f"actor:{actor_id.hex()}", dumps(("RESTARTING", "")))
            self._schedule_actor(info)
        else:
            self.emit_event("ERROR", "head", "actor_dead",
                            f"actor {actor_id.hex()[:8]} dead: "
                            f"{info.death_cause}",
                            entity_id=actor_id.hex())
            self._publish(f"actor:{actor_id.hex()}",
                          dumps(("DEAD", info.death_cause)))

    def _mark_actor_dead(self, info: ActorInfo, cause: str):
        with self._lock:
            info.state = "DEAD"
            info.death_cause = cause
            waiters = list(info.pending_get_replies)
            info.pending_get_replies.clear()
            self._release_actor_name(info)
        self.emit_event("ERROR", "head", "actor_dead",
                        f"actor {info.actor_id.hex()[:8]} dead: {cause}",
                        entity_id=info.actor_id.hex())
        for wconn, wrid in waiters:
            try:
                wconn.reply(wrid, "DEAD", cause,
                            msg_type=P.GET_ACTOR_REPLY)
            except P.ConnectionLost:
                pass  # that waiter died; the rest must still hear
        self._publish(f"actor:{info.actor_id.hex()}", dumps(("DEAD", cause)))

    def _release_actor_name(self, info: ActorInfo):
        """Free a dead actor's name for reuse (head tables + KV mirror).

        The reference's GcsActorManager does the same on actor death
        (gcs_actor_manager.cc RemoveActorNameFromRegistry). Caller holds
        the lock."""
        if info.name and self.named_actors.get(info.name) == info.actor_id:
            del self.named_actors[info.name]
            self.kv.get("named_actor", {}).pop(info.name, None)
            if self._persist is not None:
                # callers hold self._lock — defer the file write (WAL
                # append can compact = read+rewrite+fsync the whole log).
                # The kv_del keeps the restored KV mirror consistent: a
                # restart must not resurrect a handle to a dead actor.
                self._wal_backlog.append(
                    ("actor_gone", info.actor_id.binary()))
                self._wal_backlog.append(
                    ("kv_del", "named_actor", info.name))

    def _h_get_actor(self, conn, rid, actor_id_bin_or_name):
        with self._lock:
            if isinstance(actor_id_bin_or_name, str):
                aid = self.named_actors.get(actor_id_bin_or_name)
                dead = aid is not None and (
                    self.actors.get(aid) is None
                    or self.actors[aid].state == "DEAD")
                if aid is None or dead:
                    conn.reply(rid, "NOT_FOUND", "",
                               msg_type=P.GET_ACTOR_REPLY)
                    return
            else:
                aid = ActorID(actor_id_bin_or_name)
            info = self.actors.get(aid)
            if info is None:
                conn.reply(rid, "NOT_FOUND", "", msg_type=P.GET_ACTOR_REPLY)
                return
            if info.state in ("PENDING", "RESTARTING"):
                info.pending_get_replies.append((conn, rid))
                return
            state, addr = info.state, info.listen_addr
            extra = info.death_cause if state == "DEAD" else ""
        conn.reply(rid, state, addr if state == "ALIVE" else extra,
                   msg_type=P.GET_ACTOR_REPLY,
                   )

    def _h_kill_actor(self, conn, rid, actor_id_bin, no_restart):
        aid = ActorID(actor_id_bin)
        with self._lock:
            info = self.actors.get(aid)
            if info is None:
                if rid > 0:
                    conn.reply(rid, False)
                return
            if no_restart:
                info.spec.max_restarts = 0
                info.state = "DEAD"
                info.death_cause = "killed via kill()"
                self._release_actor_name(info)
            node = self.nodes.get(
                next((n.idx for n in self.nodes.values()
                      if info.worker_id in n.workers), -1))
            w = node.workers.get(info.worker_id) if node else None
        if w is not None:
            self._kill_worker_process(w)
            # Reap synchronously: _kill_worker_process marks the worker dead,
            # which suppresses the conn-close death path — without this the
            # lease (and its CPU/TPU grant) leaks on every kill().
            self._handle_worker_death(w)
            with self._lock:
                node = self.nodes.get(w.node_idx)
                if node is not None:
                    node.workers.pop(w.worker_id, None)
        if no_restart:
            self.emit_event("ERROR", "head", "actor_dead",
                            f"actor {aid.hex()[:8]} killed via kill()",
                            entity_id=aid.hex())
            self._publish(f"actor:{aid.hex()}",
                          dumps(("DEAD", "killed via kill()")))
        if rid > 0:
            conn.reply(rid, True)

    # ------------------------------------------------------ placement groups

    def _h_create_pg(self, conn, rid, spec_bytes):
        spec: PlacementGroupSpec = loads(spec_bytes)
        with self._lock:
            placement = self.scheduler.place_bundles(spec)
            if placement is None:
                feasible = all(
                    any(self.nodes[i].resources.is_feasible(
                        ResourceSet(b.resources))
                        for i in self.scheduler.schedulable_nodes())
                    for b in spec.bundles)
                if not feasible:
                    self.emit_event(
                        "ERROR", "head", "pg_infeasible",
                        f"placement group {spec.pg_id.hex()[:8]} "
                        "infeasible: no node can ever fit some bundle",
                        entity_id=spec.pg_id.hex())
                    # not persisted: the client sees an error, so a restart
                    # must not resurrect a phantom group
                    conn.reply_error(rid, RuntimeError(
                        "placement group infeasible: no node can ever fit "
                        "some bundle"))
                    return
                # retry later when resources free up
                info = PgInfo(spec=spec)
                self.pgs[spec.pg_id] = info
                self._pending_pg.append(spec.pg_id)
                reply = ("PENDING",)
            else:
                self._commit_pg(spec, placement)
                reply = ("CREATED",)
        if self._persist is not None:
            self._enqueue_wal(("pg", spec_bytes))
        conn.reply(rid, *reply, msg_type=P.CREATE_PG_REPLY)

    def _commit_pg(self, spec: PlacementGroupSpec, placement: List[int]):
        """Reserve bundle resources on nodes (2PC prepare+commit collapses to
        one step in-process; reference gcs_placement_group_scheduler.cc)."""
        info = self.pgs.get(spec.pg_id) or PgInfo(spec=spec)
        info.spec = spec
        info.placement = placement
        info.bundle_available = []
        for b, node_idx in zip(spec.bundles, placement):
            rs = ResourceSet(b.resources)
            self.nodes[node_idx].resources.allocate(rs)
            info.bundle_available.append(rs)
        info.state = "CREATED"
        self.pgs[spec.pg_id] = info
        self.emit_event("INFO", "head", "pg_ready",
                        f"placement group {spec.pg_id.hex()[:8]} ready on "
                        f"nodes {placement}",
                        entity_id=spec.pg_id.hex(),
                        extra={"placement": list(placement)})
        # mirror into KV: non-driver processes poll kv_get("pg_state", ...)
        # from PlacementGroup.ready() (api.py _pg_state)
        self.kv.setdefault("pg_state", {})[spec.pg_id.hex()] = b"CREATED"
        self._publish(f"pg:{spec.pg_id.hex()}", dumps("CREATED"))

    def _retry_pending_pgs(self):
        with self._lock:
            pending = list(self._pending_pg)
            for pg_id in pending:
                info = self.pgs.get(pg_id)
                if info is None or info.state != "PENDING":
                    self._pending_pg.remove(pg_id)
                    continue
                placement = self.scheduler.place_bundles(info.spec)
                if placement is not None:
                    self._commit_pg(info.spec, placement)
                    self._pending_pg.remove(pg_id)

    def _h_remove_pg(self, conn, rid, pg_id_bin):
        pg_id = PlacementGroupID(pg_id_bin)
        if self._persist is not None:
            self._enqueue_wal(("pg_gone", pg_id_bin))
        with self._lock:
            self.kv.setdefault("pg_state", {})[pg_id.hex()] = b"REMOVED"
            info = self.pgs.pop(pg_id, None)
            if info and info.state == "CREATED":
                for b, node_idx, avail in zip(info.spec.bundles,
                                              info.placement,
                                              info.bundle_available):
                    node = self.nodes.get(node_idx)
                    if node:
                        # return whatever portion is not currently in use by
                        # leases; in-use portions return on lease release
                        node.resources.release(avail)
        if rid > 0:
            conn.reply(rid, True)
        self._try_fulfill_pending()

    def _pg_node_for(self, pg_id, bundle_index, request) -> Optional[int]:
        info = self.pgs.get(pg_id)
        if info is None or info.state != "CREATED":
            return None
        if bundle_index >= 0:
            if info.bundle_available[bundle_index].covers(request):
                return info.placement[bundle_index]
            return None
        for i, avail in enumerate(info.bundle_available):
            if avail.covers(request):
                return info.placement[i]
        return None

    def _pg_allocate(self, pg_id, bundle_index, request):
        info = self.pgs[pg_id]
        if bundle_index < 0:
            for i, avail in enumerate(info.bundle_available):
                if avail.covers(request):
                    bundle_index = i
                    break
        info.bundle_available[bundle_index] = \
            info.bundle_available[bundle_index].subtract(request)

    def _pg_release(self, pg_id, bundle_index, request):
        info = self.pgs.get(pg_id)
        if info is None:
            return
        if bundle_index < 0:
            bundle_index = 0
        info.bundle_available[bundle_index] = \
            info.bundle_available[bundle_index].add(request)

    def pg_state(self, pg_id: PlacementGroupID) -> str:
        with self._lock:
            info = self.pgs.get(pg_id)
            return info.state if info else "REMOVED"

    def pg_placement(self, pg_id: PlacementGroupID) -> List[int]:
        with self._lock:
            info = self.pgs.get(pg_id)
            return list(info.placement) if info else []

    # ------------------------------------------------------------ KV store

    def _h_kv_put(self, conn, rid, ns, key, value, overwrite):
        with self._lock:
            table = self.kv.setdefault(ns, {})
            if not overwrite and key in table:
                added = False
            else:
                table[key] = value
                added = True
        if added and self._persist is not None:
            self._enqueue_wal(("kv_put", ns, key, value))
        if rid > 0:
            conn.reply(rid, added)

    def _h_kv_get(self, conn, rid, ns, key):
        with self._lock:
            conn.reply(rid, self.kv.get(ns, {}).get(key))

    def _h_kv_del(self, conn, rid, ns, key):
        with self._lock:
            existed = self.kv.get(ns, {}).pop(key, None) is not None
        if existed and self._persist is not None:
            self._enqueue_wal(("kv_del", ns, key))
        if rid > 0:
            conn.reply(rid, existed)

    def _h_kv_keys(self, conn, rid, ns, prefix):
        with self._lock:
            keys = [k for k in self.kv.get(ns, {}) if k.startswith(prefix)]
        conn.reply(rid, keys)

    # ------------------------------------------------------------- pubsub

    def _h_subscribe(self, conn, rid, channel):
        with self._lock:
            self.subs.setdefault(channel, set()).add(conn)
        if rid > 0:
            conn.reply(rid, True)

    def _h_publish(self, conn, rid, channel, payload):
        self._publish(channel, payload)
        if rid > 0:
            conn.reply(rid, True)

    def _publish(self, channel: str, payload: bytes):
        with self._lock:
            targets = list(self.subs.get(channel, ()))
        for c in targets:
            try:
                c.send(P.PUBLISH, channel, payload)
            except P.ConnectionLost:
                with self._lock:
                    self.subs.get(channel, set()).discard(c)

    # ------------------------------------------------- object directory

    def _h_object_sealed(self, conn, rid, oid_bin, node_idx, size, owner,
                         job_id_hex=""):
        oid = ObjectID(oid_bin)
        node_idx, size, waiters = self.objects.record_sealed(
            oid, node_idx, size, owner, job_id_hex)
        for wconn, wrid in waiters:
            try:
                wconn.reply(wrid, node_idx, size, "",
                            msg_type=P.OBJECT_LOCATE_REPLY)
            except P.ConnectionLost:
                pass  # that waiter died; the rest must still hear
        self._maybe_spill(node_idx)

    def _h_obj_tag(self, conn, rid, oid_bins, tag):
        """Reference-class tag stamp (one-way; memory observatory)."""
        self.objects.tag_objects([ObjectID(ob) for ob in oid_bins],
                                 str(tag))
        if rid > 0:
            conn.reply(rid, True)

    def _directory_add(self, oid: ObjectID, node_idx: int, size: int = 0):
        """A node gained a copy (pull completion / replica creation)."""
        node_idx, size, waiters = self.objects.add_location(
            oid, node_idx, size)
        for wconn, wrid in waiters:
            try:
                wconn.reply(wrid, node_idx, size, "",
                            msg_type=P.OBJECT_LOCATE_REPLY)
            except P.ConnectionLost:
                pass

    def _h_obj_location_add(self, conn, rid, oid_bin, node_idx, size=0):
        self._directory_add(ObjectID(oid_bin), node_idx, size)
        if rid > 0:
            conn.reply(rid, True)

    def _on_local_evictions(self, node_idx: int, oids):
        """on_evict hook for head-local arenas: same directory upkeep as
        an agent's OBJ_LOCATION_REMOVE report, minus the network hop. The
        bookkeeping is in-memory under the head RLock — safe from any
        locked head path, including the head puller's IO thread — but the
        LOST-waiter replies are blocking socket writes, so they go to a
        side thread rather than stalling whatever triggered the eviction."""
        waiters = self.objects.remove_locations(list(oids), node_idx)
        if waiters:
            threading.Thread(target=self._reply_lost, args=(waiters,),
                             daemon=True).start()

    def _h_obj_location_remove(self, conn, rid, oid_bins, node_idx):
        """A node dropped copies (arena eviction / local deletion) — one
        batched message per eviction sweep."""
        self._reply_lost(self.objects.remove_locations(
            [ObjectID(ob) for ob in oid_bins], node_idx))
        if rid > 0:
            conn.reply(rid, True)

    def _reply_lost(self, waiters):
        """Answer blocked locates with the LOST sentinel (-2)."""
        for wconn, wrid in waiters:
            try:
                wconn.reply(wrid, -2, 0, "", msg_type=P.OBJECT_LOCATE_REPLY)
            except P.ConnectionLost:
                pass

    def _h_obj_location_lookup(self, conn, rid, oid_bin):
        """Full holder-set query: ([holder_idxs], [transfer_addrs], size,
        spilled_url). The lists are PARALLEL — addrs[i] serves holders[i]
        ('' when that holder has no reachable transfer server), so two
        head-local holders both report the head's one TransferServer
        address. A puller dedupes before striping."""
        oid = ObjectID(oid_bin)
        with self.objects.lock_for(oid):
            loc = self.objects.get(oid)
            if loc is None:
                conn.reply(rid, [], [], 0, "")
                return
            nodes = sorted(self._holder_nodes(loc), key=lambda n: n.idx)
            holders = [n.idx for n in nodes]
            addrs = [self._node_transfer_addr(n) for n in nodes]
            size, spilled = loc.size, loc.spilled_path
        conn.reply(rid, holders, addrs, size, spilled)

    def _h_object_locate(self, conn, rid, oid_bin, block):
        oid = ObjectID(oid_bin)
        with self.objects.lock_for(oid):
            loc = self.objects.get(oid)
            if loc is not None and (loc.node_idx >= 0 or loc.spilled_path):
                conn.reply(rid, loc.node_idx, loc.size, loc.spilled_path,
                           msg_type=P.OBJECT_LOCATE_REPLY)
                return
            if self.objects.is_lost(oid):
                # sealed once, then its node died: fail fast so the owner
                # can reconstruct instead of blocking forever
                conn.reply(rid, -2, 0, "", msg_type=P.OBJECT_LOCATE_REPLY)
                return
            if not block:
                conn.reply(rid, -1, 0, "", msg_type=P.OBJECT_LOCATE_REPLY)
                return
            self.objects.setdefault(oid).waiters.append((conn, rid))

    def _h_seal_aborted(self, conn, rid, oid_bins):
        """The creating task failed permanently: these returns will never
        seal. Mark them LOST and answer blocked locates with -2 so
        borrowers surface ObjectLostError instead of hanging (the owner
        holds the actual error in its in-process store)."""
        lost = []
        for ob in oid_bins:
            oid = ObjectID(ob)
            with self.objects.lock_for(oid):
                loc = self.objects.get(oid)
                if loc is not None and (loc.node_idx >= 0 or
                                        loc.spilled_path):
                    continue  # a real copy exists (e.g. partial returns)
                lost.append(oid)
        self._reply_lost(self.objects.mark_lost(lost))

    def _h_object_recovering(self, conn, rid, oid_bins):
        """An owner is re-executing the creating task for these lost
        objects: clear the LOST marker so consumers' blocking locates queue
        as waiters for the re-seal rather than failing fast."""
        for ob in oid_bins:
            self.objects.clear_lost(ObjectID(ob))
        if rid > 0:
            conn.reply(rid, True)

    def _h_object_free(self, conn, rid, oid_bins):
        for ob in oid_bins:
            oid = ObjectID(ob)
            loc = self.objects.pop(oid)
            self.objects.clear_lost(oid)
            if loc is None:
                continue
            if loc.spilled_path:
                try:
                    os.unlink(loc.spilled_path)
                except OSError:
                    pass
            # every holder in the directory drops its copy
            targets = set(loc.holders)
            if loc.node_idx >= 0:
                targets.add(loc.node_idx)
            for idx in targets:
                node = self.nodes.get(idx)
                if node is None or not node.alive:
                    continue
                if node.store is not None:
                    node.store.delete(oid)
                elif node.agent_conn is not None:
                    try:
                        node.agent_conn.send(P.AGENT_OBJ_FREE, [ob])
                    except P.ConnectionLost:
                        pass

    # ---- node-store access that works for local and remote nodes ----

    def _node_store_contains(self, node: NodeState, oid: ObjectID) -> bool:
        if node.store is not None:
            return node.store.contains(oid)
        return False  # remote: let the put be idempotent instead

    def _node_store_read(self, node: NodeState, oid: ObjectID):
        """-> (payload_bytes, meta_bytes) or None."""
        if node.store is not None:
            got = node.store.get(oid)
            if got is None:
                return None
            data_v, meta_v = got
            try:
                return bytes(data_v), bytes(meta_v)
            finally:
                del data_v, meta_v, got
                node.store.release(oid)
        payload, meta = node.agent_conn.call(
            P.AGENT_OBJ_GET, oid.binary(), timeout=120)
        if payload is not None:
            self.relay_bytes += len(payload)
        return None if payload is None else (payload, meta)

    def _node_store_write(self, node: NodeState, oid: ObjectID,
                          payload: bytes, meta: bytes):
        if node.store is not None:
            if node.store.contains(oid):
                return
            cfg = get_config()
            buf = node.store.create(oid, len(payload), len(meta))
            # chunked copy (mirrors 5 MiB transfer chunks)
            cs = cfg.object_transfer_chunk_bytes
            for off in range(0, len(payload), cs):
                buf[off:off + min(cs, len(payload) - off)] = \
                    payload[off:off + cs]
            buf[len(payload):] = meta
            node.store.seal(oid)
        else:
            self.relay_bytes += len(payload)
            node.agent_conn.call(P.AGENT_OBJ_PUT, oid.binary(), payload,
                                 meta, timeout=120)

    def _holder_nodes(self, loc: _ObjLoc, exclude_idx: int = -1
                      ) -> List[NodeState]:
        """Live holder nodes, primary first — THE directory traversal
        every read/transfer path shares (caller holds the lock)."""
        out: List[NodeState] = []
        for idx in dict.fromkeys([loc.node_idx] + sorted(loc.holders)):
            if idx < 0 or idx == exclude_idx:
                continue
            node = self.nodes.get(idx)
            if node is None or not node.alive:
                continue
            out.append(node)
        return out

    def _node_transfer_addr(self, node: NodeState) -> str:
        """The transfer address serving a node's objects — every
        head-local holder is served by the head host's one TransferServer."""
        if node.is_remote:
            return node.transfer_addr or ""
        return self._transfer_server.addr if self._transfer_server else ""

    def _plan_pull_sources(self, oid: ObjectID, loc: _ObjLoc,
                           dst_node: NodeState):
        """Broadcast-aware source planning for ONE brokered pull
        (reference: PullManager source selection over the
        ObjectDirectory's location set, pull_manager.cc — extended with
        the in-progress locations that make a cold one-to-many
        distribution a pipelined tree). Returns ``(addrs, relay_addrs,
        max_sources, charged)`` where ``charged`` is [(addr, weight)];
        the caller MUST pass ``charged`` to ``_finish_pull_assignment``
        when the pull ends, success or not.

        Policy: prefer sealed holders below their ``broadcast_fanout``
        load (striped, the PR1 behavior); with every root saturated,
        hand out ONE in-progress relay under the bound (max_sources=1 so
        the puller never also stripes the saturated roots — they stay in
        the list as failover-only candidates); with everything
        saturated, overload the least-loaded root and note it."""
        cfg = get_config()
        fanout = cfg.broadcast_fanout
        with self.objects.lock_for(oid):
            sealed_addrs = list(dict.fromkeys(
                a for n in self._holder_nodes(loc, exclude_idx=dst_node.idx)
                for a in (self._node_transfer_addr(n),) if a))
            if fanout <= 0 or not sealed_addrs or \
                    loc.size < cfg.pull_min_stripe_bytes:
                # cooperative planning off / object too small to matter:
                # the pre-r9 plan (stripe the full sealed holder set)
                return sealed_addrs, (), 0, []
            dst_addr = self._node_transfer_addr(dst_node)
            load = loc.serving
            relays: Tuple[str, ...] = ()
            free_roots = sorted(
                (a for a in sealed_addrs if load.get(a, 0) < fanout),
                key=lambda a: load.get(a, 0))
            if free_roots:
                chosen = free_roots[:max(1, cfg.pull_max_sources)]
                max_sources = len(chosen)
                # a k-way stripe takes ~1/k of each root's uplink:
                # charge fractionally so ordinary multi-holder striped
                # workloads don't read as broadcast saturation
                weight = 1.0 / len(chosen)
                self.broadcast_root_assignments += 1
            else:
                free_relays = sorted(
                    (a for i, a in loc.inprog.items()
                     if i != dst_node.idx and a and a != dst_addr
                     and a not in sealed_addrs
                     and load.get(a, 0) < fanout
                     and i in self.nodes and self.nodes[i].alive),
                    key=lambda a: load.get(a, 0))
                if free_relays:
                    chosen = [free_relays[0]]
                    relays = (free_relays[0],)
                    max_sources = 1
                    weight = 1.0
                    self.broadcast_relay_assignments += 1
                else:
                    # every source saturated: overload the least-loaded
                    # root rather than queueing (rate-limited event)
                    chosen = [min(sealed_addrs,
                                  key=lambda a: load.get(a, 0))]
                    max_sources = 1
                    weight = 1.0
                    self.broadcast_root_assignments += 1
                    self._note_fanout_saturated(oid, dst_node.idx)
            charged = [(a, weight) for a in chosen]
            for a, w in charged:
                load[a] = load.get(a, 0) + w
            if dst_addr:
                # the requester becomes an in-progress location the
                # moment its pull is brokered — later planner calls may
                # relay off it
                loc.inprog[dst_node.idx] = dst_addr
            # failover tail: every sealed holder not already primary, so
            # a dead or aborting relay re-requests from the root set
            addrs = chosen + [a for a in sealed_addrs if a not in chosen]
            return addrs, relays, max_sources, charged

    def _finish_pull_assignment(self, oid: ObjectID, dst_idx: int,
                                charged):
        """A brokered pull ended (either way): release the source slots
        it charged and retire the requester's in-progress location.
        Shares the object's SHARD lock with the planner, so an
        aborted/failed puller can never be handed out as a source after
        its failure is known (directory-staleness-on-abort guarantee)."""
        if not charged:
            return  # non-cooperative plan: nothing was registered
        with self.objects.lock_for(oid):
            loc = self.objects.get(oid)
            if loc is None:
                return
            loc.inprog.pop(dst_idx, None)
            for a, w in charged:
                n = loc.serving.get(a, 0) - w
                if n > 1e-9:  # float residue from fractional stripes
                    loc.serving[a] = n
                else:
                    loc.serving.pop(a, None)

    def _note_fanout_saturated(self, oid: ObjectID, dst_idx: int):
        """Caller holds the lock. Rate-limited: a hot broadcast can hit
        this once per puller."""
        self.broadcast_fanout_saturations += 1
        now = time.monotonic()
        if now - self._last_saturation_event_ts < 5.0:
            return
        self._last_saturation_event_ts = now
        self.emit_event(
            "WARNING", "head", "broadcast_fanout_saturated",
            f"every source for object {oid.hex()[:16]} is at its "
            f"broadcast_fanout bound ({get_config().broadcast_fanout}); "
            "assigning the least-loaded sealed holder anyway",
            extra={"object_id": oid.hex(), "dst_node": dst_idx,
                   "saturations": self.broadcast_fanout_saturations})

    # ---------------------------------- speculative arg prefetch (r13)

    def _maybe_prefetch_args(self, lease_id: str, node_idx: int,
                             arg_ids, inline_ids=()) -> int:
        """Fire prefetch-flagged PULL_OBJECTs at ``node_idx``'s agent
        for every by-ref arg its directory entry is missing (the
        reference PullManager's prefetch role). Called off the head
        lock — from the dispatch pass right after the lease replies go
        out, and from the driver's dispatch-time PREFETCH_HINT — so the
        pulls overlap the lease reply, driver dispatch and worker
        wakeup; the worker's ``_decode_args`` get() then JOINS the
        in-flight pull via the agent puller's ``_pending`` leadership
        instead of starting cold. Remote nodes only: a head-local
        node's consumers share the head host's arenas, where the demand
        path is an in-memory hop. Returns how many pulls were issued.

        ``inline_ids`` (r16): arg ids the DRIVER tagged as
        inline-promoted — tiny owner values materialized into the store
        only so borrowers can fetch them (``_promote_if_needed``).
        Their pulls still fire (the demand path would fetch them
        anyway) but count in ``prefetch_issued_inline`` /
        ``prefetch_wasted_inline``, so the issued/wasted ratio behind
        ``doctor_warnings()``'s waste check measures only REAL
        speculative pulls."""
        cfg = get_config()
        if not cfg.arg_prefetch_enabled or \
                cfg.arg_prefetch_max_inflight <= 0 or not arg_ids:
            return 0
        with self._lock:
            node = self.nodes.get(node_idx)
            # WARM / actor keys are not real leases: no liveness gate
            # (warm entries age out via the sweep; a dead actor's
            # entries do too — teardown never names these keys)
            synthetic = lease_id == _WARM_LEASE or \
                lease_id.startswith("actor:")
            if node is None or not node.alive or node.draining \
                    or node.agent_conn is None \
                    or (not synthetic and lease_id not in self.leases):
                # draining nodes are never prefetch DESTINATIONS (the
                # copies are moving off); they may still SERVE pulls
                return 0
            conn = node.agent_conn
        issued = 0
        inline_set = {bytes(a) for a in inline_ids}
        for ab in dict.fromkeys(bytes(a) for a in arg_ids):
            oid = ObjectID(ab)
            loc = self.objects.get(oid)
            if loc is None or loc.size <= 0 or loc.spilled_path:
                continue  # unknown size / spilled: demand path handles
            if node_idx in loc.holders or loc.node_idx == node_idx:
                continue  # already local: nothing to overlap
            if loc.size > cfg.arg_prefetch_max_bytes:
                # can NEVER fit under the byte cap: queueing it would
                # churn forever (every drain re-queues it); the demand
                # path handles oversized args
                continue
            key = (ab, node_idx)
            with self._prefetch_lock:
                if key in self._prefetches:
                    continue  # in flight or freshly landed: dedupe
                infl = [p for p in self._prefetches.values()
                        if p.node_idx == node_idx
                        and p.state == "inflight"]
                if len(infl) >= cfg.arg_prefetch_max_inflight or \
                        sum(p.size for p in infl) + loc.size > \
                        cfg.arg_prefetch_max_bytes:
                    # over the caps: QUEUE, don't drop — the next
                    # PREFETCH_RESULT activates it (bounded per node)
                    q = self._prefetch_pending.setdefault(
                        node_idx, deque())
                    if len(q) < 256 and \
                            not any(e[1] == ab for e in q):
                        q.append((lease_id, ab, ab in inline_set))
                    continue
                p = _PrefetchState(oid_bin=ab, node_idx=node_idx,
                                   lease_id=lease_id, size=loc.size,
                                   ts=time.monotonic(),
                                   inline=ab in inline_set)
                self._prefetches[key] = p
                self._prefetch_by_lease.setdefault(
                    lease_id, []).append(key)
            # plan OUTSIDE the prefetch lock (shard locks inside): the
            # cooperative planner charges the chosen sources and lists
            # the destination in-progress, so later pullers of the same
            # object may relay off the prefetching node (r9 tree)
            addrs, relays, max_sources, charged = \
                self._plan_pull_sources(oid, loc, node)
            if not addrs:
                with self._prefetch_lock:
                    self._unlink_prefetch_locked(key, p)
                continue
            released = None
            with self._prefetch_lock:
                if self._prefetches.get(key) is not p:
                    # purged while planning (node died between the two
                    # locks): the entry is gone, so nothing will ever
                    # answer for these charges — release them here
                    released = charged
                else:
                    p.charged = charged
            if released:
                self._finish_pull_assignment(oid, node_idx, released)
                continue
            try:
                conn.send(P.PULL_OBJECT, ab, addrs, loc.size,
                          max_sources, list(relays), True)
            except P.ConnectionLost:
                self._prefetch_finished(ab, node_idx, ok=False)
                continue
            with self._prefetch_lock:
                if p.inline:
                    self.prefetch_issued_inline += 1
                else:
                    self.prefetch_issued += 1
                    self.prefetch_bytes_issued += loc.size
            issued += 1
        return issued

    def _h_prefetch_hint(self, conn, rid, lease_id, arg_bins,
                         inline_bins=()):
        """Driver dispatch-time prefetch (PREFETCH_HINT): leases are
        long-lived and serve many tasks, so grant-time args cover only
        the first — the submitter names each pushed batch's by-ref args
        for the lease's node and the same caps/dedupe apply. r14: keys
        of the form ``actor:<hex>`` name an ACTOR's pushed batch (the
        serve-handle hot loop); the head resolves the actor to its
        worker's node here — the driver only knows the actor's socket
        address, not its node. r16: the optional third field names the
        subset of ``arg_bins`` that are inline-promoted objects (their
        pulls are counted apart from real speculation — absent from
        pre-r16 drivers, which is equivalent to empty)."""
        if isinstance(lease_id, str) and lease_id.startswith("actor:"):
            node_idx = self._actor_node_idx(lease_id[len("actor:"):])
            if node_idx is not None:
                self._maybe_prefetch_args(lease_id, node_idx, arg_bins,
                                          inline_ids=inline_bins)
            return
        with self._lock:
            lease = self.leases.get(lease_id)
        if lease is None:
            return  # lease already returned: nothing to speculate for
        self._maybe_prefetch_args(lease_id, lease[0], arg_bins,
                                  inline_ids=inline_bins)

    def _h_prefetch_hint_batch(self, conn, rid, entries):
        """PREFETCH_HINT_BATCH (r15): one frame carrying every hint a
        driver buffered since its last submitter wakeup — a pipeline
        hot loop's per-microbatch activations arrive as one frame per
        tick instead of one per pushed batch. Each (lease_key, ids[,
        inline_ids]) entry takes the exact single-hint path (actor
        resolution, caps, holder checks, dedupe); 2-tuples from r15
        drivers decode with no inline tags."""
        for entry in entries:
            self._h_prefetch_hint(conn, 0, entry[0], entry[1],
                                  entry[2] if len(entry) > 2 else ())

    def _actor_node_idx(self, actor_hex: str) -> Optional[int]:
        """Node currently hosting an actor's worker (None when the
        actor is dead/unknown/not yet placed)."""
        try:
            aid = ActorID(bytes.fromhex(actor_hex))
        except ValueError:
            return None
        with self._lock:
            actor = self.actors.get(aid)
            if actor is None or actor.state != "ALIVE" or \
                    not actor.worker_id:
                return None
            for node in self.nodes.values():
                if actor.worker_id in node.workers:
                    return node.idx
        return None

    def _h_object_warm(self, conn, rid, oid_bin, node_idx):
        """OBJECT_WARM (r14): warm one object onto node(s) BEFORE any
        consumer exists — the serve controller fires this at scale-up
        decision time so deployment weights are landing (or landed)
        when the new replicas' constructors ask. Rides the r13 prefetch
        machinery under the reserved WARM lease: same per-node
        inflight/byte caps and pacing queue, same PREFETCH_RESULT
        charge accounting, same holder dedupe — and because each warm
        pull registers as an in-progress location, N concurrent warms
        of one object form the r9 cooperative broadcast tree
        (root egress ~2xS, not NxS). node_idx -1 = every alive remote
        node not already holding the object. Replies the number of
        pulls issued when sent as a call."""
        ab = bytes(oid_bin)
        with self._lock:
            if node_idx >= 0:
                node = self.nodes.get(node_idx)
                targets = [node_idx] if node is not None and node.alive \
                    and not node.draining else []
            else:
                targets = [n.idx for n in self.nodes.values()
                           if n.alive and not n.draining
                           and n.agent_conn is not None]
        issued = 0
        for idx in targets:
            issued += self._maybe_prefetch_args(_WARM_LEASE, idx, [ab])
        if rid > 0:
            conn.reply(rid, issued)

    def _h_prefetch_result(self, conn, rid, oid_bin, node_idx, ok):
        self._prefetch_finished(bytes(oid_bin), int(node_idx), bool(ok))

    def _prefetch_finished(self, oid_bin: bytes, node_idx: int,
                           ok: bool):
        """A speculative pull ended (agent PREFETCH_RESULT, send
        failure, or TTL sweep): release the planner charges exactly
        once; successful pulls linger as ``done`` so a late demand
        fetch still reads as satisfied-by-prefetch."""
        key = (oid_bin, node_idx)
        with self._prefetch_lock:
            p = self._prefetches.get(key)
            if p is None or p.state == "done":
                return
            charged, p.charged = p.charged, []
            if ok and p.state == "inflight":
                p.state = "done"
                p.ts = time.monotonic()
                if p.inline:
                    # keep the issued/completed/wasted triple coherent
                    # per class: inline pulls never appear in the real
                    # speculation counters (completed > issued would
                    # otherwise be possible)
                    self.prefetch_completed_inline += 1
                else:
                    self.prefetch_completed += 1
            else:
                self._unlink_prefetch_locked(key, p)
        if charged:
            self._finish_pull_assignment(ObjectID(oid_bin), node_idx,
                                         charged)
        # a result frees an inflight slot: activate queued requests
        self._drain_prefetch_pending(node_idx)

    def _drain_prefetch_pending(self, node_idx: int):
        """Activate cap-queued prefetch requests while slots last (the
        reference PullManager's bounded activation loop). Entries
        re-check holders/caps/lease liveness through the normal issue
        path; one still-over-caps entry re-queues and stops the drain
        until the next slot frees. Reentrancy-guarded per node: an
        issue failure inside the drain reports through
        _prefetch_finished, which calls back here."""
        while True:
            with self._prefetch_lock:
                if node_idx in self._prefetch_draining:
                    return
                q = self._prefetch_pending.get(node_idx)
                if not q:
                    return
                lease_id, ab, inline = q.popleft()
                self._prefetch_draining.add(node_idx)
            try:
                issued = self._maybe_prefetch_args(
                    lease_id, node_idx, [ab],
                    inline_ids=(ab,) if inline else ())
            finally:
                with self._prefetch_lock:
                    self._prefetch_draining.discard(node_idx)
            if issued == 0:
                with self._prefetch_lock:
                    requeued = any(
                        e[1] == ab for e in
                        self._prefetch_pending.get(node_idx, ()))
                if requeued:
                    return  # caps still full: wait for the next slot

    def _abort_lease_prefetches(self, lease_id: str):
        """Lease teardown (worker returned/died, driver gone, task
        cancelled or retried elsewhere): abort this lease's unconsumed
        in-flight prefetches through the r9 abort path and count them
        wasted; satisfied entries just drop their records."""
        aborts: List[_PrefetchState] = []
        with self._prefetch_lock:
            for q in self._prefetch_pending.values():
                # cap-queued requests of the dead lease never activate
                stale = [e for e in q if e[0] == lease_id]
                for e in stale:
                    q.remove(e)
            keys = self._prefetch_by_lease.pop(lease_id, None)
            if not keys:
                return
            for key in keys:
                p = self._prefetches.get(key)
                if p is None:
                    continue
                if p.state == "done":
                    self._prefetches.pop(key, None)  # list popped above
                elif p.state == "inflight" and not p.consumed:
                    p.state = "aborted"
                    if p.inline:
                        self.prefetch_wasted_inline += 1
                    else:
                        self.prefetch_wasted += 1
                    aborts.append(p)
                # consumed in-flight entries: a demand fetch is riding
                # the pull — leave it to finish; PREFETCH_RESULT (or
                # the sweep) releases the charges and drops the entry
        for p in aborts:
            with self._lock:
                node = self.nodes.get(p.node_idx)
                conn = node.agent_conn if node is not None else None
            if conn is not None:
                try:
                    conn.send(P.PULL_ABORT, p.oid_bin)
                except P.ConnectionLost:
                    pass

    def _prefetch_inflight_count(self) -> int:
        with self._prefetch_lock:  # stats poll races insert/pop threads
            return sum(1 for p in self._prefetches.values()
                       if p.state == "inflight")

    def _unlink_prefetch_locked(self, key, p: "_PrefetchState"):
        """Drop an entry AND its per-lease record (caller holds
        _prefetch_lock). Every pop must route through here: a
        long-lived lease issues prefetches for the whole stream of
        tasks it serves, and per-lease key lists pruned only at lease
        teardown would grow for the lease's entire lifetime."""
        self._prefetches.pop(key, None)
        keys = self._prefetch_by_lease.get(p.lease_id)
        if keys is not None:
            if key in keys:
                keys.remove(key)
            if not keys:
                del self._prefetch_by_lease[p.lease_id]

    def _purge_node_prefetches(self, node_idx: int):
        """Node death: drop every prefetch targeted at it (charges on
        surviving sources released; no waste counting — this is host
        loss, not task churn)."""
        dead: List[_PrefetchState] = []
        with self._prefetch_lock:
            self._prefetch_pending.pop(node_idx, None)
            for key in [k for k in self._prefetches
                        if k[1] == node_idx]:
                p = self._prefetches[key]
                if p.charged:
                    dead.append(p)
                self._unlink_prefetch_locked(key, p)
        for p in dead:
            self._finish_pull_assignment(ObjectID(p.oid_bin),
                                         p.node_idx, p.charged)

    def _sweep_prefetches(self):
        """Housekeeping: entries whose agent never answered (died, frame
        lost) release their charges after ``_PREFETCH_SWEEP_S``; done
        records drop after ``_PREFETCH_DONE_TTL_S``."""
        now = time.monotonic()
        expired: List[_PrefetchState] = []
        with self._prefetch_lock:
            for key, p in list(self._prefetches.items()):
                if p.state == "done":
                    if now - p.ts > _PREFETCH_DONE_TTL_S:
                        self._unlink_prefetch_locked(key, p)
                elif now - p.ts > _PREFETCH_SWEEP_S:
                    self._unlink_prefetch_locked(key, p)
                    if p.charged:
                        expired.append(p)
            stalled = [idx for idx, q in self._prefetch_pending.items()
                       if q]
        for p in expired:
            self._finish_pull_assignment(ObjectID(p.oid_bin),
                                         p.node_idx, p.charged)
        for idx in stalled:
            # expired entries freed slots without a PREFETCH_RESULT
            # (that's what expired them) — the drain is the only other
            # activation edge, so run it or queued requests strand
            self._drain_prefetch_pending(idx)

    def _p2p_transfer(self, oid: ObjectID, loc: _ObjLoc,
                      dst_node: NodeState) -> bool:
        """Direct host-to-host pull, sources chosen by the broadcast-
        aware planner; returns False to fall back to relay."""
        with self._prefetch_lock:
            p = self._prefetches.get((oid.binary(), dst_node.idx))
            if p is not None and not p.consumed and \
                    p.state in ("inflight", "done"):
                # the demand fetch arrived while (or just after) the
                # speculative pull ran: the agent-side puller joins the
                # in-flight pull via _pending leadership, or finds the
                # landed copy — either way the arg fetch started warm
                p.consumed = True
                if p.state == "inflight":
                    self.prefetch_joined += 1
        addrs, relays, max_sources, charged = \
            self._plan_pull_sources(oid, loc, dst_node)
        if not addrs:
            return False
        try:
            if dst_node.is_remote:
                # dst agent pulls straight from the holder hosts
                reply = dst_node.agent_conn.call(
                    P.PULL_OBJECT, oid.binary(), addrs, loc.size,
                    max_sources, list(relays), timeout=120)
                ok = bool(reply[0])
            else:
                # dst is a head-local node: the head IS the destination
                # host — pull straight into the local arena.
                ok = bool(self._puller_for(dst_node).pull(
                    oid, addrs, size_hint=loc.size,
                    max_sources=max_sources, relay_addrs=relays))
            if ok:
                self._directory_add(oid, dst_node.idx)
            return ok
        except P.ConnectionLost:
            return False  # dst/agent died: let the relay path try
        except TimeoutError:
            # the agent may STILL be pulling: falling back to the relay
            # path would both funnel the payload through head memory and
            # collide with the in-flight pull's unsealed arena entry.
            # Surface the timeout instead — if the pull lands later the
            # agent's OBJ_LOCATION_ADD records the holder and the
            # requester's retry finds it. (The finally below releases
            # this pull's source charges early in that case; accounting
            # errs toward optimism for the straggler's tail.)
            raise
        finally:
            # after _directory_add: a finishing puller is continuously
            # visible (holder by the time its in-progress entry retires)
            self._finish_pull_assignment(oid, dst_node.idx, charged)

    def _h_object_transfer(self, conn, rid, oid_bin, to_node_idx):
        """Copy an object from its node's arena (or spill file) into
        `to_node_idx`'s arena — the reference's ObjectManager chunked pull
        (object_manager.cc). Within one host this is a memcpy between shm
        arenas; across hosts the payload rides the head<->agent TCP links.

        Remote transfers block on agent RPCs, and agent replies are
        delivered by this same head IO thread — so any transfer touching a
        remote node runs on a side thread (otherwise: deadlock)."""
        oid = ObjectID(oid_bin)
        with self.objects.lock_for(oid):
            loc = self.objects.get(oid)
            any_remote_holder = loc is not None and any(
                self.nodes[h].is_remote for h in loc.holders
                if h in self.nodes)
        if loc is None:
            conn.reply_error(rid, KeyError(f"object {oid.hex()} unknown"))
            return
        dst_node = self.nodes[to_node_idx]
        if dst_node.is_remote or any_remote_holder:
            threading.Thread(
                target=self._do_object_transfer,
                args=(conn, rid, oid, loc, dst_node), daemon=True).start()
            return
        self._do_object_transfer(conn, rid, oid, loc, dst_node)

    def _do_object_transfer(self, conn, rid, oid, loc, dst_node):
        try:
            if self._node_store_contains(dst_node, oid):
                conn.reply(rid, True)
                return
            with self.objects.lock_for(oid):
                any_remote_holder = any(
                    self.nodes[h].is_remote for h in loc.holders
                    if h in self.nodes)
            if not loc.spilled_path and (dst_node.is_remote
                                         or any_remote_holder):
                # Peer-to-peer path: the head only brokers the pull — the
                # payload rides direct host<->host connections, striped
                # across the directory's holder set (reference:
                # ObjectManager chunked pull, never through the GCS).
                if self._p2p_transfer(oid, loc, dst_node):
                    conn.reply(rid, True)
                    return
                # fall through to the relay path on any P2P failure
            if loc.spilled_path:
                with open(loc.spilled_path, "rb") as f:
                    data = f.read()
                # spill file layout: [8B meta_len][meta][payload]
                meta_len = int.from_bytes(data[:8], "little")
                meta = data[8:8 + meta_len]
                payload = data[8 + meta_len:]
            else:
                # relay read from any live holder (primary first)
                with self.objects.lock_for(oid):
                    cand = self._holder_nodes(loc)
                got = None
                for node in cand:
                    # a holder entry can be stale (eviction report lost):
                    # keep trying the remaining holders before giving up
                    got = self._node_store_read(node, oid)
                    if got is not None:
                        break
                if got is None:
                    conn.reply_error(
                        rid, KeyError(f"object {oid.hex()} gone"))
                    return
                payload, meta = got
            self._node_store_write(dst_node, oid, payload, meta)
            self._directory_add(oid, dst_node.idx)
            conn.reply(rid, True)
        except P.ConnectionLost:
            pass
        except Exception as e:  # noqa: BLE001 — surface to the requester
            try:
                conn.reply_error(rid, e)
            except P.ConnectionLost:
                pass

    # --------------------------------------------------------- spilling

    def _maybe_spill(self, node_idx: int):
        """Spill cold sealed objects to disk when the arena crosses the
        threshold (reference: LocalObjectManager::SpillObjects,
        local_object_manager.h:110; FileSystemStorage external_storage.py)."""
        cfg = get_config()
        node = self.nodes.get(node_idx)
        if node is None or node.store is None:
            return  # remote nodes spill locally (agent-side), not via head
        store = node.store
        if store.bytes_in_use() < cfg.object_spilling_threshold * \
                store.capacity():
            return
        spill_dir = cfg.spill_dir or os.path.join(self.session_dir, "spill")
        os.makedirs(spill_dir, exist_ok=True)
        candidates = [
            (oid, loc) for oid, loc in self.objects.items_snapshot()
            if loc.node_idx == node_idx and not loc.spilled_path
        ]
        target = store.capacity() * (cfg.object_spilling_threshold - 0.2)
        spilled_n, spilled_bytes = 0, 0
        for oid, loc in candidates:
            if store.bytes_in_use() <= target:
                break
            got = store.get(oid)
            if got is None:
                continue
            data_v, meta_v = got
            path = os.path.join(spill_dir, oid.hex())
            try:
                with open(path, "wb") as f:
                    f.write(len(meta_v).to_bytes(8, "little"))
                    f.write(meta_v)
                    f.write(data_v)
            finally:
                del data_v, meta_v, got
                store.release(oid)
            with self.objects.lock_for(oid):
                loc.spilled_path = path
                loc.holders.discard(node_idx)
                # another node may still hold a live replica; only fall
                # back to the spill file when no arena copy remains
                loc.node_idx = min(loc.holders) if loc.holders else -1
            store.delete(oid)
            spilled_n += 1
            spilled_bytes += loc.size
        if spilled_n:
            self.emit_event(
                "WARNING", "head", "object_spill",
                f"spilled {spilled_n} objects "
                f"({spilled_bytes} bytes) from node {node_idx} arena",
                node_idx=node_idx,
                extra={"objects": spilled_n, "bytes": spilled_bytes})

    # ------------------------------------------------------------ cluster info

    def _h_metrics_report(self, conn, rid, batch):
        """Merge per-process metric deltas into the cluster aggregate
        (reference: opencensus exporter -> dashboard agent; stats/
        metric.h:103). Counters/histograms arrive as deltas and sum;
        gauges overwrite. Runs under the dedicated metrics lock — merge
        work never convoys a lease grant on the head lock."""
        with self._metrics_lock:
            for kind, name, desc, meta, tags_key, value in batch:
                # reporter telemetry rows are identified by name prefix
                # AND the reserved ("node",) tag-key shape, so user
                # metrics that merely start with "node." are untouched.
                # The arena memory-observatory gauges ride the same
                # heartbeat with the same tag shape — they mirror into
                # node rows too (and flow through the metric table into
                # Prometheus + the flight recorder like any gauge).
                is_node_telemetry = (
                    kind == "gauge" and tuple(meta) == ("node",)
                    and (name.startswith("node.")
                         or name.startswith("object_plane.arena_")))
                if is_node_telemetry:
                    # drop in-flight reports from nodes already removed
                    # — merging them would resurrect a dead host's
                    # gauges post-prune
                    try:
                        if int(tags_key[0]) not in self.nodes:
                            continue
                    except ValueError:
                        pass
                key = (name, tags_key)
                row = self.metrics.get(key)
                if row is None:
                    if kind == "histogram":
                        tag_keys, boundaries = meta
                    else:
                        tag_keys, boundaries = meta, None
                    row = self.metrics[key] = {
                        "name": name, "kind": kind, "description": desc,
                        "tags": dict(zip(tag_keys, tags_key)),
                        "boundaries": boundaries,
                        "value": list(value) if kind == "histogram"
                        else 0.0,
                    }
                    if kind == "histogram":
                        continue
                if kind == "gauge":
                    row["value"] = value
                    # mirror reporter gauges into the per-node telemetry
                    # view list_nodes() rows expose
                    if is_node_telemetry:
                        try:
                            nidx = int(tags_key[0])
                        except ValueError:
                            pass
                        else:
                            self.node_telemetry.setdefault(
                                nidx, {})[name] = value
                elif kind == "counter":
                    row["value"] += value
                else:  # histogram delta: element-wise sum
                    row["value"] = [a + b for a, b in
                                    zip(row["value"], value)]

    def _h_task_events(self, conn, rid, batch, dropped):
        """Workers' task-state transitions land in a bounded ring buffer
        (reference: GcsTaskManager; src/ray/gcs/gcs_server/gcs_task_manager.h).
        A request_id means the sender wants a flush-ack: the reply is
        issued only after ingestion, so a subsequent STATE_QUERY
        observes this batch (tracing.timeline's ordering barrier).
        Every event is ALSO folded into the bounded per-task timeline
        table (state_ts / phase histograms / straggler bookkeeping).

        r11: wire batches are handed to the FOLD THREAD through a
        bounded queue — the fold (dict churn + histogram observes,
        measured ~15 ms per flush batch at burst) no longer runs on the
        IO loop, and the flush-ack is issued by the fold thread AFTER
        ingestion so the ordering barrier holds. Direct calls
        (conn is None — unit tests) and unstarted heads fold inline.
        A full queue sheds the batch with drop accounting: observability
        never backpressures the control plane."""
        ft = self._fold_thread
        if conn is None or ft is None or not ft.is_alive():
            self._ingest_task_events(batch, dropped)
            if rid > 0 and conn is not None:
                conn.reply(rid, True)
            return
        if len(self._fold_q) >= get_config().task_event_fold_queue_max:
            with self._timeline_lock:
                self.task_events_dropped += len(batch) + dropped
            self.fold_queue_drops += 1
            if rid > 0:
                conn.reply(rid, True)  # ack: the batch was consumed (shed)
            return
        self._fold_q.append((batch, dropped, conn, rid))
        self._fold_event.set()

    def _fold_loop(self):
        """Dedicated fold thread: drains TASK_EVENTS batches in arrival
        order, folds them under the timeline lock, then acks sync
        flushes."""
        q = self._fold_q
        while not self._shutdown:
            self._fold_event.wait(0.5)
            self._fold_event.clear()
            while q:
                try:
                    batch, dropped, conn, rid = q.popleft()
                except IndexError:
                    break
                try:
                    self._ingest_task_events(batch, dropped)
                finally:
                    if rid > 0 and conn is not None:
                        try:
                            conn.reply(rid, True)
                        except P.ConnectionLost:
                            pass

    def _ingest_task_events(self, batch, dropped):
        with self._timeline_lock:
            # count HEAD-ring evictions too (the deque drops oldest
            # silently) — the satellite drop counters must cover both
            # the worker buffers and this ring
            overflow = max(0, len(self.task_events) + len(batch)
                           - self.task_events.maxlen)
            self.task_events.extend(batch)
            self.task_events_seq += len(batch)
            self.task_events_dropped += dropped + overflow
            for ev in batch:
                self._fold_task_event(ev)

    # --------------------------------------- task timelines / stragglers

    def _fold_task_event(self, ev):
        """Fold one task-state event into its timeline row (caller holds
        the TIMELINE lock). Tolerates the pre-r10 10-field tuple shape
        (no monotonic stamp: state_ts still fills, phases stay
        unknown)."""
        tid, name, state, wid, nidx, ts = ev[:6]
        rank = E.STATE_RANK.get(state)
        if rank is None:
            return  # span records ride the raw ring only
        err = ev[6] if len(ev) > 6 else ""
        trace_id = ev[7] if len(ev) > 7 else ""
        mono = ev[10] if len(ev) > 10 else None
        # fold the recorder's monotonic stamp into the HEAD timebase
        folded_mono = None if mono is None else \
            mono - self.node_clock_offsets.get(nidx, 0.0)
        row = self.task_timelines.get(tid)
        if row is None:
            cap = get_config().task_timeline_max_entries
            if cap <= 0:
                return  # folding disabled (raw ring still serves)
            while len(self.task_timelines) >= cap:
                self.task_timelines.popitem(last=False)
            row = self.task_timelines[tid] = _TaskTimeline(task_id=tid)
        self.task_timelines.move_to_end(tid)  # newest-activity-first view
        if name:
            row.name = name
        row.ts = max(row.ts, ts)
        # display state ends at the terminal execution states — RETURNED
        # is a phase endpoint, not a TaskStatus (reference parity).
        # Compared against the DISPLAYED state's rank, not row.rank: a
        # RETURNED that outruns its FINISHED (driver flushed first) must
        # not wedge the display at RUNNING. A FINISHED arriving after
        # FAILED/CANCELLED (equal rank) DOES win: a retry that succeeded
        # supersedes the failed attempt, and its stale error clears.
        disp_rank = E.STATE_RANK.get(row.state, -1)
        term_mono = row.state_mono.get(row.state)
        if state == E.RUNNING and row.state in (E.FAILED, E.CANCELLED) \
                and folded_mono is not None \
                and (term_mono is None or folded_mono > term_mono):
            # a RETRY started after a terminal attempt: re-open the
            # timeline from this attempt's RUNNING (fresh stamps, error
            # cleared, terminal/RETURNED stamps dropped so the retry's
            # own completion re-terminates the row and the straggler
            # detector can watch it — including re-flagging, so the
            # first attempt's flag is reset too). Guarded by the
            # monotonic comparison: a STALE first-attempt RUNNING whose
            # flush was outrun by the owner's terminal stamp (events
            # ride different connections) predates it in the folded
            # timebase and must NOT destroy the terminal state — the
            # fold stays commutative. Phases already observed into the
            # histograms stay observed — each task contributes each
            # phase at most once (first attempt wins), which keeps the
            # exec distribution honest without per-attempt tracking.
            row.state = E.RUNNING
            row.error = ""
            row.straggler = False
            row.straggler_ms = 0.0
            row.state_ts[E.RUNNING] = ts
            row.state_mono.pop(E.RUNNING, None)
            for st in (E.FINISHED, E.FAILED, E.CANCELLED, E.RETURNED):
                row.state_ts.pop(st, None)
                row.state_mono.pop(st, None)
        elif state != E.RETURNED and (
                rank > disp_rank
                or (state == E.FINISHED
                    and row.state in (E.FAILED, E.CANCELLED))):
            row.state = state
            if state == E.FINISHED:
                row.error = ""
        if state in (E.FETCHING_ARGS, E.RUNNING, E.FINISHED):
            # the executing worker's identity wins over the submitter's
            row.worker_id, row.node_idx = wid, nidx
        elif state in (E.FAILED, E.CANCELLED) and \
                E.FETCHING_ARGS not in row.state_ts and \
                E.RUNNING not in row.state_ts:
            # owner-side terminal stamps (worker crash, dep failure)
            # must not clobber the identity of the worker that actually
            # ran the task; they only fill it for never-dispatched tasks
            row.worker_id, row.node_idx = wid, nidx
        elif not row.worker_id:
            row.worker_id, row.node_idx = wid, nidx
        if err and row.state != E.FINISHED:
            row.error = err
        if trace_id and not row.trace_id:
            row.trace_id = trace_id
        row.state_ts.setdefault(state, ts)
        if folded_mono is not None and state not in row.state_mono:
            row.state_mono[state] = folded_mono
            self._observe_new_phases(row, state)

    def _observe_new_phases(self, row: _TaskTimeline, new_state: str):
        """Histogram each phase exactly once, the moment both endpoints
        are known (caller holds the timeline lock). Incremental: only
        phases that have ``new_state`` as an endpoint can have newly
        completed — re-deriving ALL six phases per folded event was a
        measurable slice of the fold's hot loop."""
        monos = row.state_mono
        for ph, starts, ends in E.PHASES_TOUCHING.get(new_state, ()):
            if ph in row.observed:
                continue
            a = E._first_stamp(monos, starts)
            b = E._first_stamp(monos, ends)
            if a is None or b is None:
                continue
            ms = max(0.0, (b - a) * 1000.0)
            if ph == "exec" and E.FINISHED not in monos:
                # a FAILED/CANCELLED attempt's exec time must not seed
                # the COMPLETED-exec baseline the straggler detector
                # compares against (5 fast transient failures would arm
                # a ~ms bound that flags every legitimate run). Not
                # marked observed: if a retry re-opens and FINISHES,
                # its exec observes then.
                continue
            row.observed.add(ph)
            self._observe_phase_hist(
                "task.phase_ms",
                "Per-phase task lifecycle latency by function "
                "(sched_wait/dispatch/arg_fetch/exec/result_return/e2e)",
                {"func": row.name, "phase": ph}, ms)
            if ph in ("dispatch", "arg_fetch") and row.node_idx >= 0:
                # the phases that END on the executing node — the
                # slow-node skew detector compares these across nodes
                self._observe_phase_hist(
                    "task.node_phase_ms",
                    "Per-phase task lifecycle latency by executing node",
                    {"node": str(row.node_idx), "phase": ph}, ms)

    def _observe_phase_hist(self, name: str, desc: str, tags: Dict[str, str],
                            value_ms: float):
        """Head-side histogram observation straight into the merged
        metric table (same row schema as _h_metrics_report ingests), so
        the phase histograms ride metrics_summary() / the Prometheus
        exposition (`task_phase_ms_bucket{func=...,phase=...}`) with no
        extra plumbing. Takes the metrics lock itself (callers hold the
        timeline lock — the fixed ordering)."""
        key = (name, tuple(tags.values()))
        with self._metrics_lock:
            row = self.metrics.get(key)
            if row is None:
                row = self.metrics[key] = {
                    "name": name, "kind": "histogram",
                    "description": desc,
                    "tags": dict(tags),
                    "boundaries": list(TASK_PHASE_MS_BOUNDARIES),
                    "value": [0.0] * (len(TASK_PHASE_MS_BOUNDARIES) + 3),
                }
            v = row["value"]
            for i, b in enumerate(TASK_PHASE_MS_BOUNDARIES):
                if value_ms <= b:
                    v[i] += 1
                    break
            else:
                v[len(TASK_PHASE_MS_BOUNDARIES)] += 1
            v[-2] += value_ms
            v[-1] += 1

    def _task_phase_summary(self, funcs=None,
                            include_raw=False) -> Dict[str, dict]:
        """{func: {phase: {count, mean_ms, p50_ms, p95_ms, p99_ms}}}
        from the folded phase histograms (takes the metrics lock).
        ``funcs`` restricts the scan to those func names — the serve
        controller's 1/s SLO-burn poll asks for exactly its replica
        methods, so the reply stays a few rows no matter how many other
        funcs the cluster has run (the summary never rides the per-
        request hot path; it feeds scale decisions). ``include_raw``
        (the phase_summary state query only) adds the raw cumulative
        vectors — the dashboard/CLI task summary reuses this method and
        must not ship ~35-element arrays per row it never reads."""
        out: Dict[str, dict] = {}
        with self._metrics_lock:
            rows = list(self.metrics.items())
        for key, row in rows:
            if key[0] != "task.phase_ms":
                continue
            if funcs is not None and row["tags"]["func"] not in funcs:
                continue
            v, b = row["value"], row["boundaries"]
            n = v[-1]
            if n <= 0:
                continue
            entry = {
                "count": n,
                "mean_ms": v[-2] / n,
                "p50_ms": _hist_quantile(b, v, 0.50),
                "p95_ms": _hist_quantile(b, v, 0.95),
                "p99_ms": _hist_quantile(b, v, 0.99),
            }
            if include_raw:
                # raw cumulative vector ([buckets..., overflow, sum_ms,
                # count]) so pollers can delta successive snapshots
                # into a WINDOWED quantile (the lifetime percentiles
                # above stop moving once history dwarfs the recent
                # past)
                entry["buckets"] = list(v)
                entry["boundaries"] = list(b)
            out.setdefault(row["tags"]["func"], {})[
                row["tags"]["phase"]] = entry
        return out

    def detect_stragglers(self):
        """One detector sweep (the detector thread's body; callable
        directly from tests). A RUNNING task whose current exec time
        exceeds ``straggler_factor`` x its func's completed-exec p95
        (min-sample-gated) is flagged once and emits ONE rate-limited
        ``task_straggler`` cluster event naming task, node and worker;
        per-node dispatch/arg_fetch p95 skew vs the cluster median emits
        ``slow_node`` (>= 30s apart per node+phase)."""
        from . import events as E

        if self._grace_active():
            # a restarted head's timelines/histograms are rebuilding —
            # flagging against half-folded distributions would alarm on
            # every re-registered task
            return
        cfg = get_config()
        now = time.monotonic()
        flagged: List[tuple] = []
        with self._timeline_lock, self._metrics_lock:
            for row in self.task_timelines.values():
                if len(flagged) >= 10:
                    # cap the event volume per sweep; the rest stay
                    # UN-flagged and get their one event on a later
                    # sweep (a mass stall's node-level signal is the
                    # slow_node / node_dead path anyway)
                    break
                if row.straggler or row.state != E.RUNNING:
                    continue
                start = row.state_mono.get(E.RUNNING)
                if start is None:
                    continue
                hist = self.metrics.get(("task.phase_ms",
                                         (row.name, "exec")))
                if hist is None or \
                        hist["value"][-1] < cfg.straggler_min_samples:
                    continue
                v, nb = hist["value"], len(TASK_PHASE_MS_BOUNDARIES)
                if sum(v[:nb]) < 0.95 * v[-1]:
                    # the p95 falls in the +Inf bucket: the upper tail
                    # is unknown (quantile would clamp to the last
                    # finite bound and falsely flag EVERY run of a
                    # func whose normal exec exceeds it) — no robust
                    # bound exists, so don't flag
                    continue
                p95 = _hist_quantile(hist["boundaries"], hist["value"],
                                     0.95)
                bound_ms = max(p95, 1.0) * cfg.straggler_factor
                running_ms = (now - start) * 1000.0
                if running_ms > bound_ms:
                    row.straggler = True
                    row.straggler_ms = running_ms
                    self.stragglers_flagged += 1
                    flagged.append((row.task_id, row.name, row.worker_id,
                                    row.node_idx, running_ms, p95))
            slow_nodes = self._detect_slow_nodes(now)
        # rate limit: the per-task flag means one event per straggler
        # ever, and the sweep loop above caps flags per sweep
        for tid, func, wid, nidx, running_ms, p95 in flagged:
            self.emit_event(
                "WARNING", "head", "task_straggler",
                f"task {tid[:16]} ({func}) running {running_ms:.0f}ms on "
                f"node {nidx}, over {get_config().straggler_factor:g}x "
                f"its p95 exec ({p95:.0f}ms)",
                node_idx=nidx, entity_id=tid,
                extra={"task_id": tid, "func": func, "worker_id": wid,
                       "node_idx": nidx, "running_ms": running_ms,
                       "exec_p95_ms": p95})
        for nidx, phase, p95, med in slow_nodes:
            self.emit_event(
                "WARNING", "head", "slow_node",
                f"node {nidx} {phase} p95 {p95:.0f}ms vs cluster median "
                f"{med:.0f}ms — host-level skew (slow NIC/disk/CPU?)",
                node_idx=nidx,
                extra={"node_idx": nidx, "phase": phase, "p95_ms": p95,
                       "cluster_median_ms": med})

    def _detect_slow_nodes(self, now: float) -> List[tuple]:
        """Per-node phase-skew check (caller holds the lock): a node
        whose dispatch/arg_fetch p95 is ``straggler_factor`` x the
        cluster median (and at least 5ms over it — sub-ms noise never
        alarms) is flagged, rate-limited per (node, phase)."""
        cfg = get_config()
        out: List[tuple] = []
        for phase in ("dispatch", "arg_fetch"):
            p95s: Dict[int, float] = {}
            for key, row in self.metrics.items():
                if key[0] != "task.node_phase_ms" or \
                        row["tags"].get("phase") != phase:
                    continue
                try:
                    nidx = int(row["tags"]["node"])
                except ValueError:
                    continue
                # judge the delta since the last sweep, not the lifetime
                # vector (see _node_phase_prev) — and advance the
                # baseline for EVERY row so every node's window covers
                # the same span regardless of gating below
                cur = row["value"]
                prev = self._node_phase_prev.get((nidx, phase))
                self._node_phase_prev[(nidx, phase)] = list(cur)
                delta = cur if prev is None or len(prev) != len(cur) \
                    else [cur[i] - prev[i] for i in range(len(cur))]
                if delta[-1] < cfg.straggler_min_samples:
                    continue  # too few RECENT samples to judge
                node = self.nodes.get(nidx)
                if node is None or not node.alive:
                    continue  # stale histogram of a removed node
                p95s[nidx] = _hist_quantile(row["boundaries"],
                                            delta, 0.95)
            if len(p95s) < 2:
                continue
            med = statistics.median(p95s.values())
            for nidx, p95 in p95s.items():
                if p95 > med * cfg.straggler_factor and p95 >= med + 5.0:
                    # routing flag refreshes on EVERY detection (the
                    # event below is rate-limited; the flag must not
                    # lapse between throttled events while the skew
                    # persists)
                    if cfg.slow_node_route_ttl_s > 0:
                        self._slow_node_until[nidx] = \
                            now + cfg.slow_node_route_ttl_s
                    last = self._last_slow_node_event.get((nidx, phase),
                                                          -1e18)
                    if now - last < 30.0:
                        continue
                    self._last_slow_node_event[(nidx, phase)] = now
                    self.slow_nodes_flagged += 1
                    out.append((nidx, phase, p95, med))
        return out

    def _straggler_loop(self):
        period = get_config().straggler_detect_period_s
        while not self._shutdown:
            time.sleep(period)
            try:
                self.detect_stragglers()
            except Exception:
                if not self._shutdown:
                    import traceback

                    traceback.print_exc()

    # --------------------------------------------------- cluster events

    def emit_event(self, severity: str, source: str, event_type: str,
                   message: str, node_idx: int = -1, entity_id: str = "",
                   extra: Optional[dict] = None):
        """Head-side cluster event emitter (reference: the GCS writing
        its own node/actor/job transitions into the event log). Safe
        from any locked head path — the event ring has its own leaf
        lock, so emitting never extends a head/shard-lock hold."""
        ev = E.make_cluster_event(severity, source, event_type, message,
                                  node_idx=node_idx, entity_id=entity_id,
                                  extra=extra)
        with self._cev_lock:
            self._append_cluster_event(ev)

    def _append_cluster_event(self, ev: tuple):
        """Ring append with drop accounting (caller holds _cev_lock) —
        the ONE place the overflow counter is maintained, shared by the
        head's own emitters and CLUSTER_EVENT pushes."""
        if len(self.cluster_events) == self.cluster_events.maxlen:
            self.cluster_events_dropped += 1
        self.cluster_events.append(ev)

    def _h_cluster_events(self, conn, rid, batch, dropped=0):
        """CLUSTER_EVENT pushes from node agents / workers / the job
        manager merge into the same ring the head's own emitters use."""
        with self._cev_lock:
            for ev in batch:
                self._append_cluster_event(tuple(ev))
            self.cluster_events_dropped += dropped
        if rid > 0:
            conn.reply(rid, True)

    def _h_state_query(self, conn, rid, kind, limit):
        """Observability state API (reference: python/ray/util/state/api.py
        backed by the GCS aggregator endpoints). Each kind takes ONLY
        the lock that owns its table (head lock for node/actor/PG
        tables, timeline/metrics/event-ring locks for observability
        state, per-shard snapshots for the object directory) — a
        dashboard poll can no longer stall lease granting."""
        if isinstance(kind, str) and kind.startswith("phase_summary"):
            # "phase_summary" or "phase_summary:func1,func2" — the
            # func-scoped per-phase percentile query the serve
            # controller polls for SLO-burn autoscaling (r14)
            _, _, spec = kind.partition(":")
            funcs = frozenset(f for f in spec.split(",") if f) or None
            conn.reply(rid, [self._task_phase_summary(
                funcs, include_raw=True)])
            return
        if isinstance(kind, str) and kind.startswith("metrics_history"):
            # "metrics_history" or "metrics_history:<window_s>:<names>"
            # — flight-recorder readback (r19). window_s empty/0 means
            # the full fine window; names are comma-separated exact
            # keys, prefixes, or fnmatch globs ("collective.*").
            _, _, spec = kind.partition(":")
            win_s, _, names_s = spec.partition(":")
            names = [n for n in names_s.split(",") if n] or None
            window = float(win_s) if win_s else None
            conn.reply(rid, [self.recorder.history(names, window)])
            return
        if isinstance(kind, str) and kind.startswith("task_events_page"):
            # "task_events_page:<cursor>" — chunked raw-event readback
            # (r19). Replaces timeline()'s single
            # STATE_QUERY("task_events", 1_000_000) pull: each page is
            # at most `limit` rows, so a long job's export can never
            # build one huge reply frame on the head's IO path. The
            # cursor is an absolute ingest sequence number; a cursor
            # that has already been evicted from the ring fast-forwards
            # to the oldest retained event (the ring's drop accounting
            # covers the gap).
            _, _, spec = kind.partition(":")
            cursor = int(spec) if spec else 0
            with self._timeline_lock:
                seq = self.task_events_seq
                ring = self.task_events
                oldest = seq - len(ring)
                start = max(cursor, oldest)
                page = list(itertools.islice(
                    ring, start - oldest, start - oldest + max(limit, 1)))
            nxt = start + len(page)
            conn.reply(rid, [{
                "rows": [self._fmt_task_event(ev) for ev in page],
                "next": nxt,
                "done": nxt >= seq,
            }])
            return
        fn = self._STATE_KINDS.get(kind)
        if fn is None:
            conn.reply_error(rid, ValueError(f"unknown kind {kind!r}"))
            return
        rows = fn(self, limit)
        conn.reply(rid, rows[:limit])

    def _sq_nodes(self, limit):
        now = time.monotonic()
        with self._metrics_lock:
            telemetry = {i: dict(t) for i, t in self.node_telemetry.items()}
            slow = {i for i, until in self._slow_node_until.items()
                    if until > now}
        with self._lock:
            return [{
                "node_idx": n.idx, "alive": n.alive,
                "is_remote": n.is_remote, "node_ip": n.node_ip,
                # graceful drain (r16): draining nodes take no new
                # leases/placements/prefetches while their work moves
                # off; drain_age_s > drain_deadline_s means the
                # escalation wedged (doctor_warnings flags it)
                "draining": n.draining,
                "drain_age_s": round(now - n.drain_started, 1)
                if n.draining else 0.0,
                # live slow_node detector flag (r14): the node's
                # dispatch/arg_fetch p95 skewed off the cluster median
                # within the last slow_node_route_ttl_s — serve routers
                # steer traffic away while it is set
                "slow": n.idx in slow,
                "resources_total": n.resources.total.to_dict(),
                "resources_available": n.resources.available.to_dict(),
                # last reporter-agent sample for this node (node.*
                # gauges; empty until the first telemetry period)
                "telemetry": telemetry.get(n.idx, {}),
                # RTT-midpoint (agent_mono - head_mono) estimate used
                # to fold this node's event stamps (0 for local
                # nodes: CLOCK_MONOTONIC is host-wide)
                "clock_offset_s": n.clock_offset_s,
                "clock_rtt_s": n.clock_rtt_s,
            } for n in self.nodes.values()]

    def _sq_workers(self, limit):
        with self._lock:
            return [{
                "worker_id": w.worker_id, "node_idx": n.idx,
                "pid": w.pid, "state": w.state,
                "actor_id": w.actor_id.hex() if w.actor_id else None,
            } for n in self.nodes.values()
                for w in n.workers.values()]

    def _sq_actors(self, limit):
        with self._lock:
            return [{
                "actor_id": a.actor_id.hex(), "state": a.state,
                "name": a.name, "class_name": a.spec.class_name,
                "worker_id": a.worker_id, "restarts": a.restarts_used,
                "death_cause": a.death_cause,
            } for a in self.actors.values()]

    def _sq_placement_groups(self, limit):
        with self._lock:
            return [{
                "pg_id": pid.hex(), "state": info.state,
                "strategy": info.spec.strategy,
                "bundles": [b.resources for b in info.spec.bundles],
                "placement": list(info.placement),
            } for pid, info in self.pgs.items()]

    def _sq_objects(self, limit):
        # holder sets copied under the shard locks (a live set can
        # mutate mid-iteration once the snapshot lock is released)
        return self.objects.listing_rows()

    def _sq_object_plane(self, limit):
        # object data-plane snapshot: directory shape + locality
        # placement counters (pull-side counters arrive via the
        # normal METRICS_REPORT path and land under "metrics")
        live = [loc for loc in self.objects.values_snapshot()
                if loc.node_idx >= 0 or loc.spilled_path]
        return [{
            "directory_objects": len(live),
            "directory_bytes": sum(l.size for l in live),
            "replicated_objects": sum(
                1 for l in live if len(l.holders) > 1),
            "holder_entries": sum(len(l.holders) for l in live),
            "locality_hits": self.locality_hits,
            "locality_misses": self.locality_misses,
            "relay_bytes": self.relay_bytes,
            # cooperative-broadcast planner state: live
            # in-progress locations + cumulative source-role
            # assignment / saturation counters (the per-serve
            # root-vs-relay counters ride the metrics channel
            # as object_plane.serves{role=...})
            "inprog_locations": sum(
                len(l.inprog) for l in live),
            "broadcast_root_assignments":
                self.broadcast_root_assignments,
            "broadcast_relay_assignments":
                self.broadcast_relay_assignments,
            "broadcast_fanout_saturations":
                self.broadcast_fanout_saturations,
            # speculative arg prefetch (r13): issued = speculative
            # pulls fired at lease grant / dispatch hint; joined =
            # demand fetches that overlapped one in flight; wasted =
            # aborted as stale speculation (task cancelled / retried
            # elsewhere before any worker asked) — doctor_warnings()
            # flags a high wasted:issued ratio
            "prefetch_issued": self.prefetch_issued,
            "prefetch_joined": self.prefetch_joined,
            "prefetch_completed": self.prefetch_completed,
            "prefetch_wasted": self.prefetch_wasted,
            "prefetch_bytes_issued": self.prefetch_bytes_issued,
            "prefetch_inflight": self._prefetch_inflight_count(),
            # r16: pulls of driver-tagged inline-promoted objects —
            # real transfers, but not the speculation the waste-ratio
            # doctor check judges (issued/completed/wasted above
            # exclude them)
            "prefetch_issued_inline": self.prefetch_issued_inline,
            "prefetch_completed_inline": self.prefetch_completed_inline,
            "prefetch_wasted_inline": self.prefetch_wasted_inline,
            # r18 host-plane collectives: the cluster-merged
            # collective.* metric rows summarized (ops / bytes by
            # algorithm + hop p95) — the ring's payload bytes move
            # store-to-store, so they show up HERE and in the agents'
            # serve counters, never in relay_bytes or the head
            # server's bytes_served
            "collective": self._collective_summary_locked(),
            # the head host's own transfer server, split by
            # source role (root = sealed copy, relay = re-served
            # in-progress partial); agent-side servers report
            # the same split via object_plane.serves metrics
            "head_server": ({
                "pull_requests":
                    self._transfer_server.pull_requests,
                "served_root": self._transfer_server.served_root,
                "served_relay":
                    self._transfer_server.served_relay,
                "bytes_served":
                    self._transfer_server.bytes_served,
                "relay_bytes_served":
                    self._transfer_server.relay_bytes_served,
            } if self._transfer_server is not None else {}),
        }]

    def _collective_summary_locked(self):
        """Aggregate the merged ``collective.*`` metric rows into the
        object_plane snapshot (r18): per-algorithm tag slices sum into
        ops / bytes_sent / bytes_recv totals plus a per-algorithm
        breakdown, and the merged hop histogram yields hop_p95_s.
        Takes the metrics lock itself (called from _sq_object_plane,
        which holds no locks)."""
        out = {"ops": 0.0, "bytes_sent": 0.0, "bytes_recv": 0.0,
               "hop_p95_s": 0.0, "by_algorithm": {}}
        hop = None
        hop_bounds = None
        with self._metrics_lock:
            rows = [dict(r) for (name, _), r in self.metrics.items()
                    if name.startswith("collective.")]
        for row in rows:
            short = row["name"][len("collective."):]
            alg = row["tags"].get("algorithm", "")
            if row["kind"] == "histogram":
                if short == "hop_s":
                    v = row["value"]
                    if hop is None:
                        hop = list(v)
                        hop_bounds = row["boundaries"]
                    else:
                        hop = [a + b for a, b in zip(hop, v)]
                continue
            if short in ("ops", "bytes_sent", "bytes_recv"):
                out[short] += row["value"]
                if alg:
                    slot = out["by_algorithm"].setdefault(
                        alg, {"ops": 0.0, "bytes_sent": 0.0,
                              "bytes_recv": 0.0})
                    slot[short] += row["value"]
        if hop and hop_bounds:
            out["hop_p95_s"] = round(
                _hist_quantile(hop_bounds, hop, 0.95), 6)
        return out

    # telemetry gauge -> short key in the per-node "arena" block of the
    # memory summary (reported by NodeTelemetryReporter off each store's
    # memory_stats(); absent until the first heartbeat lands)
    _ARENA_TELEMETRY_KEYS = {
        "object_plane.arena_capacity_bytes": "capacity",
        "object_plane.arena_used_bytes": "used_bytes",
        "object_plane.arena_highwater_bytes": "highwater_bytes",
        "object_plane.arena_entries": "entries",
        "object_plane.arena_sealed_bytes": "sealed_bytes",
        "object_plane.arena_sealed_data_bytes": "sealed_data_bytes",
        "object_plane.arena_unsealed_bytes": "unsealed_bytes",
        "object_plane.arena_pinned_bytes": "pinned_bytes",
        "object_plane.arena_borrow_pinned_bytes": "borrow_pinned_bytes",
        "object_plane.arena_deferred_deletes": "deferred_deletes",
        "object_plane.arena_deferred_delete_oldest_s":
            "deferred_delete_oldest_s",
    }

    def _sq_memory_summary(self, limit):
        """Cluster memory rollup (memory observatory): per-node and
        per-job/per-owner resident-byte aggregates off the object
        directory, merged with each node's last arena heartbeat, plus
        the reference-class breakdown and the top-N largest objects.
        Reference: `ray memory` / memory_utils.py's grouped object
        table, served from GCS object tables there, from the sharded
        directory here. Per-node resident bytes count every COPY on
        that node (so they compare exactly against the node store's
        sealed payload bytes); job/owner/total aggregates do too —
        they answer "whose bytes sit in arenas", not "how many
        distinct values exist"."""
        cfg = get_config()
        top_n = max(1, min(int(cfg.memory_summary_top_n),
                           limit if limit > 0 else 1 << 30))
        now = time.time()
        with self._lock:
            live_owners = {w.worker_id
                           for n in self.nodes.values()
                           for w in n.workers.values()}
            node_idxs = sorted(self.nodes)
        with self._metrics_lock:
            telemetry = {i: dict(t)
                         for i, t in self.node_telemetry.items()}
        nodes: Dict[int, dict] = {
            i: {"resident_bytes": 0, "resident_objects": 0,
                "spilled_bytes": 0, "arena": {}} for i in node_idxs}
        jobs: Dict[str, dict] = {}
        owners: Dict[str, dict] = {}
        classes = {"sealed_bytes": 0, "spilled_bytes": 0,
                   "checkpoint_bytes": 0, "prefetch_inflight_bytes": 0,
                   "borrow_pinned_bytes": 0}
        dead_owner = {"objects": 0, "bytes": 0, "owners": set()}
        all_objs: List[dict] = []
        for oid, loc in self.objects.items_snapshot():
            with self.objects.lock_for(oid):
                holders = sorted(loc.holders)
                size, owner, job = loc.size, loc.owner, loc.job
                tag, sealed_at = loc.tag, loc.sealed_at
                spilled = bool(loc.spilled_path)
                inprog = bool(loc.inprog)
            copies = len(holders)
            resident = size * copies
            if not resident and not spilled:
                continue
            for h in holders:
                row = nodes.setdefault(
                    h, {"resident_bytes": 0, "resident_objects": 0,
                        "spilled_bytes": 0, "arena": {}})
                row["resident_bytes"] += size
                row["resident_objects"] += 1
            if spilled:
                classes["spilled_bytes"] += size
            classes["sealed_bytes"] += resident
            if tag == "checkpoint":
                classes["checkpoint_bytes"] += resident
            if inprog:
                classes["prefetch_inflight_bytes"] += size
            jrow = jobs.setdefault(job or "", {
                "resident_bytes": 0, "objects": 0, "per_node": {}})
            jrow["resident_bytes"] += resident
            jrow["objects"] += 1
            for h in holders:
                jrow["per_node"][h] = jrow["per_node"].get(h, 0) + size
            orow = owners.setdefault(owner or "", {
                "resident_bytes": 0, "objects": 0, "live": True})
            orow["resident_bytes"] += resident
            orow["objects"] += 1
            if owner and owner not in live_owners:
                orow["live"] = False
                if resident:
                    dead_owner["objects"] += 1
                    dead_owner["bytes"] += resident
                    dead_owner["owners"].add(owner)
            all_objs.append({
                "object_id": oid.hex(), "size": size,
                "node_idx": holders[0] if holders else -1,
                "holders": holders, "owner": owner, "job": job,
                "tag": tag, "spilled": spilled,
                "age_s": round(now - sealed_at, 3) if sealed_at
                else 0.0,
            })
        for idx, row in nodes.items():
            t = telemetry.get(idx, {})
            row["arena"] = {
                short: t[g] for g, short in
                self._ARENA_TELEMETRY_KEYS.items() if g in t}
            spilled_here = sum(
                o["size"] for o in all_objs
                if o["spilled"] and o["node_idx"] == idx)
            row["spilled_bytes"] = spilled_here
        classes["borrow_pinned_bytes"] = int(sum(
            t.get("object_plane.arena_borrow_pinned_bytes", 0)
            for t in telemetry.values()))
        all_objs.sort(key=lambda o: o["size"], reverse=True)
        dead_owner["owners"] = sorted(dead_owner["owners"])
        return [{
            "nodes": nodes,
            "jobs": jobs,
            "owners": owners,
            "classes": classes,
            "dead_owner": dead_owner,
            "top_objects": all_objs[:top_n],
            "totals": {
                "resident_bytes": sum(
                    n["resident_bytes"] for n in nodes.values()),
                "resident_objects": len(
                    [o for o in all_objs if o["holders"]]),
                "spilled_bytes": classes["spilled_bytes"],
                "prefetch_inflight": self._prefetch_inflight_count(),
            },
        }]

    def _sq_metrics(self, limit):
        # merged client metrics plus the head's own ring-buffer
        # health counters, so silent event drops surface in
        # metrics_summary() / the Prometheus exposition
        with self._metrics_lock:
            rows = list(self.metrics.values())
        return rows + [
            {"name": "head.task_events_dropped",
             "kind": "counter",
             "description": "Task events dropped by bounded "
                            "buffers (worker + head ring)",
             "tags": {}, "boundaries": None,
             "value": float(self.task_events_dropped)},
            {"name": "head.cluster_events_dropped",
             "kind": "counter",
             "description": "Cluster events dropped by the head "
                            "ring buffer",
             "tags": {}, "boundaries": None,
             "value": float(self.cluster_events_dropped)},
            {"name": "object_plane.prefetch_issued",
             "kind": "counter",
             "description": "Speculative arg pulls fired at lease "
                            "grant / dispatch hint (r13)",
             "tags": {}, "boundaries": None,
             "value": float(self.prefetch_issued)},
            {"name": "object_plane.prefetch_joined",
             "kind": "counter",
             "description": "Demand arg fetches that joined an "
                            "in-flight speculative pull",
             "tags": {}, "boundaries": None,
             "value": float(self.prefetch_joined)},
            {"name": "object_plane.prefetch_wasted",
             "kind": "counter",
             "description": "Speculative pulls aborted as stale "
                            "(task cancelled/retried elsewhere)",
             "tags": {}, "boundaries": None,
             "value": float(self.prefetch_wasted)},
            {"name": "head.reconnects",
             "kind": "counter",
             "description": "Head-channel reattachments "
                            "(CLIENT_HELLO with reattach=true) from "
                            "agents/drivers/workers",
             "tags": {}, "boundaries": None,
             "value": float(self.client_reconnects)},
            {"name": "head.node_reattaches",
             "kind": "counter",
             "description": "Node agents that re-registered with a "
                            "prior node id after a head restart or "
                            "socket loss",
             "tags": {}, "boundaries": None,
             "value": float(self.node_reattaches)},
            {"name": "head.actor_reclaims",
             "kind": "counter",
             "description": "Actors re-claimed by surviving workers "
                            "after a head restart",
             "tags": {}, "boundaries": None,
             "value": float(self.actor_reclaims)},
            {"name": "head.request_dedupe_hits",
             "kind": "counter",
             "description": "Retried mutations answered from the "
                            "(client, request-id) dedupe cache "
                            "instead of re-applied",
             "tags": {}, "boundaries": None,
             "value": float(self.dedupe_hits)},
            {"name": "head.drain_migrated_leases",
             "kind": "counter",
             "description": "Leases released off draining nodes while "
                            "still alive (work migrated, not killed)",
             "tags": {}, "boundaries": None,
             "value": float(self.drain_migrated_leases)},
            {"name": "head.drains_completed",
             "kind": "counter",
             "description": "Graceful node drains that finished with "
                            "zero live leases (vs drains_forced)",
             "tags": {}, "boundaries": None,
             "value": float(self.drains_completed)},
        ]

    def _sq_io_loop(self, limit):
        # head event-loop lag (analog: the reference's
        # instrumented_io_context / event_stats.h per-handler
        # timing surfaced through the debug state endpoints) +
        # ring-buffer drop counters: overflow of the bounded
        # event buffers must be detectable, not silent
        now = time.monotonic()
        with self._lock:
            # workers recreated from agent re-registration reports
            # that have not re-REGISTERed themselves yet — nonzero
            # long after a restart means a node is stuck
            # re-registering (doctor_warnings flags it)
            pending = [now - w.spawned_at
                       for n in self.nodes.values()
                       for w in n.workers.values()
                       if w.state == "starting"
                       and w.sched_class == self.REATTACH_CLASS]
        return [dict(loop=self.io.name, **self.io.stats(),
                     **self.io.lag_stats(),
                     task_events_dropped=self.task_events_dropped,
                     cluster_events_dropped=(
                         self.cluster_events_dropped),
                     # off-loop fold-queue health: depth right now +
                     # batches shed because the queue hit its bound
                     fold_queue_depth=len(self._fold_q),
                     fold_queue_drops=self.fold_queue_drops,
                     lease_grant_batches=self.lease_grant_batches,
                     lease_grants_batched=self.lease_grants_batched,
                     # head fault tolerance (r12): channel reattaches,
                     # node/actor re-registrations, retried-mutation
                     # dedupe hits, grace-window state
                     client_reconnects=self.client_reconnects,
                     reconnect_clients=len(self._reconnect_clients),
                     node_reattaches=self.node_reattaches,
                     actor_reclaims=self.actor_reclaims,
                     dedupe_hits=self.dedupe_hits,
                     restart_grace_active=bool(self._grace_until),
                     # graceful node drain (r16)
                     drains_started=self.drains_started,
                     drains_completed=self.drains_completed,
                     drains_forced=self.drains_forced,
                     drain_migrated_leases=self.drain_migrated_leases,
                     drain_objects_replicated=(
                         self.drain_objects_replicated),
                     reattach_pending_workers=len(pending),
                     reattach_oldest_s=round(max(pending, default=0.0),
                                             3),
                     # this process's data/return-plane fast-path
                     # counters (vectored sends, coalesced
                     # flushes, batched completions, zero-copy
                     # raw bytes) — cluster-wide per-process
                     # totals ride the metrics channel instead
                     wire=P.WIRE.snapshot())]

    def _sq_cluster_events(self, limit):
        # most recent `limit` records, oldest first
        with self._cev_lock:
            recent = list(self.cluster_events)[-limit:]
        return [{
            "ts": ts, "severity": sev, "source": src,
            "node_idx": nidx, "entity_id": eid, "type": etype,
            "message": msg, "extra": extra,
        } for (ts, sev, src, nidx, eid, etype, msg, extra) in recent]

    @staticmethod
    def _fmt_task_event(ev):
        # wire tuple -> state-API dict; tolerant of the pre-r10
        # 10-field shape (no monotonic stamp)
        return {
            "task_id": ev[0], "name": ev[1], "state": ev[2],
            "worker_id": ev[3], "node_idx": ev[4], "ts": ev[5],
            "error": ev[6], "trace_id": ev[7], "span_id": ev[8],
            "parent_span_id": ev[9],
            "mono": ev[10] if len(ev) > 10 else None,
        }

    def _sq_task_events(self, limit):
        # raw transition log (timeline/tracing export)
        with self._timeline_lock:
            evs = list(self.task_events)
        return [self._fmt_task_event(ev) for ev in evs]

    def _sq_tasks(self, limit):
        # folded timelines, newest activity first: full state_ts
        # map + derived per-phase latency breakdown per row.
        # Materialize only `limit` rows — building 10k fat dicts
        # per dashboard poll would stall the fold thread.
        rows = []
        with self._timeline_lock:
            for r in reversed(self.task_timelines.values()):
                if len(rows) >= limit:
                    break
                rows.append({
                    "task_id": r.task_id, "name": r.name,
                    "state": r.state, "worker_id": r.worker_id,
                    "node_idx": r.node_idx, "ts": r.ts,
                    "error": r.error, "trace_id": r.trace_id,
                    "state_ts": dict(r.state_ts),
                    "phase_ms": E.derive_phase_ms(r.state_mono),
                    "straggler": r.straggler,
                })
        return rows

    def _sq_task_summary(self, limit):
        # per-func per-phase percentile summary from the folded
        # phase histograms (`ray summary tasks` parity++), plus
        # the (name, state) counts computed HERE — summarizing
        # must not ship every fat timeline row over the RPC
        # just to count states
        counts: Dict[str, Dict[str, int]] = {}
        with self._timeline_lock:
            for r in self.task_timelines.values():
                by_state = counts.setdefault(r.name, {})
                by_state[r.state] = by_state.get(r.state, 0) + 1
            total = len(self.task_timelines)
        return [{
            "phases": self._task_phase_summary(),
            "stragglers_flagged": self.stragglers_flagged,
            "slow_nodes_flagged": self.slow_nodes_flagged,
            "total": total,
            "by_func_name": dict(sorted(counts.items())),
        }]

    def _sq_slow_tasks(self, limit):
        rows = []
        with self._timeline_lock:
            for r in reversed(self.task_timelines.values()):
                if len(rows) >= limit:
                    break
                if not r.straggler:
                    continue
                rows.append({
                    "task_id": r.task_id, "name": r.name,
                    "state": r.state, "worker_id": r.worker_id,
                    "node_idx": r.node_idx,
                    "running_ms_when_flagged": r.straggler_ms,
                    "phase_ms": E.derive_phase_ms(r.state_mono),
                })
        return rows

    _STATE_KINDS = {
        "nodes": _sq_nodes,
        "workers": _sq_workers,
        "actors": _sq_actors,
        "placement_groups": _sq_placement_groups,
        "objects": _sq_objects,
        "object_plane": _sq_object_plane,
        "memory_summary": _sq_memory_summary,
        "metrics": _sq_metrics,
        "io_loop": _sq_io_loop,
        "cluster_events": _sq_cluster_events,
        "task_events": _sq_task_events,
        "tasks": _sq_tasks,
        "task_summary": _sq_task_summary,
        "slow_tasks": _sq_slow_tasks,
    }

    def _h_node_info(self, conn, rid):
        with self._lock:
            infos = [{
                "node_idx": n.idx,
                "alive": n.alive,
                "draining": n.draining,
                "resources_total": n.resources.total.to_dict(),
                "resources_available": n.resources.available.to_dict(),
                "store_name": n.store_name,
                "num_workers": len([w for w in n.workers.values()
                                    if w.state != "dead"]),
                "labels": n.resources.labels,
                "tpu": n.resources.tpu,
            } for n in self.nodes.values()]
        conn.reply(rid, infos, msg_type=P.NODE_INFO_REPLY)

    def _h_drain_node(self, conn, rid, node_idx):
        """DRAIN_NODE (r16): the full graceful-drain protocol — not just
        the scheduler exclusion the pre-r16 handler did. See
        ``drain_node``."""
        ok = self.drain_node(int(node_idx))
        if rid > 0:
            conn.reply(rid, ok)

    def _h_ping(self, conn, rid):
        conn.reply(rid, "pong")

    def _h_worker_exit(self, conn, rid):
        pass  # connection close handles cleanup

    # ------------------------------------------------ cross-language calls

    def _h_xlang_call(self, conn, rid, payload):
        """C++/non-Python frontend task submission (ref analog:
        cpp/src/ray/runtime/task/task_submitter.h:26 + the Ray Client
        proxy pattern, util/client/server/proxier.py — a thin client
        submits by FUNCTION DESCRIPTOR and the Python side executes).

        Request: JSON {"op": "submit", "function": "module:qualname",
        "args": [...], "kwargs": {...}, "options": {...},
        "timeout_s": 300}. The reply is a RAW frame of JSON (never
        pickle) keyed by this request's rid, so a C client only needs to
        frame-skip pickled traffic and parse JSON.
        """
        import json as _json

        req = _json.loads(bytes(payload).decode()
                          if isinstance(payload, (bytes, bytearray,
                                                  memoryview))
                          else payload)

        def run():
            try:
                out = {"rid": rid, "status": "ok",
                       "result": self._xlang_execute(req)}
            except BaseException as e:  # noqa: BLE001 — ship to client
                out = {"rid": rid, "status": "error", "error": repr(e)}
            try:
                conn.send_with_raw(
                    P.OK, rid,
                    raw=_json.dumps(out, default=repr).encode())
            except P.ConnectionLost:
                pass

        # off the IO thread: submission blocks on lease grant + execution
        threading.Thread(target=run, daemon=True, name="xlang").start()

    def _xlang_resolve(self, target: str):
        """'module:qualname' -> the python object, allowlist-checked."""
        import importlib

        mod_name, _, qual = target.partition(":")
        if not qual:
            raise ValueError(
                f"target {target!r} must be 'module:qualname'")
        allowed = get_config().xlang_allowed_prefixes
        if allowed:
            def _matches(p: str) -> bool:
                # module-boundary aware: "myapp" allows myapp and myapp.sub
                # but NOT myapp_evil; "myapp." allows the subtree only
                base = p.rstrip(".")
                return mod_name == base or mod_name.startswith(base + ".")
            prefixes = [p.strip() for p in allowed.split(",") if p.strip()]
            if not any(_matches(p) for p in prefixes):
                raise PermissionError(
                    f"module {mod_name!r} is not in xlang_allowed_prefixes")
        obj = importlib.import_module(mod_name)
        for part in qual.split("."):
            obj = getattr(obj, part)
        return obj

    def _xlang_execute(self, req: dict):
        """Cross-language frontend ops (C++/Java clients; the raw-JSON
        reply path of XLANG_CALL). Ref analog:
        cpp/src/ray/runtime/task/task_submitter.h:26 — normal tasks AND
        actor create/submit/kill from non-Python frontends."""
        import ray_tpu

        op = req.get("op", "submit")
        timeout = float(req.get("timeout_s", 300))
        if op == "cluster":
            with self._lock:
                alive = [n for n in self.nodes.values() if n.alive]
                totals: Dict[str, float] = {}
                for n in alive:
                    for k, v in n.resources.total.to_dict().items():
                        totals[k] = totals.get(k, 0.0) + v
                return {"nodes": len(alive), "resources": totals}
        if op == "submit":
            rf = ray_tpu.remote(self._xlang_resolve(req["function"]))
            opts = req.get("options") or {}
            if opts:
                rf = rf.options(**opts)
            ref = rf.remote(*req.get("args", []),
                            **(req.get("kwargs") or {}))
            return ray_tpu.get(ref, timeout=timeout)
        if op == "actor_create":
            cls = ray_tpu.remote(self._xlang_resolve(req["class"]))
            opts = dict(req.get("options") or {})
            name = opts.pop("name", None) or \
                f"xlang-actor-{next(self._xlang_actor_seq)}"
            cls.options(name=name, **opts).remote(
                *req.get("args", []), **(req.get("kwargs") or {}))
            # the name registers at creation; subsequent actor_calls
            # queue behind __init__ per actor task ordering
            return {"actor": name}
        if op == "actor_call":
            handle = ray_tpu.get_actor(req["actor"])
            method = getattr(handle, req["method"])
            ref = method.remote(*req.get("args", []),
                                **(req.get("kwargs") or {}))
            return ray_tpu.get(ref, timeout=timeout)
        if op == "actor_kill":
            ray_tpu.kill(ray_tpu.get_actor(req["actor"]))
            return {"killed": req["actor"]}
        raise ValueError(f"unknown xlang op {op!r}")

    _HANDLERS = {
        P.REGISTER: _h_register,
        P.LEASE_REQUEST: _h_lease_request,
        P.RETURN_WORKER: _h_return_worker,
        P.CREATE_ACTOR: _h_create_actor,
        P.GET_ACTOR: _h_get_actor,
        P.KILL_ACTOR: _h_kill_actor,
        P.CREATE_PG: _h_create_pg,
        P.REMOVE_PG: _h_remove_pg,
        P.KV_PUT: _h_kv_put,
        P.KV_GET: _h_kv_get,
        P.KV_DEL: _h_kv_del,
        P.KV_KEYS: _h_kv_keys,
        P.SUBSCRIBE: _h_subscribe,
        P.PUBLISH: _h_publish,
        P.OBJECT_SEALED: _h_object_sealed,
        P.OBJ_TAG: _h_obj_tag,
        P.OBJECT_LOCATE: _h_object_locate,
        P.OBJECT_FREE: _h_object_free,
        P.OBJ_LOCATION_ADD: _h_obj_location_add,
        P.OBJ_LOCATION_REMOVE: _h_obj_location_remove,
        P.OBJ_LOCATION_LOOKUP: _h_obj_location_lookup,
        P.OBJECT_RECOVERING: _h_object_recovering,
        P.OBJECT_TRANSFER: _h_object_transfer,
        P.NODE_INFO: _h_node_info,
        P.DRAIN_NODE: _h_drain_node,
        P.PING: _h_ping,
        P.WORKER_EXIT: _h_worker_exit,
        P.TASK_REPLY: _h_creation_reply,
        # workers batch completions toward whichever connection pushed
        # the tasks; nothing head-pushed batches today (creation replies
        # are inline), but a future head-routed task path must not
        # silently drop a batched ack
        P.TASK_DONE_BATCH: lambda self, conn, rid, replies: [
            self._h_creation_reply(conn, 0, *r) for r in replies],
        P.ACTOR_DEAD: _h_actor_dead,
        P.BORROW_ADD: lambda self, conn, rid, oid, owner, borrower:
            self._forward_to_worker(owner, P.BORROW_ADD, oid, borrower),
        P.BORROW_REMOVE: lambda self, conn, rid, oid, owner, borrower:
            self._forward_to_worker(owner, P.BORROW_REMOVE, oid, borrower),
        P.RECOVER_OBJECT: lambda self, conn, rid, oid, owner:
            self._forward_to_worker(owner, P.RECOVER_OBJECT, oid),
        P.REGISTER_NODE: _h_register_node,
        P.CLIENT_HELLO: _h_client_hello,
        P.TASK_EVENTS: _h_task_events,
        P.CLUSTER_EVENT: _h_cluster_events,
        P.STATE_QUERY: _h_state_query,
        P.SEAL_ABORTED: _h_seal_aborted,
        P.METRICS_REPORT: _h_metrics_report,
        P.XLANG_CALL: _h_xlang_call,
        P.PREFETCH_RESULT: _h_prefetch_result,
        P.PREFETCH_HINT: _h_prefetch_hint,
        P.PREFETCH_HINT_BATCH: _h_prefetch_hint_batch,
        P.OBJECT_WARM: _h_object_warm,
    }

    def _forward_to_worker(self, worker_id: str, mt: int, *fields):
        with self._lock:
            for node in self.nodes.values():
                w = node.workers.get(worker_id)
                if w is not None and w.conn is not None:
                    conn = w.conn
                    break
            else:
                return
        try:
            conn.send(mt, *fields)
        except P.ConnectionLost:
            pass

    # ------------------------------------------------------------ lifecycle

    def _enqueue_wal(self, rec: tuple):
        """Queue a durable record; the housekeeping thread does the file
        IO (append can trigger compaction = read+rewrite+fsync of the
        whole log — never on the RPC dispatch thread). Trade-off: a hard
        head crash can lose the last <0.25s of records; shutdown drains."""
        with self._lock:
            self._wal_backlog.append(rec)

    def _health_check(self):
        """Probe remote agents on a period; evict after N consecutive
        failures. Socket-close detection only catches DEAD agents — a
        WEDGED one (process alive, event loop stuck) keeps its socket
        open forever; the probe is what evicts it (reference: 3s period /
        5 failures, gcs_health_check_manager.h:39, ray_config_def.h)."""
        cfg = get_config()
        now = time.monotonic()
        with self._lock:
            targets = [
                n for n in self.nodes.values()
                if n.is_remote and n.alive and not n.ping_inflight
                and now - n.last_ping >= cfg.health_check_period_s
            ]
            for n in targets:
                n.ping_inflight = True
        for node in targets:
            threading.Thread(target=self._ping_node, args=(node,),
                             daemon=True, name="health-probe").start()

    def _ping_node(self, node: NodeState):
        cfg = get_config()
        try:
            t0 = time.monotonic()
            reply = node.agent_conn.call(
                P.PING, timeout=max(cfg.health_check_period_s, 1.0))
            t1 = time.monotonic()
            node.health_failures = 0
            # Heartbeat doubles as the clock-offset sampler: agents reply
            # with their own monotonic clock; the RTT midpoint estimates
            # (agent_mono - head_mono), refreshed every probe so drift
            # stays bounded. Folded task-event stamps from this node have
            # the offset subtracted (phase math in one timebase).
            if len(reply) >= 2 and isinstance(reply[1], (int, float)):
                off = float(reply[1]) - (t0 + t1) / 2.0
                with self._lock:
                    node.clock_offset_s = off
                    node.clock_rtt_s = t1 - t0
                    self.node_clock_offsets[node.idx] = off
        except Exception:  # noqa: BLE001 — timeout or conn error
            node.health_failures += 1
            if node.health_failures >= \
                    cfg.health_check_failure_threshold and node.alive:
                self.remove_node(node.idx)
        finally:
            node.last_ping = time.monotonic()
            node.ping_inflight = False

    def _drain_wal_backlog(self):
        if self._persist is None:
            return
        with self._lock:
            batch, self._wal_backlog = self._wal_backlog, []
        for rec in batch:
            self._persist.append(rec)

    def _housekeeping_loop(self):
        while not self._shutdown:
            time.sleep(0.25)
            try:
                self.periodic()
            except Exception:
                if not self._shutdown:
                    import traceback

                    traceback.print_exc()

    def periodic(self):
        """Housekeeping: PG retries, lease grants, idle worker reaping.
        Driven by the head's own keeper thread (and callable from tests)."""
        self._drain_wal_backlog()
        self._health_check()
        self._retry_pending_pgs()
        self._try_fulfill_pending()
        self._sweep_prefetches()
        self._check_drains()
        # restored actors/PGs held back by the restart grace window are
        # rescheduled here once it lifts (no-op on fresh sessions and
        # after the first post-grace flush)
        if self._restored_actor_specs or self._restored_pg_specs:
            self._flush_restored()
        # Loop-lag sampling: a timestamped self-wakeup measures how long
        # a newly-arrived event waits for the IO thread (the reference's
        # instrumented_io_context event-stats role). Sampled every
        # housekeeping tick; published as head.loop_lag_ms{quantile}
        # gauges so dashboards/scrapers see the control-plane headroom.
        self.io.probe_lag()
        self._publish_loop_lag_gauges()
        cfg = get_config()
        # Flight-recorder sampling (r19): fold the merged metric table
        # (same rows metrics_summary() serves, head built-ins included)
        # into the bounded ring-buffer series. Wall-clock stamps so
        # history aligns with timeline() event timestamps.
        if cfg.timeseries_sample_s > 0:
            wall = time.time()
            if wall - self._ts_last_sample >= cfg.timeseries_sample_s:
                self._ts_last_sample = wall
                self.recorder.sample(self._sq_metrics(1 << 30), wall)
        now = time.monotonic()
        with self._lock:
            # sweep ghost workers: a spawn whose process died (or whose
            # request was lost) before registering would otherwise sit in
            # "starting" forever, looking busy to idle-node accounting
            for node in self.nodes.values():
                for w in list(node.workers.values()):
                    if w.state == "starting" and now - w.spawned_at > \
                            cfg.worker_register_timeout_s:
                        self._kill_worker_process(w)
                        if node.is_remote and node.agent_conn is not None:
                            try:
                                node.agent_conn.send(P.KILL_WORKER,
                                                     w.worker_id)
                            except P.ConnectionLost:
                                pass
                        node.workers.pop(w.worker_id, None)
            for node in self.nodes.values():
                for cls, lst in list(node.idle_by_class.items()):
                    keep = []
                    for wid in lst:
                        w = node.workers[wid]
                        if now - w.idle_since > cfg.idle_worker_keep_alive_s:
                            self._kill_worker_process(w)
                            node.workers.pop(wid, None)
                        else:
                            keep.append(wid)
                    node.idle_by_class[cls] = keep

    @property
    def lost_objects(self):
        """The directory's LOST-id FIFO (read-only view; kept for the
        pre-r11 attribute surface — tests and tooling membership-check
        it)."""
        return self.objects._lost

    def _publish_loop_lag_gauges(self):
        """head.loop_lag_ms{quantile=p50|p99} gauges straight into the
        merged metric table (same direct-write path as the phase
        histograms) — the SCALE bench gate and doctor_warnings() read
        these."""
        lag = self.io.lag_stats()
        if not lag.get("loop_lag_samples"):
            return
        with self._metrics_lock:
            for q in ("p50", "p99"):
                key = ("head.loop_lag_ms", (q,))
                row = self.metrics.get(key)
                if row is None:
                    row = self.metrics[key] = {
                        "name": "head.loop_lag_ms", "kind": "gauge",
                        "description":
                            "Head IO-loop lag (self-probe wakeup wait), "
                            "milliseconds",
                        "tags": {"quantile": q}, "boundaries": None,
                        "value": 0.0,
                    }
                row["value"] = lag[f"loop_lag_ms_{q}"]

    def shutdown(self):
        self._shutdown = True
        self._fold_event.set()
        self._dispatch_event.set()
        if self._log_monitor is not None:
            self._log_monitor.stop()
        if self._telemetry is not None:
            self._telemetry.stop()
        if getattr(self, "_memory_monitor", None) is not None:
            self._memory_monitor.stop()
        with self._lock:
            workers = [w for n in self.nodes.values()
                       for w in n.workers.values()]
        for w in workers:
            self._kill_worker_process(w)
        for w in workers:
            if w.proc is not None:
                try:
                    w.proc.wait(timeout=2)
                except subprocess.TimeoutExpired:
                    pass
        self.io.stop()
        try:
            self._listener.close()
        except OSError:
            pass
        if self._tcp_listener is not None:
            try:
                self._tcp_listener.close()
            except OSError:
                pass
        for n in self.nodes.values():
            try:
                if n.store is not None:
                    n.store.close()
                if n.agent_conn is not None:
                    n.agent_conn.on_close = None
                    try:
                        # cluster shutdown is deliberate: agents exit
                        # now instead of re-dialing the dead head for
                        # the whole reconnect window
                        n.agent_conn.send(P.SHUTDOWN_NODE)
                    except P.ConnectionLost:
                        pass
                    n.agent_conn.close()
            except Exception:
                pass
        self.nodes.clear()
        if self._persist is not None:
            self._drain_wal_backlog()
            self._persist.close()


def env_jax_platform(node: NodeState) -> str:
    """Workers on TPU-less logical nodes must not touch the TPU runtime."""
    if node.resources.total.get("TPU") > 0:
        return os.environ.get("JAX_PLATFORMS", "")
    return "cpu"
